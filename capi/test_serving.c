/* Smoke driver: async batched serving through the C ABI.
 *
 * Submits runs from several same-shaped solvers, checks the
 * submit/poll/await round trip (poll pending before the batch fills,
 * done after), verifies the awaited result matches what a same-seed
 * synchronous pga_run produces (bit-exact through the batched path),
 * and exercises the error surfaces (NULL/stale tickets, await-once).
 */
#include <math.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <unistd.h>

#include "pga_tpu.h"

#define POP 1024
#define LEN 32
#define GENS 5
#define NSOLVERS 3

static pga_t *make_solver(long seed, population_t **pop) {
    pga_t *p = pga_init(seed);
    if (!p) return NULL;
    *pop = pga_create_population(p, POP, LEN, RANDOM_POPULATION);
    if (!*pop || pga_set_objective_name(p, "onemax") != 0) {
        pga_deinit(p);
        return NULL;
    }
    return p;
}

int main(void) {
    /* Deterministic batching for the test: launch only on a full
     * bucket of NSOLVERS or a forcing await. */
    if (pga_serving_config(NSOLVERS, 0.0f) != 0)
        return fprintf(stderr, "pga_serving_config failed\n"), 1;

    pga_t *solvers[NSOLVERS];
    population_t *pops[NSOLVERS];
    pga_ticket_t *tickets[NSOLVERS];

    /* Reference result: a synchronous run on a same-seed solver. */
    population_t *ref_pop;
    pga_t *ref = make_solver(1000, &ref_pop);
    if (!ref) return fprintf(stderr, "reference solver failed\n"), 1;
    if (pga_run_n(ref, GENS) != GENS)
        return fprintf(stderr, "reference pga_run failed\n"), 1;
    gene *ref_best = pga_get_best(ref, ref_pop);
    if (!ref_best) return fprintf(stderr, "reference get_best failed\n"), 1;

    for (int i = 0; i < NSOLVERS; i++) {
        solvers[i] = make_solver(1000 + i, &pops[i]);
        if (!solvers[i])
            return fprintf(stderr, "solver %d failed\n", i), 1;
    }

    /* Submit NSOLVERS-1 runs: bucket below max_batch, so everything
     * must still be pending. NULL tenant = the "anon" default; the
     * two-tenant attribution leg runs below. */
    for (int i = 0; i < NSOLVERS - 1; i++) {
        tickets[i] = pga_submit_n(solvers[i], GENS, NULL);
        if (!tickets[i])
            return fprintf(stderr, "pga_submit %d failed\n", i), 1;
        if (pga_poll(tickets[i]) != 0)
            return fprintf(stderr, "ticket %d not pending\n", i), 1;
    }

    /* The filling submission launches the bucket: every ticket done. */
    tickets[NSOLVERS - 1] = pga_submit_n(solvers[NSOLVERS - 1], GENS, NULL);
    if (!tickets[NSOLVERS - 1])
        return fprintf(stderr, "filling pga_submit failed\n"), 1;
    for (int i = 0; i < NSOLVERS; i++)
        if (pga_poll(tickets[i]) != 1)
            return fprintf(stderr, "ticket %d not done post-launch\n", i), 1;

    /* Ticket 0 through pga_await_ex: same install semantics, plus the
     * latency breakdown (every span reached => finite and ordered). */
    float lat[4] = {-1.0f, -1.0f, -1.0f, -1.0f};
    int gens0 = pga_await_ex(tickets[0], lat);
    if (gens0 != GENS)
        return fprintf(stderr, "pga_await_ex returned %d\n", gens0), 1;
    for (int i = 0; i < 4; i++)
        if (!(lat[i] == lat[i]) || lat[i] < 0.0f)
            return fprintf(stderr, "latency[%d] = %g invalid\n", i, lat[i]),
                   1;
    if (lat[3] + 1e-3f < lat[1]) /* e2e >= execute (readback-inclusive) */
        return fprintf(stderr, "e2e %g < execute %g\n", lat[3], lat[1]), 1;
    for (int i = 1; i < NSOLVERS; i++) {
        int gens = pga_await(tickets[i]);
        if (gens != GENS)
            return fprintf(stderr, "pga_await %d returned %d\n", i, gens), 1;
    }

    /* Solver 0 was seeded like the reference: the batched run must have
     * installed the bit-identical best genome. */
    gene *batched_best = pga_get_best(solvers[0], pops[0]);
    if (!batched_best)
        return fprintf(stderr, "batched get_best failed\n"), 1;
    for (unsigned j = 0; j < LEN; j++)
        if (batched_best[j] != ref_best[j])
            return fprintf(stderr,
                           "batched best diverges from pga_run at gene %u "
                           "(%.9g != %.9g)\n",
                           j, batched_best[j], ref_best[j]),
                   1;
    free(batched_best);
    free(ref_best);

    /* A run with an unreachable-from-start target must also terminate
     * early identically: target barely above the initial best. */
    pga_ticket_t *t = pga_submit(solvers[1], 200, (float)LEN, NULL);
    if (!t) return fprintf(stderr, "target submit failed\n"), 1;
    int gens = pga_await(t); /* await forces the flush */
    if (gens < 0 || gens > 200)
        return fprintf(stderr, "target await returned %d\n", gens), 1;

    /* Metrics snapshot: size query, then a real read — the JSON must
     * mention the per-ticket latency histograms the awaits fed. */
    long need = pga_metrics_snapshot(NULL, 0);
    if (need <= 0)
        return fprintf(stderr, "metrics size query returned %ld\n", need), 1;
    {
        /* The snapshot is live (its timestamp alone changes length
         * between calls) — allocate slack, as the header prescribes. */
        unsigned long cap = (unsigned long)need + 4096;
        char *json = (char *)malloc(cap);
        if (!json) return fprintf(stderr, "malloc failed\n"), 1;
        long got = pga_metrics_snapshot(json, cap);
        if (got <= 0 || (unsigned long)got >= cap)
            return fprintf(stderr, "metrics read %ld (cap %lu)\n", got, cap),
                   1;
        if (!strstr(json, "serving.ticket.e2e_ms"))
            return fprintf(stderr, "snapshot missing latency histogram\n"),
                   1;
        free(json);
    }

    /* Error surfaces. */
    if (pga_poll(NULL) != -1)
        return fprintf(stderr, "NULL ticket poll not rejected\n"), 1;
    if (pga_await(NULL) != -1)
        return fprintf(stderr, "NULL ticket await not rejected\n"), 1;
    if (pga_await(tickets[0]) >= 0) /* already awaited: released */
        return fprintf(stderr, "double await not rejected\n"), 1;
    if (pga_submit_n(NULL, 5, NULL) != NULL)
        return fprintf(stderr, "NULL solver submit not rejected\n"), 1;

    /* Two-tenant attribution leg (ISSUE 14): submit one run per tenant
     * and check the metrics snapshot carries a per-tenant slice for
     * each — the tenant id is host-side labeling only, so these runs
     * share the warm bucket program compiled above. An ill-formed
     * tenant id must be rejected at submit. */
    {
        pga_ticket_t *ta = pga_submit_n(solvers[1], GENS, "tenant-a");
        pga_ticket_t *tb = pga_submit_n(solvers[2], GENS, "tenant-b");
        if (!ta || !tb)
            return fprintf(stderr, "tenant submit failed\n"), 1;
        if (pga_await(ta) != GENS || pga_await(tb) != GENS)
            return fprintf(stderr, "tenant await failed\n"), 1;
        if (pga_submit_n(solvers[2], GENS, "bad tenant!") != NULL)
            return fprintf(stderr, "ill-formed tenant not rejected\n"), 1;
        long tneed = pga_metrics_snapshot(NULL, 0);
        unsigned long tcap = (unsigned long)tneed + 4096;
        char *tjson = (char *)malloc(tcap);
        if (!tjson) return fprintf(stderr, "malloc failed\n"), 1;
        long tgot = pga_metrics_snapshot(tjson, tcap);
        if (tgot <= 0 || (unsigned long)tgot >= tcap)
            return fprintf(stderr, "tenant metrics read %ld\n", tgot), 1;
        if (!strstr(tjson, "serving.tenant.e2e_ms") ||
            !strstr(tjson, "tenant-a") || !strstr(tjson, "tenant-b"))
            return fprintf(stderr, "snapshot missing tenant slices\n"), 1;
        free(tjson);
    }

    /* Cross-process serving fleet (ISSUE 8): start a 2-worker fleet on
     * a private spool, submit a plain and a supervised ticket, await
     * both, drain, and close. The worker processes are real OS
     * processes — this is the ABI round trip; bit-identity across
     * kills/drains is proven by tests/test_fleet.py and
     * tools/fleet_smoke.py. */
    {
        char spool[] = "/tmp/pga-fleet-capi-XXXXXX";
        if (!mkdtemp(spool))
            return fprintf(stderr, "mkdtemp failed\n"), 1;
        if (pga_fleet_start(spool, "onemax", 2, 2, 5.0f, 1, 1) != 0)
            return fprintf(stderr, "pga_fleet_start failed\n"), 1;
        /* Leadership snapshot (ISSUE 20), size query then a real
         * read: under coordinators=1 the HA machinery must stay cold
         * — the block reports enabled:false and the spool keeps the
         * pre-HA byte format. */
        {
            long lneed = pga_fleet_leader_snapshot(NULL, 0);
            if (lneed <= 0)
                return fprintf(stderr, "leader snapshot size %ld\n", lneed),
                       1;
            unsigned long lcap = (unsigned long)lneed + 4096;
            char *ljson = (char *)malloc(lcap);
            if (!ljson) return fprintf(stderr, "malloc failed\n"), 1;
            long lgot = pga_fleet_leader_snapshot(ljson, lcap);
            if (lgot <= 0 || (unsigned long)lgot >= lcap)
                return fprintf(stderr, "leader snapshot read %ld (cap %lu)\n",
                               lgot, lcap),
                       1;
            if (!strstr(ljson, "\"enabled\"") || !strstr(ljson, "false"))
                return fprintf(stderr,
                               "leader snapshot not disabled under a "
                               "single coordinator: %s\n",
                               ljson),
                       1;
            free(ljson);
        }
        /* Two tenants through the fleet (ISSUE 14): the ids ride the
         * batch files to the workers and back in the result metas, so
         * the merged snapshot below must carry both tenant slices. */
        pga_fleet_ticket_t *f1 =
            pga_fleet_submit(POP, LEN, GENS, 42, 0, -1, "fleet-ten-a");
        pga_fleet_ticket_t *f2 =
            pga_fleet_submit(POP, LEN, 2 * GENS, 43, GENS, -1,
                             "fleet-ten-b");
        if (!f1 || !f2)
            return fprintf(stderr, "pga_fleet_submit failed\n"), 1;
        /* Admission control (ISSUE 15): install a quota of 1 for a
         * third tenant — its first submit admits, the second sheds
         * DETERMINISTICALLY (NULL ticket), and the installed fleet
         * state stays intact: the admitted ticket still completes and
         * every other tenant is untouched. Bad policy values error
         * without clobbering the installed policy. */
        if (pga_fleet_tenant_policy("fleet-ten-q", 2.0f, 1, 0) != 0)
            return fprintf(stderr, "pga_fleet_tenant_policy failed\n"), 1;
        if (pga_fleet_tenant_policy("fleet-ten-q", -1.0f, 1, 0) == 0)
            return fprintf(stderr, "bad tenant weight accepted\n"), 1;
        pga_fleet_ticket_t *q1 =
            pga_fleet_submit(POP, LEN, GENS, 44, 0, 1, "fleet-ten-q");
        if (!q1)
            return fprintf(stderr, "quota tenant first submit failed\n"), 1;
        if (pga_fleet_submit(POP, LEN, GENS, 45, 0, 1, "fleet-ten-q"))
            return fprintf(stderr, "quota breach not shed\n"), 1;
        float bestq = -1.0f;
        if (pga_fleet_await(q1, &bestq, 300.0) != GENS)
            return fprintf(stderr, "quota tenant await failed\n"), 1;
        if (!(bestq >= 0.0f && bestq <= (float)LEN))
            return fprintf(stderr, "quota tenant best %g out of range\n",
                           (double)bestq),
                   1;
        /* Ticket 1 through the observability-extended await (ISSUE 9):
         * same release semantics, plus the six-span cross-process
         * breakdown — every span finite with tracing on (the default),
         * and the spans TILE, so their sum covers >=95% of e2e. */
        float best1 = -1.0f, best2 = -1.0f, flat[6];
        for (int i = 0; i < 6; i++) flat[i] = -1.0f;
        int fg1 = pga_fleet_await_ex(f1, &best1, flat, 300.0);
        int fg2 = pga_fleet_await(f2, &best2, 300.0);
        if (fg1 != GENS || fg2 != 2 * GENS)
            return fprintf(stderr, "fleet await gens %d/%d\n", fg1, fg2), 1;
        {
            float sum = 0.0f;
            for (int i = 0; i < 6; i++) {
                if (!(flat[i] == flat[i]) || flat[i] < 0.0f)
                    return fprintf(stderr, "fleet latency[%d] = %g invalid\n",
                                   i, (double)flat[i]),
                           1;
                if (i < 5) sum += flat[i];
            }
            if (sum < 0.95f * flat[5])
                return fprintf(stderr,
                               "fleet spans %g cover < 95%% of e2e %g\n",
                               (double)sum, (double)flat[5]),
                       1;
        }
        if (!(best1 >= 0.0f && best1 <= (float)LEN) ||
            !(best2 >= 0.0f && best2 <= (float)LEN))
            return fprintf(stderr, "fleet best %g/%g out of range\n",
                           (double)best1, (double)best2),
                   1;
        if (pga_fleet_await(f1, NULL, 1.0) >= 0) /* released */
            return fprintf(stderr, "double fleet await not rejected\n"), 1;
        /* Merged fleet snapshot: size query, then a real read — the
         * JSON must carry the coordinator's fleet-level series. */
        long fneed = pga_fleet_metrics_snapshot(NULL, 0);
        if (fneed <= 0)
            return fprintf(stderr, "fleet metrics size query %ld\n", fneed),
                   1;
        {
            unsigned long fcap = (unsigned long)fneed + 8192;
            char *fjson = (char *)malloc(fcap);
            if (!fjson) return fprintf(stderr, "malloc failed\n"), 1;
            long fgot = pga_fleet_metrics_snapshot(fjson, fcap);
            if (fgot <= 0 || (unsigned long)fgot >= fcap)
                return fprintf(stderr, "fleet metrics read %ld (cap %lu)\n",
                               fgot, fcap),
                       1;
            if (!strstr(fjson, "fleet.tickets.completed") ||
                !strstr(fjson, "coordinator"))
                return fprintf(stderr,
                               "fleet snapshot missing merged series\n"),
                       1;
            /* Per-tenant slice (ISSUE 14): both tenants' series must
             * be reachable through the merged snapshot. */
            if (!strstr(fjson, "fleet.tenant.e2e_ms") ||
                !strstr(fjson, "fleet-ten-a") ||
                !strstr(fjson, "fleet-ten-b"))
                return fprintf(stderr,
                               "fleet snapshot missing tenant slices\n"),
                       1;
            free(fjson);
        }
        if (pga_fleet_drain() < 0)
            return fprintf(stderr, "pga_fleet_drain failed\n"), 1;
        if (pga_fleet_close() != 0)
            return fprintf(stderr, "pga_fleet_close failed\n"), 1;
    }

    /* Self-tuning kernels (ISSUE 10): autotune a tiny signature into a
     * fresh database (tiny budget — the ABI round trip, not a perf
     * claim; determinism and never-regress are proven by
     * tools/autotune_smoke.py), install it, run a solver under it, and
     * check the error surfaces. */
    {
        char tdir[] = "/tmp/pga-tuning-capi-XXXXXX";
        if (!mkdtemp(tdir))
            return fprintf(stderr, "mkdtemp failed\n"), 1;
        char db_path[256];
        snprintf(db_path, sizeof db_path, "%s/tuning.json", tdir);
        int measured = pga_autotune(POP, LEN, "onemax", 2, db_path, 0);
        if (measured < 1)
            return fprintf(stderr, "pga_autotune measured %d\n", measured),
                   1;
        if (pga_set_tuning_db(db_path) != 0)
            return fprintf(stderr, "pga_set_tuning_db failed\n"), 1;
        population_t *tpop;
        pga_t *tuned = make_solver(77, &tpop);
        if (!tuned) return fprintf(stderr, "tuned solver failed\n"), 1;
        if (pga_run_n(tuned, GENS) != GENS)
            return fprintf(stderr, "tuned pga_run failed\n"), 1;
        pga_deinit(tuned);
        /* Error surfaces: a bogus path must fail without disturbing
         * the installed DB; clearing is always fine. */
        char bogus[256];
        snprintf(bogus, sizeof bogus, "%s/nope.json", tdir);
        if (pga_set_tuning_db(bogus) != -1)
            return fprintf(stderr, "bogus tuning db not rejected\n"), 1;
        if (pga_autotune(POP, LEN, "no_such_objective", 2, db_path, 0) != -1)
            return fprintf(stderr, "bogus objective not rejected\n"), 1;
        if (pga_set_tuning_db(NULL) != 0)
            return fprintf(stderr, "pga_set_tuning_db(NULL) failed\n"), 1;
    }

    /* Genetic programming (ISSUE 11): switch a solver to tree-GP
     * breeding, install a symbolic-regression objective over a tiny
     * dataset, run, and check the error surfaces leave installed
     * state intact (the round-15 pattern). Exact recovery and
     * bit-determinism are proven by tools/gp_smoke.py. */
    {
        enum { NS = 16, NV = 2, NODES = 8 };
        float X[NS * NV], Y[NS];
        for (int i = 0; i < NS; i++) {
            float a = -1.0f + 2.0f * (float)i / (NS - 1);
            float b = 1.0f - 2.0f * (float)i / (NS - 1);
            X[i * NV] = a;
            X[i * NV + 1] = b;
            Y[i] = a * a + b;
        }
        pga_t *gps = pga_init(123);
        if (!gps) return fprintf(stderr, "gp solver init failed\n"), 1;
        /* Error surface: SR objective before gp_config must fail. */
        if (pga_set_objective_sr(gps, X, Y, NS) != -1)
            return fprintf(stderr, "sr-before-gp_config not rejected\n"), 1;
        /* Error surface: a degenerate encoding must fail... */
        if (pga_gp_config(gps, 1, NV, -1.0f) != -1)
            return fprintf(stderr, "max_nodes=1 not rejected\n"), 1;
        if (pga_gp_create_population(gps, 64) != NULL)
            return fprintf(stderr,
                           "gp population without gp_config not rejected\n"),
                   1;
        /* ...and leave nothing half-installed: the real config works. */
        if (pga_gp_config(gps, NODES, NV, -1.0f) != 0)
            return fprintf(stderr, "pga_gp_config failed\n"), 1;
        population_t *gpop = pga_gp_create_population(gps, 64);
        if (!gpop)
            return fprintf(stderr, "pga_gp_create_population failed\n"), 1;
        if (pga_set_objective_sr(gps, X, Y, NS) != 0)
            return fprintf(stderr, "pga_set_objective_sr failed\n"), 1;
        /* Error surface: a bad sample count must fail WITHOUT
         * disturbing the installed objective... */
        if (pga_set_objective_sr(gps, X, Y, 0) != -1)
            return fprintf(stderr, "n_samples=0 not rejected\n"), 1;
        /* ...proven by running: fitness is -RMSE, so best in [-inf, 0]
         * and finite for a bred population of well-formed programs. */
        if (pga_run_n(gps, 5) != 5)
            return fprintf(stderr, "gp pga_run failed\n"), 1;
        gene *gbest = pga_get_best(gps, gpop);
        if (!gbest) return fprintf(stderr, "gp get_best failed\n"), 1;
        for (unsigned j = 0; j < 2 * NODES; j++)
            if (!(gbest[j] >= 0.0f && gbest[j] < 1.0f))
                return fprintf(stderr, "gp best gene %u = %g out of [0,1)\n",
                               j, gbest[j]),
                       1;
        free(gbest);
        pga_deinit(gps);
    }

    /* Streaming evolution service (ISSUE 12): the ask/tell/step round
     * trip, suspend/resume bit-identity through the ABI, the warm-pool
     * reuse path, and the sized-snapshot RETRY-ONCE contract. */
    {
        enum { SPOP = 256, SLEN = 16 };
        pga_session_t *sess =
            pga_session_open("onemax", SPOP, SLEN, 7, "stream-ten-a");
        if (!sess) return fprintf(stderr, "pga_session_open failed\n"), 1;

        /* ask before any fitness: k rows of the initial population. */
        float cand[4 * SLEN], fit[4];
        if (pga_session_ask(sess, cand, 4) != 4)
            return fprintf(stderr, "pga_session_ask failed\n"), 1;
        for (int i = 0; i < 4; i++) {
            float sum = 0.0f;
            for (int j = 0; j < SLEN; j++) sum += cand[i * SLEN + j];
            fit[i] = sum; /* external evaluation (onemax itself) */
        }
        if (pga_session_tell(sess, cand, fit, 4) != 0)
            return fprintf(stderr, "pga_session_tell failed\n"), 1;
        if (pga_session_step(sess, GENS, NAN) != GENS)
            return fprintf(stderr, "pga_session_step failed\n"), 1;
        float sbest = -1.0f, sbest_genome[SLEN];
        if (pga_session_best(sess, &sbest, sbest_genome) != 0)
            return fprintf(stderr, "pga_session_best failed\n"), 1;
        if (!(sbest >= 0.0f && sbest <= (float)SLEN))
            return fprintf(stderr, "session best %g out of range\n",
                           (double)sbest),
                   1;

        /* A step-only session is bit-identical to pga_run: drive a
         * second session and a same-seed solver side by side. */
        pga_session_t *only =
            pga_session_open("onemax", SPOP, SLEN, 9, "stream-ten-b");
        population_t *rpop2;
        pga_t *ref2 = make_solver(9, &rpop2);
        if (!only || !ref2)
            return fprintf(stderr, "step-only setup failed\n"), 1;
        /* make_solver builds POP x LEN — rebuild at the session shape. */
        pga_deinit(ref2);
        ref2 = pga_init(9);
        rpop2 = pga_create_population(ref2, SPOP, SLEN, RANDOM_POPULATION);
        if (!rpop2 || pga_set_objective_name(ref2, "onemax") != 0)
            return fprintf(stderr, "step-only solver failed\n"), 1;
        if (pga_session_step(only, GENS, NAN) != GENS ||
            pga_run_n(ref2, GENS) != GENS)
            return fprintf(stderr, "step-only advance failed\n"), 1;
        float only_best = -1.0f, only_genome[SLEN];
        if (pga_session_best(only, &only_best, only_genome) != 0)
            return fprintf(stderr, "step-only best failed\n"), 1;
        gene *ref2_best = pga_get_best(ref2, rpop2);
        if (!ref2_best)
            return fprintf(stderr, "step-only ref best failed\n"), 1;
        for (unsigned j = 0; j < SLEN; j++)
            if (only_genome[j] != ref2_best[j])
                return fprintf(stderr,
                               "session step diverges from pga_run at gene "
                               "%u (%.9g != %.9g)\n",
                               j, only_genome[j], ref2_best[j]),
                       1;
        free(ref2_best);
        pga_deinit(ref2);

        /* Suspend → resume: the resumed session's next step must land
         * bit-identically with the original's. */
        char sdir[] = "/tmp/pga-session-capi-XXXXXX";
        if (!mkdtemp(sdir)) return fprintf(stderr, "mkdtemp failed\n"), 1;
        char spath[256];
        snprintf(spath, sizeof spath, "%s/tenant.ckpt.npz", sdir);
        if (pga_session_suspend(only, spath) != 0)
            return fprintf(stderr, "pga_session_suspend failed\n"), 1;
        pga_session_t *back = pga_session_resume(spath, NULL);
        if (!back) return fprintf(stderr, "pga_session_resume failed\n"), 1;
        if (pga_session_step(only, GENS, NAN) != GENS ||
            pga_session_step(back, GENS, NAN) != GENS)
            return fprintf(stderr, "post-resume step failed\n"), 1;
        float g1[SLEN], g2[SLEN];
        if (pga_session_best(only, NULL, g1) != 0 ||
            pga_session_best(back, NULL, g2) != 0)
            return fprintf(stderr, "post-resume best failed\n"), 1;
        for (unsigned j = 0; j < SLEN; j++)
            if (g1[j] != g2[j])
                return fprintf(stderr,
                               "resume diverges at gene %u (%.9g != %.9g)\n",
                               j, g1[j], g2[j]),
                       1;

        /* Sized-snapshot retry-once contract: (a) the canonical
         * size-query -> fill loop succeeds with got == need even
         * though the snapshot is live; (b) a deliberately under-sized
         * fill truncates safely (NUL-terminated) and its ONE retry
         * with the returned length succeeds exactly. Opening another
         * session between query and fill is the growth race the
         * contract exists for — the parked rendering absorbs it. */
        long need = pga_session_snapshot(NULL, 0);
        if (need <= 0)
            return fprintf(stderr, "session snapshot size %ld\n", need), 1;
        pga_session_t *grow =
            pga_session_open("onemax", SPOP, SLEN, 11, NULL);
        if (!grow) return fprintf(stderr, "growth session failed\n"), 1;
        {
            char *json = (char *)malloc((unsigned long)need + 1);
            if (!json) return fprintf(stderr, "malloc failed\n"), 1;
            long got = pga_session_snapshot(json, (unsigned long)need + 1);
            if (got != need)
                return fprintf(stderr,
                               "retry-once violated: fill %ld != query %ld\n",
                               got, need),
                       1;
            if (json[0] != '{' || json[got] != '\0' ||
                !strstr(json, "\"pool\""))
                return fprintf(stderr, "session snapshot malformed\n"), 1;
            /* Tenant attribution rides the session records (ISSUE 14). */
            if (!strstr(json, "stream-ten-a") ||
                !strstr(json, "stream-ten-b"))
                return fprintf(stderr,
                               "session snapshot missing tenants\n"),
                       1;
            free(json);
        }
        {
            char tiny[8];
            long got = pga_session_snapshot(tiny, sizeof tiny);
            if (got < (long)sizeof tiny || tiny[sizeof tiny - 1] != '\0')
                return fprintf(stderr, "truncated fill unsafe (%ld)\n", got),
                       1;
            char *json = (char *)malloc((unsigned long)got + 1);
            if (!json) return fprintf(stderr, "malloc failed\n"), 1;
            long got2 = pga_session_snapshot(json, (unsigned long)got + 1);
            if (got2 != got)
                return fprintf(stderr,
                               "truncated-fill retry %ld != %ld\n", got2,
                               got),
                       1;
            free(json);
        }
        /* Same contract holds for pga_metrics_snapshot. */
        {
            long mneed = pga_metrics_snapshot(NULL, 0);
            if (mneed <= 0)
                return fprintf(stderr, "metrics size query %ld\n", mneed), 1;
            char *json = (char *)malloc((unsigned long)mneed + 1);
            if (!json) return fprintf(stderr, "malloc failed\n"), 1;
            long mgot = pga_metrics_snapshot(json, (unsigned long)mneed + 1);
            if (mgot != mneed)
                return fprintf(stderr,
                               "metrics retry-once violated: %ld != %ld\n",
                               mgot, mneed),
                       1;
            free(json);
        }

        /* Error surfaces + pool release. */
        if (pga_session_ask(NULL, cand, 4) != -1)
            return fprintf(stderr, "NULL session ask not rejected\n"), 1;
        if (pga_session_close(NULL) != -1)
            return fprintf(stderr, "NULL session close not rejected\n"), 1;
        if (pga_session_close(sess) != 0 || pga_session_close(only) != 0 ||
            pga_session_close(back) != 0 || pga_session_close(grow) != 0)
            return fprintf(stderr, "pga_session_close failed\n"), 1;
    }

    for (int i = 0; i < NSOLVERS; i++) pga_deinit(solvers[i]);
    pga_deinit(ref);
    printf("PASS\n");
    return 0;
}
