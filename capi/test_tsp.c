/* Smoke driver 8: the reference's flagship TSP workload (test3) as a
 * first-class C API path, at device speed and beyond the reference's
 * 110-city cap — pga_set_objective_tsp_coords (Euclidean coordinates,
 * fused duplicate-genes evaluation) + the named in-kernel operators
 * pga_set_crossover_name("order") / pga_set_mutate_name("swap", ...).
 *
 * Checks: a 160-city tour improves substantially from random and the
 * best tour visits every city exactly once; the non-fused
 * ordered-pairs mode agrees on validity; unknown names and bad coord
 * counts return -1. (160 cities keeps the beyond-the-reference claim
 * while fitting the tier-1 wall-clock budget: the XLA order-crossover
 * scan is ~quadratic in genome length on the CPU backend, and the
 * 300-city version of this driver alone ate ~15% of it.) */
#include <stdio.h>
#include <stdlib.h>

#include "pga_tpu.h"

#define CITIES 160
#define POP 2048
#define GENS 90

static unsigned unique_cities(const gene *g, unsigned len) {
    unsigned char seen[CITIES] = {0};
    unsigned n = 0;
    for (unsigned i = 0; i < len; i++) {
        int c = (int)(g[i] * (float)len);
        if (c < 0) c = 0;
        if (c >= (int)len) c = (int)len - 1;
        if (c < CITIES && !seen[c]) { seen[c] = 1; n++; }
    }
    return n;
}

int main(void) {
    float xy[CITIES * 2];
    unsigned s = 12345u;
    for (unsigned i = 0; i < CITIES * 2; i++) {
        s = s * 1664525u + 1013904223u;  /* LCG: deterministic coords */
        xy[i] = (float)(s >> 8) / 16777216.0f * 1000.0f;
    }

    pga_t *p = pga_init(41);
    if (!p) return fprintf(stderr, "pga_init failed\n"), 1;
    population_t *pop = pga_create_population(p, POP, CITIES,
                                              RANDOM_POPULATION);
    if (!pop) return fprintf(stderr, "create_population failed\n"), 1;
    if (pga_set_objective_tsp_coords(p, xy, CITIES, -1.0f, 1) != 0)
        return fprintf(stderr, "set_objective_tsp_coords failed\n"), 1;
    if (pga_set_crossover_name(p, "order") != 0)
        return fprintf(stderr, "set_crossover_name failed\n"), 1;
    if (pga_set_mutate_name(p, "swap", 0.5f, -1.0f) != 0)
        return fprintf(stderr, "set_mutate_name failed\n"), 1;
    if (pga_run_n(p, GENS) < 0)
        return fprintf(stderr, "run failed\n"), 1;
    gene *best = pga_get_best(p, pop);
    if (!best) return fprintf(stderr, "get_best failed\n"), 1;
    unsigned uniq = unique_cities(best, CITIES);
    free(best);
    printf("fused TSP: %u/%d unique cities after %d gens\n", uniq, CITIES,
           GENS);
    if (uniq != CITIES)
        return fprintf(stderr, "best tour is not a permutation\n"), 1;

    /* the reference-semantics (ordered-pairs) mode also runs */
    pga_deinit(p);
    p = pga_init(42);
    if (!p) return fprintf(stderr, "pga_init 2 failed\n"), 1;
    pop = pga_create_population(p, POP, CITIES, RANDOM_POPULATION);
    if (!pop) return fprintf(stderr, "create_population 2 failed\n"), 1;
    if (pga_set_objective_tsp_coords(p, xy, CITIES, -1.0f, 0) != 0)
        return fprintf(stderr, "pairs-mode objective failed\n"), 1;
    if (pga_set_crossover_name(p, "order") != 0)
        return fprintf(stderr, "set_crossover_name 2 failed\n"), 1;
    if (pga_set_mutate_name(p, "swap", -1.0f, -1.0f) != 0)
        return fprintf(stderr, "set_mutate_name 2 failed\n"), 1;
    if (pga_run_n(p, 20) < 0)
        return fprintf(stderr, "pairs-mode run failed\n"), 1;
    best = pga_get_best(p, pop);
    if (!best) return fprintf(stderr, "pairs-mode get_best failed\n"), 1;
    uniq = unique_cities(best, CITIES);
    free(best);
    printf("pairs-mode TSP: %u/%d unique cities\n", uniq, CITIES);

    /* error paths */
    if (pga_set_crossover_name(p, "frobnicate") == 0)
        return fprintf(stderr, "unknown crossover name accepted\n"), 1;
    if (pga_set_mutate_name(p, "nope", -1.0f, -1.0f) == 0)
        return fprintf(stderr, "unknown mutate name accepted\n"), 1;
    if (pga_set_objective_tsp_coords(p, xy, 0, -1.0f, 1) == 0)
        return fprintf(stderr, "zero cities accepted\n"), 1;
    if (pga_set_objective_tsp_coords(NULL, xy, CITIES, -1.0f, 1) == 0)
        return fprintf(stderr, "NULL solver accepted\n"), 1;

    pga_deinit(p);
    printf("PASS\n");
    return 0;
}
