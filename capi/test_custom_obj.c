/* Smoke driver 2: a CUSTOM host-C objective through the C ABI — the
 * bounded-knapsack shape of the reference's second driver
 * (test2/test.cu:22-36), rewritten for the host-callback path. Small
 * population: every evaluation round-trips genomes to the CPU. */
#include <stdio.h>
#include <stdlib.h>

#include "pga_tpu.h"

#define NITEMS 6
#define MAX_COUNT 2
#define CAPACITY 10.0f

static const float values[NITEMS] = {75, 150, 250, 35, 10, 100};
static const float weights[NITEMS] = {7, 8, 6, 4, 3, 9};

/* Decode gene -> item count as int(g * MAX_COUNT); infeasible solutions
 * score the negative overweight (same scheme as test2/test.cu:28-36). */
static float knapsack(gene *g, unsigned len) {
    float value = 0.0f, weight = 0.0f;
    for (unsigned i = 0; i < len && i < NITEMS; i++) {
        int count = (int)(g[i] * MAX_COUNT);
        value += values[i] * count;
        weight += weights[i] * count;
    }
    return weight <= CAPACITY ? value : CAPACITY - weight;
}

int main(void) {
    pga_t *p = pga_init(7);
    if (!p) return fprintf(stderr, "pga_init failed\n"), 1;

    population_t *pop = pga_create_population(p, 128, NITEMS, RANDOM_POPULATION);
    if (!pop) return fprintf(stderr, "create_population failed\n"), 1;

    if (pga_set_objective_function(p, knapsack) != 0)
        return fprintf(stderr, "set_objective_function failed\n"), 1;

    if (pga_run_n(p, 15) < 0) return fprintf(stderr, "pga_run failed\n"), 1;

    gene *best = pga_get_best(p, pop);
    if (!best) return fprintf(stderr, "get_best failed\n"), 1;
    float score = knapsack(best, NITEMS);
    printf("knapsack best: score %.1f  counts [", score);
    for (int i = 0; i < NITEMS; i++)
        printf("%d%s", (int)(best[i] * MAX_COUNT), i + 1 < NITEMS ? " " : "]\n");
    free(best);
    pga_deinit(p);

    /* true optimum is 285: items 2+3 (values 250+35, weights 6+4 = 10);
     * require >= 250 so a near-optimal run still passes */
    if (score < 250.0f) {
        fprintf(stderr, "FAIL: best %.1f below 250\n", score);
        return 1;
    }
    printf("PASS\n");
    return 0;
}
