/* test_compat.c — full-surface smoke driver for the exact-reference ABI
 * (capi/pga.h / libpga.so).
 *
 * Exercises every entry point of the compat header at least once, in the
 * reference's calling style (void returns, gene** top-k), with all three
 * callback kinds installed as plain host function pointers:
 *
 *   init → 4 populations → custom objective + mutate + crossover →
 *   step-by-step evaluate/crossover/mutate/swap → fill_random_values →
 *   run → run_islands → migrate → migrate_between →
 *   get_best / get_best_top / get_best_all / get_best_top_all → deinit
 *
 * Problem: maximize the sum of 8 genes in [0,1) — optimum approaches 8.
 */
#include <pga.h>

#include <stdio.h>
#include <stdlib.h>

#define GENOME_LEN 8
#define POP_SIZE 32
#define N_POPS 4

static int checks_failed = 0;

#define CHECK(cond, msg)                                       \
    do {                                                       \
        if (!(cond)) {                                         \
            printf("FAIL: %s\n", msg);                         \
            checks_failed++;                                   \
        }                                                      \
    } while (0)

static float sum_obj(gene *g, unsigned len) {
    float s = 0.0f;
    unsigned i;
    for (i = 0; i < len; ++i) s += g[i];
    return s;
}

/* Write an out-of-band marker (genes are otherwise in [0,1)): the later
 * "custom mutate applied" check can only pass if this ran. */
static void my_mutate(gene *g, float *rand, unsigned len) {
    (void)len;
    g[0] = 2.0f + rand[2];
}

/* One-point crossover at a random cut. */
static void my_crossover(gene *p1, gene *p2, gene *child, float *rand,
                         unsigned len) {
    unsigned cut = (unsigned)(rand[0] * len);
    unsigned i;
    for (i = 0; i < len; ++i) child[i] = i < cut ? p1[i] : p2[i];
}

int main() {
    unsigned i;

    pga_t *p = pga_init();
    CHECK(p != NULL, "pga_init");

    population_t *pops[N_POPS];
    for (i = 0; i < N_POPS; ++i) {
        pops[i] = pga_create_population(p, POP_SIZE, GENOME_LEN,
                                        RANDOM_POPULATION);
        CHECK(pops[i] != NULL, "pga_create_population");
    }

    pga_set_objective_function(p, sum_obj);
    pga_set_mutate_function(p, my_mutate);
    pga_set_crossover_function(p, my_crossover);

    /* --- step-by-step generation, reference calling order ------------- */
    pga_fill_random_values(p, pops[0]);
    pga_evaluate(p, pops[0]);
    pga_evaluate_all(p);
    pga_crossover(p, pops[0], TOURNAMENT);
    pga_mutate(p, pops[0]);
    pga_swap_generations(p, pops[0]);
    pga_crossover_all(p, TOURNAMENT);
    pga_mutate_all(p);
    pga_evaluate_all(p);

    /* every individual of pops[0]'s current generation went through
     * my_mutate exactly once (staged → mutated → swapped), so gene 0
     * must carry the out-of-band marker. */
    gene *after = pga_get_best(p, pops[0]);
    CHECK(after != NULL, "pga_get_best after step ops");
    CHECK(after[0] >= 2.0f, "custom mutate applied");
    free(after);

    /* oversized top-k must fail cleanly, not hand back short buffers */
    CHECK(pga_get_best_top(p, pops[0], POP_SIZE + 1) == NULL,
          "oversized top-k returns NULL");

    /* --- restore default operators via NULL, then fused runs ---------- */
    pga_set_mutate_function(p, NULL);
    pga_set_crossover_function(p, NULL);

    pga_run(p, 10);

    gene *b0 = pga_get_best(p, pops[0]);
    CHECK(b0 != NULL, "pga_get_best");
    float best_run = sum_obj(b0, GENOME_LEN);
    free(b0);
    CHECK(best_run > 4.0f, "run improves over random (~4)");

    pga_run_islands(p, 12, 4, 0.25f);
    pga_migrate(p, 0.25f);
    pga_migrate_between(p, pops[0], pops[1], 0.25f);
    pga_evaluate_all(p);

    /* migrate_between copies pops[0]'s best over pops[1]'s worst: the two
     * populations must now share their best individual's score. */
    gene *src_best = pga_get_best(p, pops[0]);
    gene *dst_best = pga_get_best(p, pops[1]);
    CHECK(src_best && dst_best, "get_best after migrate_between");
    CHECK(sum_obj(dst_best, GENOME_LEN) >= sum_obj(src_best, GENOME_LEN) - 1e-5f,
          "migrated elite visible in destination");
    free(src_best);
    free(dst_best);

    /* --- top-k getters: reference gene** ownership contract ----------- */
    gene **top = pga_get_best_top(p, pops[0], 3);
    CHECK(top != NULL, "pga_get_best_top");
    if (top) {
        float prev = 1e30f;
        for (i = 0; i < 3; ++i) {
            float s = sum_obj(top[i], GENOME_LEN);
            CHECK(s <= prev + 1e-5f, "top-k sorted best-first");
            prev = s;
            free(top[i]);
        }
        free(top);
    }

    gene *gall = pga_get_best_all(p);
    CHECK(gall != NULL, "pga_get_best_all");
    float global_best = gall ? sum_obj(gall, GENOME_LEN) : 0.0f;
    free(gall);

    gene **topall = pga_get_best_top_all(p, 5);
    CHECK(topall != NULL, "pga_get_best_top_all");
    if (topall) {
        /* global top-1 must equal get_best_all's score */
        CHECK(sum_obj(topall[0], GENOME_LEN) >= global_best - 1e-5f,
              "top_all[0] is the global best");
        for (i = 0; i < 5; ++i) free(topall[i]);
        free(topall);
    }

    pga_deinit(p);

    if (checks_failed) {
        printf("compat ABI: %d checks FAILED\n", checks_failed);
        return 1;
    }
    printf("compat best sum %.3f / %d\n", global_best, GENOME_LEN);
    printf("PASS\n");
    return 0;
}
