/* pga.h — source-compatible C API, exactly shaped after the reference
 * libpga header (reference repo include/pga.h:26-150: same type names,
 * same 20 entry points, same signatures — void returns, seedless init,
 * gene** top-k results). Implemented by libpga.so (pga_compat.cc) over
 * the TPU-native engine.
 *
 * A driver written against the reference header compiles against this
 * one unchanged, minus the CUDA-isms its toolchain required:
 *
 *  - callbacks are plain HOST function pointers — drop the __device__
 *    qualifiers and pass the function directly (the reference makes you
 *    fetch a device pointer with cudaMemcpyFromSymbol, pga.h:66);
 *  - problem data lives in ordinary host arrays — drop __constant__.
 *
 * Semantics notes (all matching the reference's behavior, not just its
 * header):
 *  - pga_init() seeds from OS entropy, the analog of the reference's
 *    time(NULL) cuRAND seed (pga.cu:154). For reproducible runs use the
 *    improved ABI (pga_tpu.h) which takes an explicit seed.
 *  - pga_run(p, n) runs exactly n generations on the FIRST population,
 *    as the reference implements it (pga.cu:376-391). The header-promised
 *    early termination is available via the improved ABI's pga_run target.
 *  - The functions the reference declares but leaves as stubs — the
 *    _top/_all best getters (pga.cu:238-248), pga_migrate(_between)
 *    (pga.cu:368-374) and pga_run_islands (pga.cu:393-395) — are fully
 *    implemented here per their documented contracts.
 *  - pga_get_best_top(_all) return a malloc'd array of `length` pointers,
 *    each a malloc'd genome row (best first); free each row, then the
 *    array. NULL when `length` exceeds the (total) population size.
 *
 * Do NOT link libpga.so and libpga_tpu_c.so into the same image: they
 * define the same symbol names with different signatures on purpose.
 * Thread safety: none (matches the reference). One in-process user.
 */
#ifndef PGA_H
#define PGA_H

#ifdef __cplusplus
extern "C" {
#endif

typedef struct pga pga_t;
typedef struct population population_t;

typedef float gene;

enum population_type {
    RANDOM_POPULATION,
    MAX_POPULATION_TYPE
};

enum crossover_selection_type {
    TOURNAMENT,
    MAX_SELECTION_TYPE
};

#define MAX_POPULATIONS 10

typedef float (*obj_f)(gene *, unsigned);
typedef void (*mutate_f)(gene *, float *, unsigned);
typedef void (*crossover_f)(gene *, gene *, gene *, float *, unsigned);

/* Solver lifecycle. */
pga_t *pga_init();
void pga_deinit(pga_t *);

/* Add a population of `size` genomes, `genome_len` >= 4 genes each;
 * at most MAX_POPULATIONS per solver. NULL on error. */
population_t *pga_create_population(pga_t *, unsigned long size,
                                    unsigned genome_len,
                                    enum population_type type);

/* Callback registration. Higher objective = better. NULL mutate /
 * crossover restores the defaults (0.01 point mutation, uniform
 * crossover — reference pga.cu:127-143). */
void pga_set_objective_function(pga_t *, obj_f);
void pga_set_mutate_function(pga_t *, mutate_f);
void pga_set_crossover_function(pga_t *, crossover_f);

/* Best-individual extraction. Single-genome getters return one malloc'd
 * row; the _top variants return length malloc'd rows behind a malloc'd
 * pointer array, best first. */
gene *pga_get_best(pga_t *, population_t *);
gene **pga_get_best_top(pga_t *, population_t *, unsigned length);
gene *pga_get_best_all(pga_t *);
gene **pga_get_best_top_all(pga_t *, unsigned length);

/* Step-by-step generation operators. */
void pga_evaluate(pga_t *, population_t *);
void pga_evaluate_all(pga_t *);

void pga_crossover(pga_t *, population_t *, enum crossover_selection_type);
void pga_crossover_all(pga_t *, enum crossover_selection_type);

void pga_migrate(pga_t *, float pct);
void pga_migrate_between(pga_t *, population_t *, population_t *, float pct);

void pga_mutate(pga_t *, population_t *);
void pga_mutate_all(pga_t *);

/* Promote the staged next generation to current. Deliberate semantic
 * divergence: the reference's pointer swap (pga.cu:362-366) leaves the
 * PREVIOUS generation's stale scores readable until the next
 * pga_evaluate; here the swapped-in population's scores read as -INF
 * until evaluated. A driver calling pga_get_best between swap and
 * evaluate sees an arbitrary not-yet-scored genome either way — this
 * implementation just makes the staleness visible instead of
 * plausible-looking. Call pga_evaluate after swapping, as the
 * reference drivers do. */
void pga_swap_generations(pga_t *, population_t *);

void pga_fill_random_values(pga_t *, population_t *);

/* Fused run loops: n generations of evaluate/crossover/mutate on the
 * first population (pga_run), or across ALL populations as islands with
 * top-`pct` migration every m generations (pga_run_islands). */
void pga_run(pga_t *, unsigned n);
void pga_run_islands(pga_t *, unsigned n, unsigned m, float pct);

#ifdef __cplusplus
}
#endif

#endif
