/* Smoke driver 5: the selection strategies the reference's placeholder
 * crossover_selection_type enum declared room for. Runs OneMax under
 * TRUNCATION (explicit tau) and LINEAR_RANK (default pressure), checks
 * both converge, and checks the error paths (bad param / bad enum). */
#include <stdio.h>
#include <stdlib.h>

#include "pga_tpu.h"

#define POP 4096
#define LEN 64
#define GENS 40

static float best_sum(pga_t *p, population_t *pop) {
    gene *best = pga_get_best(p, pop);
    if (!best) return -1.0f;
    float sum = 0.0f;
    for (unsigned i = 0; i < LEN; i++) sum += best[i];
    free(best);
    return sum;
}

static int run_with(enum crossover_selection_type type, float param,
                    const char *name) {
    pga_t *p = pga_init(7);
    if (!p) return fprintf(stderr, "pga_init failed\n"), 1;
    population_t *pop = pga_create_population(p, POP, LEN, RANDOM_POPULATION);
    if (!pop) return fprintf(stderr, "create_population failed\n"), 1;
    if (pga_set_objective_name(p, "onemax") != 0)
        return fprintf(stderr, "set_objective_name failed\n"), 1;
    if (pga_set_selection(p, type, param) != 0)
        return fprintf(stderr, "pga_set_selection(%s) failed\n", name), 1;
    if (pga_run_n(p, GENS) < 0)
        return fprintf(stderr, "pga_run failed\n"), 1;
    float sum = best_sum(p, pop);
    printf("%s best sum after %d gens: %.2f (random ~%d, max %d)\n", name,
           GENS, sum, LEN / 2, LEN);
    pga_deinit(p);
    /* random init ~LEN/2; any working selection clears LEN*0.85 easily */
    return sum > LEN * 0.85f ? 0 : 1;
}

int main(void) {
    if (run_with(TRUNCATION, 0.25f, "truncation(0.25)")) return 1;
    if (run_with(LINEAR_RANK, PGA_SELECTION_DEFAULT_PARAM, "linear_rank"))
        return 1;

    /* error paths: out-of-range param and unknown enum value must fail
     * without corrupting the solver */
    pga_t *p = pga_init(1);
    if (pga_set_selection(p, TRUNCATION, 2.0f) == 0)
        return fprintf(stderr, "bad tau accepted\n"), 1;
    if (pga_set_selection(p, (enum crossover_selection_type)9, -1.0f) == 0)
        return fprintf(stderr, "bad enum accepted\n"), 1;
    if (pga_set_selection(p, TOURNAMENT, -1.0f) != 0)
        return fprintf(stderr, "tournament reset failed\n"), 1;
    /* pga_crossover* must reject unknown enum values with -1 (same
     * error surface as pga_set_selection), not silently no-op */
    population_t *pop2 = pga_create_population(p, 256, 8, RANDOM_POPULATION);
    if (!pop2) return fprintf(stderr, "create_population failed\n"), 1;
    if (pga_set_objective_name(p, "onemax") != 0)
        return fprintf(stderr, "set_objective_name failed\n"), 1;
    if (pga_evaluate(p, pop2) != 0)
        return fprintf(stderr, "evaluate failed\n"), 1;
    if (pga_crossover(p, pop2, (enum crossover_selection_type)9) == 0)
        return fprintf(stderr, "crossover accepted bad enum\n"), 1;
    if (pga_crossover_all(p, (enum crossover_selection_type)9) == 0)
        return fprintf(stderr, "crossover_all accepted bad enum\n"), 1;
    if (pga_crossover(p, pop2, TOURNAMENT) != 0)
        return fprintf(stderr, "crossover(TOURNAMENT) failed\n"), 1;
    pga_deinit(p);

    printf("PASS\n");
    return 0;
}
