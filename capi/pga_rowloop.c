/* pga_rowloop.c — batched marshaling for host C callbacks.
 *
 * The compatibility path for the reference's device-function-pointer
 * operators (include/pga.h:46-48 in the reference tree) runs the user's
 * C callback once per individual. Doing that loop in Python costs one
 * ctypes crossing per ROW; these helpers take the whole generation's
 * batch and loop in C, so the Python side pays exactly ONE crossing per
 * generation regardless of population size.
 *
 * Pure C, no Python: loaded by libpga_tpu/capi_bridge.py via ctypes
 * (which releases the GIL for the duration of the call).
 *
 * Row-major contiguous float32 buffers; `len` is the genome length.
 */

#include <stddef.h>

typedef float (*pga_obj_f)(float *, unsigned);
typedef void (*pga_mut_f)(float *, float *, unsigned);
typedef void (*pga_cross_f)(float *, float *, float *, float *, unsigned);

void pga_rowloop_obj(void *fn, float *batch, float *out, unsigned rows,
                     unsigned len) {
    pga_obj_f f = (pga_obj_f)fn;
    for (unsigned i = 0; i < rows; ++i)
        out[i] = f(batch + (size_t)i * len, len);
}

/* Mutation is in-place on `batch` (the caller passes a copy). */
void pga_rowloop_mut(void *fn, float *batch, float *rand, unsigned rows,
                     unsigned len) {
    pga_mut_f f = (pga_mut_f)fn;
    for (unsigned i = 0; i < rows; ++i)
        f(batch + (size_t)i * len, rand + (size_t)i * len, len);
}

void pga_rowloop_cross(void *fn, float *p1, float *p2, float *child,
                       float *rand, unsigned rows, unsigned len) {
    pga_cross_f f = (pga_cross_f)fn;
    for (unsigned i = 0; i < rows; ++i)
        f(p1 + (size_t)i * len, p2 + (size_t)i * len,
          child + (size_t)i * len, rand + (size_t)i * len, len);
}
