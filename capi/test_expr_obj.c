/* Smoke driver 6: device-speed custom objectives via the expression
 * surface (pga_set_objective_expr) — the TPU-native replacement for the
 * reference's __device__ objective pointers. Unlike test_custom_obj's
 * host-pointer path, the solver stays on the accelerator.
 *
 * Checks: a vector-constant weighted objective converges to picking the
 * high-weight genes; a sphere-style expression converges toward 0; all
 * error paths return -1 without corrupting the solver. */
#include <stdio.h>
#include <stdlib.h>

#include "pga_tpu.h"

#define POP 8192
#define LEN 64
#define GENS 60

static float best_under(pga_t *p, population_t *pop, const float *w) {
    gene *best = pga_get_best(p, pop);
    if (!best) return -1e30f;
    float sum = 0.0f;
    for (unsigned i = 0; i < LEN; i++)
        sum += (w ? w[i] : 1.0f) * best[i];
    free(best);
    return sum;
}

int main(void) {
    pga_t *p = pga_init(21);
    if (!p) return fprintf(stderr, "pga_init failed\n"), 1;
    population_t *pop = pga_create_population(p, POP, LEN, RANDOM_POPULATION);
    if (!pop) return fprintf(stderr, "create_population failed\n"), 1;

    /* weighted OneMax: maximize dot(w, g) with ramp weights — the GA
     * must drive every gene toward 1 (weights are all positive) */
    float w[LEN];
    for (unsigned i = 0; i < LEN; i++) w[i] = 1.0f + (float)i / LEN;
    if (pga_set_objective_expr_const(p, "w", w, LEN) != 0)
        return fprintf(stderr, "expr_const failed\n"), 1;
    if (pga_set_objective_expr(p, "dot(w, g)") != 0)
        return fprintf(stderr, "set_objective_expr failed\n"), 1;
    if (pga_run_n(p, GENS) < 0)
        return fprintf(stderr, "run failed\n"), 1;
    float got = best_under(p, pop, w);
    float maxv = 0.0f;
    for (unsigned i = 0; i < LEN; i++) maxv += w[i];
    printf("weighted onemax: %.2f of max %.2f\n", got, maxv);
    if (got < 0.9f * maxv)
        return fprintf(stderr, "weighted onemax did not converge\n"), 1;

    /* sphere: -sum((g-0.5)^2), optimum at g = 0.5 everywhere. Fresh
     * solver: the weighted-OneMax run just converged pop toward
     * all-ones, which would start this phase at err ~ 16 instead of a
     * random population's ~LEN/12. */
    pga_deinit(p);
    p = pga_init(22);
    if (!p) return fprintf(stderr, "pga_init 2 failed\n"), 1;
    pop = pga_create_population(p, POP, LEN, RANDOM_POPULATION);
    if (!pop) return fprintf(stderr, "create_population 2 failed\n"), 1;
    if (pga_set_objective_expr(p, "-sum((g - 0.5)**2)") != 0)
        return fprintf(stderr, "sphere expr failed\n"), 1;
    if (pga_run_n(p, GENS) < 0)
        return fprintf(stderr, "sphere run failed\n"), 1;
    gene *best = pga_get_best(p, pop);
    if (!best) return fprintf(stderr, "get_best failed\n"), 1;
    float err = 0.0f;
    for (unsigned i = 0; i < LEN; i++)
        err += (best[i] - 0.5f) * (best[i] - 0.5f);
    free(best);
    printf("sphere residual: %.4f\n", err);
    /* random init expects LEN/12 ~ 5.3; the default 0.01 point mutation
     * refines genes slowly, so after 60 generations ~0.8 is typical —
     * the check is that the expression DROVE the search, not that it
     * polished the optimum */
    if (err > 2.0f)
        return fprintf(stderr, "sphere did not converge\n"), 1;

    /* NK-style epistatic objective via the v2 primitives: bindings,
     * roll, and a per-locus gather table registered with _const2. The
     * table rewards 1-bits in each 4-bit neighborhood code (entry =
     * popcount(code)/4), so the optimum is all-ones with mean
     * contribution 1.0 — the GA must clear ~0.85 from a random ~0.5. */
    pga_deinit(p);
    p = pga_init(23);
    if (!p) return fprintf(stderr, "pga_init 3 failed\n"), 1;
    pop = pga_create_population(p, POP, LEN, RANDOM_POPULATION);
    if (!pop) return fprintf(stderr, "create_population 3 failed\n"), 1;
    float table[16 * LEN];
    for (unsigned c = 0; c < 16; c++) {
        unsigned bits = (c & 1) + ((c >> 1) & 1) + ((c >> 2) & 1) + ((c >> 3) & 1);
        for (unsigned i = 0; i < LEN; i++)
            table[c * LEN + i] = (float)bits / 4.0f;
    }
    if (pga_set_objective_expr_const2(p, "T", table, 16, LEN) != 0)
        return fprintf(stderr, "expr_const2 failed\n"), 1;
    if (pga_set_objective_expr(p,
            "b = g >= 0.5;"
            "codes = b + 2*roll(b, 1) + 4*roll(b, 2) + 8*roll(b, 3);"
            "mean(gather(T, codes))") != 0)
        return fprintf(stderr, "NK expression failed\n"), 1;
    if (pga_run_n(p, GENS) < 0)
        return fprintf(stderr, "NK run failed\n"), 1;
    gene *nkbest = pga_get_best(p, pop);
    if (!nkbest) return fprintf(stderr, "NK get_best failed\n"), 1;
    float ones = 0.0f;
    for (unsigned i = 0; i < LEN; i++) ones += nkbest[i] >= 0.5f ? 1.0f : 0.0f;
    free(nkbest);
    printf("NK-expr best ones: %.0f of %d\n", ones, LEN);
    if (ones < 0.85f * LEN)
        return fprintf(stderr, "NK expression did not converge\n"), 1;

    /* error paths: each must return -1 and leave the solver usable */
    if (pga_set_objective_expr_const2(p, "bad", table, 0, LEN) == 0)
        return fprintf(stderr, "const2 zero rows accepted\n"), 1;
    if (pga_set_objective_expr(p, "sum(T * g)") == 0)
        return fprintf(stderr, "elementwise 2-D const accepted\n"), 1;
    if (pga_set_objective_expr(p, "sum(roll(g, L))") == 0)
        return fprintf(stderr, "non-literal roll shift accepted\n"), 1;
    if (pga_set_objective_expr(p, "sum(") == 0)
        return fprintf(stderr, "bad syntax accepted\n"), 1;
    if (pga_set_objective_expr(p, "sum(nosuch * g)") == 0)
        return fprintf(stderr, "unknown name accepted\n"), 1;
    if (pga_set_objective_expr(p, "g * 2") == 0)
        return fprintf(stderr, "non-reduced expression accepted\n"), 1;
    if (pga_set_objective_expr(p, "frobnicate(g)") == 0)
        return fprintf(stderr, "unknown function accepted\n"), 1;
    if (pga_set_objective_expr(NULL, "sum(g)") == 0)
        return fprintf(stderr, "NULL solver accepted\n"), 1;
    /* solver still healthy after the failed registrations */
    if (pga_set_objective_expr(p, "sum(g)") != 0)
        return fprintf(stderr, "recovery set failed\n"), 1;
    if (pga_run_n(p, 5) < 0)
        return fprintf(stderr, "recovery run failed\n"), 1;

    pga_deinit(p);
    printf("PASS\n");
    return 0;
}
