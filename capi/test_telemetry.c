/* Smoke driver: in-run telemetry through the C ABI. Enables the
 * on-device per-generation history, runs a short OneMax GA, and checks
 * the returned history — shape, NaN-free rows, a non-decreasing
 * RUNNING best (row best is the population best, which generational
 * replacement may lower; the cumulative max may not), and a sane stall
 * column. Also checks the disabled/edge surfaces: no history before any
 * run, NULL after disabling, and errors on bad handles. */
#include <math.h>
#include <stdio.h>
#include <stdlib.h>

#include "pga_tpu.h"

#define POP 4096
#define LEN 64
#define GENS 25

int main(void) {
    pga_t *p = pga_init(7);
    if (!p) return fprintf(stderr, "pga_init failed\n"), 1;
    population_t *pop = pga_create_population(p, POP, LEN, RANDOM_POPULATION);
    if (!pop) return fprintf(stderr, "pga_create_population failed\n"), 1;
    if (pga_set_objective_name(p, "onemax") != 0)
        return fprintf(stderr, "pga_set_objective_name failed\n"), 1;

    /* No telemetry configured yet: no history. */
    unsigned rows = 99, cols = 0;
    float *hist = pga_get_history(p, pop, &rows, &cols);
    if (hist != NULL || rows != 0)
        return fprintf(stderr, "history before telemetry not empty\n"), 1;

    if (pga_set_telemetry(p, 64) != 0)
        return fprintf(stderr, "pga_set_telemetry failed\n"), 1;
    if (pga_run_n(p, GENS) != GENS)
        return fprintf(stderr, "pga_run failed\n"), 1;

    hist = pga_get_history(p, pop, &rows, &cols);
    if (!hist) return fprintf(stderr, "pga_get_history failed\n"), 1;
    if (rows != GENS || cols != PGA_HISTORY_COLS)
        return fprintf(stderr, "bad history shape %ux%u\n", rows, cols), 1;

    float run_best = -1e30f;
    for (unsigned r = 0; r < rows; r++) {
        for (unsigned c = 0; c < cols; c++)
            if (isnan(hist[r * cols + c]))
                return fprintf(stderr, "NaN at row %u col %u\n", r, c), 1;
        float best = hist[r * cols + 0];
        float mean = hist[r * cols + 1];
        float stall = hist[r * cols + 4];
        if (best < run_best - 1e-4f && stall == 0.0f)
            return fprintf(stderr, "best dropped without stall\n"), 1;
        if (best > run_best) run_best = best;
        if (mean > best + 1e-4f)
            return fprintf(stderr, "mean above best at row %u\n", r), 1;
    }
    printf("telemetry history: %u gens, final best %.2f (first %.2f)\n",
           rows, hist[(rows - 1) * cols], hist[0]);
    if (run_best <= hist[0] + 1.0f)
        return fprintf(stderr, "FAIL: no convergence recorded\n"), 1;
    free(hist);

    /* Disable: later history reads revert to empty-after-next-run, and
     * the existing buffer is NOT retroactively dropped. */
    if (pga_set_telemetry(p, 0) != 0)
        return fprintf(stderr, "pga_set_telemetry(0) failed\n"), 1;

    if (pga_get_history(NULL, pop, &rows, &cols) != NULL)
        return fprintf(stderr, "NULL solver not rejected\n"), 1;
    if (pga_set_telemetry(NULL, 8) != -1)
        return fprintf(stderr, "NULL solver not rejected (set)\n"), 1;

    pga_deinit(p);
    printf("PASS\n");
    return 0;
}
