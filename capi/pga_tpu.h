/* pga_tpu.h — C API for the TPU-native genetic-algorithm framework.
 *
 * Drop-in shaped after the reference libpga C API (reference repo
 * include/pga.h:26-150): same types, same 20 entry points, same call
 * order. Differences, all forced by the hardware model and all additive:
 *
 *  - Callbacks are plain HOST function pointers. The reference requires
 *    CUDA __device__ pointers fetched via cudaMemcpyFromSymbol
 *    (pga.h:66); a TPU has no device function pointers. Host callbacks
 *    round-trip genomes to the CPU each operator — correct for any
 *    driver, fast only for small populations. For on-device speed, use
 *    pga_set_objective_name() with a builtin (e.g. "onemax",
 *    "rastrigin") instead.
 *  - pga_init() takes a seed (pass PGA_SEED_RANDOM for the reference's
 *    time(NULL) behavior, pga.cu:154).
 *  - Functions the reference declares but stubs out — pga_get_best_top,
 *    pga_get_best_all, pga_get_best_top_all (pga.cu:238-248), pga_migrate,
 *    pga_migrate_between (pga.cu:368-374), pga_run_islands (pga.cu:393-395),
 *    and pga_run's early-termination variant (pga.h:137-143) — are fully
 *    implemented here.
 *
 * Thread safety: none (matches the reference). One in-process user.
 */
#ifndef PGA_TPU_H
#define PGA_TPU_H

#ifdef __cplusplus
extern "C" {
#endif

typedef struct pga pga_t;               /* opaque solver (pga.h:26) */
typedef struct population population_t; /* opaque population (pga.h:27) */

typedef float gene;                     /* pga.h:29 */

#define PGA_SEED_RANDOM (-1)

enum population_type {
    RANDOM_POPULATION = 0               /* pga.h:31-34 */
};

/* Parent-selection strategies. The reference declares this enum as a
 * self-described placeholder with one member and ignores the argument
 * (pga.h:37-42, pga.cu:329); here every member is implemented — in the
 * fused TPU kernel each strategy is just a different inverse CDF over
 * rank space, at identical cost.
 *
 * Porting note: the reference enum's MAX_SELECTION_TYPE sentinel has
 * value 1, which here is TRUNCATION. A driver ported from pga.h that
 * forwards MAX_SELECTION_TYPE into pga_crossover* would switch the
 * solver to truncation selection — pass TOURNAMENT (0, inert) instead.
 * Values outside the enum return -1 from pga_crossover*, matching
 * pga_set_selection's error surface. */
enum crossover_selection_type {
    TOURNAMENT = 0,                     /* k-way tournament (default) */
    TRUNCATION = 1,                     /* uniform over the top-tau ranks */
    LINEAR_RANK = 2                     /* linear ranking, pressure s */
};

#define PGA_SELECTION_DEFAULT_PARAM (-1.0f)

/* Callback signatures — the reference's exact shapes (pga.h:46-48),
 * minus the __device__ qualifier. rand is a per-individual slice of
 * uniform [0,1) values, genome_len long. Higher objective = better. */
typedef float (*obj_f)(gene *genome, unsigned genome_len);
typedef void (*mutate_f)(gene *genome, float *rand, unsigned genome_len);
typedef void (*crossover_f)(gene *p1, gene *p2, gene *child, float *rand,
                            unsigned genome_len);

/* Lifecycle (pga.h:53,58). */
pga_t *pga_init(long seed);
void pga_deinit(pga_t *p);

/* Create a population of `size` genomes, `genome_len >= 4` genes each
 * (pga.h:63; the length guard mirrors pga.cu:184). Returns NULL on
 * error. At most 10 populations per solver (pga.h:44). */
population_t *pga_create_population(pga_t *p, unsigned size,
                                    unsigned genome_len,
                                    enum population_type type);

/* Callback registration (pga.h:72,78,85). NULL mutate/crossover restores
 * the defaults (uniform crossover, 0.01 point mutation — pga.cu:127-143). */
int pga_set_objective_function(pga_t *p, obj_f f);
int pga_set_mutate_function(pga_t *p, mutate_f f);
int pga_set_crossover_function(pga_t *p, crossover_f f);

/* On-device builtin objective by name ("onemax", "onemax_bits", "sphere",
 * "rastrigin", "ackley", "knapsack"). The fast path: the whole GA stays
 * on the TPU. Returns 0 on success, -1 on unknown name. */
int pga_set_objective_name(pga_t *p, const char *name);

/* DEVICE-SPEED custom objective from an expression — the TPU answer to
 * the reference's __device__ objective pointers (pga.h:59,66): where a
 * CUDA user writes a device function, a pga_tpu user writes a small
 * expression over the gene vector, which compiles into the evaluation
 * path of the fused kernel (children scored in on-chip memory; no host
 * round trip, unlike pga_set_objective_function's host-pointer path).
 *
 * Language: `g` (the genome, length-L vector of floats in [0,1)), `i`
 * (gene index vector), `L`, literals, `pi`, `e`, registered constants
 * by name; `+ - * / % **`, comparisons `< <= > >= ==` (0/1-valued),
 * `where(c,a,b)`; elementwise `sin cos tan tanh exp log sqrt abs floor
 * round`, `min(a,b)`/`max(a,b)`; reductions `sum(x) mean(x) min(x)
 * max(x)` and `dot(a,b)`. The expression must reduce to ONE scalar per
 * genome; higher is better. Examples:
 *     pga_set_objective_expr(p, "sum(g)");               // OneMax
 *     pga_set_objective_expr(p, "-sum((g*10.24-5.12)**2)"); // sphere
 *     pga_set_objective_expr_const(p, "w", weights, L);
 *     pga_set_objective_expr_const(p, "v", values, L);
 *     pga_set_objective_expr(p, "where(dot(w, floor(g*2)) <= 100,"
 *                               " dot(v, floor(g*2)),"
 *                               " 100 - dot(w, floor(g*2)))");
 *
 * v2 (indexed/adjacency primitives):
 *   - statements: `name = expr;` bindings before the final expression,
 *     so decode/lookup/reduce stages are written once;
 *   - `roll(x, k)`: circular shift along the gene axis by an integer
 *     literal k — roll(x,k)[i] = x[(i+k) mod L];
 *   - `gather(t, idx)`: bounded table lookup; `t` must be a registered
 *     constant (1-D of n entries: shared table t[idx[i]]; 2-D n x L via
 *     pga_set_objective_expr_const2: per-locus table t[idx[i]][i] — the
 *     NK-landscape form). idx is floored and clipped into the table;
 *     n is capped at 512 entries.
 *   NK landscape (n=16, k=3, table T of 16 rows x 16 loci):
 *     pga_set_objective_expr_const2(p, "T", table, 16, 16);
 *     pga_set_objective_expr(p,
 *         "b = g >= 0.5;"
 *         "codes = b + 2*roll(b,1) + 4*roll(b,2) + 8*roll(b,3);"
 *         "mean(gather(T, codes))");
 *   Euclidean tour cost (C city coordinates in X/Y):
 *     pga_set_objective_expr_const(p, "X", xs, C);  // 1-D table: its
 *     pga_set_objective_expr_const(p, "Y", ys, C);  // length is the
 *     pga_set_objective_expr(p,                     // INDEX domain,
 *         "c = floor(g * L);"                       // not genome_len
 *         "x = gather(X, c); y = gather(Y, c);"
 *         "dx = roll(x, 1) - x; dy = roll(y, 1) - y;"
 *         "-sum(where(i < L - 1, sqrt(dx*dx + dy*dy + 1e-12), 0))");
 *
 * Constants (scalar: n == 1; per-gene vector: n == genome_len; gather
 * tables: any n <= 512) must be registered BEFORE the
 * pga_set_objective_expr call that uses them. _const2 registers a 2-D
 * rows x cols matrix (row-major), usable only as a gather table.
 * Returns 0, or -1 for any syntax/name/arity/shape error (diagnostic
 * with a character position on stderr). */
int pga_set_objective_expr(pga_t *p, const char *expr);
int pga_set_objective_expr_const(pga_t *p, const char *name,
                                 const float *data, unsigned n);
int pga_set_objective_expr_const2(pga_t *p, const char *name,
                                  const float *data, unsigned rows,
                                  unsigned cols);

/* DEVICE-SPEED custom CROSSOVER and MUTATION from expressions — the
 * remaining two reference callbacks (pga.h:47-48) at device speed: the
 * expression compiles into the fused breed kernel and evaluates on the
 * on-chip parents, unlike pga_set_mutate_function /
 * pga_set_crossover_function whose host pointers pin the solver to the
 * CPU. Variables (all per-gene, rows x L):
 *   crossover: p1, p2 (the selected parents);
 *   mutation:  g (the child genome), rate, sigma (runtime parameters —
 *              pass rate/sigma below; negative = defaults 0.01 / 0.0);
 *   both:      r, r2 (two per-gene uniform [0,1) streams), q, q2 (two
 *              per-CHILD uniforms — cut points, gates), i, L, literals,
 *              pi, e, and registered scalar/vector constants.
 * Breeding expressions are strictly per-gene: reductions (sum/mean/
 * dot/1-arg min/max) and roll/gather are rejected. Results are clipped
 * into the gene domain [0, 1). Examples:
 *   pga_set_crossover_expr(p, "where(r < 0.5, p1, p2)");   // uniform
 *   pga_set_crossover_expr(p, "where(i < floor(q*L), p1, p2)"); // 1-pt
 *   pga_set_crossover_expr(p, "r*p1 + (1-r)*p2");          // blend
 *   pga_set_mutate_expr(p, "where(r < rate, r2, g)", 0.02f, -1); // reset
 *   pga_set_mutate_expr(p, "where(r < rate, g + sigma*(2*r2-1), g)",
 *                       0.1f, 0.05f);                      // creep
 * Returns 0, or -1 for any syntax/name/arity/shape error (diagnostic on
 * stderr). Restore the defaults with pga_set_mutate_function(p, NULL) /
 * pga_set_crossover_function(p, NULL). */
int pga_set_crossover_expr(pga_t *p, const char *expr);
int pga_set_mutate_expr(pga_t *p, const char *expr, float rate,
                        float sigma);

/* BUILTIN operators by name — the kinds the fused kernel implements
 * natively, for operator classes expressions cannot express:
 *   crossover: "uniform", "one_point", "arithmetic", "order" — order
 *     is the uniqueness-preserving operator of the reference's TSP
 *     driver (test3/test.cu:48-64), an in-kernel sequential
 *     visited-bitmask walk (inherently not per-gene);
 *   mutation: "point", "gaussian", "swap" with runtime rate/sigma
 *     (negative = operator default; swap pairs with order for
 *     permutation GAs).
 * Returns 0, or -1 on an unknown name. */
int pga_set_crossover_name(pga_t *p, const char *name);
int pga_set_mutate_name(pga_t *p, const char *name, float rate,
                        float sigma);

/* Euclidean TSP objective over city coordinates — the reference test3
 * workload as a first-class objective, beyond its 110-city
 * __constant__-memory cap (test3/test.cu:22-24). `xy` is n_cities
 * (x, y) float32 pairs; genes decode as city = floor(g * genome_len).
 * `duplicate_penalty` < 0 takes the default 10000. Nonzero
 * `fused_duplicate_genes` counts duplicate GENES (L - distinct; same
 * zero set as the reference's ordered-pairs count) and — combined with
 * pga_set_crossover_name(p, "order") — evaluates INSIDE the breed
 * kernel (the long-genome path: 1,000-city tours at ~300
 * generations/sec, ~6x the XLA gather evaluation); zero keeps the
 * reference's ordered-pairs penalty semantics on the XLA path. */
int pga_set_objective_tsp_coords(pga_t *p, const float *xy,
                                 unsigned n_cities, float duplicate_penalty,
                                 int fused_duplicate_genes);

/* Result extraction (pga.h:90-93). Return malloc'd gene arrays (caller
 * frees), genome_len genes per row; NULL on error — including a _top
 * `length` larger than the (total) population, since the caller's buffer
 * arithmetic assumes exactly length rows come back. The reference
 * returns NULL unconditionally for the _top/_all variants
 * (pga.cu:238-248). */
gene *pga_get_best(pga_t *p, population_t *pop);
gene *pga_get_best_top(pga_t *p, population_t *pop, unsigned length);
gene *pga_get_best_all(pga_t *p);
gene *pga_get_best_top_all(pga_t *p, unsigned length);

/* Select the parent-selection strategy for all subsequent breeding
 * (crossover, run, run_islands). param: tau in (0,1] for TRUNCATION,
 * pressure s in (1,2] for LINEAR_RANK, or PGA_SELECTION_DEFAULT_PARAM
 * for the strategy default (tau 0.5 / s 2.0); ignored for TOURNAMENT.
 * Returns 0, or -1 for an unknown strategy / out-of-range param. */
int pga_set_selection(pga_t *p, enum crossover_selection_type type,
                      float param);

/* Step-by-step operators (pga.h:98-134). The crossover calls honor a
 * NON-tournament `type` by switching the solver's strategy at its
 * default parameter (the reference ignores this argument entirely);
 * passing TOURNAMENT is inert so reference-style drivers that pass it
 * on every call cannot clobber a pga_set_selection choice. */
int pga_evaluate(pga_t *p, population_t *pop);
int pga_evaluate_all(pga_t *p);
int pga_crossover(pga_t *p, population_t *pop,
                  enum crossover_selection_type type);
int pga_crossover_all(pga_t *p, enum crossover_selection_type type);
int pga_migrate(pga_t *p, float pct);
int pga_migrate_between(pga_t *p, population_t *from, population_t *to,
                        float pct);
int pga_mutate(pga_t *p, population_t *pop);
int pga_mutate_all(pga_t *p);
/* Promote the staged next generation to current. The new generation's
 * scores read as -INF until pga_evaluate runs (the reference's pointer
 * swap instead exposes the previous generation's stale scores — see
 * the semantics note in pga.h). */
int pga_swap_generations(pga_t *p, population_t *pop);
int pga_fill_random_values(pga_t *p, population_t *pop);

/* Fused run loops (pga.h:143,150). pga_run returns the number of
 * generations executed (early termination when the best objective reaches
 * `target` — pass pga_run_n for the reference's fixed-count behavior).
 * pga_run_islands evolves ALL populations with top-`pct` migration every
 * `m` generations. Negative return = error. */
int pga_run(pga_t *p, unsigned n, float target);
int pga_run_n(pga_t *p, unsigned n);
int pga_run_islands(pga_t *p, unsigned n, unsigned m, float pct);

/* ---- Fault-tolerant execution (no reference analog: its correctness
 * net is CUDA_CALL exit-on-error, pga.cu:24-31) --------------------------
 *
 * pga_supervised_run wraps pga_run in the supervisor
 * (robustness/supervisor): a failing chunk is retried up to
 * `max_retries` times with exponential backoff after rolling back to
 * the pre-chunk snapshot (PRNG key + populations), so a retried run is
 * bit-identical to one that never failed; with `checkpoint_path`
 * non-empty the run auto-checkpoints every `checkpoint_every`
 * generations (0 = only a final save) through the atomic checkpoint
 * writer, and `resume` != 0 restores the checkpoint + progress sidecar
 * first — the crash-recovery entry point. Returns generations
 * completed toward `n` (including resumed progress), or -1.
 *
 * pga_set_fault_plan installs (or clears) the process-global
 * fault-injection plan for chaos testing — see robustness/faults for
 * sites and kinds. `json_spec` is a JSON object/array of plans, e.g.
 *   {"site": "objective.eval", "kind": "raise", "at_call_n": 2}
 * or "" / "off" to clear. Faults are OFF unless a plan is installed;
 * the disabled path costs one attribute read per site. Returns 0 or
 * -1 (bad spec). */
int pga_supervised_run(pga_t *p, unsigned n, unsigned checkpoint_every,
                       unsigned max_retries, const char *checkpoint_path,
                       int resume);
int pga_set_fault_plan(const char *json_spec);

/* In-run telemetry (no reference analog — its observability is one
 * printf of the best score, pga.cu:230). pga_set_telemetry enables a
 * per-generation history recorded ON DEVICE inside the fused run loop
 * (no host round trip per generation): up to `max_gens` rows of
 * PGA_HISTORY_COLS float32 statistics — best, mean, std fitness, a
 * genome-diversity proxy, and a stall counter (generations since the
 * best improved). Runs longer than `max_gens` keep the LAST row
 * current; `max_gens` 0 disables. Returns 0, or -1 on error.
 *
 * pga_get_history returns the rows recorded by the population's most
 * recent pga_run / pga_run_islands (islands record one shared global
 * history) as a malloc'd row-major rows x cols float array (caller
 * frees); the rows and cols out-params (either may be NULL) receive the
 * shape. NULL when nothing is recorded (telemetry off / no run yet) or
 * on error. */
#define PGA_HISTORY_COLS 5
int pga_set_telemetry(pga_t *p, unsigned max_gens);
float *pga_get_history(pga_t *p, population_t *pop, unsigned *rows,
                       unsigned *cols);

/* Population sharding (no reference analog — the reference caps every
 * run at one GPU's memory). pga_set_pop_shards splits the POPULATION
 * AXIS of subsequent pga_run calls across `shards` mesh devices: each
 * shard breeds its local rows with the normal operator stack, and
 * exactly one cross-shard collective pair per generation (a comb-slab
 * ppermute plus an all-gather of shards x max(1, elitism) fitness
 * scalars) keeps the run panmictic-equivalent — see the library's
 * "Giant populations" documentation. shards=1 restores the unsharded
 * path (byte-identical program). The population size must be divisible
 * by shards^2 and shards must not exceed the visible devices; an
 * inadmissible value fails at the next pga_run. Returns 0, -1 on
 * error. */
int pga_set_pop_shards(pga_t *p, unsigned shards);

/* ---- Async batched serving (no reference analog) ----------------------
 *
 * pga_submit admits an asynchronous run of the solver's first
 * population — the population pga_run operates on — and returns
 * immediately with an opaque ticket. Submitted runs accumulate in a
 * process-global queue, bucketed by exact shape signature (population
 * size, genome length, gene dtype, objective, operator kinds, solver
 * config); a bucket launches as ONE batched device program when it
 * fills (`max_batch` requests) or when its oldest request has waited
 * `max_wait_ms`. Runs in one bucket share a single cached compilation,
 * so N same-shaped solvers submitting concurrently pay one compile,
 * not N — and each run's result is bit-identical to what pga_run would
 * have produced on that solver at that moment. Solvers whose shapes or
 * configs differ can never share a program (they land in different
 * buckets).
 *
 * pga_poll returns 1 once the ticket's batch has launched and its
 * result is assigned (device buffers may still be in flight), 0 while
 * pending, -1 on an invalid ticket.
 *
 * pga_await blocks until the run finishes, installs the final
 * population into the solver exactly as pga_run does (scores current,
 * staged generation cleared, telemetry history updated when enabled),
 * RELEASES the ticket, and returns the generations executed (negative
 * on error). Awaiting is what completes the submit→result round trip;
 * a ticket must be awaited exactly once. Between submit and await the
 * solver's populations must not be mutated (run, crossover, swap, ...)
 * — the submitted run captured the population at submit time and
 * await overwrites whatever is installed.
 *
 * pga_serving_config adjusts the process-global queue (applies to
 * subsequent submissions): max_batch requests per bucket launch,
 * max_wait_ms accumulation window (0 = launch only when a bucket
 * fills or an await forces the flush). Returns 0, -1 on error.
 *
 * TENANT ATTRIBUTION (ISSUE 14): every submission entry point takes a
 * `tenant` id — NULL (or "") submits as the default "anon" tenant,
 * preserving pre-tenancy behavior bit for bit. An explicit id must be
 * 1-64 chars of [A-Za-z0-9_.-] not starting with '_' (the reserved
 * library prefix); anything else fails the call. The id is host-side
 * attribution ONLY — it never reaches a compiled program, so two
 * tenants with equal configurations share buckets, programs, and warm
 * engines exactly as before — but it rides every ticket's latency
 * breakdown, trace span, event record, and the tenant-labeled metric
 * series (serving.tenant.* / fleet.tenant.* / streaming.tenant.*)
 * reachable through pga_metrics_snapshot and
 * pga_fleet_metrics_snapshot, so per-tenant p99s, queue depths, and
 * SLO burn rates can be sliced out of one snapshot. */
typedef struct pga_ticket pga_ticket_t;
pga_ticket_t *pga_submit(pga_t *p, unsigned n, float target,
                         const char *tenant);
pga_ticket_t *pga_submit_n(pga_t *p, unsigned n, const char *tenant);
int pga_poll(pga_ticket_t *t);
int pga_await(pga_ticket_t *t);
int pga_serving_config(unsigned max_batch, float max_wait_ms);

/* ---- Serving observability (ISSUE 6) ----------------------------------
 *
 * pga_await_ex behaves exactly like pga_await and additionally reports
 * the awaited ticket's latency breakdown into latency_ms[4]:
 * [0] queue wait (submit -> mega-run launch), [1] execute (launch ->
 * run complete), [2] readback (complete -> host materialization),
 * [3] end-to-end (submit -> readback) — all in milliseconds, NaN for
 * spans the ticket's lifecycle never reached (e.g. a dead-lettered
 * run). latency_ms may be NULL (then it is pga_await). Returns the
 * generations executed, negative on error.
 *
 * pga_metrics_snapshot writes the process-global metrics registry —
 * per-ticket latency histograms with p50/p95/p99, queue/cache gauges,
 * serving counters — as a UTF-8 JSON document into buf (NUL-terminated,
 * truncated at cap). Returns the full JSON length in bytes (excluding
 * the NUL) so a caller receiving ret >= cap can retry with a larger
 * buffer; negative on error. buf may be NULL with cap 0 to query the
 * size.
 *
 * RETRY-ONCE CONTRACT (all pga_*_snapshot entry points): the snapshot
 * is LIVE — it can grow between a size query and the fill call (new
 * metric series, new sessions, even timestamp width). The library
 * therefore PARKS any rendering that did not fit the caller's cap
 * (the cap-0 size query included): the immediately following call
 * with cap > ret receives exactly the parked bytes, never a fresh,
 * larger rendering. So the loop
 *
 *     long need = pga_metrics_snapshot(NULL, 0);
 *     char *buf = malloc(need + 1);
 *     long got = pga_metrics_snapshot(buf, need + 1);
 *
 * is guaranteed to succeed with got == need — one retry after a
 * truncated fill always suffices (a truncated fill re-parks, so the
 * invariant holds for its retry too). A fill that truncates is always
 * safe: the buffer is NUL-terminated at cap - 1, never overrun. */
int pga_await_ex(pga_ticket_t *t, float latency_ms[4]);
long pga_metrics_snapshot(char *buf, unsigned long cap);

/* ---- Cross-process serving fleet (ISSUE 8) ----------------------------
 *
 * The process-global FLEET lifts the serving queue across processes: a
 * coordinator in this process owns ticket intake and `n_workers`
 * spawned worker processes claim shape-bucket batches under
 * time-bounded heartbeat leases. A worker killed mid-batch (SIGKILL,
 * preemption) has its lease expire and its batch re-run bit-identically
 * on a survivor — seeds and runtime parameters travel with the ticket,
 * never with the worker. All cross-process state lives in `spool_dir`
 * as atomic filesystem transitions.
 *
 * pga_fleet_start creates (or replaces, closing the old one) the fleet
 * on `spool_dir` serving the named builtin objective, with `max_batch`/
 * `max_wait_ms` as the batch-formation admission window. `ring` != 0
 * enables the shared-memory ticket ring (ISSUE 18): a coordinator-owned
 * mmap'd notification ring under the spool that carries claim/
 * heartbeat/publish wakeups, collapsing the coordination floor from
 * polling cadence to microseconds. The spool stays the sole source of
 * truth — a corrupt, stale, or absent ring degrades the fleet back to
 * pure-spool polling with identical results. 0 = pure-spool (the
 * pre-ring behavior, bit-for-bit). `coordinators` is the candidate
 * count sharing the spool (ISSUE 20): 1 (the pre-HA behavior,
 * byte-for-byte spool compatible) runs this process as the sole
 * coordinator; > 1 joins the spool's leader election — intake moves
 * to the durable spool journal, every leader-authored artifact is
 * tagged with the election epoch (lower-epoch writes from a deposed
 * leader are fenced), and a standby coordinator process (spawn via
 * `python -m libpga_tpu.serving.coordinator`) takes over a dead
 * leader's work losslessly. Returns 0/-1.
 *
 * pga_fleet_leader_snapshot writes the spool's leadership block
 * (leader pid + liveness, election epoch, lease age, standby count,
 * last-failover timestamp; `enabled` false under coordinators=1) as a
 * UTF-8 JSON document into buf (NUL-terminated, truncated at cap).
 * Same size-query + retry-once contract as pga_metrics_snapshot:
 * returns the full length excluding the NUL, negative on error or
 * when no fleet is running.
 *
 * pga_fleet_submit admits one run (a fresh size x genome_len population
 * from `seed`, `n` generations); `checkpoint_every` > 0 makes the
 * ticket SUPERVISED — executed under the supervisor at that
 * auto-checkpoint cadence, so drains and worker deaths resume it from
 * the last durable chunk boundary. `priority` picks the scheduling
 * lane (0-9, higher claims first and may preempt a lower-priority
 * supervised batch at a chunk boundary; < 0 = the tenant policy's
 * default lane). `tenant` attributes the ticket
 * (NULL = "anon"; see the tenant-attribution block above) — the id
 * rides the batch file to the worker and back in the result meta, so
 * the merged fleet snapshot carries per-tenant latency histograms,
 * queue gauges, and burn-rate series. Returns a ticket or NULL — NULL
 * also when the tenant is at its pga_fleet_tenant_policy quota
 * (deterministic shed; the installed fleet state is unchanged and
 * later submits succeed once outstanding work completes).
 *
 * pga_fleet_tenant_policy installs (or replaces) one tenant's
 * scheduling policy on the live fleet (ISSUE 15): `weight` is the
 * tenant's deficit-round-robin service share (> 0), `max_pending` its
 * submission quota (<= 0 = unlimited; a breach makes pga_fleet_submit
 * return NULL deterministically), `priority` its default lane (0-9).
 * Returns 0, or -1 on invalid values / no running fleet.
 *
 * pga_fleet_await blocks (up to timeout_s; <= 0 = forever) for one
 * ticket, releases it, writes the best objective value into *best
 * (may be NULL), and returns the generations executed; -1 on error or
 * a dead-lettered ticket (a batch that cost too many distinct workers
 * their lease is quarantined, not retried forever).
 *
 * pga_fleet_drain SIGTERMs every worker: each checkpoints in-flight
 * supervised runs at the next chunk boundary, returns its lease, and
 * exits. Returns workers drained; pga_fleet_start on the same spool
 * resumes the work. pga_fleet_close drains and shuts the fleet down.
 *
 * Fleet observability (ISSUE 9):
 *
 * pga_fleet_await_ex behaves exactly like pga_fleet_await and
 * additionally reports the ticket's CROSS-PROCESS latency breakdown
 * into latency_ms[6] — six spans that tile the ticket's life, so they
 * sum to the end-to-end time: [0] intake (submit -> batch file
 * durable, coordinator), [1] spool wait (batch durable -> winning
 * worker's claim), [2] execute (claim -> run complete, worker),
 * [3] publish (complete -> result durable, worker), [4] readback
 * (result durable -> coordinator loaded it), [5] end-to-end. All in
 * milliseconds; NaN where tracing was off or the lifecycle never
 * reached the span. latency_ms may be NULL (then it is
 * pga_fleet_await). Returns generations executed, negative on error.
 *
 * pga_fleet_metrics_snapshot writes the MERGED fleet metrics snapshot
 * — every worker process's latest spool flush plus the coordinator's
 * live registry, each series labeled with its origin process and
 * histograms additionally merged into fleet-wide aggregates — as a
 * UTF-8 JSON document into buf (NUL-terminated, truncated at cap).
 * Same size-query contract as pga_metrics_snapshot: returns the full
 * length (excluding the NUL; the snapshot is live, allocate slack),
 * negative on error or when no fleet is running. */
typedef struct pga_fleet_ticket pga_fleet_ticket_t;
int pga_fleet_start(const char *spool_dir, const char *objective,
                    unsigned n_workers, unsigned max_batch,
                    float max_wait_ms, int ring, unsigned coordinators);
long pga_fleet_leader_snapshot(char *buf, unsigned long cap);
pga_fleet_ticket_t *pga_fleet_submit(unsigned size, unsigned genome_len,
                                     unsigned n, long seed,
                                     unsigned checkpoint_every,
                                     int priority, const char *tenant);
int pga_fleet_tenant_policy(const char *tenant, float weight,
                            long max_pending, int priority);
int pga_fleet_await(pga_fleet_ticket_t *t, float *best, double timeout_s);
int pga_fleet_await_ex(pga_fleet_ticket_t *t, float *best,
                       float latency_ms[6], double timeout_s);
long pga_fleet_metrics_snapshot(char *buf, unsigned long cap);
int pga_fleet_drain(void);
int pga_fleet_close(void);

/* ---- Self-tuning kernels (ISSUE 10) -----------------------------------
 *
 * pga_set_tuning_db installs (path) or clears (NULL / "") the
 * process-global kernel TUNING DATABASE — the artifact
 * tools/autotune.py produces: best-known fused-kernel configurations
 * per (population, genome length, dtype, backend, device kind,
 * objective, operator kinds) signature. While installed, every kernel
 * selection (pga_run, islands, sharded runs) and every serving AOT
 * warm-up resolves its knobs with precedence explicit-user-knob >
 * DB entry > built-in default, and compiled-program caches key on the
 * RESOLVED knobs. Loads eagerly: a missing/torn/schema-mismatched
 * file fails HERE with -1 (and leaves the previous installation
 * unchanged), never inside a serving warm-up. Returns 0 on success.
 *
 * pga_autotune runs the evolutionary autotuner for one signature of
 * the named builtin objective: the library's own GA searches the
 * kernel config space (deme size, output layout, sub-block pipeline),
 * measuring up to `budget` distinct configurations interleaved
 * against the default config (repeat-until-confidence medians; a
 * config that fails to compile scores worst instead of crashing), and
 * merges the winner — which NEVER regresses the default beyond the
 * measurement drift floor — into the database at db_path (created if
 * absent, atomic replace). Deterministic for a fixed seed where plans
 * are discrete (always, on a CPU backend). Returns the number of
 * configurations measured, negative on error. The database is NOT
 * auto-installed; call pga_set_tuning_db(db_path) to apply it. */
int pga_set_tuning_db(const char *path);
int pga_autotune(unsigned size, unsigned genome_len,
                 const char *objective, unsigned budget,
                 const char *db_path, long seed);

/* ---- Genetic programming (ISSUE 11) -----------------------------------
 *
 * Tree GP on the ordinary gene-vector populations: programs are
 * bounded POSTFIX token sequences, two genes per token (opcode +
 * operand), genome_len = 2 * max_nodes. Evaluation is a fused stack
 * machine (VMEM-scratch Pallas kernel on TPU, XLA interpreter
 * elsewhere); breeding is size-fair subtree crossover plus chained
 * subtree/point mutation — both provably preserve postfix
 * well-formedness, so every population stays decodable.
 *
 * pga_gp_config switches a solver to GP breeding: installs the
 * encoding (max_nodes tokens over n_vars input variables with the
 * default constant/function tables), the subtree crossover, and the
 * standard mutation (mutation_rate drives the subtree half; pass a
 * negative rate for the default 0.4). Validation precedes any state
 * change — on error (-1) the solver's operators and any previous GP
 * config are untouched. Call BEFORE creating GP populations.
 *
 * pga_gp_create_population creates a population of size
 * strictly-well-formed random programs under the installed encoding
 * (ramped-length grow init) — use this instead of
 * pga_create_population for GP solvers (plain RANDOM_POPULATION noise
 * still evaluates — the interpreter is total — but starts from
 * degenerate programs). Returns NULL without pga_gp_config.
 *
 * pga_set_objective_sr installs a symbolic-regression objective over
 * an (n_samples, n_vars) float32 dataset X (row-major) and target
 * vector y: fitness is -RMSE of each genome's decoded program over
 * the batch (higher is better; 0 = exact fit, the natural pga_run
 * target). Requires pga_gp_config first (the encoding fixes n_vars);
 * all validation precedes installation, so -1 leaves the previously
 * installed objective intact. */
int pga_gp_config(pga_t *p, unsigned max_nodes, unsigned n_vars,
                  float mutation_rate);
population_t *pga_gp_create_population(pga_t *p, unsigned size);
int pga_set_objective_sr(pga_t *p, const float *X, const float *y,
                         unsigned n_samples);

/* ---- Streaming evolution service (ISSUE 12) ---------------------------
 *
 * Long-lived ask/tell tenants over the serving stack: a SESSION holds
 * a population open across calls, breeds candidates for EXTERNAL
 * evaluation (ask), folds externally measured fitnesses back in at
 * the next generation boundary (tell), advances on the internal
 * objective (step), and persists across processes (suspend/resume,
 * bit-identical). Sessions draw engines from a process-global WARM
 * POOL keyed by bucket signature: the second pga_session_open of one
 * signature compiles 0 programs.
 *
 * pga_session_open creates a session of a fresh size x genome_len
 * population from `seed` over the named builtin objective; `tenant`
 * attributes the session, its warm-pool hit/miss, and every
 * ask/tell/step metric (NULL = "anon"; see the tenant-attribution
 * block above). Returns a session or NULL. A step-only session is
 * bit-identical to pga_run on a same-seed solver.
 *
 * pga_session_ask writes k candidate genomes (k * genome_len floats,
 * row-major) into out; returns k, negative on error. Candidates are
 * bred from the current population under its last known fitnesses
 * (internal evaluations and told values alike); before any fitness is
 * known the first k population rows are returned.
 *
 * pga_session_tell hands back k externally evaluated candidates
 * (genomes: k * genome_len floats, fitness: k floats, higher better,
 * finite). They fold at the next generation boundary: the first breed
 * after the fold selects over the told fitnesses. Returns 0/-1.
 *
 * pga_session_step advances up to n generations on the internal
 * objective (target as in pga_run; pass NAN for none), folding any
 * pending tells first. Returns generations executed, negative on
 * error.
 *
 * pga_session_best writes the best score into *best (may be NULL) and
 * the best genome into genome (genome_len floats; may be NULL).
 * Returns 0/-1.
 *
 * pga_session_suspend persists the session durably at path (atomic
 * checkpoint + sidecar meta, written commit-last); the session stays
 * usable. pga_session_resume restores it — in this or ANY process
 * that sees the files — bit-identically (objective may be NULL to use
 * the name recorded at suspend). pga_session_close releases the
 * session's engine back to the warm pool (the population is dropped —
 * suspend first to keep it).
 *
 * pga_session_snapshot writes the streaming layer's state — one
 * record per open session (shape, generations done, pending tells,
 * best) plus the warm-pool hit/miss/prewarm counters — as a UTF-8
 * JSON document into buf. Same size-query and RETRY-ONCE contract as
 * pga_metrics_snapshot (see above); this snapshot grows with every
 * opened session, which is exactly the race the contract covers. */
typedef struct pga_session pga_session_t;
pga_session_t *pga_session_open(const char *objective, unsigned size,
                                unsigned genome_len, long seed,
                                const char *tenant);
long pga_session_ask(pga_session_t *s, float *out, unsigned k);
int pga_session_tell(pga_session_t *s, const float *genomes,
                     const float *fitness, unsigned k);
int pga_session_step(pga_session_t *s, unsigned n, float target);
int pga_session_best(pga_session_t *s, float *best, float *genome);
int pga_session_suspend(pga_session_t *s, const char *path);
pga_session_t *pga_session_resume(const char *path, const char *objective);
int pga_session_close(pga_session_t *s);
long pga_session_snapshot(char *buf, unsigned long cap);

/* ---- Performance observatory (ISSUE 17) -------------------------------
 *
 * pga_program_report_snapshot writes the roofline-attributed program
 * report for one population's resolved program — per-generation FLOPs,
 * HBM bytes, VMEM footprint, the analytic roofline bound and which
 * roof (compute/bandwidth) binds, keyed like the tuning database
 * (pop|len|dtype|backend|device|objective|operators) — as a UTF-8
 * JSON document into buf. Derived from the dry-run kernel plan, so it
 * works on any backend (a CPU process predicts the chip's roofline).
 * Same size-query and RETRY-ONCE contract as pga_metrics_snapshot
 * (see above). */
long pga_program_report_snapshot(pga_t *p, population_t *pop, char *buf,
                                 unsigned long cap);

#ifdef __cplusplus
}
#endif

#endif /* PGA_TPU_H */
