/* test_islands.c — improved-ABI (pga_tpu.h) coverage of the entry points
 * the other smoke drivers don't touch: the island run loop, both
 * migration calls, the top-k getters, the step-by-step operator chain,
 * and early-terminating pga_run — all on a builtin named objective so
 * the whole GA stays on-device.
 */
#include "pga_tpu.h"

#include <stdio.h>
#include <stdlib.h>

#define GENOME_LEN 16
#define POP_SIZE 64
#define N_POPS 4

static int checks_failed = 0;

#define CHECK(cond, msg)                                       \
    do {                                                       \
        if (!(cond)) {                                         \
            printf("FAIL: %s\n", msg);                         \
            checks_failed++;                                   \
        }                                                      \
    } while (0)

static float sum_of(const gene *g, unsigned len) {
    float s = 0.0f;
    unsigned i;
    for (i = 0; i < len; ++i) s += g[i];
    return s;
}

int main() {
    unsigned i;

    pga_t *p = pga_init(42);
    CHECK(p != NULL, "pga_init");

    population_t *pops[N_POPS];
    for (i = 0; i < N_POPS; ++i) {
        pops[i] = pga_create_population(p, POP_SIZE, GENOME_LEN,
                                        RANDOM_POPULATION);
        CHECK(pops[i] != NULL, "pga_create_population");
    }

    CHECK(pga_set_objective_name(p, "onemax") == 0, "builtin objective");
    CHECK(pga_set_objective_name(p, "no_such_objective") != 0,
          "unknown objective rejected");
    CHECK(pga_set_objective_name(p, "onemax") == 0, "re-set objective");

    /* step-by-step operator chain */
    CHECK(pga_fill_random_values(p, pops[0]) == 0, "fill_random_values");
    CHECK(pga_evaluate(p, pops[0]) == 0, "evaluate");
    CHECK(pga_evaluate_all(p) == 0, "evaluate_all");
    CHECK(pga_crossover(p, pops[0], TOURNAMENT) == 0, "crossover");
    CHECK(pga_mutate(p, pops[0]) == 0, "mutate");
    CHECK(pga_swap_generations(p, pops[0]) == 0, "swap_generations");
    CHECK(pga_crossover_all(p, TOURNAMENT) == 0, "crossover_all");
    CHECK(pga_mutate_all(p) == 0, "mutate_all");
    CHECK(pga_evaluate_all(p) == 0, "evaluate_all 2");

    /* islands + migration */
    int gens = pga_run_islands(p, 20, 5, 0.1f);
    CHECK(gens == 20, "run_islands generation count");
    CHECK(pga_migrate(p, 0.1f) == 0, "migrate");
    CHECK(pga_migrate_between(p, pops[1], pops[2], 0.1f) == 0,
          "migrate_between");
    CHECK(pga_evaluate_all(p) == 0, "evaluate after migration");

    /* top-k getters (flat rows, best first) */
    gene *top = pga_get_best_top(p, pops[0], 4);
    CHECK(top != NULL, "get_best_top");
    if (top) {
        float prev = 1e30f;
        for (i = 0; i < 4; ++i) {
            float s = sum_of(top + i * GENOME_LEN, GENOME_LEN);
            CHECK(s <= prev + 1e-5f, "get_best_top sorted");
            prev = s;
        }
        free(top);
    }

    gene *ball = pga_get_best_all(p);
    CHECK(ball != NULL, "get_best_all");
    float global_best = ball ? sum_of(ball, GENOME_LEN) : 0.0f;
    free(ball);

    gene *topall = pga_get_best_top_all(p, 6);
    CHECK(topall != NULL, "get_best_top_all");
    if (topall) {
        CHECK(sum_of(topall, GENOME_LEN) >= global_best - 1e-5f,
              "top_all row 0 is the global best");
        free(topall);
    }

    /* early termination: a target pop 0 already meets must stop at 0
     * generations (pga_run operates on the first population only) */
    gene *b0 = pga_get_best(p, pops[0]);
    CHECK(b0 != NULL, "get_best");
    float b0_score = b0 ? sum_of(b0, GENOME_LEN) : 0.0f;
    free(b0);
    int done = pga_run(p, 100000, b0_score - 0.1f);
    CHECK(done == 0, "target already met -> 0 generations");
    int done2 = pga_run_n(p, 3);
    CHECK(done2 == 3, "fixed-count run");

    pga_deinit(p);

    if (checks_failed) {
        printf("islands ABI: %d checks FAILED\n", checks_failed);
        return 1;
    }
    printf("islands best sum %.3f / %d\n", global_best, GENOME_LEN);
    printf("PASS\n");
    return 0;
}
