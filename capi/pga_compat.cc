/* pga_compat.cc — the exact-reference-ABI shim (libpga.so).
 *
 * Implements capi/pga.h: the reference repo's include/pga.h signatures,
 * verbatim — seedless pga_init, void returns, fixed-count pga_run,
 * gene** top-k getters — over the same libpga_tpu.capi_bridge the
 * improved shim (pga_tpu.cc) uses. A reference driver's source compiles
 * against this header unchanged once its CUDA-isms (__device__,
 * __constant__, cudaMemcpyFromSymbol) are dropped; tests/test_capi.py
 * proves that by de-CUDA-ing the reference's own knapsack driver at test
 * time and running it against this library.
 *
 * Error model: the reference aborts the process on any CUDA error
 * (pga.cu:25-33) so its void returns never report failure; here a failed
 * call prints the Python error and the program continues with NULL
 * results where applicable — strictly more survivable.
 */

#include "pga.h"

#include "pga_marshal.h"

namespace {
using namespace pga_marshal;

/* Split a flat float32 payload of `rows` genome rows into the reference's
 * gene** ownership contract: a malloc'd array of `rows` pointers, each a
 * malloc'd row copy. Frees the flat buffer. */
gene **split_rows(float *flat, size_t nbytes, unsigned rows) {
    if (!flat || rows == 0) {
        std::free(flat);
        return nullptr;
    }
    size_t total = nbytes / sizeof(gene);
    if (total % rows != 0) {
        std::free(flat);
        return nullptr;
    }
    size_t row_len = total / rows;
    gene **out = static_cast<gene **>(std::malloc(rows * sizeof(gene *)));
    if (!out) {
        std::free(flat);
        return nullptr;
    }
    for (unsigned r = 0; r < rows; ++r) {
        out[r] = static_cast<gene *>(std::malloc(row_len * sizeof(gene)));
        if (!out[r]) {
            for (unsigned q = 0; q < r; ++q) std::free(out[q]);
            std::free(out);
            std::free(flat);
            return nullptr;
        }
        std::memcpy(out[r], flat + r * row_len, row_len * sizeof(gene));
    }
    std::free(flat);
    return out;
}

}  // namespace

extern "C" {

pga_t *pga_init() {
    /* seed < 0 = OS entropy: the analog of the reference's time(NULL)
     * cuRAND seeding (pga.cu:154). */
    long h = call_long("init", "(l)", -1L);
    return h <= 0 ? nullptr : pack_solver<pga_t *>(h);
}

void pga_deinit(pga_t *p) {
    if (!p) return;
    call_long("deinit", "(l)", solver_of(p));
}

population_t *pga_create_population(pga_t *p, unsigned long size,
                                    unsigned genome_len,
                                    enum population_type type) {
    if (!p) return nullptr;
    long idx = call_long("create_population", "(lkIi)", solver_of(p), size,
                         genome_len, static_cast<int>(type));
    return idx < 0 ? nullptr
                   : pack_pop<population_t *>(solver_of(p), idx);
}

void pga_set_objective_function(pga_t *p, obj_f f) {
    if (!p || !f) return;
    call_long("set_objective_ptr", "(ll)", solver_of(p),
              static_cast<long>(reinterpret_cast<intptr_t>(f)));
}

void pga_set_mutate_function(pga_t *p, mutate_f f) {
    if (!p) return;
    call_long("set_mutate_ptr", "(ll)", solver_of(p),
              static_cast<long>(reinterpret_cast<intptr_t>(f)));
}

void pga_set_crossover_function(pga_t *p, crossover_f f) {
    if (!p) return;
    call_long("set_crossover_ptr", "(ll)", solver_of(p),
              static_cast<long>(reinterpret_cast<intptr_t>(f)));
}

gene *pga_get_best(pga_t *p, population_t *pop) {
    if (!p || !pop) return nullptr;
    return bytes_to_floats(
        call("get_best", "(ll)", solver_of(p), pop_index_of(pop)));
}

gene **pga_get_best_top(pga_t *p, population_t *pop, unsigned length) {
    if (!p || !pop || length == 0) return nullptr;
    size_t nbytes = 0;
    float *flat = bytes_to_floats(
        call("get_best_top", "(llI)", solver_of(p), pop_index_of(pop),
             length),
        &nbytes);
    return split_rows(flat, nbytes, length);
}

gene *pga_get_best_all(pga_t *p) {
    if (!p) return nullptr;
    return bytes_to_floats(call("get_best_all", "(l)", solver_of(p)));
}

gene **pga_get_best_top_all(pga_t *p, unsigned length) {
    if (!p || length == 0) return nullptr;
    size_t nbytes = 0;
    float *flat = bytes_to_floats(
        call("get_best_top_all", "(lI)", solver_of(p), length), &nbytes);
    return split_rows(flat, nbytes, length);
}

void pga_evaluate(pga_t *p, population_t *pop) {
    if (!p || !pop) return;
    call_long("evaluate", "(ll)", solver_of(p), pop_index_of(pop));
}

void pga_evaluate_all(pga_t *p) {
    if (!p) return;
    call_long("evaluate_all", "(l)", solver_of(p));
}

void pga_crossover(pga_t *p, population_t *pop,
                   enum crossover_selection_type type) {
    /* The reference ignores `type` entirely (pga.cu:329) — a driver may
     * legally pass any value. The improved-ABI bridge honors non-zero
     * values, so this exact-reference shim pins TOURNAMENT to keep the
     * reference's observable behavior verbatim. */
    (void)type;
    if (!p || !pop) return;
    call_long("crossover", "(lli)", solver_of(p), pop_index_of(pop), 0);
}

void pga_crossover_all(pga_t *p, enum crossover_selection_type type) {
    (void)type;
    if (!p) return;
    call_long("crossover_all", "(li)", solver_of(p), 0);
}

void pga_migrate(pga_t *p, float pct) {
    if (!p) return;
    call_long("migrate", "(lf)", solver_of(p), static_cast<double>(pct));
}

void pga_migrate_between(pga_t *p, population_t *from, population_t *to,
                         float pct) {
    if (!p || !from || !to) return;
    call_long("migrate_between", "(lllf)", solver_of(p), pop_index_of(from),
              pop_index_of(to), static_cast<double>(pct));
}

void pga_mutate(pga_t *p, population_t *pop) {
    if (!p || !pop) return;
    call_long("mutate", "(ll)", solver_of(p), pop_index_of(pop));
}

void pga_mutate_all(pga_t *p) {
    if (!p) return;
    call_long("mutate_all", "(l)", solver_of(p));
}

void pga_swap_generations(pga_t *p, population_t *pop) {
    if (!p || !pop) return;
    call_long("swap_generations", "(ll)", solver_of(p), pop_index_of(pop));
}

void pga_fill_random_values(pga_t *p, population_t *pop) {
    if (!p || !pop) return;
    call_long("fill_random_values", "(ll)", solver_of(p), pop_index_of(pop));
}

void pga_run(pga_t *p, unsigned n) {
    /* Fixed generation count on the first population — the reference's
     * implemented behavior (pga.cu:376-391). */
    if (!p) return;
    call_long("run", "(lIif)", solver_of(p), n, 0, 0.0);
}

void pga_run_islands(pga_t *p, unsigned n, unsigned m, float pct) {
    if (!p) return;
    call_long("run_islands", "(lIIf)", solver_of(p), n, m,
              static_cast<double>(pct));
}

}  // extern "C"
