/* Smoke driver 1: the reference's first workload (test/test.cu — maximize
 * the sum of genes) through the C ABI, using the on-device builtin
 * objective so the whole GA runs on the TPU. Exits 0 iff the best genome
 * clearly improved over random initialization. */
#include <stdio.h>
#include <stdlib.h>

#include "pga_tpu.h"

#define POP 8192
#define LEN 100
#define GENS 60

int main(void) {
    pga_t *p = pga_init(42);
    if (!p) return fprintf(stderr, "pga_init failed\n"), 1;

    population_t *pop = pga_create_population(p, POP, LEN, RANDOM_POPULATION);
    if (!pop) return fprintf(stderr, "pga_create_population failed\n"), 1;

    if (pga_set_objective_name(p, "onemax") != 0)
        return fprintf(stderr, "pga_set_objective_name failed\n"), 1;

    int gens = pga_run_n(p, GENS);
    if (gens < 0) return fprintf(stderr, "pga_run failed\n"), 1;

    gene *best = pga_get_best(p, pop);
    if (!best) return fprintf(stderr, "pga_get_best failed\n"), 1;

    float sum = 0.0f;
    for (int i = 0; i < LEN; i++) sum += best[i];
    printf("onemax best sum after %d gens: %.2f (random ~%.0f, max %d)\n",
           gens, sum, LEN / 2.0, LEN);
    free(best);

    /* top-k across the (single) population — stubbed NULL in the
     * reference (pga.cu:238-240), real here. */
    gene *top = pga_get_best_top(p, pop, 3);
    if (!top) return fprintf(stderr, "pga_get_best_top failed\n"), 1;
    free(top);

    pga_deinit(p);
    if (sum < 80.0f) {
        fprintf(stderr, "FAIL: best sum %.2f below threshold 80\n", sum);
        return 1;
    }
    printf("PASS\n");
    return 0;
}
