/* Smoke driver 7: device-speed custom BREEDING operators via the
 * expression surface (pga_set_crossover_expr / pga_set_mutate_expr) —
 * the last two reference callbacks (pga.h:47-48) at device speed. The
 * reference's flagship TSP driver installs a custom crossover
 * (test3/test.cu:87-91); this is the TPU-native equivalent of that
 * extension point: no host round trip, no CPU pin (unlike the
 * function-pointer compatibility path).
 *
 * Checks: a NON-builtin blend crossover plus creep mutation drive
 * OneMax from C; a one-point crossover (per-child cut via q) works; the
 * per-gene restriction and syntax errors return -1 without corrupting
 * the solver; NULL restores the builtin defaults. */
#include <stdio.h>
#include <stdlib.h>

#include "pga_tpu.h"

#define POP 8192
#define LEN 64
#define GENS 120

static float best_sum(pga_t *p, population_t *pop) {
    gene *best = pga_get_best(p, pop);
    if (!best) return -1e30f;
    float sum = 0.0f;
    for (unsigned i = 0; i < LEN; i++) sum += best[i];
    free(best);
    return sum;
}

int main(void) {
    pga_t *p = pga_init(31);
    if (!p) return fprintf(stderr, "pga_init failed\n"), 1;
    population_t *pop = pga_create_population(p, POP, LEN, RANDOM_POPULATION);
    if (!pop) return fprintf(stderr, "create_population failed\n"), 1;
    if (pga_set_objective_name(p, "onemax") != 0)
        return fprintf(stderr, "set_objective_name failed\n"), 1;

    /* blend crossover (NOT a builtin kind: probabilistic parent average)
     * + creep mutation (+-sigma steps at the runtime rate) */
    if (pga_set_crossover_expr(
            p, "where(r < 0.3, (p1 + p2) / 2, where(r2 < 0.5, p1, p2))") != 0)
        return fprintf(stderr, "set_crossover_expr failed\n"), 1;
    if (pga_set_mutate_expr(
            p, "where(r < rate, g + sigma * (2*r2 - 1), g)", 0.1f, 0.15f) != 0)
        return fprintf(stderr, "set_mutate_expr failed\n"), 1;
    if (pga_run_n(p, GENS) < 0)
        return fprintf(stderr, "run failed\n"), 1;
    float got = best_sum(p, pop);
    printf("blend+creep best: %.1f of %d\n", got, LEN);
    if (got < 0.85f * LEN)
        return fprintf(stderr, "blend+creep did not converge\n"), 1;

    /* one-point crossover via the per-child cut q, on a fresh solver */
    pga_deinit(p);
    p = pga_init(32);
    if (!p) return fprintf(stderr, "pga_init 2 failed\n"), 1;
    pop = pga_create_population(p, POP, LEN, RANDOM_POPULATION);
    if (!pop) return fprintf(stderr, "create_population 2 failed\n"), 1;
    if (pga_set_objective_name(p, "onemax") != 0)
        return fprintf(stderr, "set_objective_name 2 failed\n"), 1;
    if (pga_set_crossover_expr(p, "where(i < floor(q * L), p1, p2)") != 0)
        return fprintf(stderr, "one-point expr failed\n"), 1;
    if (pga_set_mutate_expr(p, "where(r < rate, r2, g)", 0.02f, -1.0f) != 0)
        return fprintf(stderr, "reset mutate expr failed\n"), 1;
    if (pga_run_n(p, GENS) < 0)
        return fprintf(stderr, "one-point run failed\n"), 1;
    got = best_sum(p, pop);
    printf("one-point+reset best: %.1f of %d\n", got, LEN);
    if (got < 0.8f * LEN)
        return fprintf(stderr, "one-point did not converge\n"), 1;

    /* error paths: each must return -1 and leave the solver usable */
    if (pga_set_crossover_expr(p, "sum(p1)") == 0)
        return fprintf(stderr, "reduction in crossover accepted\n"), 1;
    if (pga_set_mutate_expr(p, "roll(g, 1)", -1.0f, -1.0f) == 0)
        return fprintf(stderr, "roll in mutation accepted\n"), 1;
    if (pga_set_crossover_expr(p, "where(r < 0.5, g, p2)") == 0)
        return fprintf(stderr, "'g' in crossover accepted\n"), 1;
    if (pga_set_mutate_expr(p, "where(", -1.0f, -1.0f) == 0)
        return fprintf(stderr, "bad mutate syntax accepted\n"), 1;
    if (pga_set_crossover_expr(NULL, "p1") == 0)
        return fprintf(stderr, "NULL solver accepted\n"), 1;

    /* solver still healthy; NULL restores the builtin defaults */
    if (pga_set_crossover_function(p, NULL) != 0)
        return fprintf(stderr, "crossover NULL restore failed\n"), 1;
    if (pga_set_mutate_function(p, NULL) != 0)
        return fprintf(stderr, "mutate NULL restore failed\n"), 1;
    if (pga_run_n(p, 5) < 0)
        return fprintf(stderr, "post-restore run failed\n"), 1;

    pga_deinit(p);
    printf("PASS\n");
    return 0;
}
