/* pga_tpu.cc — native C ABI shim over the libpga_tpu Python package.
 *
 * Architecture: this shared library embeds a CPython interpreter
 * (initialized lazily on the first pga_init) and forwards every API call
 * to libpga_tpu.capi_bridge, which owns the JAX/TPU engine. All marshal
 * traffic is ints/floats/strings/bytes; genome arrays cross the boundary
 * as raw float32 bytes and are re-exposed to C as malloc'd gene buffers
 * (the reference's ownership contract, pga.cu:231-235).
 *
 * Host callbacks (custom objective/mutate/crossover) are passed as raw
 * function-pointer addresses; the bridge wraps them with ctypes and
 * evaluates through jax.pure_callback. See pga_tpu.h for the tradeoff.
 *
 * This is the IMPROVED ABI (int error returns, explicit seed, run
 * targets). For source compatibility with drivers written against the
 * reference's exact include/pga.h, link libpga.so (pga_compat.cc)
 * instead.
 */

#include "pga_tpu.h"

#include "pga_marshal.h"

namespace {
using namespace pga_marshal;

gene *bytes_to_genes(PyObject *out) { return bytes_to_floats(out); }

pga_t *pack(long h) { return pack_solver<pga_t *>(h); }
}  // namespace

extern "C" {

pga_t *pga_init(long seed) {
    long h = call_long("init", "(l)", seed);
    return h <= 0 ? nullptr : pack(h);
}

void pga_deinit(pga_t *p) {
    if (!p) return;
    call_long("deinit", "(l)", solver_of(p));
}

population_t *pga_create_population(pga_t *p, unsigned size,
                                    unsigned genome_len,
                                    enum population_type type) {
    if (!p) return nullptr;
    long idx = call_long("create_population", "(lIIi)", solver_of(p), size,
                         genome_len, static_cast<int>(type));
    return idx < 0 ? nullptr
                   : pack_pop<population_t *>(solver_of(p), idx);
}

int pga_set_objective_function(pga_t *p, obj_f f) {
    if (!p || !f) return -1;
    return static_cast<int>(
        call_long("set_objective_ptr", "(ll)", solver_of(p),
                  static_cast<long>(reinterpret_cast<intptr_t>(f))));
}

int pga_set_mutate_function(pga_t *p, mutate_f f) {
    if (!p) return -1;
    return static_cast<int>(
        call_long("set_mutate_ptr", "(ll)", solver_of(p),
                  static_cast<long>(reinterpret_cast<intptr_t>(f))));
}

int pga_set_crossover_function(pga_t *p, crossover_f f) {
    if (!p) return -1;
    return static_cast<int>(
        call_long("set_crossover_ptr", "(ll)", solver_of(p),
                  static_cast<long>(reinterpret_cast<intptr_t>(f))));
}

int pga_set_objective_name(pga_t *p, const char *name) {
    if (!p || !name) return -1;
    return static_cast<int>(
        call_long("set_objective_name", "(ls)", solver_of(p), name));
}

int pga_set_objective_expr(pga_t *p, const char *expr) {
    if (!p || !expr) return -1;
    return static_cast<int>(
        call_long("set_objective_expr", "(ls)", solver_of(p), expr));
}

int pga_set_objective_expr_const(pga_t *p, const char *name,
                                 const float *data, unsigned n) {
    if (!p || !name || (n && !data)) return -1;
    return static_cast<int>(call_long(
        "set_objective_expr_const", "(lsy#)", solver_of(p), name,
        reinterpret_cast<const char *>(data),
        static_cast<Py_ssize_t>(n * sizeof(float))));
}

int pga_set_crossover_name(pga_t *p, const char *name) {
    if (!p || !name) return -1;
    return static_cast<int>(
        call_long("set_crossover_name", "(ls)", solver_of(p), name));
}

int pga_set_mutate_name(pga_t *p, const char *name, float rate,
                        float sigma) {
    if (!p || !name) return -1;
    return static_cast<int>(
        call_long("set_mutate_name", "(lsdd)", solver_of(p), name,
                  static_cast<double>(rate), static_cast<double>(sigma)));
}

int pga_set_objective_tsp_coords(pga_t *p, const float *xy,
                                 unsigned n_cities, float duplicate_penalty,
                                 int fused_duplicate_genes) {
    if (!p || !xy || !n_cities) return -1;
    return static_cast<int>(call_long(
        "set_objective_tsp_coords", "(ly#Idi)", solver_of(p),
        reinterpret_cast<const char *>(xy),
        static_cast<Py_ssize_t>(static_cast<size_t>(n_cities) * 2 *
                                sizeof(float)),
        n_cities, static_cast<double>(duplicate_penalty),
        fused_duplicate_genes));
}

int pga_set_crossover_expr(pga_t *p, const char *expr) {
    if (!p || !expr) return -1;
    return static_cast<int>(
        call_long("set_crossover_expr", "(ls)", solver_of(p), expr));
}

int pga_set_mutate_expr(pga_t *p, const char *expr, float rate,
                        float sigma) {
    if (!p || !expr) return -1;
    return static_cast<int>(
        call_long("set_mutate_expr", "(lsdd)", solver_of(p), expr,
                  static_cast<double>(rate), static_cast<double>(sigma)));
}

int pga_set_objective_expr_const2(pga_t *p, const char *name,
                                  const float *data, unsigned rows,
                                  unsigned cols) {
    if (!p || !name || (rows && cols && !data)) return -1;
    return static_cast<int>(call_long(
        "set_objective_expr_const2", "(lsy#II)", solver_of(p), name,
        reinterpret_cast<const char *>(data),
        static_cast<Py_ssize_t>(static_cast<size_t>(rows) * cols *
                                sizeof(float)),
        rows, cols));
}

int pga_set_selection(pga_t *p, enum crossover_selection_type type,
                      float param) {
    if (!p) return -1;
    return static_cast<int>(
        call_long("set_selection", "(lid)", solver_of(p),
                  static_cast<int>(type), static_cast<double>(param)));
}

gene *pga_get_best(pga_t *p, population_t *pop) {
    if (!p || !pop) return nullptr;
    return bytes_to_genes(
        call("get_best", "(ll)", solver_of(p), pop_index_of(pop)));
}

gene *pga_get_best_top(pga_t *p, population_t *pop, unsigned length) {
    if (!p || !pop) return nullptr;
    return bytes_to_genes(call("get_best_top", "(llI)", solver_of(p),
                               pop_index_of(pop), length));
}

gene *pga_get_best_all(pga_t *p) {
    if (!p) return nullptr;
    return bytes_to_genes(call("get_best_all", "(l)", solver_of(p)));
}

gene *pga_get_best_top_all(pga_t *p, unsigned length) {
    if (!p) return nullptr;
    return bytes_to_genes(
        call("get_best_top_all", "(lI)", solver_of(p), length));
}

int pga_evaluate(pga_t *p, population_t *pop) {
    if (!p || !pop) return -1;
    return static_cast<int>(
        call_long("evaluate", "(ll)", solver_of(p), pop_index_of(pop)));
}

int pga_evaluate_all(pga_t *p) {
    if (!p) return -1;
    return static_cast<int>(call_long("evaluate_all", "(l)", solver_of(p)));
}

int pga_crossover(pga_t *p, population_t *pop,
                  enum crossover_selection_type type) {
    if (!p || !pop) return -1;
    return static_cast<int>(call_long("crossover", "(lli)", solver_of(p),
                                      pop_index_of(pop),
                                      static_cast<int>(type)));
}

int pga_crossover_all(pga_t *p, enum crossover_selection_type type) {
    if (!p) return -1;
    return static_cast<int>(
        call_long("crossover_all", "(li)", solver_of(p),
                  static_cast<int>(type)));
}

int pga_migrate(pga_t *p, float pct) {
    if (!p) return -1;
    return static_cast<int>(
        call_long("migrate", "(lf)", solver_of(p), static_cast<double>(pct)));
}

int pga_migrate_between(pga_t *p, population_t *from, population_t *to,
                        float pct) {
    if (!p || !from || !to) return -1;
    return static_cast<int>(call_long("migrate_between", "(lllf)",
                                      solver_of(p), pop_index_of(from),
                                      pop_index_of(to),
                                      static_cast<double>(pct)));
}

int pga_mutate(pga_t *p, population_t *pop) {
    if (!p || !pop) return -1;
    return static_cast<int>(
        call_long("mutate", "(ll)", solver_of(p), pop_index_of(pop)));
}

int pga_mutate_all(pga_t *p) {
    if (!p) return -1;
    return static_cast<int>(call_long("mutate_all", "(l)", solver_of(p)));
}

int pga_swap_generations(pga_t *p, population_t *pop) {
    if (!p || !pop) return -1;
    return static_cast<int>(
        call_long("swap_generations", "(ll)", solver_of(p), pop_index_of(pop)));
}

int pga_fill_random_values(pga_t *p, population_t *pop) {
    if (!p || !pop) return -1;
    return static_cast<int>(call_long("fill_random_values", "(ll)",
                                      solver_of(p), pop_index_of(pop)));
}

int pga_run(pga_t *p, unsigned n, float target) {
    if (!p) return -1;
    return static_cast<int>(call_long("run", "(lIif)", solver_of(p), n, 1,
                                      static_cast<double>(target)));
}

int pga_run_n(pga_t *p, unsigned n) {
    if (!p) return -1;
    return static_cast<int>(
        call_long("run", "(lIif)", solver_of(p), n, 0, 0.0));
}

int pga_run_islands(pga_t *p, unsigned n, unsigned m, float pct) {
    if (!p) return -1;
    return static_cast<int>(call_long("run_islands", "(lIIf)", solver_of(p),
                                      n, m, static_cast<double>(pct)));
}

int pga_supervised_run(pga_t *p, unsigned n, unsigned checkpoint_every,
                       unsigned max_retries, const char *checkpoint_path,
                       int resume) {
    if (!p) return -1;
    return static_cast<int>(call_long(
        "supervised_run", "(lIIIsi)", solver_of(p), n, checkpoint_every,
        max_retries, checkpoint_path ? checkpoint_path : "", resume));
}

int pga_set_fault_plan(const char *json_spec) {
    return static_cast<int>(call_long(
        "set_fault_plan", "(s)", json_spec ? json_spec : ""));
}

pga_ticket_t *pga_submit(pga_t *p, unsigned n, float target,
                         const char *tenant) {
    if (!p) return nullptr;
    long tid = call_long("submit", "(lIifs)", solver_of(p), n, 1,
                         static_cast<double>(target),
                         tenant ? tenant : "");
    return tid <= 0 ? nullptr
                    : reinterpret_cast<pga_ticket_t *>(
                          static_cast<intptr_t>(tid));
}

pga_ticket_t *pga_submit_n(pga_t *p, unsigned n, const char *tenant) {
    if (!p) return nullptr;
    long tid = call_long("submit", "(lIifs)", solver_of(p), n, 0, 0.0,
                         tenant ? tenant : "");
    return tid <= 0 ? nullptr
                    : reinterpret_cast<pga_ticket_t *>(
                          static_cast<intptr_t>(tid));
}

int pga_poll(pga_ticket_t *t) {
    if (!t) return -1;
    return static_cast<int>(call_long(
        "poll", "(l)",
        static_cast<long>(reinterpret_cast<intptr_t>(t))));
}

int pga_await(pga_ticket_t *t) {
    if (!t) return -1;
    return static_cast<int>(call_long(
        "await_ticket", "(l)",
        static_cast<long>(reinterpret_cast<intptr_t>(t))));
}

int pga_await_ex(pga_ticket_t *t, float latency_ms[4]) {
    if (!t) return -1;
    size_t nbytes = 0;
    /* float32[5]: generations, then queue_wait/execute/readback/e2e ms
     * (NaN where the lifecycle never reached the transition). */
    float *vals = bytes_to_floats(
        call("await_ticket_ex", "(l)",
             static_cast<long>(reinterpret_cast<intptr_t>(t))),
        &nbytes);
    if (!vals || nbytes < 5 * sizeof(float)) {
        std::free(vals);
        return -1;
    }
    if (latency_ms)
        for (int i = 0; i < 4; i++) latency_ms[i] = vals[1 + i];
    int gens = static_cast<int>(vals[0]);
    std::free(vals);
    return gens;
}

namespace {
/* Shared body of the sized-snapshot entry points: copy the rendered
 * JSON into buf (NUL-terminated, truncated at cap) and return the full
 * length. The RETRY-ONCE guarantee lives on the Python side
 * (capi_bridge._sized_snapshot parks renderings that did not fit), so
 * the bridge call must carry the caller's cap. */
long snapshot_out(PyObject *out, char *buf, unsigned long cap) {
    if (!out) return -1;
    char *data = nullptr;
    Py_ssize_t len = 0;
    if (PyBytes_AsStringAndSize(out, &data, &len) != 0) {
        PyErr_Print();
        Py_DECREF(out);
        return -1;
    }
    if (buf && cap > 0) {
        size_t n = static_cast<size_t>(len) < cap - 1
                       ? static_cast<size_t>(len)
                       : cap - 1;
        std::memcpy(buf, data, n);
        buf[n] = '\0';
    }
    Py_DECREF(out);
    return static_cast<long>(len);
}
}  // namespace

long pga_metrics_snapshot(char *buf, unsigned long cap) {
    return snapshot_out(call("metrics_snapshot_json", "(k)", cap), buf, cap);
}

long pga_program_report_snapshot(pga_t *p, population_t *pop, char *buf,
                                 unsigned long cap) {
    if (!p || !pop) return -1;
    return snapshot_out(call("program_report_snapshot_json", "(llk)",
                             solver_of(p), pop_index_of(pop), cap),
                        buf, cap);
}

int pga_fleet_start(const char *spool_dir, const char *objective,
                    unsigned n_workers, unsigned max_batch,
                    float max_wait_ms, int ring, unsigned coordinators) {
    if (!spool_dir || !objective) return -1;
    return static_cast<int>(call_long(
        "fleet_start", "(ssIIfiI)", spool_dir, objective, n_workers,
        max_batch, static_cast<double>(max_wait_ms), ring, coordinators));
}

long pga_fleet_leader_snapshot(char *buf, unsigned long cap) {
    return snapshot_out(call("fleet_leader_snapshot_json", "(k)", cap),
                        buf, cap);
}

pga_fleet_ticket_t *pga_fleet_submit(unsigned size, unsigned genome_len,
                                     unsigned n, long seed,
                                     unsigned checkpoint_every,
                                     int priority, const char *tenant) {
    long tid = call_long("fleet_submit", "(IIIlIis)", size, genome_len, n,
                         seed, checkpoint_every, priority,
                         tenant ? tenant : "");
    return tid <= 0 ? nullptr
                    : reinterpret_cast<pga_fleet_ticket_t *>(
                          static_cast<intptr_t>(tid));
}

int pga_fleet_tenant_policy(const char *tenant, float weight,
                            long max_pending, int priority) {
    if (!tenant) return -1;
    return static_cast<int>(call_long(
        "fleet_tenant_policy", "(sdli)", tenant,
        static_cast<double>(weight), max_pending, priority));
}

int pga_fleet_await(pga_fleet_ticket_t *t, float *best, double timeout_s) {
    if (!t) return -1;
    size_t nbytes = 0;
    /* float32[2]: generations, best objective value. */
    float *vals = bytes_to_floats(
        call("fleet_await", "(ld)",
             static_cast<long>(reinterpret_cast<intptr_t>(t)), timeout_s),
        &nbytes);
    if (!vals || nbytes < 2 * sizeof(float)) {
        std::free(vals);
        return -1;
    }
    if (best) *best = vals[1];
    int gens = static_cast<int>(vals[0]);
    std::free(vals);
    return gens;
}

int pga_fleet_await_ex(pga_fleet_ticket_t *t, float *best,
                       float latency_ms[6], double timeout_s) {
    if (!t) return -1;
    size_t nbytes = 0;
    /* float32[8]: generations, best, then the six tiling spans
     * intake/spool_wait/execute/publish/readback/e2e in ms (NaN where
     * tracing was off or the span never happened). */
    float *vals = bytes_to_floats(
        call("fleet_await_ex", "(ld)",
             static_cast<long>(reinterpret_cast<intptr_t>(t)), timeout_s),
        &nbytes);
    if (!vals || nbytes < 8 * sizeof(float)) {
        std::free(vals);
        return -1;
    }
    if (best) *best = vals[1];
    if (latency_ms)
        for (int i = 0; i < 6; i++) latency_ms[i] = vals[2 + i];
    int gens = static_cast<int>(vals[0]);
    std::free(vals);
    return gens;
}

long pga_fleet_metrics_snapshot(char *buf, unsigned long cap) {
    return snapshot_out(call("fleet_metrics_snapshot_json", "(k)", cap),
                        buf, cap);
}

int pga_fleet_drain(void) {
    return static_cast<int>(call_long("fleet_drain", "()"));
}

int pga_fleet_close(void) {
    return static_cast<int>(call_long("fleet_close", "()"));
}

int pga_serving_config(unsigned max_batch, float max_wait_ms) {
    return static_cast<int>(
        call_long("serving_config", "(If)", max_batch,
                  static_cast<double>(max_wait_ms)));
}

int pga_set_tuning_db(const char *path) {
    return static_cast<int>(
        call_long("set_tuning_db", "(s)", path ? path : ""));
}

int pga_autotune(unsigned size, unsigned genome_len,
                 const char *objective, unsigned budget,
                 const char *db_path, long seed) {
    if (!objective || !db_path) return -1;
    return static_cast<int>(call_long(
        "autotune", "(IIsIsl)", size, genome_len, objective, budget,
        db_path, seed));
}

int pga_gp_config(pga_t *p, unsigned max_nodes, unsigned n_vars,
                  float mutation_rate) {
    if (!p) return -1;
    return static_cast<int>(call_long(
        "gp_config", "(lIIf)", solver_of(p), max_nodes, n_vars,
        static_cast<double>(mutation_rate)));
}

population_t *pga_gp_create_population(pga_t *p, unsigned size) {
    if (!p) return nullptr;
    long idx = call_long("gp_create_population", "(lI)", solver_of(p),
                         size);
    return idx < 0 ? nullptr
                   : pack_pop<population_t *>(solver_of(p), idx);
}

int pga_set_objective_sr(pga_t *p, const float *X, const float *y,
                         unsigned n_samples) {
    if (!p || !X || !y || !n_samples) return -1;
    /* n_vars comes from the installed GP encoding on the bridge side;
     * the X buffer length is validated there against it. The byte
     * count here trusts the caller's n_samples times the encoding's
     * n_vars — read it back from the bridge first. */
    long n_vars = call_long("gp_n_vars", "(l)", solver_of(p));
    if (n_vars <= 0) return -1;
    return static_cast<int>(call_long(
        "set_objective_sr", "(ly#y#I)", solver_of(p),
        reinterpret_cast<const char *>(X),
        static_cast<Py_ssize_t>(static_cast<size_t>(n_samples) *
                                static_cast<size_t>(n_vars) *
                                sizeof(float)),
        reinterpret_cast<const char *>(y),
        static_cast<Py_ssize_t>(static_cast<size_t>(n_samples) *
                                sizeof(float)),
        n_samples));
}

int pga_set_telemetry(pga_t *p, unsigned max_gens) {
    if (!p) return -1;
    return static_cast<int>(
        call_long("set_telemetry", "(lI)", solver_of(p), max_gens));
}

int pga_set_pop_shards(pga_t *p, unsigned shards) {
    if (!p) return -1;
    return static_cast<int>(
        call_long("set_pop_shards", "(lI)", solver_of(p), shards));
}

/* ---- Streaming evolution service (ISSUE 12) -------------------------- */

static pga_session_t *pack_session(long h) {
    return h <= 0 ? nullptr
                  : reinterpret_cast<pga_session_t *>(
                        static_cast<intptr_t>(h));
}

static long session_of(pga_session_t *s) {
    return static_cast<long>(reinterpret_cast<intptr_t>(s));
}

pga_session_t *pga_session_open(const char *objective, unsigned size,
                                unsigned genome_len, long seed,
                                const char *tenant) {
    if (!objective || !size || !genome_len) return nullptr;
    return pack_session(call_long("session_open", "(sIIls)", objective,
                                  size, genome_len, seed,
                                  tenant ? tenant : ""));
}

long pga_session_ask(pga_session_t *s, float *out, unsigned k) {
    if (!s || !out || !k) return -1;
    size_t nbytes = 0;
    float *vals = bytes_to_floats(
        call("session_ask", "(lI)", session_of(s), k), &nbytes);
    if (!vals || nbytes == 0) {
        std::free(vals);
        return -1;
    }
    std::memcpy(out, vals, nbytes);
    std::free(vals);
    return static_cast<long>(k);
}

int pga_session_tell(pga_session_t *s, const float *genomes,
                     const float *fitness, unsigned k) {
    if (!s || !genomes || !fitness || !k) return -1;
    /* genome_len comes from the session on the bridge side; the byte
     * count is validated there against it. Read it back first. */
    long glen = call_long("session_genome_len", "(l)", session_of(s));
    if (glen <= 0) return -1;
    return static_cast<int>(call_long(
        "session_tell", "(ly#y#I)", session_of(s),
        reinterpret_cast<const char *>(genomes),
        static_cast<Py_ssize_t>(static_cast<size_t>(k) *
                                static_cast<size_t>(glen) *
                                sizeof(float)),
        reinterpret_cast<const char *>(fitness),
        static_cast<Py_ssize_t>(static_cast<size_t>(k) * sizeof(float)),
        k));
}

int pga_session_step(pga_session_t *s, unsigned n, float target) {
    if (!s) return -1;
    int has_target = target == target; /* NAN = no target */
    return static_cast<int>(call_long(
        "session_step", "(lIif)", session_of(s), n, has_target,
        has_target ? target : 0.0f));
}

int pga_session_best(pga_session_t *s, float *best, float *genome) {
    if (!s) return -1;
    size_t nbytes = 0;
    /* float32[1 + genome_len]: best score, then the best genome. */
    float *vals = bytes_to_floats(
        call("session_best", "(l)", session_of(s)), &nbytes);
    if (!vals || nbytes < 2 * sizeof(float)) {
        std::free(vals);
        return -1;
    }
    if (best) *best = vals[0];
    if (genome)
        std::memcpy(genome, vals + 1, nbytes - sizeof(float));
    std::free(vals);
    return 0;
}

int pga_session_suspend(pga_session_t *s, const char *path) {
    if (!s || !path) return -1;
    return static_cast<int>(
        call_long("session_suspend", "(ls)", session_of(s), path));
}

pga_session_t *pga_session_resume(const char *path, const char *objective) {
    if (!path) return nullptr;
    return pack_session(call_long("session_resume", "(ss)", path,
                                  objective ? objective : ""));
}

int pga_session_close(pga_session_t *s) {
    if (!s) return -1;
    return static_cast<int>(
        call_long("session_close", "(l)", session_of(s)));
}

long pga_session_snapshot(char *buf, unsigned long cap) {
    return snapshot_out(call("session_snapshot_json", "(k)", cap), buf,
                        cap);
}

float *pga_get_history(pga_t *p, population_t *pop, unsigned *rows,
                       unsigned *cols) {
    if (!p || !pop) return nullptr;
    long c = call_long("history_cols", "()");
    if (c <= 0) return nullptr;
    size_t nbytes = 0;
    float *vals = bytes_to_floats(
        call("get_history", "(ll)", solver_of(p), pop_index_of(pop)),
        &nbytes);
    if (!vals || nbytes == 0) {
        std::free(vals); /* empty history: no rows recorded */
        if (rows) *rows = 0;
        if (cols) *cols = static_cast<unsigned>(c);
        return nullptr;
    }
    if (rows) *rows = static_cast<unsigned>(nbytes / (c * sizeof(float)));
    if (cols) *cols = static_cast<unsigned>(c);
    return vals;
}

}  // extern "C"
