/* pga_marshal.h — shared internals of the native C ABI shims.
 *
 * Both shim flavors — pga_tpu.cc (the improved, int-returning ABI) and
 * pga_compat.cc (the exact reference-shaped ABI from the reference repo's
 * include/pga.h) — embed one CPython interpreter and forward calls to
 * libpga_tpu.capi_bridge. This header holds the embedding + marshaling
 * machinery they share. Internal: not installed, not a public API.
 *
 * Everything is `static` so each shim gets its own copy; the two shared
 * libraries are never linked into the same image (they define colliding
 * pga_* symbols by design — same names, different signatures).
 */
#ifndef PGA_MARSHAL_H
#define PGA_MARSHAL_H

/* '#'-format lengths (e.g. the y# used for expression constants) are
 * Py_ssize_t; CPython >= 3.12 refuses '#' formats without this. */
#define PY_SSIZE_T_CLEAN

#include <Python.h>

#include <cstdarg>
#include <cstdio>
#include <cstdlib>
#include <cstring>

namespace pga_marshal {

constexpr const char *kBridge = "libpga_tpu.capi_bridge";

inline PyObject *&bridge_module() {
    static PyObject *mod = nullptr;
    return mod;
}

static void print_py_error(const char *where) {
    std::fprintf(stderr, "pga_tpu: python error in %s:\n", where);
    PyErr_Print();
}

/* Initialize the embedded interpreter and import the bridge module. */
static bool ensure_python() {
    if (bridge_module()) return true;
    if (!Py_IsInitialized()) Py_InitializeEx(0);
    PyObject *mod = PyImport_ImportModule(kBridge);
    if (!mod) {
        print_py_error("import libpga_tpu.capi_bridge "
                       "(is the repo root on PYTHONPATH?)");
        return false;
    }
    bridge_module() = mod;
    return true;
}

/* Core marshaling: bridge.<name>(*args) with a Py_BuildValue format
 * string (always parenthesized at call sites, so the built value is a
 * tuple). Returns a new reference or nullptr (python error printed). */
static PyObject *call_va(const char *name, const char *fmt, va_list ap) {
    if (!ensure_python()) return nullptr;
    PyObject *callable = PyObject_GetAttrString(bridge_module(), name);
    if (!callable) {
        print_py_error(name);
        return nullptr;
    }
    PyObject *args = Py_VaBuildValue(fmt, ap);
    PyObject *out = args ? PyObject_CallObject(callable, args) : nullptr;
    Py_XDECREF(args);
    Py_DECREF(callable);
    if (!out) print_py_error(name);
    return out;
}

static PyObject *call(const char *name, const char *fmt, ...) {
    va_list ap;
    va_start(ap, fmt);
    PyObject *out = call_va(name, fmt, ap);
    va_end(ap);
    return out;
}

/* Integer-returning variant; -1 signals an error (None maps to 0). */
static long call_long(const char *name, const char *fmt, ...) {
    va_list ap;
    va_start(ap, fmt);
    PyObject *out = call_va(name, fmt, ap);
    va_end(ap);
    if (!out) return -1;
    long v = out == Py_None ? 0 : PyLong_AsLong(out);
    if (PyErr_Occurred()) {
        print_py_error(name);
        v = -1;
    }
    Py_DECREF(out);
    return v;
}

/* Convert a bytes result (float32 payload) into a malloc'd float buffer.
 * Consumes the reference. Optionally reports the byte length. */
static float *bytes_to_floats(PyObject *out, size_t *nbytes = nullptr) {
    if (!out) return nullptr;
    char *buf = nullptr;
    Py_ssize_t len = 0;
    if (PyBytes_AsStringAndSize(out, &buf, &len) != 0) {
        print_py_error("bytes result");
        Py_DECREF(out);
        return nullptr;
    }
    float *vals = static_cast<float *>(std::malloc(len));
    if (vals) std::memcpy(vals, buf, len);
    if (nbytes) *nbytes = static_cast<size_t>(len);
    Py_DECREF(out);
    return vals;
}

/* Handle packing: pga_t* carries the solver handle; population_t* carries
 * (solver_handle << 16 | pop_index + 1) so both sides stay opaque,
 * pointer-shaped, and never collide with NULL. */
template <typename SolverPtr>
static SolverPtr pack_solver(long h) {
    return reinterpret_cast<SolverPtr>(static_cast<intptr_t>(h));
}
template <typename SolverPtr>
static long solver_of(SolverPtr p) {
    return static_cast<long>(reinterpret_cast<intptr_t>(p));
}
template <typename PopPtr>
static PopPtr pack_pop(long solver, long index) {
    return reinterpret_cast<PopPtr>(
        static_cast<intptr_t>((solver << 16) | (index + 1)));
}
template <typename PopPtr>
static long pop_index_of(PopPtr pop) {
    return (static_cast<long>(reinterpret_cast<intptr_t>(pop)) & 0xffff) - 1;
}

}  // namespace pga_marshal

#endif /* PGA_MARSHAL_H */
