"""Example 1: continuous OneMax — the reference's first driver.

Reproduces ``/root/reference/test/test.cu``: population 40,000 × 100
genes, 100 generations, objective = sum of genes (``test.cu:24-30,37,43``).
There the objective is a CUDA ``__device__`` function handed over as a
device pointer; here it's the builtin name "onemax" and the whole run is
one jitted TPU program.

Run: python examples/onemax.py
"""

import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import time

import libpga_tpu as lp


def main():
    pga = lp.pga_init(seed=1234)
    pop = lp.pga_create_population(pga, 40_000, 100, lp.RANDOM_POPULATION)
    lp.pga_set_objective_function(pga, "onemax")

    t0 = time.perf_counter()
    gens = lp.pga_run(pga, 100)
    dt = time.perf_counter() - t0

    best = lp.pga_get_best(pga, pop)
    print(f"ran {gens} generations in {dt:.2f}s ({gens/dt:.1f} gens/sec)")
    print(f"best sum: {best.sum():.2f} / 100 (random init ~50)")

    # Early termination — promised by the reference header (pga.h:137-143),
    # never implemented there. Stop as soon as any genome sums past 99.
    pga2 = lp.pga_init(seed=99)
    lp.pga_create_population(pga2, 40_000, 100)
    lp.pga_set_objective_function(pga2, "onemax")
    gens = lp.pga_run(pga2, 10_000, target=99.0)
    print(f"with target=99.0: stopped after {gens} generations")

    # Convergence curve via in-run telemetry: the fused loop records
    # best/mean/std fitness, a diversity proxy, and a stall counter per
    # generation ON DEVICE (no host round trip mid-run) — the reference
    # could only printf the final best (pga.cu:230).
    pga3 = lp.PGA(
        seed=7,
        config=lp.PGAConfig(
            telemetry=lp.TelemetryConfig(history_gens=256)
        ),
    )
    pop3 = pga3.create_population(40_000, 100)
    pga3.set_objective("onemax")
    pga3.run(100)
    hist = pga3.history(pop3)
    print(f"telemetry: {hist}")
    for g in range(0, len(hist), 20):
        bar = "#" * int((hist.best[g] - 50) * 1.5)
        print(
            f"  gen {g + 1:3d}: best {hist.best[g]:6.2f} "
            f"mean {hist.mean[g]:6.2f} diversity {hist.diversity[g]:.4f} "
            f"{bar}"
        )
    print(
        f"  gen {len(hist):3d}: best {hist.best[-1]:6.2f} "
        f"(stalled for {int(hist.stall[-1])} gens)"
    )


if __name__ == "__main__":
    main()
