"""Example 3: travelling salesman — the reference's third driver.

Reproduces ``/root/reference/test3/``: a random distance matrix with a
planted cheap path i→i+1 of weight 10 (the construction of
``test3/gen.c:27-38``), tour decoded as ``city[i] = int(g[i] * L)``
(``test3/test.cu:31-32``), +10000 penalty per duplicate city
(``test3/test.cu:40-45``), and the driver's custom uniqueness-preserving
crossover (``test3/test.cu:48-64``) — here the builtin
``order_preserving_crossover``, a ``lax.scan`` over gene positions
vmapped across the population. Reference budget: pop 1000 × 1000 gens.

Run: python examples/tsp.py [n_cities]
"""

import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import sys

import numpy as np

import libpga_tpu as lp
from libpga_tpu.objectives import make_tsp, random_tsp_matrix
from libpga_tpu.ops.crossover import order_preserving_crossover
from libpga_tpu.ops.mutate import make_swap_mutate


def main():
    n_cities = int(sys.argv[1]) if len(sys.argv) > 1 else 100
    matrix = random_tsp_matrix(n_cities, seed=7)  # planted path length: 10*(L-1)

    pga = lp.pga_init(seed=5)
    pop = lp.pga_create_population(pga, 1000, n_cities, lp.RANDOM_POPULATION)
    lp.pga_set_objective_function(pga, make_tsp(matrix))
    lp.pga_set_crossover_function(pga, order_preserving_crossover)
    lp.pga_set_mutate_function(pga, make_swap_mutate(rate=0.5))

    lp.pga_run(pga, 1000)

    best = lp.pga_get_best(pga, pop)
    tour = np.clip(np.floor(best * n_cities).astype(int), 0, n_cities - 1)
    unique = len(set(tour.tolist()))
    length = float(matrix[tour[:-1], tour[1:]].sum())
    print(f"cities: {n_cities}  unique in best tour: {unique}")
    print(f"tour length: {length:.0f}  (planted cheap path: {10*(n_cities-1)}, "
          f"random tour ~{int(matrix.mean() * (n_cities-1))})")
    assert unique == n_cities, "custom crossover must preserve uniqueness"


if __name__ == "__main__":
    main()
