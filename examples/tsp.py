"""Example 3: travelling salesman — the reference's third driver.

Reproduces ``/root/reference/test3/``: a random distance matrix with a
planted cheap path i→i+1 of weight 10 (the construction of
``test3/gen.c:27-38``), tour decoded as ``city[i] = int(g[i] * L)``
(``test3/test.cu:31-32``), +10000 penalty per duplicate city
(``test3/test.cu:40-45``), and the driver's custom uniqueness-preserving
crossover (``test3/test.cu:48-64``) — here the builtin
``order_preserving_crossover``, a ``lax.scan`` over gene positions
vmapped across the population. Reference budget: pop 1000 × 1000 gens.

Run: python examples/tsp.py [n_cities]

The reference caps at 110 cities (``test3/test.cu:22-24`` — the matrix
must fit ``__constant__`` memory); here any size runs, on the fused
kernel's runtime order-crossover walk. Beyond a few hundred cities the
distance-MATRIX objective's one-hot evaluation is O(L³)/genome, so the
example switches to the Euclidean coordinate objective
(``make_tsp_coords``, O(L²)) — try ``python examples/tsp.py 1000``.
"""

import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import sys

import numpy as np

import libpga_tpu as lp
from libpga_tpu.objectives import (
    make_tsp,
    make_tsp_coords,
    random_tsp_coords,
    random_tsp_matrix,
)
from libpga_tpu.ops.crossover import order_preserving_crossover
from libpga_tpu.ops.mutate import make_swap_mutate


def main():
    n_cities = int(sys.argv[1]) if len(sys.argv) > 1 else 100
    euclidean = n_cities > 300
    if euclidean:
        coords = random_tsp_coords(n_cities, seed=7)
        objective = make_tsp_coords(coords)
    else:
        matrix = random_tsp_matrix(n_cities, seed=7)  # planted path: 10*(L-1)
        objective = make_tsp(matrix)

    pga = lp.pga_init(seed=5)
    pop_size = 1000 if not euclidean else 8192
    gens = 1000  # long tours converge slowly; ~45 gens/sec at 1000 cities
    pop = lp.pga_create_population(pga, pop_size, n_cities, lp.RANDOM_POPULATION)
    lp.pga_set_objective_function(pga, objective)
    lp.pga_set_crossover_function(pga, order_preserving_crossover)
    lp.pga_set_mutate_function(pga, make_swap_mutate(rate=0.5))

    lp.pga_run(pga, gens)

    best = lp.pga_get_best(pga, pop)
    tour = np.clip(np.floor(best * n_cities).astype(int), 0, n_cities - 1)
    unique = len(set(tour.tolist()))
    print(f"cities: {n_cities}  unique in best tour: {unique}")
    if euclidean:
        xy = coords[tour]
        length = float(np.sqrt(((xy[1:] - xy[:-1]) ** 2).sum(axis=1)).sum())
        rand_xy = coords[np.random.default_rng(0).permutation(n_cities)]
        rand_len = float(
            np.sqrt(((rand_xy[1:] - rand_xy[:-1]) ** 2).sum(axis=1)).sum()
        )
        print(f"tour length: {length:.0f}  (random tour ~{rand_len:.0f})")
        assert length < 0.8 * rand_len, "no optimization happened"
    else:
        length = float(matrix[tour[:-1], tour[1:]].sum())
        print(f"tour length: {length:.0f}  (planted cheap path: "
              f"{10*(n_cities-1)}, random tour "
              f"~{int(matrix.mean() * (n_cities-1))})")
    assert unique == n_cities, "custom crossover must preserve uniqueness"


if __name__ == "__main__":
    main()
