"""Symbolic regression with tree-based genetic programming (ISSUE 11).

Programs are linear postfix trees packed into the library's ordinary
gene vectors (two genes per token: opcode + operand), bred by
size-fair subtree crossover and chained subtree/point mutation, and
scored by the fused stack-machine interpreter — dataset-resident
-RMSE fitness, so a score of exactly 0.0 means the target expression
was recovered bit-for-bit on the sample batch.

    JAX_PLATFORMS=cpu python examples/symbolic_regression.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import numpy as np

from libpga_tpu import GPConfig, PGA, PGAConfig, TelemetryConfig
from libpga_tpu.gp import (
    decode_expression,
    make_dataset,
    make_gp_mutate,
    make_subtree_crossover,
    random_population,
    symbolic_regression,
)

POP, GENS, SEED = 512, 120, 0


def main() -> None:
    # The search space: up to 12-token programs over two inputs with a
    # small constant table and the arithmetic/trig function set.
    gp = GPConfig(max_nodes=12, n_vars=2)
    # Ground truth to recover: f(a, b) = a*b + sin(a).
    X, y = make_dataset(
        lambda a, b: a * b + np.sin(a), n_samples=64, n_vars=2, seed=1
    )

    pga = PGA(seed=SEED, config=PGAConfig(
        use_pallas=False,
        selection="truncation",
        elitism=2,
        telemetry=TelemetryConfig(history_gens=GENS),
    ))
    pga.set_objective(symbolic_regression(X, y, gp=gp))
    pga.set_crossover(make_subtree_crossover(gp))
    pga.set_mutate(make_gp_mutate(gp))
    # GP populations install explicitly: random WELL-FORMED programs
    # (ramped grow init), not uniform gene noise.
    handle = pga.install_population(
        random_population(jax.random.key(SEED), POP, gp)
    )

    gens = pga.run(GENS, target=-1e-6)
    best, score = pga.get_best_with_score(handle)
    hist = pga.history(handle)

    print(f"ran {gens} generations (pop {POP}, {gp.max_nodes}-token programs)")
    print(f"best RMSE: {-score:.3g}")
    print(f"best program: {decode_expression(best, gp)}")
    if hist is not None and len(hist) > 1:
        mid = len(hist) // 2
        print(
            "convergence (best -RMSE): "
            f"gen 1: {hist.best[0]:.3g} -> "
            f"gen {mid + 1}: {hist.best[mid]:.3g} -> "
            f"gen {len(hist)}: {hist.best[-1]:.3g}"
        )


if __name__ == "__main__":
    main()
