"""Streaming evolution: an ask/tell tenant with suspend/resume.

The interactive workload class (ISSUE 12): instead of submitting a
batch run and reading one result, a TENANT keeps a population open and
steers it with fitnesses the library never sees — here, recovering a
hidden target vector whose only oracle is an external black-box
scoring function. Halfway through, the tenant suspends (one atomic
checkpoint + sidecar) and resumes — in real deployments on a DIFFERENT
fleet worker — bit-identically, then finishes the recovery.

Run:  JAX_PLATFORMS=cpu python examples/streaming_session.py
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from libpga_tpu import PGAConfig
from libpga_tpu.streaming import EvolutionSession

GENOME_LEN = 16
ASK = 16
ROUNDS = 160


def main() -> None:
    # The hidden target: only the external oracle below knows it.
    rng = np.random.default_rng(42)
    target = rng.uniform(0.1, 0.9, size=GENOME_LEN).astype(np.float32)

    def external_oracle(genomes: np.ndarray) -> np.ndarray:
        """Black-box fitness the tenant measures OUTSIDE the library
        (a lab instrument, a simulator, a user's rating...)."""
        return -np.sum((genomes - target) ** 2, axis=1)

    # The internal objective is irrelevant here — evolution is driven
    # purely by told fitnesses — but sessions accept any builtin, and
    # step() would use it if called. Gaussian mutation suits the
    # continuous search space better than the default point flip.
    from libpga_tpu.ops.mutate import make_gaussian_mutate

    session = EvolutionSession(
        "sphere", size=256, genome_len=GENOME_LEN, seed=0,
        config=PGAConfig(use_pallas=False),
        mutate=make_gaussian_mutate(rate=0.5, sigma=0.08),
    )

    # Seed the session with one externally scored batch, then loop:
    # ask -> measure externally -> tell.
    cand = session.ask(ASK)
    session.tell(cand, external_oracle(cand))
    best = float(external_oracle(cand).max())
    print(f"start: best external fitness {best:.4f}")

    for round_idx in range(ROUNDS // 2):
        cand = session.ask(ASK)
        fitness = external_oracle(cand)
        session.tell(cand, fitness)
        best = max(best, float(fitness.max()))
    print(f"after {ROUNDS // 2} ask/tell rounds: best {best:.4f}")

    # Suspend at a generation boundary: checkpoint + sidecars, written
    # commit-last, so the tenant can reconnect anywhere the file is
    # visible (Fleet.session_store() serves these off the fleet spool).
    path = os.path.join(
        tempfile.mkdtemp(prefix="pga-streaming-"), "tenant.ckpt.npz"
    )
    session.suspend(path)
    print(f"suspended -> {path}")

    # Objective/config come back from the suspend meta; the custom
    # mutation operator is an opaque callable, so it is re-provided.
    resumed = EvolutionSession.resume(
        path, mutate=make_gaussian_mutate(rate=0.5, sigma=0.08)
    )
    for round_idx in range(ROUNDS // 2):
        cand = resumed.ask(ASK)
        fitness = external_oracle(cand)
        resumed.tell(cand, fitness)
        best = max(best, float(fitness.max()))

    genome, _ = resumed.best()
    err = float(np.max(np.abs(genome - target)))
    print(
        f"after resume + {ROUNDS // 2} more rounds: best {best:.4f}, "
        f"max |gene - target| = {err:.3f}"
    )
    if best < -0.2:
        raise SystemExit("target not recovered — something regressed")
    print("recovered the hidden target through ask/tell alone")


if __name__ == "__main__":
    main()
