"""Example 2: bounded knapsack — the reference's second driver.

Reproduces ``/root/reference/test2/test.cu``: 6 items (values/weights in
``test2/test.cu:22-26``), at most 2 copies each, capacity 10; gene i
decodes to a count as ``int(g[i] * 2)``; infeasible genomes score the
negative overweight (``test2/test.cu:28-36``). The reference runs pop 100
for 5 generations; that tiny budget rarely finds the optimum, so this
example also shows a proper run.

Known optimum: one copy of item 2 (value 250, weight 6) + one of item 3
(value 35, weight 4) = value 285 at weight 10.

Run: python examples/knapsack.py
"""

import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import numpy as np

import libpga_tpu as lp
from libpga_tpu.objectives import default_knapsack

MAX_ITEM_COUNT = 2


def decode(genome):
    return np.floor(np.asarray(genome) * MAX_ITEM_COUNT).astype(int)


def main():
    # The reference's exact budget: pop 100, 5 generations.
    pga = lp.pga_init(seed=0)
    pop = lp.pga_create_population(pga, 100, 6, lp.RANDOM_POPULATION)
    lp.pga_set_objective_function(pga, "knapsack")
    lp.pga_run(pga, 5)
    best = lp.pga_get_best(pga, pop)
    print("reference budget (100×5):  counts", decode(best),
          "value", float(default_knapsack(best)))

    # A sensible budget on TPU costs nothing extra.
    pga = lp.pga_init(seed=0)
    pop = lp.pga_create_population(pga, 4096, 6, lp.RANDOM_POPULATION)
    lp.pga_set_objective_function(pga, "knapsack")
    lp.pga_run(pga, 30)
    best = lp.pga_get_best(pga, pop)
    print("proper budget (4096×30):   counts", decode(best),
          "value", float(default_knapsack(best)), "(optimum 285)")


if __name__ == "__main__":
    main()
