"""Example 4: Rastrigin-30D with Gaussian mutation on an island model.

The "Rastrigin-30D real-valued GA (float chromosome, Gaussian mutation)"
config from BASELINE.json, run as the island GA the reference declared
but never implemented (``pga_run_islands`` spec ``include/pga.h:144-150``,
empty stub ``src/pga.cu:393-395``): 8 islands, ring migration of the top
5% every 20 generations. Pass --mesh to shard islands across all visible
devices (one island group per core, migration over ICI).

Optimum is 0 at x=0 (genes 0.5); typical single-island GA stalls in a
local optimum — migration keeps diversity flowing.

Run: python examples/rastrigin_islands.py [--mesh]
"""

import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import sys

import libpga_tpu as lp
from libpga_tpu import PGAConfig, parallel
from libpga_tpu.ops.mutate import make_gaussian_mutate


def main():
    use_mesh = "--mesh" in sys.argv
    # Elitism (a capability the reference lacks) matters on multimodal
    # surfaces: the per-island best survives between migration events.
    config = PGAConfig(elitism=2)
    pga = lp.pga_init(seed=3, config=config)
    for _ in range(8):
        lp.pga_create_population(pga, 4096, 30, lp.RANDOM_POPULATION)
    lp.pga_set_objective_function(pga, "rastrigin")
    lp.pga_set_mutate_function(pga, make_gaussian_mutate(rate=0.15, sigma=0.05))

    mesh = parallel.default_mesh() if use_mesh else None
    if mesh is not None:
        print(f"sharding 8 islands across {mesh.devices.size} device(s)")

    # Anneal sigma across phases: a constant step size equilibrates around
    # -60; shrinking it walks the population into the global basin. On the
    # fused TPU path mutation rate/sigma are runtime inputs, so all phases
    # reuse ONE compiled program.
    gens = 0
    for sigma in (0.05, 0.01, 0.002):
        lp.pga_set_mutate_function(
            pga, make_gaussian_mutate(rate=0.15, sigma=sigma)
        )
        gens += lp.pga_run_islands(pga, 134, 20, 0.05, mesh=mesh)
    best = lp.pga_get_best_all(pga)
    from libpga_tpu.objectives import rastrigin

    print(f"ran {gens} generations over 8 islands")
    print(f"best Rastrigin value: {float(rastrigin(best)):.3f} (optimum 0)")
    top = lp.pga_get_best_top_all(pga, 3)
    print(f"global top-3 values: "
          f"{[round(float(rastrigin(g)), 3) for g in top]}")


if __name__ == "__main__":
    main()
