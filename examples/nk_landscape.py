"""Example 5: epistatic fitness at scale — NK landscape and deceptive trap.

The "NK-landscape / deceptive-trap fitness (epistatic, 4M population)"
config from BASELINE.json. Nothing like this exists in the reference —
its largest driver is 40k individuals — but the architecture is the same
GA; only the objective and the population size change. The NK gather
(each locus indexes a (k+1)-bit neighborhood code into its own table row)
runs fully on-device.

Run: python examples/nk_landscape.py [pop_exp]   (default 2^22 = 4M)
"""

import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import sys
import time

import libpga_tpu as lp
from libpga_tpu.objectives import make_deceptive_trap, make_nk_landscape


def main():
    pop_exp = int(sys.argv[1]) if len(sys.argv) > 1 else 22
    pop = 1 << pop_exp
    n, k = 64, 3

    pga = lp.pga_init(seed=11)
    h = lp.pga_create_population(pga, pop, n, lp.RANDOM_POPULATION)
    lp.pga_set_objective_function(pga, make_nk_landscape(n, k, seed=0))
    lp.pga_run(pga, 2)  # compile + warm before timing
    t0 = time.perf_counter()
    gens = lp.pga_run(pga, 50)
    dt = time.perf_counter() - t0
    best = lp.pga_get_best(pga, h)
    from libpga_tpu.objectives import classic

    nk = classic.make_nk_landscape(n, k, seed=0)
    print(f"NK(n={n}, k={k}) pop {pop:,}: {gens} gens in {dt:.1f}s "
          f"({gens/dt:.1f} gens/sec), best fitness {float(nk(best)):.4f}")

    # Deceptive trap: gradient points away from the optimum; selection
    # pressure alone mostly falls into the deceptive attractor — the
    # classic hard case for a plain GA.
    trap = make_deceptive_trap(trap_size=5)
    pga2 = lp.pga_init(seed=12)
    h2 = lp.pga_create_population(pga2, pop // 4, 60, lp.RANDOM_POPULATION)
    lp.pga_set_objective_function(pga2, trap)
    lp.pga_run(pga2, 50)
    best2 = lp.pga_get_best(pga2, h2)
    print(f"deceptive-trap(5) pop {pop//4:,}: best {float(trap(best2)):.0f} "
          f"/ optimum 60")


if __name__ == "__main__":
    main()
