"""Hyperparameter sweep through the serving queue (ISSUE 4).

The "before" version of this script is the loop every tuning workflow
writes: for each candidate mutation rate, build a solver, run it, read
the result — N requests, N compile pipelines, N synchronous dispatches.
The serving subsystem turns the same sweep into submit() calls: every
configuration here shares one shape signature (the rate is a runtime
input), so the whole sweep executes as ONE batched device program with
one cached compilation, and results stream back through tickets.

    JAX_PLATFORMS=cpu python examples/serving_sweep.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from libpga_tpu import PGAConfig, ServingConfig, TelemetryConfig
from libpga_tpu.serving import BatchedRuns, RunQueue, RunRequest

POP, LEN, GENS = 8192, 64, 30
RATES = [0.005, 0.01, 0.02, 0.05, 0.1, 0.2, 0.35, 0.5]


def main() -> None:
    executor = BatchedRuns(
        "onemax",
        config=PGAConfig(
            use_pallas=False,
            telemetry=TelemetryConfig(history_gens=GENS),
        ),
    )
    queue = RunQueue(
        executor,
        serving=ServingConfig(max_batch=len(RATES), max_wait_ms=10.0),
    )

    # The sweep: one submit per candidate — no loop-carried engine, no
    # per-candidate compile. The final submit fills the bucket and
    # launches the mega-run.
    tickets = {
        rate: queue.submit(
            RunRequest(
                size=POP, genome_len=LEN, n=GENS,
                seed=42,  # identical seed isolates the rate's effect
                mutation_rate=rate,
            )
        )
        for rate in RATES
    }

    print(f"rate      best     mean(last)  stall  (pop {POP}x{LEN}, "
          f"{GENS} gens, shared seed)")
    best_rate, best_score = None, -float("inf")
    for rate, ticket in tickets.items():
        result = ticket.result(timeout=600)
        hist = result.history
        print(
            f"{rate:<8}  {result.best_score:7.3f}  {hist.mean[-1]:9.3f}"
            f"  {int(hist.stall[-1]):5d}"
        )
        if result.best_score > best_score:
            best_rate, best_score = rate, result.best_score
    queue.close()
    print(f"\nwinner: rate={best_rate} (best {best_score:.3f})")
    from libpga_tpu.serving import COUNTERS

    counters = COUNTERS.snapshot()
    print(
        f"compiled programs built: {counters.get('builds', 0)} "
        f"(the whole sweep shares one bucket)"
    )

    # Per-ticket latency summary (ISSUE 6): every ticket was stamped
    # submit -> admit -> launch -> complete -> readback on its way
    # through the queue; the breakdown survives result().
    print("\nlatency   queue_wait  execute   readback  e2e  (ms)")
    for rate, ticket in tickets.items():
        lat = ticket.latency()
        print(
            f"{rate:<8}  {lat['queue_wait_ms']:9.2f}  "
            f"{lat['execute_ms']:8.2f}  {lat['readback_ms']:8.2f}  "
            f"{lat['e2e_ms']:8.2f}"
        )
    from libpga_tpu.utils.metrics import REGISTRY

    e2e = REGISTRY.histogram("serving.ticket.e2e_ms").snapshot()
    print(
        f"\np50 {e2e.p50:.1f} ms / p99 {e2e.p99:.1f} ms end-to-end "
        f"over {e2e.count} tickets"
    )


if __name__ == "__main__":
    main()
