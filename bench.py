"""Headline benchmark: generations/sec on 1M-population OneMax, one chip.

The workload is the reference's first driver scaled to the BASELINE.json
target: the reference runs pop 40,000 × 100 genes × 100 generations
(``/root/reference/test/test.cu:37,43,22``) as ~79 chunked kernel launches ×
3 operators × 100 generations, each followed by a full device sync
(``/root/reference/src/pga.cu:62-77,269``). Here the same GA — tournament-2
selection, uniform crossover, 0.01 point mutation — runs as ONE jitted
program per whole run at pop 1,048,576 × 100.

Prints exactly one JSON line. Headline fields:
  metric/value/unit/vs_baseline — f32 gens/sec vs the reference's analytic
    launch-bound floor (see below);
  ms_per_gen, achieved_tflops, mfu — chip-relative figures so progress is
    measured against the hardware, not only against the reference's worst
    property. The FLOPs model counts ONLY the one-hot parent-selection
    matmuls (2·K²·Lp FLOPs per (K,K)@(K,Lp) matmul, 4 matmuls/deme for
    f32 hi/lo genes, 2 for bf16 → P·K·Lp·8 (f32) or ·4 (bf16)
    FLOPs/generation) — selection sampling, the per-generation rank
    sort, PRNG, crossover/mutation, and fused evaluation are real work
    the model deliberately excludes, so treat mfu as a matmul-
    utilization gauge (gens/sec is the headline; see BASELINE.md);
  achieved_hbm_gbps / hbm_frac_of_peak — population HBM traffic per
    second under the floor model of ``hbm_bytes_per_gen`` (genome +
    score read/write per launch, /T for the multi-generation kernel)
    against the chip's 819 GB/s: the per-round tracker for the round-4
    finding that launch IO is mostly pipeline-hidden (a LOW fraction at
    high gens/sec means compute-bound, which is where the kernel now
    lives — see BASELINE.md);
  bf16_* — the bfloat16 gene mode (single exact selection matmul, half
    the FLOPs; genes at bf16 resolution);
  islands_* — 8-island × 131,072 OneMax with ring migration every 10
    generations, the BASELINE.json island config on one chip.

``vs_baseline`` is measured against an analytic model of the reference on a
modern datacenter GPU (see BASELINE.md — the reference publishes no numbers,
so the baseline is its launch-bound execution model: ceil(pop/512) serialized
launches × 3 operators × ~3.5 µs launch+sync overhead per generation), i.e.
values > 1 mean faster than the reference's architecture could possibly go
regardless of its per-thread compute speed.

Timing: the tunneled bench chip memoizes identical executions and varies
~±15% between process states, so every figure is a two-length
subtraction — (min over tries of time(150 gens)) − (min over tries of
time(50 gens)), divided by 100. Warm-up, compile, and dispatch overheads
cancel in the difference, and taking the per-length minima FIRST keeps
the estimator bounded by true hardware speed (a max over per-try deltas
would instead select the try where noise shrank the difference).

INTERLEAVED ROUNDS (round-5 protocol, from the round-4 lesson in
BASELINE.md): sequential same-process measurements minutes apart drift
more than the effects being compared, so the five benchmarks (f32,
islands, bf16, ref40k, tsp1k) are measured in ``ROUNDS`` alternating rounds
with a fixed per-round ordering — every metric reports the MEDIAN and
IQR across rounds (``*_median`` / ``*_iqr``), and the islands/single-
population ratio is computed per round from ADJACENT measurements
before taking its median, so cross-round deltas in BENCH_r{N}.json are
attributable to code, not chip state. The legacy flat keys carry the
medians for continuity.
"""

from __future__ import annotations

import json
import math
import os
import time


POP = 1 << 20  # 1,048,576
GENOME_LEN = 100

# Serving arm (ISSUE 4): N concurrent 16k x 100 OneMax requests, each a
# SERVING_GENS-generation run, batched mega-run vs the sequential
# per-request PGA.run pipeline (a fresh engine per request — the
# "compile caches are per-engine-instance" baseline the serving
# subsystem exists to kill; the warm-engine loop is ALSO reported for
# the charitable reading).
SERVING_POP = 1 << 14  # 16,384
SERVING_GENS = 10
SERVING_WIDTHS = (1, 8, 32)

# Population-sharding arm (ISSUE 7): one SHARDED_POP x SHARDED_LEN
# OneMax population split SHARDED_SHARDS ways (parallel/shard_pop.py),
# A/B'd against the collective-ablated loop (the same program minus the
# per-generation all_gather) and the unsharded engine path — so the
# one-collective-per-generation cost model is tracked from day one.
SHARDED_POP = 1 << 16  # 65,536
SHARDED_LEN = 64
SHARDED_SHARDS = 4
SHARDED_GENS_PER_CALL = 10
V5E_BF16_PEAK = 197e12  # TPU v5e: 197 TFLOP/s bf16 per chip
V5E_HBM_PEAK = 819e9  # TPU v5e: 819 GB/s HBM bandwidth per chip

# Version of the emitted JSON artifact's schema. Bump when keys are
# added/renamed; tools/ci.sh gates on the newest artifact speaking a
# version this code still parses (older artifacts keep their stamp —
# the perf-history normalizer, libpga_tpu/perf/history.py, reads every
# generation).
# 1 = rounds <= 7 implicit schema + the provenance block below.
# 2 = + git_rev / monotonic run_id provenance (ISSUE 17 — the identity
#     fields the perf-history DB orders and dedupes ingested
#     artifacts by).
SCHEMA_VERSION = 2


def enable_persistent_cache():
    """Wire utils/profiling.enable_compilation_cache into the bench hot
    path (ISSUE 4 satellite — it existed since round 2 but nothing
    called it here): island/fused kernels then reload in milliseconds
    on rerun instead of recompiling. Returns the cache dir for the
    provenance stamp.

    TPU sessions only: on the jaxlib-0.4.37 CPU backend, executing
    persistent-cache-deserialized executables with donated buffers
    corrupts the runtime heap (found by the ISSUE 5 chaos matrix —
    donation-heavy checkpoint/restore loops segfault or silently
    corrupt; see tools/ci.sh). CPU compiles are cheap; the cache's
    motivation is tens-of-seconds Mosaic compiles. Returns None on
    non-TPU backends (provenance then omits the cache fields)."""
    import jax

    if jax.default_backend() != "tpu":
        return None
    from libpga_tpu.utils.profiling import enable_compilation_cache

    path = os.environ.get(
        "JAX_COMPILATION_CACHE_DIR", "~/.cache/libpga_tpu_xla"
    )
    enable_compilation_cache(path)
    return os.path.expanduser(path)


def _cache_entries(path: str) -> int:
    try:
        return len([f for f in os.listdir(path) if not f.startswith(".")])
    except OSError:
        return 0


def provenance(cache_dir: str = None) -> dict:
    """Measurement-context stamp for the JSON artifact (ISSUE 3
    satellite): WHAT ran WHERE, plus the cross-process caveat
    BASELINE.md documents — carried on the artifact itself so a number
    read in isolation cannot be mistaken for a cross-process-comparable
    one. ``cache_dir`` set stamps the persistent-compilation-cache
    provenance (dir + entry count at emit time — entries present before
    a run mean its compiles were disk-cache hits)."""
    import jax

    dev = jax.devices()[0]
    out = {
        "schema_version": SCHEMA_VERSION,
        "backend": jax.default_backend(),
        "device_kind": getattr(dev, "device_kind", str(dev)),
        "process_state_note": (
            "BASELINE.md documents +/-15% drift across processes on the "
            "tunneled chip; only medians measured INTERLEAVED within one "
            "process are decision-grade — do not compare this artifact's "
            "absolute numbers against another process's run"
        ),
    }
    if cache_dir is not None:
        out["compilation_cache_dir"] = cache_dir
        out["compilation_cache_entries"] = _cache_entries(cache_dir)
    # Schema-2 identity stamps (ISSUE 17): the git revision the numbers
    # were measured at and a monotonic run id — what the perf-history
    # DB (libpga_tpu/perf/history.py) orders and dedupes artifacts by.
    # Never allowed to break a bench run.
    try:
        from libpga_tpu.perf.history import git_rev, new_run_id

        out["git_rev"] = git_rev()
        out["run_id"] = new_run_id()
    except Exception:
        pass
    return out


def hbm_bytes_per_gen(pop, genome_lanes, gene_bytes, T: int) -> int:
    """Population HBM traffic per generation under the fused run loop:
    one genome read + one genome write + one score read + one score
    write per KERNEL LAUNCH, divided by the T generations each launch
    breeds (the multi-generation kernel keeps demes VMEM-resident
    between sub-generations; T=1 is the one-generation kernel, whose
    score side also carries the rank sort's read+write — folded in as
    the same 2×4 bytes/row). Deliberately a FLOOR model: PRNG, SMEM
    scalars, and compiler spills are excluded, so fraction-of-peak
    overstates nothing. Tracks the round-4 finding that the launch IO
    is mostly pipeline-hidden — a small fraction means the kernel is
    compute-bound, not that bandwidth is wasted (see BASELINE.md)."""
    genome = 2 * pop * genome_lanes * gene_bytes
    scores = 2 * pop * 4
    return (genome + scores) // T


def reference_floor_seconds_per_gen() -> float:
    """Analytic lower bound on the reference's per-generation wall time.

    The reference serializes ceil(pop/512) kernel launches per operator, 3
    operators per generation, each launch followed by cudaDeviceSynchronize
    (``src/pga.cu:62-77``: blocks=8 × threads=64 = 512 individuals/launch),
    plus one cuRAND pool refill. Taking ~3.5 µs as an optimistic
    launch+sync round-trip on a modern GPU and ignoring ALL compute and
    memory time, the floor is launches × 3.5 µs.
    """
    launches_per_op = math.ceil(POP / 512)
    return launches_per_op * 3 * 3.5e-6


def _fire_bench_measure(n: int) -> None:
    """ISSUE 17 fault site on the bench measurement path: a
    ``kind="slow"`` plan (``robustness/faults``) stalls ``param``
    seconds PER GENERATION inside the timed window — a
    work-proportional synthetic regression. Per-generation matters:
    the two-length-subtraction estimator cancels any constant per-call
    overhead by construction, so only a work-scaled slowdown is
    measurable — exactly like a real kernel regression, which is what
    lets tools/perf_gate.py prove its trip wire through the REAL
    measurement path. With no plan installed this is one attribute
    read (the disabled-path purity stance of every site)."""
    from libpga_tpu.robustness import faults as _faults

    if _faults.PLAN is not None and _faults.PLAN.fire("bench.measure"):
        time.sleep(_faults.PLAN.param_of("bench.measure") * n)


def _best_gps(run, lo: int = 50, hi: int = 150, tries: int = 3) -> float:
    """Generations/sec via two-length subtraction of per-length minima.

    min(t_hi) − min(t_lo) across tries: each minimum is the least-noisy
    observation of that length, so the difference cannot be shrunk below
    the true hardware time by a single lucky/unlucky pairing (the failure
    mode of max-over-deltas). Raises when the subtraction is degenerate
    rather than publishing a fabricated figure.
    """
    t_lo, t_hi = [], []
    for _ in range(tries):
        t0 = time.perf_counter()
        _fire_bench_measure(lo)
        run(lo)
        t_lo.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        _fire_bench_measure(hi)
        run(hi)
        t_hi.append(time.perf_counter() - t0)
    delta = min(t_hi) - min(t_lo)
    if delta <= 0:
        raise RuntimeError(
            f"degenerate timing: min t({hi})={min(t_hi):.4f}s <= "
            f"min t({lo})={min(t_lo):.4f}s — refusing to report"
        )
    return (hi - lo) / delta


ROUNDS = 5  # interleaved measurement rounds (>=5 per the verdict protocol)


def _sample_gps(run, lo, hi) -> float:
    """One round's sample: a two-length subtraction with 2 tries per
    length; one retry absorbs a round where drift made the subtraction
    degenerate (the estimator refuses to fabricate, _best_gps)."""
    try:
        return _best_gps(run, lo, hi, tries=2)
    except RuntimeError:
        return _best_gps(run, lo, hi, tries=2)


def _median_iqr(xs) -> tuple:
    import statistics

    med = statistics.median(xs)
    if len(xs) >= 4:
        q = statistics.quantiles(xs, n=4)
        iqr = q[2] - q[0]
    else:
        iqr = max(xs) - min(xs)
    return med, iqr


def setup_single(gene_dtype, telemetry_gens: int = 0):
    """One-population 1M×100 OneMax runner at the given gene dtype.
    ``telemetry_gens`` > 0 enables the on-device history carry
    (``utils/telemetry``) — the telemetry-overhead A/B arm."""
    from libpga_tpu import PGA, PGAConfig, TelemetryConfig

    tel = TelemetryConfig(history_gens=telemetry_gens) if telemetry_gens else None
    pga = PGA(
        seed=42,
        config=PGAConfig(use_pallas=True, gene_dtype=gene_dtype, telemetry=tel),
    )
    pga.create_population(POP, GENOME_LEN)
    pga.set_objective("onemax")
    if not pga._pallas_gate():
        raise RuntimeError(
            "Pallas fast path not engaged (non-TPU backend?) — the FLOPs "
            "model below describes matmuls that would never execute"
        )
    pga.run(5)  # compile + warm caches
    return lambda n: pga.run(n)


def setup_reference_scale():
    """The reference driver's EXACT workload shape: population 40,000
    (no power-of-two deme divisor — exercises the internal padding
    path) × 100 genes, f32."""
    from libpga_tpu import PGA, PGAConfig

    pga = PGA(seed=3, config=PGAConfig(use_pallas=True))
    pga.create_population(40_000, GENOME_LEN)
    pga.set_objective("onemax")
    pga.run(5)
    return lambda n: pga.run(n)


def setup_tsp1k():
    """1,000-city Euclidean TSP at pop 8,192 — 10× the reference
    driver's 110-city cap (``test3/test.cu:22-24``): order crossover +
    swap mutation + the gene-major fused evaluation
    (``make_tsp_coords(duplicate_mode="genes")``), all inside one
    kernel launch per generation."""
    from libpga_tpu import PGA, PGAConfig
    from libpga_tpu.objectives.classic import (
        make_tsp_coords, random_tsp_coords,
    )
    from libpga_tpu.ops.crossover import order_preserving_crossover
    from libpga_tpu.ops.mutate import make_swap_mutate

    tsp = make_tsp_coords(
        random_tsp_coords(1000, seed=2), duplicate_mode="genes"
    )
    pga = PGA(seed=11, config=PGAConfig(use_pallas=True))
    pga.create_population(8192, 1000)
    pga.set_objective(tsp)
    pga.set_crossover(order_preserving_crossover)
    pga.set_mutate(make_swap_mutate(0.5))
    pga.run(3)
    return lambda n: pga.run(n)


def setup_islands():
    """8 islands × 131,072 × 100, ring migration of the top 5% every 10
    generations (BASELINE.json island config), vmapped on one chip."""
    from libpga_tpu import PGA, PGAConfig

    pga = PGA(seed=7, config=PGAConfig(use_pallas=True))
    for _ in range(8):
        pga.create_population(131_072, GENOME_LEN)
    pga.set_objective("onemax")
    pga.run_islands(10, 10, 0.05)  # compile
    return lambda n: pga.run_islands(n, 10, 0.05)


def serving_arm(rounds: int = ROUNDS) -> dict:
    """The permanent serving A/B (ISSUE 4): runs/sec for N concurrent
    SERVING_POP x GENOME_LEN OneMax requests of SERVING_GENS generations
    each, batched mega-run vs the sequential per-request ``PGA.run``
    pipeline, interleaved per round.

    The workload is a MUTATION-RATE SWEEP — every request carries a
    distinct (seed, mutation_rate) pair, fresh rates each round. This
    is the serving subsystem's load-bearing case: rates are runtime
    inputs of the batched program (one bucket, one compilation for the
    entire stream), while the engine bakes the rate into its compiled
    run loop — so EVERY sequential request pays the trace+compile
    pipeline, and neither the per-engine jit cache nor the persistent
    XLA disk cache (wired below, distinct HLO constants per rate) can
    amortize it. A same-config request stream WOULD let the disk cache
    rescue the sequential loop after its first request; the artifact
    reports that regime separately as serving_seq_samecfg_runs_per_sec.

    Protocol note: unlike the gens/sec arms, the quantity here is
    END-TO-END request service rate, so samples time whole executions
    (no two-length subtraction — the per-request constants ARE the
    effect under test). The batched arm is warm (its one compile per
    bucket amortizes to zero over the request stream; the cache
    counters in serving_cache prove the steady state compiles nothing).
    """
    from libpga_tpu import PGA, PGAConfig
    from libpga_tpu.ops.mutate import make_point_mutate
    from libpga_tpu.serving import BatchedRuns, RunRequest

    ex = BatchedRuns("onemax", config=PGAConfig(use_pallas=False))

    def sweep(n_reqs, base):
        """Distinct (seed, rate) per request; rates never repeat across
        rounds, as a sweep server's traffic never does."""
        return [
            (base + i, 0.005 + 2e-5 * (base % 7919) + 0.002 * i)
            for i in range(n_reqs)
        ]

    def serve_batched(n_reqs, base):
        results = ex.run([
            RunRequest(
                size=SERVING_POP, genome_len=GENOME_LEN, n=SERVING_GENS,
                seed=seed, mutation_rate=rate,
            )
            for seed, rate in sweep(n_reqs, base)
        ])
        for r in results:
            r.block()

    def serve_fresh(n_reqs, base):
        for seed, rate in sweep(n_reqs, base):
            pga = PGA(seed=seed, config=PGAConfig(use_pallas=False))
            pga.create_population(SERVING_POP, GENOME_LEN)
            pga.set_objective("onemax")
            pga.set_mutate(make_point_mutate(rate))
            pga.run(SERVING_GENS)

    warm_pga = PGA(seed=1, config=PGAConfig(use_pallas=False))
    warm_pga.create_population(SERVING_POP, GENOME_LEN)
    warm_pga.set_objective("onemax")

    def serve_warm_sweep(n_reqs, base):
        """One persistent engine serving the sweep: still recompiles
        per request (each rate is a new baked operator)."""
        for seed, rate in sweep(n_reqs, base):
            warm_pga.set_mutate(make_point_mutate(rate))
            warm_pga.run(SERVING_GENS)

    # Warm-up: compile every batched width + the same-config engine
    # before any timed round (the batched compile is the
    # amortized-to-zero cost; the sequential arms deliberately get no
    # warm-up — per-request compile IS their cost).
    for width in SERVING_WIDTHS:
        serve_batched(width, 10_000)
    warm_pga.run(SERVING_GENS)

    samples = {f"batched_{w}": [] for w in SERVING_WIDTHS}
    samples["seq_fresh"] = []
    samples["seq_warm"] = []
    samples["seq_samecfg"] = []
    speedups, warm_speedups = [], []
    seq_count = 3
    for rnd in range(rounds):
        base = 20_000 + 1_000 * rnd
        for width in SERVING_WIDTHS:
            t0 = time.perf_counter()
            serve_batched(width, base + width)
            samples[f"batched_{width}"].append(
                width / (time.perf_counter() - t0)
            )
        t0 = time.perf_counter()
        serve_fresh(seq_count, base)
        samples["seq_fresh"].append(seq_count / (time.perf_counter() - t0))
        t0 = time.perf_counter()
        serve_warm_sweep(seq_count, base + 500)
        samples["seq_warm"].append(seq_count / (time.perf_counter() - t0))
        # The same-config regime: the persistent engine re-running its
        # already-compiled program (best sequential case — no sweep).
        warm_pga.set_mutate(None)
        warm_pga.run(SERVING_GENS)  # recompile once after the sweep
        t0 = time.perf_counter()
        for _ in range(2):
            warm_pga.run(SERVING_GENS)
        samples["seq_samecfg"].append(2 / (time.perf_counter() - t0))
        # per-round ratios from ADJACENT measurements (the interleaved
        # protocol's decision-grade quantity).
        top = samples[f"batched_{max(SERVING_WIDTHS)}"][-1]
        speedups.append(top / samples["seq_fresh"][-1])
        warm_speedups.append(top / samples["seq_warm"][-1])

    med = {name: _median_iqr(xs) for name, xs in samples.items()}
    sp_med, sp_iqr = _median_iqr(speedups)
    wsp_med, _ = _median_iqr(warm_speedups)
    from libpga_tpu.serving import COUNTERS

    # Per-ticket latency (ISSUE 6, ROADMAP item 5): one full-width batch
    # through the async queue per round, tickets carrying the complete
    # submit -> launch -> complete -> readback breakdown. A PRIVATE
    # registry so the percentiles describe exactly these rounds; the
    # bucket is warm (compiles were amortized above), so this measures
    # serving latency, not compilation.
    from libpga_tpu import ServingConfig
    from libpga_tpu.serving import RunQueue
    from libpga_tpu.utils import metrics as _metrics

    lat_width = max(SERVING_WIDTHS)
    lat_registry = _metrics.MetricsRegistry()
    lat_queue = RunQueue(
        ex,
        serving=ServingConfig(max_batch=lat_width, max_wait_ms=0),
        registry=lat_registry,
    )
    for rnd in range(rounds):
        tickets = [
            lat_queue.submit(RunRequest(
                size=SERVING_POP, genome_len=GENOME_LEN, n=SERVING_GENS,
                seed=seed, mutation_rate=rate,
            ))
            for seed, rate in sweep(lat_width, 60_000 + 1_000 * rnd)
        ]
        lat_queue.drain()
        for t in tickets:
            t.result(timeout=600)
    e2e = lat_registry.histogram("serving.ticket.e2e_ms").snapshot()
    qwait = lat_registry.histogram(
        "serving.ticket.queue_wait_ms"
    ).snapshot()
    fill = lat_registry.histogram(
        "serving.batch.fill_ratio"
    ).snapshot()
    lat_queue.close()

    out = {
        "serving_pop": SERVING_POP,
        "serving_genome_len": GENOME_LEN,
        "serving_gens": SERVING_GENS,
        "serving_rounds": rounds,
        "serving_seq_runs_per_sec": round(med["seq_fresh"][0], 3),
        "serving_seq_runs_per_sec_iqr": round(med["seq_fresh"][1], 3),
        "serving_seq_warm_runs_per_sec": round(med["seq_warm"][0], 3),
        "serving_seq_samecfg_runs_per_sec": round(med["seq_samecfg"][0], 3),
        "serving_speedup_median": round(sp_med, 2),
        "serving_speedup_iqr": round(sp_iqr, 2),
        "serving_speedup_vs_warm_median": round(wsp_med, 2),
        # Per-ticket serving latency over rounds x max-width warm
        # batches (submit -> readback, ms) + the admission window's
        # occupancy — the SLO quantities (ISSUE 6 / ROADMAP item 5).
        "serving_latency_p50_ms": round(e2e.p50, 3),
        "serving_latency_p99_ms": round(e2e.p99, 3),
        "serving_queue_wait_p50_ms": round(qwait.p50, 3),
        "serving_queue_wait_p99_ms": round(qwait.p99, 3),
        "serving_latency_samples": e2e.count,
        "serving_batch_fill_ratio_median": round(fill.p50, 4),
        "serving_cache": {
            k: v
            for k, v in COUNTERS.snapshot().items()
            if k in ("hits", "misses", "builds", "evictions")
        },
        "serving_note": (
            "runs/sec of end-to-end request service on a mutation-rate "
            "sweep (distinct seed+rate per request). seq = a fresh PGA "
            "instance per request, seq_warm = one persistent engine "
            "serving the sweep (both recompile per request: the engine "
            "bakes the rate into its program — the ISSUE 4 baseline); "
            "seq_samecfg = the persistent engine re-running ONE config "
            "warm, the no-sweep best case. The batched mega-run treats "
            "rates as runtime inputs: one compile per bucket, excluded "
            "as amortized warm-up (serving_cache counters prove the "
            "steady state builds nothing)"
        ),
    }
    for width in SERVING_WIDTHS:
        m, iqr = med[f"batched_{width}"]
        out[f"serving_runs_per_sec_{width}"] = round(m, 3)
        out[f"serving_runs_per_sec_{width}_iqr"] = round(iqr, 3)
    return out


def sharded_arm(rounds: int = ROUNDS, shards: int = SHARDED_SHARDS) -> dict:
    """The permanent population-sharding A/B (ISSUE 7): gens/sec of a
    SHARDED_POP x SHARDED_LEN OneMax run with the population axis split
    ``shards`` ways, measured three ways ADJACENT per round (the
    interleaved protocol every arm uses):

    - ``sharded_gens_per_sec`` — the full sharded loop (one ppermute +
      one all_gather per generation);
    - ``shard_allreduce_pct`` — per-round overhead of the
      per-generation all-gather, from the ablate=("sync",) loop (the
      identical program minus the rank-threshold collective — the
      component isolation tools/ablate_floor.py applies to kernels);
    - ``sharded_vs_single_ratio`` — against the unsharded engine path
      at the same shape (NOTE: on a single-socket CPU host all shards
      timeshare one core, so this ratio measures sharding OVERHEAD,
      not speedup; cross-device scaling is a chip-round measurement).

    Needs ``shards`` visible devices; returns a skip note otherwise
    (the TPU bench on a single chip skips, the CPU harness forces a
    multi-device platform in ``sharded_main``)."""
    import jax
    import jax.numpy as jnp

    if len(jax.devices()) < shards:
        return {
            "sharded_note": (
                f"sharded arm skipped: {len(jax.devices())} device(s) "
                f"< pop_shards={shards}"
            )
        }
    from libpga_tpu import PGA, PGAConfig
    from libpga_tpu.parallel import shard_pop as _sp
    from libpga_tpu.parallel.islands import _shard_host_array
    from libpga_tpu.parallel.mesh import pop_sharding
    from libpga_tpu.objectives import get as get_obj
    from libpga_tpu.ops.crossover import uniform_crossover
    from libpga_tpu.ops.mutate import make_point_mutate
    from libpga_tpu.ops.step import make_breed
    from libpga_tpu.utils.profiling import best_ms_per_unit

    obj = get_obj("onemax")
    breed = make_breed(
        uniform_crossover, make_point_mutate(0.01), tournament_size=2
    )

    def local_step(g, s, sub, mparams, gen):
        del mparams, gen
        return breed(g, s, sub), None

    def build(ablate):
        return _sp.make_sharded_run(
            obj, local_step, SHARDED_POP, SHARDED_LEN, shards,
            donate=False, ablate=ablate,
        )

    full = build(())
    nosync = build(("sync",))
    genomes0 = jax.random.uniform(
        jax.random.key(11), (SHARDED_POP, SHARDED_LEN), dtype=jnp.float32
    )
    placed = _shard_host_array(genomes0, pop_sharding(full.mesh))
    mparams = jnp.asarray([[0.01, 0.0]], dtype=jnp.float32)
    T = SHARDED_GENS_PER_CALL

    def runner(fn):
        def run(calls):
            out = None
            for _ in range(calls):
                out = fn(
                    placed, jax.random.key(3), jnp.int32(T),
                    jnp.float32(jnp.inf), mparams,
                )
            jax.block_until_ready(out)

        return run

    run_full, run_nosync = runner(full), runner(nosync)
    single = PGA(seed=11, config=PGAConfig(use_pallas=False,
                                           donate_buffers=False))
    single.create_population(SHARDED_POP, SHARDED_LEN)
    single.set_objective("onemax")

    def run_single(calls):
        for _ in range(calls):
            single.run(T)

    # warm-up: compile every arm before any timed round
    run_full(1), run_nosync(1), run_single(1)

    ms_full, ms_nosync, ratios, pcts = [], [], [], []
    for _ in range(rounds):
        f = best_ms_per_unit(run_full, 2, 6, units_per_call=T)
        ns = best_ms_per_unit(run_nosync, 2, 6, units_per_call=T)
        sg = best_ms_per_unit(run_single, 2, 6, units_per_call=T)
        ms_full.append(f)
        ms_nosync.append(ns)
        pcts.append((f - ns) / f * 100.0)
        ratios.append(sg / f)  # >1 = sharded faster than single
    med_ms, iqr_ms = _median_iqr(ms_full)
    pct_med, pct_iqr = _median_iqr(pcts)
    ratio_med, _ = _median_iqr(ratios)
    return {
        "sharded_pop_shards": shards,
        "sharded_shape": f"{SHARDED_POP}x{SHARDED_LEN}",
        "sharded_gens_per_sec": round(1000.0 / med_ms, 2),
        "sharded_gens_per_sec_iqr": round(
            abs(1000.0 / (med_ms + iqr_ms / 2)
                - 1000.0 / max(med_ms - iqr_ms / 2, 1e-9)), 2
        ),
        "shard_allreduce_pct": round(pct_med, 2),
        "shard_allreduce_pct_iqr": round(pct_iqr, 2),
        "sharded_vs_single_ratio": round(ratio_med, 3),
        "sharded_note": (
            "shard_allreduce_pct is the full-vs-ablated('sync') "
            "interleaved A/B; on CPU hosts all shards timeshare one "
            "socket, so a pct within the IQR means the all-gather "
            "cost is below this host's drift floor — re-measure on a "
            "chip round for the cross-device number"
        ),
    }


FLEET_POP = 1 << 12  # 4,096 — small enough that 8 workers' compiles
FLEET_LEN = 64       # and runs fit a CPU bench round
FLEET_GENS = 10
FLEET_WIDTHS = (1, 4, 8)  # worker-process counts under test
FLEET_REQS = 8  # tickets per timed sample
FLEET_MIN_REL_CI = 0.10  # repeat-until-confidence bar (half-IQR/median)


def fleet_arm(rounds: int = ROUNDS) -> dict:
    """The permanent cross-process fleet A/B (ISSUE 8): end-to-end
    ticket service rate of FLEET_REQS plain tickets
    (FLEET_POP x FLEET_LEN OneMax, FLEET_GENS generations) through
    fleets of 1/4/8 WORKER PROCESSES, interleaved per round — plus the
    two robustness figures: the requeue count of a deliberate
    worker-kill recovery, and the wall seconds of a full SIGTERM
    drain -> restart -> resume cycle on a supervised ticket.

    CPU caveat (stamped in fleet_note): every worker timeshares this
    host's core, so runs/sec across widths measures the COORDINATION
    overhead (spool protocol, leases, batch formation), not parallel
    speedup — the scaling number awaits a chip round. Protocol: whole
    service times per round (end-to-end rate, like the serving arm),
    medians + IQR across rounds.

    ISSUE 9 additions: the widest fleet's cross-process latency
    percentiles (fleet_latency_p50/p99_ms, fleet_spool_wait_p99_ms,
    from the coordinator's fleet.ticket.* histograms fed by the span
    breakdowns) and the TRACE OVERHEAD A/B — two same-shape 2-worker
    fleets, tracing on vs off, served interleaved within every round;
    acceptance bar: the median overhead is within this host's CPU
    drift floor (direction-only, stamped in the note).

    ISSUE 18 reshape: the width-scaling samples and both A/Bs now run
    through ``profiling.interleaved_medians`` in repeat-until-confidence
    mode (``min_rel_ci=FLEET_MIN_REL_CI``) — every arm is one runner in
    a single fixed-order interleave, and rounds extend past ``rounds``
    until each arm's half-IQR/median is under the bar (capped at
    3x rounds). The width arms serve RING-ON (the new default), and two
    PURE-SPOOL arms (widths 1 and 8) ride in the same interleave so the
    headline comparison — 8-worker ring-on vs 1-worker pure-spool, the
    BENCH_r15 negative-scaling floor — is measured inside one protocol,
    not across bench runs.
    """
    import shutil
    import tempfile

    from libpga_tpu.config import FleetConfig, PGAConfig
    from libpga_tpu.serving.fleet import Fleet, FleetTicket
    from libpga_tpu.utils import metrics as _metrics
    from libpga_tpu.utils.profiling import interleaved_medians

    cfg = PGAConfig(use_pallas=False)
    root = tempfile.mkdtemp(prefix="pga-bench-fleet-")

    def serve(fleet, n_reqs, base):
        handles = [
            fleet.submit(FleetTicket(
                size=FLEET_POP, genome_len=FLEET_LEN, n=FLEET_GENS,
                seed=base + i,
            ))
            for i in range(n_reqs)
        ]
        fleet.flush()
        for h in handles:
            h.result(timeout=600)

    # Width-scaling + ring A/B arms, one interleave: ring-on at every
    # width (the production default) plus pure-spool at the two widths
    # the ISSUE 18 acceptance bar compares (1 and 8).
    arm_specs = [(f"ring{w}", w, True) for w in FLEET_WIDTHS]
    arm_specs += [("spool1", 1, False), ("spool8", max(FLEET_WIDTHS), False)]
    fleets, registries = {}, {}
    for name, w, ring in arm_specs:
        registries[name] = _metrics.MetricsRegistry()
        fleets[name] = Fleet(
            os.path.join(root, name), "onemax", config=cfg,
            fleet=FleetConfig(
                n_workers=w, max_batch=max(FLEET_REQS // w, 1),
                max_wait_ms=2, lease_timeout_s=30.0, heartbeat_s=0.5,
                poll_s=0.02, ring=ring,
            ),
            registry=registries[name],
        )
        fleets[name].start()

    # Warm-up: every worker process compiles its mega-run program once
    # (the per-worker AOT cache story) before any timed round.
    for i, (name, w, _ring) in enumerate(arm_specs):
        serve(fleets[name], max(2 * w, FLEET_REQS), 40_000 + 1_000 * i)
        # Drop the warm-up observations: the latency percentiles below
        # must read steady-state service, not first-compile spool waits
        # (20+ s of AOT build per worker would dominate every p99).
        registries[name].reset()

    seed_box = [60_000]
    samples = {name: [] for name, _w, _r in arm_specs}

    def make_runner(name):
        def run():
            seed_box[0] += 100
            t0 = time.perf_counter()
            serve(fleets[name], FLEET_REQS, seed_box[0])
            rate = FLEET_REQS / (time.perf_counter() - t0)
            samples[name].append(rate)
            return rate
        return run

    med = interleaved_medians(
        {name: make_runner(name) for name, _w, _r in arm_specs},
        rounds=rounds, sample=lambda run: run(),
        min_rel_ci=FLEET_MIN_REL_CI,
    )
    # Cross-process latency percentiles from the widest ring fleet's
    # coordinator histograms (fed by every awaited ticket's span
    # breakdown over all timed rounds), plus the pure-spool twin's
    # spool-wait p99 — the ring's headline latency effect, in-run.
    widest = registries[f"ring{max(FLEET_WIDTHS)}"]
    e2e = widest.histogram("fleet.ticket.e2e_ms").snapshot()
    spool_wait = widest.histogram("fleet.ticket.spool_wait_ms").snapshot()
    spool_wait_off = registries[f"spool{max(FLEET_WIDTHS)}"].histogram(
        "fleet.ticket.spool_wait_ms"
    ).snapshot()
    for name, _w, _r in arm_specs:
        fleets[name].close()

    # Trace-overhead A/B (ISSUE 9): identical 2-worker fleets, tracing
    # on vs off, warmed separately, interleaved under the same
    # repeat-until-confidence protocol; the raw per-round seconds are
    # kept so the overhead stays a median of PAIRED ratios.
    ab, trace_secs = {}, {"on": [], "off": []}
    for mode, trace in (("on", True), ("off", False)):
        ab[mode] = Fleet(
            os.path.join(root, f"tr_{mode}"), "onemax", config=cfg,
            fleet=FleetConfig(
                n_workers=2, max_batch=max(FLEET_REQS // 2, 1),
                max_wait_ms=2, lease_timeout_s=30.0, heartbeat_s=0.5,
                poll_s=0.02, trace=trace,
            ),
            registry=_metrics.MetricsRegistry(),
        )
        ab[mode].start()
        serve(ab[mode], FLEET_REQS, 90_000 if trace else 91_000)  # warm

    def make_trace_runner(mode):
        def run():
            seed_box[0] += 100
            t0 = time.perf_counter()
            serve(ab[mode], FLEET_REQS, seed_box[0])
            secs = time.perf_counter() - t0
            trace_secs[mode].append(secs)
            return secs
        return run

    trace_med_secs = interleaved_medians(
        {mode: make_trace_runner(mode) for mode in ("on", "off")},
        rounds=rounds, sample=lambda run: run(),
        min_rel_ci=FLEET_MIN_REL_CI,
    )
    for mode in ("on", "off"):
        ab[mode].close()
    trace_overheads = [
        (on / off - 1.0) * 100.0
        for on, off in zip(trace_secs["on"], trace_secs["off"])
    ]
    trace_med, trace_iqr = _median_iqr(trace_overheads)

    # Sparse-ticket latency A/B (ISSUE 18): the coordination FLOOR the
    # ring removes. The saturated width arms above pin poll_s=0.02 and
    # keep every worker busy, so core contention — not wake latency —
    # dominates their spool-wait p99. Here: identical 2-worker fleets
    # at the PRODUCTION poll cadence (FleetConfig default poll_s),
    # served ONE ticket at a time after an idle gap, interleaved. The
    # ring worker wakes on the advertise frame in ~ms; the spool worker
    # pays up to a full poll_s nap before it even lists pending/ — the
    # e2e and spool-wait deltas are the event-driven-coordination
    # claim, measured.
    lat, lat_regs = {}, {}
    for mode, ring_on in (("ring", True), ("spool", False)):
        lat_regs[mode] = _metrics.MetricsRegistry()
        lat[mode] = Fleet(
            os.path.join(root, f"lat_{mode}"), "onemax", config=cfg,
            fleet=FleetConfig(
                n_workers=2, max_batch=1, max_wait_ms=0,
                lease_timeout_s=30.0, heartbeat_s=0.5, ring=ring_on,
            ),
            registry=lat_regs[mode],
        )
        lat[mode].start()
        serve(lat[mode], 4, 94_000 if ring_on else 94_500)  # warm
        lat_regs[mode].reset()

    def make_sparse_runner(mode):
        def run():
            seed_box[0] += 10
            time.sleep(0.3)  # idle: workers back in their wait loops
            t0 = time.perf_counter()
            lat[mode].submit(FleetTicket(
                size=FLEET_POP, genome_len=FLEET_LEN, n=FLEET_GENS,
                seed=seed_box[0],
            )).result(timeout=600)
            return (time.perf_counter() - t0) * 1000.0
        return run

    sparse_med = interleaved_medians(
        {m: make_sparse_runner(m) for m in ("ring", "spool")},
        rounds=2 * rounds, sample=lambda run: run(),
        min_rel_ci=FLEET_MIN_REL_CI,
    )
    sparse_wait = {
        m: lat_regs[m].histogram("fleet.ticket.spool_wait_ms").snapshot()
        for m in ("ring", "spool")
    }
    for mode in ("ring", "spool"):
        lat[mode].close()

    # Requeue accounting: a 2-worker fleet where one worker SIGKILLs
    # itself mid-batch — the recovery path's cost in requeues (the
    # correctness gate lives in tools/fleet_smoke.py; this records the
    # count on the scored artifact).
    rq = Fleet(
        os.path.join(root, "rq"), "onemax", config=cfg,
        fleet=FleetConfig(
            n_workers=2, max_batch=2, max_wait_ms=2,
            lease_timeout_s=30.0, heartbeat_s=0.5, poll_s=0.02,
        ),
    )
    rq.start(worker_env={0: {"PGA_WORKER_CHAOS": "sigkill@execute:1"}})
    serve(rq, 4, 70_000)
    requeues = rq.requeues
    rq.close()

    # Drain/resume cycle: SIGTERM-drain a supervised ticket mid-run,
    # restart the fleet, run to completion — the preemption round-trip
    # cost (drain wait + worker respawn + checkpoint resume).
    dr = Fleet(
        os.path.join(root, "dr"), "onemax", config=cfg,
        fleet=FleetConfig(
            n_workers=1, max_batch=1, max_wait_ms=0,
            lease_timeout_s=30.0, heartbeat_s=0.5, poll_s=0.02,
        ),
    )
    dr.start()
    h = dr.submit(FleetTicket(
        size=FLEET_POP, genome_len=FLEET_LEN, n=4 * FLEET_GENS,
        seed=80_000, checkpoint_every=FLEET_GENS,
    ))
    dr.flush()
    sidecar = dr.spool.ckpt_path(h.tid) + ".meta.json"
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline:
        try:
            with open(sidecar) as fh:
                if 0 < json.load(fh)["generations"] < 4 * FLEET_GENS:
                    break
        except (OSError, ValueError, KeyError):
            pass
        time.sleep(0.01)
    t0 = time.perf_counter()
    dr.drain()
    dr.start()
    h.result(timeout=600)
    drain_resume_s = time.perf_counter() - t0
    dr.close()

    # Fairness isolation A/B (ISSUE 15): the steady tenant's spool-wait
    # p99 served ALONE vs with a CONCURRENT 12-ticket burst tenant —
    # two identical 2-worker fleets with the weighted-fair scheduler,
    # modes served adjacent within every round. The ratio is the
    # latency-isolation figure ROADMAP item 1 asked for (1.0 = perfect
    # isolation; the FIFO intake this round replaced had no bound at
    # all — the burst simply served first).
    from libpga_tpu.config import AutoscaleConfig, TenantPolicy

    fair, fair_regs = {}, {}
    for mode in ("alone", "contended"):
        fair_regs[mode] = _metrics.MetricsRegistry()
        fair[mode] = Fleet(
            os.path.join(root, f"fair_{mode}"), "onemax", config=cfg,
            fleet=FleetConfig(
                n_workers=2, max_batch=2, max_wait_ms=2,
                lease_timeout_s=30.0, heartbeat_s=0.5, poll_s=0.02,
                sched_lookahead=1,
                tenants={"steady": TenantPolicy(weight=2.0)},
            ),
            registry=fair_regs[mode],
        )
        fair[mode].start()
        base = 95_000 if mode == "alone" else 95_500
        serve(fair[mode], 4, base)  # width-2 warm
        fair[mode].submit(FleetTicket(  # width-1 warm
            size=FLEET_POP, genome_len=FLEET_LEN, n=FLEET_GENS,
            seed=base + 900,
        )).result(timeout=600)
        fair_regs[mode].reset()
    for rnd in range(rounds):
        base = 100_000 + 1_000 * rnd
        for mode in ("alone", "contended"):
            f = fair[mode]
            burst = []
            if mode == "contended":
                burst = [
                    f.submit(FleetTicket(
                        size=FLEET_POP, genome_len=FLEET_LEN,
                        n=FLEET_GENS, seed=base + 100 + i,
                    ), tenant="burst")
                    for i in range(12)
                ]
            # Steady tickets awaited promptly: their spans must read
            # fleet latency, not driver patience.
            for i in range(4):
                f.submit(FleetTicket(
                    size=FLEET_POP, genome_len=FLEET_LEN, n=FLEET_GENS,
                    seed=base + i,
                ), tenant="steady").result(timeout=600)
            for h2 in burst:
                h2.result(timeout=600)
    fair_p99 = {}
    for mode in ("alone", "contended"):
        snap = fair_regs[mode].histogram(
            "fleet.tenant.spool_wait_ms", tenant="steady"
        ).snapshot()
        fair_p99[mode] = (
            None if snap.count == 0 else snap.percentile(99.0)
        )
        fair[mode].close()
    isolation_ratio = (
        None
        if not fair_p99["alone"] or fair_p99["contended"] is None
        else round(fair_p99["contended"] / max(fair_p99["alone"], 1e-6), 3)
    )

    # Autoscale settle (ISSUE 15): a 1-worker floor fleet under an
    # 8-ticket burst must scale up and, once idle, drain back to the
    # floor; settle_s is the wall time from last result to floor.
    az = Fleet(
        os.path.join(root, "az"), "onemax", config=cfg,
        fleet=FleetConfig(
            n_workers=1, max_batch=1, max_wait_ms=2, poll_s=0.02,
            lease_timeout_s=60.0, heartbeat_s=0.5,
            autoscale=AutoscaleConfig(
                min_workers=1, max_workers=3, target_backlog=1.0,
                up_cooldown_s=0.3, down_cooldown_s=0.5,
                idle_grace_s=0.5, check_s=0.1,
            ),
        ),
        registry=_metrics.MetricsRegistry(),
    )
    az.start()
    serve(az, 2, 98_000)  # warm the floor worker
    az_handles = [
        az.submit(FleetTicket(
            size=FLEET_POP, genome_len=FLEET_LEN, n=FLEET_GENS,
            seed=99_000 + i,
        ))
        for i in range(8)
    ]
    az_peak = 1
    while not all(h.poll() for h in az_handles):
        az_peak = max(az_peak, len(az.workers_alive()))
        time.sleep(0.05)
    for h in az_handles:
        h.result(timeout=600)
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < 120 and len(az.workers_alive()) > 1:
        time.sleep(0.05)
    autoscale_settle_s = time.perf_counter() - t0
    az.close()

    # Coordinator-failover settle (ISSUE 20): the submit blackout — two
    # HA candidates on one spool, the leader's heartbeats stop cold
    # (the in-process SIGKILL analog), and the clock runs until the
    # standby holds the lease and schedules. Lease-timeout dominated,
    # so the figure is stable on a contended host.
    ha_fc = dict(
        n_workers=1, max_batch=1, max_wait_ms=2, poll_s=0.05,
        lease_timeout_s=1.5, heartbeat_s=0.3, ring=False,
        coordinators=2,
    )
    ha_a = Fleet(
        os.path.join(root, "ha_a"), "onemax", config=cfg,
        fleet=FleetConfig(**ha_fc), registry=_metrics.MetricsRegistry(),
    )
    ha_b = Fleet(
        os.path.join(root, "ha_a"), "onemax", config=cfg,
        fleet=FleetConfig(**ha_fc), registry=_metrics.MetricsRegistry(),
    )
    ha_a._ensure_monitor()  # heartbeats without a worker pool
    ha_b.start()            # standby: election watch only
    time.sleep(2 * ha_fc["heartbeat_s"])
    t0 = time.perf_counter()
    ha_a._stop_monitor.set()
    ha_a._wake.set()
    if ha_a._monitor is not None:
        ha_a._monitor.join(timeout=30)
    while time.perf_counter() - t0 < 120 and not ha_b.is_leader:
        time.sleep(0.01)
    failover_settle_s = time.perf_counter() - t0
    ha_a._closed = True
    ha_b.close()
    shutil.rmtree(root, ignore_errors=True)

    arm_stats = {name: _median_iqr(xs) for name, xs in samples.items()}
    spool1_med = arm_stats["spool1"][0]
    spool8_med = arm_stats[f"spool{max(FLEET_WIDTHS)}"][0]
    ring8_med = arm_stats[f"ring{max(FLEET_WIDTHS)}"][0]
    out = {
        "fleet_pop": FLEET_POP,
        "fleet_genome_len": FLEET_LEN,
        "fleet_gens": FLEET_GENS,
        "fleet_reqs_per_sample": FLEET_REQS,
        "fleet_rounds": rounds,
        # ISSUE 18: repeat-until-confidence accounting — the rounds the
        # interleaves actually executed to get every arm's half-IQR /
        # median under the bar (capped at 3x fleet_rounds).
        "fleet_ab_min_rel_ci": FLEET_MIN_REL_CI,
        "fleet_width_rounds_executed": med.rounds,
        "fleet_trace_rounds_executed": trace_med_secs.rounds,
        "fleet_requeue_count": requeues,
        "fleet_drain_resume_seconds": round(drain_resume_s, 3),
        # ISSUE 9: cross-process latency percentiles (widest fleet,
        # coordinator-side fleet.ticket.* histograms) and the tracing
        # on/off A/B.
        "fleet_latency_p50_ms": (
            None if e2e.count == 0 else round(e2e.p50, 2)
        ),
        "fleet_latency_p99_ms": (
            None if e2e.count == 0 else round(e2e.p99, 2)
        ),
        "fleet_latency_samples": e2e.count,
        "fleet_spool_wait_p99_ms": (
            None if spool_wait.count == 0 else round(spool_wait.p99, 2)
        ),
        # ISSUE 18: the ring A/B — the same widest fleet served by a
        # pure-spool twin inside the same interleave.
        "fleet_spool_wait_p99_ring_off_ms": (
            None if spool_wait_off.count == 0
            else round(spool_wait_off.p99, 2)
        ),
        "fleet_ring_speedup_widest": (
            None if spool8_med <= 0 else round(ring8_med / spool8_med, 3)
        ),
        "fleet_ring_widest_vs_spool_1worker": (
            None if spool1_med <= 0 else round(ring8_med / spool1_med, 3)
        ),
        # ISSUE 18: the sparse single-ticket latency A/B at production
        # poll cadence — the wake-latency floor itself.
        "fleet_sparse_e2e_p50_ring_ms": round(sparse_med["ring"], 2),
        "fleet_sparse_e2e_p50_spool_ms": round(sparse_med["spool"], 2),
        "fleet_sparse_spool_wait_p99_ring_ms": (
            None if sparse_wait["ring"].count == 0
            else round(sparse_wait["ring"].p99, 2)
        ),
        "fleet_sparse_spool_wait_p99_spool_ms": (
            None if sparse_wait["spool"].count == 0
            else round(sparse_wait["spool"].p99, 2)
        ),
        "fleet_sparse_rounds_executed": sparse_med.rounds,
        "fleet_trace_overhead_pct_median": round(trace_med, 2),
        "fleet_trace_overhead_pct_iqr": round(trace_iqr, 2),
        # ISSUE 15: weighted-fair scheduling + autoscaling figures.
        "fleet_fairness_isolation_ratio": isolation_ratio,
        "fleet_fairness_steady_p99_alone_ms": (
            None if fair_p99["alone"] is None
            else round(fair_p99["alone"], 2)
        ),
        "fleet_fairness_steady_p99_contended_ms": (
            None if fair_p99["contended"] is None
            else round(fair_p99["contended"], 2)
        ),
        "fleet_autoscale_settle_s": round(autoscale_settle_s, 3),
        "fleet_autoscale_peak_workers": az_peak,
        # ISSUE 20: the coordinator-failover submit blackout.
        "fleet_failover_settle_s": round(failover_settle_s, 3),
        "fleet_note": (
            "runs/sec of whole fleet round trips (submit -> spool "
            "batch -> worker mega-run -> published result) at 1/4/8 "
            "WORKER PROCESSES; on this 1-core CPU host all workers "
            "timeshare, so width scaling reads coordination overhead, "
            "not parallel speedup — chip-round measurement pending. "
            "ISSUE 18: width arms serve with the shared-memory ticket "
            "ring ON (the default); fleet_spool_runs_per_sec_{1,8} are "
            "pure-spool twins inside the SAME interleave, all arms "
            "extended repeat-until-confidence (fleet_ab_min_rel_ci) — "
            "acceptance bar: fleet_ring_widest_vs_spool_1worker >= 1.0 "
            "(the widest ring fleet at least matches a 1-worker "
            "pure-spool fleet, retiring the BENCH_r15 negative-scaling "
            "floor). The saturated arms' spool-wait p99 is core-"
            "contention-bound on this 1-core host (ring on/off twins "
            "read within noise of each other); the wake-latency floor "
            "itself is the fleet_sparse_* A/B — single tickets into "
            "idle 2-worker fleets at the production poll cadence, "
            "where the ring's advertise-frame wake replaces the "
            "worker's poll_s nap and spool-wait drops materially. "
            "fleet_drain_resume_seconds is one SIGTERM drain + "
            "restart + checkpoint-resume cycle of a supervised ticket "
            "mid-run; fleet_requeue_count is the lease requeues of a "
            "deliberate worker SIGKILL recovery (bit-identity gated in "
            "tools/fleet_smoke.py). fleet_latency_* percentiles are "
            "cross-process span breakdowns (coordinator submit -> "
            "readback) of the widest fleet's TIMED rounds, warm-up "
            "compiles excluded; "
            "fleet_trace_overhead_pct_median is the interleaved "
            "tracing-on vs tracing-off A/B on identical 2-worker "
            "fleets — acceptance bar: within this host's CPU drift "
            "floor (~4%, BASELINE.md), direction-only below that. "
            "fleet_fairness_isolation_ratio (ISSUE 15) is the steady "
            "tenant's spool-wait p99 with a concurrent 12-ticket "
            "burst vs alone (adjacent within every round) under the "
            "weighted-fair scheduler — 1.0 = perfect isolation; "
            "fleet_autoscale_settle_s is the wall seconds an "
            "autoscaled fleet takes to drain from its burst peak "
            "(fleet_autoscale_peak_workers) back to the 1-worker "
            "floor after the last result. fleet_failover_settle_s "
            "(ISSUE 20) is the coordinator-HA submit blackout: wall "
            "seconds from the moment a live leader's heartbeats stop "
            "until a hot standby holds the lease and leads — lease-"
            "timeout dominated (1.5 s here), so the figure reads the "
            "election + journal-replay machinery, not host load"
        ),
    }
    for w in FLEET_WIDTHS:
        out[f"fleet_runs_per_sec_{w}"] = round(arm_stats[f"ring{w}"][0], 3)
        out[f"fleet_runs_per_sec_{w}_iqr"] = round(
            arm_stats[f"ring{w}"][1], 3
        )
    for w in (1, max(FLEET_WIDTHS)):
        out[f"fleet_spool_runs_per_sec_{w}"] = round(
            arm_stats[f"spool{w}"][0], 3
        )
        out[f"fleet_spool_runs_per_sec_{w}_iqr"] = round(
            arm_stats[f"spool{w}"][1], 3
        )
    return out


def supervised_arm(rounds: int = ROUNDS) -> dict:
    """The permanent supervisor-overhead A/B (ISSUE 5): ms/run of a
    SERVING_POP x GENOME_LEN OneMax run of SERVING_GENS generations —
    bare ``PGA.run`` vs ``robustness.supervised_run`` at auto-checkpoint
    cadence K=0 (pure supervisor wrapper: pre-chunk snapshot +
    bookkeeping, no durability) vs K=SERVING_GENS/2 (one mid-run atomic
    checkpoint + progress sidecar per run).

    Protocol: per-round samples via ``utils/profiling.best_ms_per_unit``
    (the shared two-length-subtraction estimator), the three arms
    measured ADJACENT within each round, per-round overhead ratios from
    adjacent pairs, medians + IQR across rounds — the interleaved
    protocol every bench arm uses. The acceptance bar is direction-only
    on this host (BASELINE.md documents a ±4% CPU drift floor): K=0
    overhead must be within measurement noise; the artifact reports
    median + IQR and gates nothing finer than a gross regression.
    """
    import tempfile

    from libpga_tpu import PGA, PGAConfig
    from libpga_tpu.robustness.supervisor import supervised_run
    from libpga_tpu.utils.profiling import best_ms_per_unit

    def engine():
        pga = PGA(seed=17, config=PGAConfig(use_pallas=False))
        pga.create_population(SERVING_POP, GENOME_LEN)
        pga.set_objective("onemax")
        pga.run(SERVING_GENS)  # compile + warm
        return pga

    bare_pga = engine()
    k0_pga = engine()
    km_pga = engine()
    ckpt_dir = tempfile.mkdtemp(prefix="pga-bench-supervised-")
    ckpt = os.path.join(ckpt_dir, "state.npz")
    K = max(SERVING_GENS // 2, 1)

    def run_bare(calls):
        for _ in range(calls):
            bare_pga.run(SERVING_GENS)

    def run_supervised_k0(calls):
        for _ in range(calls):
            supervised_run(k0_pga, SERVING_GENS)

    def run_supervised_ckpt(calls):
        for _ in range(calls):
            supervised_run(
                km_pga, SERVING_GENS, checkpoint_path=ckpt,
                checkpoint_every=K,
            )

    samples = {"bare": [], "supervised_k0": [], "supervised_ckpt": []}
    k0_overheads, ckpt_overheads = [], []
    for _ in range(rounds):
        samples["bare"].append(best_ms_per_unit(run_bare, 2, 6))
        samples["supervised_k0"].append(
            best_ms_per_unit(run_supervised_k0, 2, 6)
        )
        samples["supervised_ckpt"].append(
            best_ms_per_unit(run_supervised_ckpt, 2, 6)
        )
        k0_overheads.append(
            (samples["supervised_k0"][-1] / samples["bare"][-1] - 1.0)
            * 100.0
        )
        ckpt_overheads.append(
            (samples["supervised_ckpt"][-1] / samples["bare"][-1] - 1.0)
            * 100.0
        )
    med = {name: _median_iqr(xs) for name, xs in samples.items()}
    k0_med, k0_iqr = _median_iqr(k0_overheads)
    ck_med, ck_iqr = _median_iqr(ckpt_overheads)
    return {
        "supervised_pop": SERVING_POP,
        "supervised_gens": SERVING_GENS,
        "supervised_ckpt_every": K,
        "supervised_rounds": rounds,
        "supervised_bare_ms_per_run_median": round(med["bare"][0], 2),
        "supervised_bare_ms_per_run_iqr": round(med["bare"][1], 2),
        "supervised_k0_ms_per_run_median": round(
            med["supervised_k0"][0], 2
        ),
        "supervised_overhead_pct_median": round(k0_med, 2),
        "supervised_overhead_pct_iqr": round(k0_iqr, 2),
        "supervised_ckpt_ms_per_run_median": round(
            med["supervised_ckpt"][0], 2
        ),
        "supervised_ckpt_overhead_pct_median": round(ck_med, 2),
        "supervised_ckpt_overhead_pct_iqr": round(ck_iqr, 2),
        "supervised_note": (
            "ms per SERVING_GENS-generation run, adjacent per round: "
            "bare PGA.run vs supervised_run at K=0 (snapshot+bookkeeping "
            "only — the within-noise bar) vs auto-checkpoint every "
            f"{K} gens (one atomic save + sidecar per run). CPU drift "
            "floor is +/-4% (BASELINE.md): gate only on gross "
            "regressions of the medians"
        ),
    }


AUTOTUNE_POP = SERVING_POP  # 16,384 — the CPU-decision-grade shape
AUTOTUNE_LEN = 100
AUTOTUNE_BUDGET = 6


def autotuned_arm(rounds: int = ROUNDS) -> dict:
    """``--autotuned`` (ISSUE 10): run the evolutionary autotuner for
    the 16k×100 OneMax signature into a throwaway DB, then an
    INTERLEAVED A/B of two live engines — one constructed with the
    DB-resolved knobs, one stock — emitting ``tuned_vs_default_ratio``
    (per-round from adjacent samples; >= 1 means the tuned config is
    at least as fast). On a CPU backend every config resolves to the
    one XLA plan, so the ratio is a NULL MEASUREMENT of the harness
    itself (expected 1.0 within the drift floor — stamped in the
    note); on a chip it is the tuner's live verdict."""
    import tempfile

    from libpga_tpu import PGA, PGAConfig
    from libpga_tpu.tuning import tuner as _tuner

    t0 = time.perf_counter()
    db_path = tempfile.mktemp(
        suffix=".json", prefix="pga-bench-tuning-"
    )
    entry = _tuner.autotune(
        AUTOTUNE_POP, AUTOTUNE_LEN, objective="onemax",
        settings=_tuner.TunerSettings(budget=AUTOTUNE_BUDGET, seed=0),
        db_path=db_path,
    )
    autotune_seconds = time.perf_counter() - t0

    def engine(knobs: dict):
        # Applying the entry's knob values explicitly IS the
        # DB-resolved config (user-knob precedence = db values here) —
        # no global DB toggling inside the interleave.
        pga = PGA(seed=0, config=PGAConfig(**knobs))
        pga.set_objective("onemax")
        pga.create_population(AUTOTUNE_POP, AUTOTUNE_LEN)

        def run(n):
            pga.run(n)

        run.pga = pga
        return run

    runners = [
        ("autotuned", engine(entry.knobs)),
        ("default", engine({})),
    ]
    for _, r in runners:
        r(3)  # compile before the interleave
    samples = {name: [] for name, _ in runners}
    ratios = []
    for _ in range(rounds):
        for name, r in runners:
            samples[name].append(_sample_gps(r, 10, 30))
        ratios.append(samples["autotuned"][-1] / samples["default"][-1])
    tuned_med = _median_iqr(samples["autotuned"])
    default_med = _median_iqr(samples["default"])
    ratio_med, ratio_iqr = _median_iqr(ratios)
    try:
        os.remove(db_path)
    except OSError:
        pass
    return {
        "autotuned_gens_per_sec_median": round(tuned_med[0], 2),
        "autotuned_gens_per_sec_iqr": round(tuned_med[1], 2),
        "autotuned_default_gens_per_sec_median": round(default_med[0], 2),
        "tuned_vs_default_ratio_median": round(ratio_med, 4),
        "tuned_vs_default_ratio_iqr": round(ratio_iqr, 4),
        "autotuned_knobs": {k: v for k, v in entry.knobs.items()},
        "autotuned_plan": entry.plan.get("path"),
        "autotune_seconds": round(autotune_seconds, 2),
        "autotune_evaluated": entry.evaluated,
        "autotune_space_size": entry.space_size,
        "autotuned_note": (
            "per-round ratio from ADJACENT tuned/default samples "
            f"(interleaved, {rounds} rounds) at "
            f"{AUTOTUNE_POP}x{AUTOTUNE_LEN} OneMax; on CPU backends "
            "every config resolves to the one XLA plan, so the ratio "
            "is a null measurement of the harness (expected 1.0 "
            "within the ~4% drift floor) — the kernel-space verdict "
            "needs a chip"
        ),
    }


# GP arm (ISSUE 11, rebuilt for ISSUE 19): a symbolic-regression
# workload over postfix tree genomes — GP_POP programs of up to
# GP_NODES tokens scored against a GP_SAMPLES-point dataset every
# generation by the fused stack-machine interpreter (gp/interpreter.py
# on CPU; the Pallas VMEM-stack kernel on chips). Runs through
# ``interleaved_medians`` in repeat-until-confidence mode
# (min_rel_ci=GP_MIN_REL_CI) with a permanent optimizer A/B: the
# optimizer-ON engine (eval-time fold/DCE/compact + live-length trip
# reduction, the default) against an identical optimizer-OFF twin —
# plus (a) an identical GP engine with a trivial vector objective,
# isolating the EVALUATOR's share of a generation, and (b) a
# same-shape vector-genome OneMax engine, the cross-representation
# baseline.
GP_POP = 1024
GP_NODES = 16
GP_SAMPLES = 64
GP_MIN_REL_CI = 0.10


def gp_arm(rounds: int = ROUNDS) -> dict:
    """``--gp``: the tree-GP symbolic-regression arm."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from libpga_tpu import PGA, PGAConfig
    from libpga_tpu.gp import encoding as _genc
    from libpga_tpu.gp import operators as _gpo
    from libpga_tpu.gp.optimize import mean_live_length
    from libpga_tpu.gp.sr import make_dataset, symbolic_regression
    from libpga_tpu.utils.profiling import interleaved_medians

    gp = _genc.GPConfig(max_nodes=GP_NODES, n_vars=2)
    gp_off = _genc.GPConfig(max_nodes=GP_NODES, n_vars=2, optimize=False)
    X, y = make_dataset(
        lambda a, b: a * b + a, n_samples=GP_SAMPLES, n_vars=2, seed=0
    )

    def gp_engine(objective, g=gp):
        pga = PGA(seed=0, config=PGAConfig(
            use_pallas=False, selection="truncation", elitism=2,
        ))
        pga.set_objective(objective)
        pga.set_crossover(_gpo.make_subtree_crossover(g))
        pga.set_mutate(_gpo.make_gp_mutate(g))
        handle = pga.install_population(
            _genc.random_population(jax.random.key(0), GP_POP, g)
        )

        def run(n):
            pga.run(n)

        run.pga = pga
        run.handle = handle
        return run

    def vector_engine():
        pga = PGA(seed=0, config=PGAConfig(
            use_pallas=False, selection="truncation", elitism=2,
        ))
        pga.create_population(GP_POP, gp.genome_len)
        pga.set_objective("onemax")

        def run(n):
            pga.run(n)

        run.pga = pga
        return run

    runners = {
        "gp_sr": gp_engine(symbolic_regression(X, y, gp=gp)),
        # The permanent optimizer A/B twin: identical seed, breeding,
        # and dataset — only GPConfig.optimize differs, so the
        # adjacent-sample ratio IS the fast path's whole-generation win.
        "gp_sr_noopt": gp_engine(
            symbolic_regression(X, y, gp=gp_off), gp_off
        ),
        # Same breeding, trivial objective: the adjacent pair isolates
        # the stack-machine evaluator's share of a generation.
        "gp_cheap": gp_engine(lambda g: jnp.sum(g)),
        "vector": vector_engine(),
    }
    for r in runners.values():
        r(3)  # compile + warm outside the timed samples
    med = interleaved_medians(
        runners, rounds=rounds,
        sample=lambda r: _sample_gps(r, 5, 15),
        min_rel_ci=GP_MIN_REL_CI,
    )
    sr = runners["gp_sr"]
    live = float(mean_live_length(
        np.asarray(sr.pga.population(sr.handle).genomes), gp
    ))
    speedup = med["gp_sr"] / med["gp_sr_noopt"]
    overhead = (
        (1.0 / med["gp_sr"] - 1.0 / med["gp_cheap"])
        / (1.0 / med["gp_sr"]) * 100.0
    )
    return {
        "gp_gens_per_sec": round(med["gp_sr"], 2),
        "gp_gens_per_sec_median": round(med["gp_sr"], 2),
        "gp_noopt_gens_per_sec_median": round(med["gp_sr_noopt"], 2),
        "gp_opt_speedup_median": round(speedup, 3),
        "gp_live_length_mean": round(live, 2),
        "gp_cheap_obj_gens_per_sec_median": round(med["gp_cheap"], 2),
        "gp_vector_gens_per_sec_median": round(med["vector"], 2),
        "gp_vs_vector_ratio_median": round(
            med["gp_sr"] / med["vector"], 4
        ),
        "gp_eval_overhead_pct_median": round(overhead, 2),
        "gp_rel_ci": {k: round(v, 4) for k, v in med.rel_ci.items()},
        "gp_rounds": med.rounds,
        "gp_min_rel_ci": GP_MIN_REL_CI,
        "gp_dropped": dict(med.dropped),
        "gp_shape": f"{GP_POP}x{GP_NODES}nodes",
        "gp_samples": GP_SAMPLES,
        "gp_note": (
            f"symbolic regression over {GP_POP} postfix programs of up "
            f"to {GP_NODES} tokens, {GP_SAMPLES}-sample -RMSE fitness; "
            "interleaved_medians repeat-until-confidence "
            "(gp_min_rel_ci). gp_opt_speedup = optimizer-ON "
            "(fold/DCE/compact + live-length trips, the default) over "
            "an identical optimizer-OFF twin; gp_live_length_mean = "
            "mean live tokens after compaction on the evolved ON "
            "population (of gp_shape's max). gp_eval_overhead_pct = "
            "the stack-machine evaluator's share of a generation "
            "(gp_sr vs identical breeding with a trivial objective); "
            "gp_vs_vector = same-shape OneMax vector-genome engine. "
            "CPU backend: the XLA interpreter path — the fused "
            "VMEM-stack kernel's figure needs a chip."
        ),
    }


STREAM_POP = 4096
STREAM_LEN = 64
STREAM_CHURN_POP = 512
STREAM_CHURN_LEN = 16


def streaming_arm(rounds: int = ROUNDS) -> dict:
    """``--streaming``: the streaming evolution service arm (ISSUE 12).

    Three figures, interleaved per round per the house protocol:

    - ``streaming_first_ask_ms_{cold,warm}`` — time from ``acquire`` to
      the first ask+step completing, cold (a NEVER-seen signature: the
      genome length varies per round, so every cold sample pays a real
      trace+compile) vs warm (the pooled signature, engine reuse —
      0 compiles), sampled back to back;
    - ``streaming_fold_overhead_pct`` — a ``step`` whose boundary folds
      one pending tell (the injection-slot program) vs an identical
      plain step, per-round ratios from ADJACENT samples;
    - ``streaming_sessions_per_sec`` — warm-pool tenant churn:
      acquire -> step(2) -> release, sessions completed per second;
    - ``streaming_ask_ms_p50``/``_p99`` (ISSUE 14) — the per-ask
      latency distribution on a warm pooled session (individual asks
      timed, not a mean over a batch);
    - ``streaming_pool_hit_rate`` — warm-pool hits / (hits + misses)
      over the whole arm;
    - ``streaming_tenant_overhead_pct`` (ISSUE 14) — explicit-tenant
      attribution vs the anon default: two tenant-attributed sessions
      interleaved against two anon sessions, per-round ratios from
      adjacent samples (bar: within the ~4% CPU drift floor —
      attribution is host-side labeling only).
    """
    import numpy as np

    from libpga_tpu import PGAConfig
    from libpga_tpu.streaming import (
        EnginePool, EvolutionSession, StreamingConfig,
    )
    from libpga_tpu.utils.metrics import Counters

    cfg = PGAConfig(use_pallas=False)
    pool = EnginePool(config=cfg, counters=Counters())

    def first_ask_cold(genome_len: int) -> float:
        p = EnginePool(
            config=cfg, counters=Counters(),
            streaming=StreamingConfig(prewarm=False),
        )
        t0 = time.perf_counter()
        s = p.acquire("sphere", STREAM_POP, genome_len, seed=0)
        s.ask(8)
        s.step(1)
        return (time.perf_counter() - t0) * 1e3

    def first_ask_warm() -> float:
        t0 = time.perf_counter()
        s = pool.acquire("sphere", STREAM_POP, STREAM_LEN, seed=0)
        s.ask(8)
        s.step(1)
        dt = (time.perf_counter() - t0) * 1e3
        pool.release(s)
        return dt

    # Fold-overhead pair: one persistent session, adjacent fold/plain
    # steps (both programs compiled outside the timed samples).
    fold_sess = EvolutionSession(
        "sphere", STREAM_POP, STREAM_LEN, seed=1, config=cfg
    )
    told = np.zeros((1, STREAM_LEN), np.float32)
    fold_sess.tell(told, np.array([-1e9], np.float32))
    fold_sess.step(2)  # compiles the inject program
    fold_sess.step(2)  # compiles the plain program

    def step_with_fold(n: int) -> float:
        fold_sess.tell(told, np.array([-1e9], np.float32))
        t0 = time.perf_counter()
        fold_sess.step(n)
        return time.perf_counter() - t0

    def step_plain(n: int) -> float:
        t0 = time.perf_counter()
        fold_sess.step(n)
        return time.perf_counter() - t0

    def churn(seconds: float = 0.5) -> float:
        t0 = time.perf_counter()
        done = 0
        while time.perf_counter() - t0 < seconds:
            s = pool.acquire(
                "sphere", STREAM_CHURN_POP, STREAM_CHURN_LEN, seed=done
            )
            s.step(2)
            pool.release(s)
            done += 1
        return done / (time.perf_counter() - t0)

    # Two-tenant attribution A/B (ISSUE 14): two explicit-tenant
    # sessions interleaved against two anon ones, same shape and
    # budget — the host-side labeling cost, measured.
    tenant_sessions = [
        EvolutionSession(
            "sphere", STREAM_POP, STREAM_LEN, seed=50 + i, config=cfg,
            tenant=f"bench-tenant-{'ab'[i]}",
        )
        for i in range(2)
    ]
    anon_sessions = [
        EvolutionSession(
            "sphere", STREAM_POP, STREAM_LEN, seed=60 + i, config=cfg,
        )
        for i in range(2)
    ]
    for s in tenant_sessions + anon_sessions:
        s.step(2)  # compile outside the timed samples

    def tenant_pair_pct() -> float:
        t0 = time.perf_counter()
        for s in tenant_sessions:
            s.step(10)
        dt_tenant = time.perf_counter() - t0
        t0 = time.perf_counter()
        for s in anon_sessions:
            s.step(10)
        dt_anon = time.perf_counter() - t0
        return (dt_tenant / dt_anon - 1.0) * 100.0

    # Warm every pooled signature once outside the timed rounds.
    first_ask_warm()
    churn(0.1)

    cold_ms, warm_ms, fold_pct, churn_sps, tenant_pct = [], [], [], [], []
    ask_ms = []
    for r in range(rounds):
        # A fresh genome length per round keeps the cold sample cold
        # (process-wide caches key on shape).
        cold_ms.append(first_ask_cold(STREAM_LEN + 2 * (r + 1)))
        warm_ms.append(first_ask_warm())
        f = step_with_fold(20)
        p = step_plain(20)
        fold_pct.append((f / p - 1.0) * 100.0)
        churn_sps.append(churn())
        tenant_pct.append(tenant_pair_pct())
        # Per-ask latency distribution: individually timed asks on a
        # warm pooled session (fitnesses known, so ask really breeds).
        s = pool.acquire("sphere", STREAM_POP, STREAM_LEN, seed=r)
        s.step(1)
        s.ask(8)  # the k=8 ask program compiles once, outside the samples
        for _ in range(8):
            t0 = time.perf_counter()
            s.ask(8)
            ask_ms.append((time.perf_counter() - t0) * 1e3)
        pool.release(s)
    cold = _median_iqr(cold_ms)
    warm = _median_iqr(warm_ms)
    fold = _median_iqr(fold_pct)
    sps = _median_iqr(churn_sps)
    tenant = _median_iqr(tenant_pct)
    pool_stats = pool.stats()
    pool_lookups = pool_stats.get("hits", 0) + pool_stats.get("misses", 0)
    return {
        "streaming_first_ask_ms_cold": round(cold[0], 1),
        "streaming_first_ask_ms_cold_iqr": round(cold[1], 1),
        "streaming_first_ask_ms_warm": round(warm[0], 2),
        "streaming_first_ask_ms_warm_iqr": round(warm[1], 2),
        "streaming_warm_speedup": round(cold[0] / max(warm[0], 1e-9), 1),
        "streaming_fold_overhead_pct": round(fold[0], 2),
        "streaming_fold_overhead_pct_iqr": round(fold[1], 2),
        "streaming_sessions_per_sec": round(sps[0], 1),
        "streaming_sessions_per_sec_iqr": round(sps[1], 1),
        "streaming_ask_ms_p50": round(
            float(np.percentile(ask_ms, 50)), 3
        ),
        "streaming_ask_ms_p99": round(
            float(np.percentile(ask_ms, 99)), 3
        ),
        "streaming_pool_hit_rate": round(
            pool_stats.get("hits", 0) / max(pool_lookups, 1), 4
        ),
        "streaming_tenant_overhead_pct_median": round(tenant[0], 2),
        "streaming_tenant_overhead_pct_iqr": round(tenant[1], 2),
        "streaming_shape": f"{STREAM_POP}x{STREAM_LEN}",
        "streaming_churn_shape": f"{STREAM_CHURN_POP}x{STREAM_CHURN_LEN}",
        "streaming_note": (
            "cold = acquire+first ask+1 gen on a never-seen signature "
            "(fresh genome length per round, real compile); warm = the "
            "pooled signature (engine reuse, 0 compiles); "
            "fold_overhead = a 20-gen step whose boundary folds one "
            "pending tell (injection-slot program: one argsort + "
            "scatter) vs an adjacent plain step; sessions_per_sec = "
            "acquire->step(2)->release churn on the warm pool at "
            f"{STREAM_CHURN_POP}x{STREAM_CHURN_LEN}; ask_ms_p50/p99 = "
            "individually timed asks on a warm pooled session; "
            "pool_hit_rate over the whole arm; tenant_overhead = two "
            "explicit-tenant sessions vs two anon sessions, adjacent "
            "interleaved samples (attribution is host-side labeling "
            "only — bar: within the ~4% CPU drift floor). CPU backend "
            "figures; the cold/warm gap widens on TPU (Mosaic "
            "compiles are tens of seconds)."
        ),
    }


def single_derived(gene_dtype, gps) -> dict:
    """Roofline-relative figures for the single-population result,
    derived through the ISSUE 17 cost model (``libpga_tpu/perf/cost``)
    — the same plan→cost hook ``PGA.program_report`` uses, so this
    note and a program report for the same shape can never disagree.
    The flat keys keep their historical names/rounding for cross-round
    continuity; the ``roofline_*`` keys are the systematic replacement
    for the ad-hoc ``selection_matmul_mfu`` figure."""
    from libpga_tpu.perf import achieved as perf_achieved, breed_report

    report = breed_report(
        POP, GENOME_LEN, gene_dtype=gene_dtype, device_kind="TPU v5e",
    )
    got = perf_achieved(report, gps)
    # The FLOPs model counts ONLY the one-hot parent-selection matmuls
    # (perf/cost module docstring). "mfu" repeats selection_matmul_mfu
    # for cross-round continuity of the flat keys.
    mfu = round(got["flops_frac_of_peak"], 4)
    return {
        "ms_per_gen": round(1000.0 / gps, 3) if gps else None,
        "achieved_tflops": round(got["achieved_flops"] / 1e12, 2),
        "mfu": mfu,
        "selection_matmul_mfu": mfu,
        "achieved_hbm_gbps": round(
            got["achieved_hbm_bytes_per_sec"] / 1e9, 1
        ),
        "hbm_frac_of_peak": round(got["hbm_frac_of_peak"], 4),
        "roofline_gens_per_sec": round(report["roofline_gens_per_sec"], 1),
        "roofline_bound": report["bound"],
        "roofline_frac": round(got["roofline_frac"], 4),
    }


def main() -> None:
    import jax.numpy as jnp

    cache_dir = enable_persistent_cache()

    # Compile everything FIRST, then measure in ROUNDS interleaved
    # rounds with a fixed per-round ordering — the round-4 lesson
    # (BASELINE.md): only interleaved A/Bs are decision-grade on this
    # chip; sequential same-process figures minutes apart drift more
    # than the effects being compared. The islands sample immediately
    # follows the f32 sample in every round, so the tracked
    # islands/single ratio comes from adjacent measurements.
    runners = [
        ("f32", setup_single(jnp.float32), 50, 150),
        # Telemetry-overhead A/B arm: identical config + the on-device
        # history carry, sampled ADJACENT to f32 every round so the
        # tracked overhead comes from back-to-back measurements
        # (acceptance bar: < 2% at this shape).
        ("f32_telemetry", setup_single(jnp.float32, telemetry_gens=160),
         50, 150),
        ("islands", setup_islands(), 50, 150),
        ("bf16", setup_single(jnp.bfloat16), 50, 150),
        # Longer windows for the fast configs: at ~3,500 gens/sec the
        # old 400-generation ref40k delta was ~0.12 s and its IQR
        # spanned ~30% of the median; 1,000 generations keeps the
        # per-sample cost ~0.3 s and tightens the spread.
        ("ref40k", setup_reference_scale(), 200, 1200),
        ("tsp1k", setup_tsp1k(), 30, 90),
    ]
    samples: dict = {name: [] for name, *_ in runners}
    ratios = []
    tel_overheads = []
    for _ in range(ROUNDS):
        for name, run, lo, hi in runners:
            samples[name].append(_sample_gps(run, lo, hi))
        ratios.append(samples["islands"][-1] / samples["f32"][-1])
        # per-round overhead from the ADJACENT f32/f32_telemetry pair:
        # (1/gps_on) / (1/gps_off) - 1, in percent.
        tel_overheads.append(
            (samples["f32"][-1] / samples["f32_telemetry"][-1] - 1.0) * 100.0
        )

    med = {name: _median_iqr(xs) for name, xs in samples.items()}
    ratio_med, ratio_iqr = _median_iqr(ratios)
    tel_med, tel_iqr = _median_iqr(tel_overheads)

    baseline_gps = 1.0 / reference_floor_seconds_per_gen()
    f32_gps = med["f32"][0]
    out = {
        **provenance(cache_dir),
        "metric": "onemax_1M_generations_per_sec",
        "value": round(f32_gps, 2),
        "unit": "generations/sec",
        "vs_baseline": round(f32_gps / baseline_gps, 2),
        "interleaved_rounds": ROUNDS,
        "gens_per_sec_median": round(f32_gps, 2),
        "gens_per_sec_iqr": round(med["f32"][1], 2),
        "bf16_gens_per_sec": round(med["bf16"][0], 2),
        "bf16_gens_per_sec_median": round(med["bf16"][0], 2),
        "bf16_gens_per_sec_iqr": round(med["bf16"][1], 2),
        "islands_8x128k_gens_per_sec": round(med["islands"][0], 2),
        "islands_gens_per_sec_median": round(med["islands"][0], 2),
        "islands_gens_per_sec_iqr": round(med["islands"][1], 2),
        "ref40k_gens_per_sec": round(med["ref40k"][0], 1),
        "ref40k_gens_per_sec_median": round(med["ref40k"][0], 1),
        "ref40k_gens_per_sec_iqr": round(med["ref40k"][1], 1),
        "islands_single_ratio_median": round(ratio_med, 3),
        "islands_single_ratio_iqr": round(ratio_iqr, 3),
        "tsp1k_gens_per_sec": round(med["tsp1k"][0], 1),
        "tsp1k_gens_per_sec_median": round(med["tsp1k"][0], 1),
        "tsp1k_gens_per_sec_iqr": round(med["tsp1k"][1], 1),
        # Telemetry-overhead A/B (utils/telemetry history carry at the
        # headline shape; per-round from adjacent pairs, ISSUE 2 bar <2%).
        "telemetry_gens_per_sec_median": round(med["f32_telemetry"][0], 2),
        "telemetry_overhead_pct_median": round(tel_med, 2),
        "telemetry_overhead_pct_iqr": round(tel_iqr, 2),
    }
    d32 = single_derived(jnp.float32, f32_gps)
    out.update(d32)
    d16 = single_derived(jnp.bfloat16, med["bf16"][0])
    out.update({f"bf16_{k}": v for k, v in d16.items() if k != "ms_per_gen"})
    # The caveat BASELINE.md carries, now ON the scored artifact: mfu is
    # a matmul-utilization gauge, not a hardware-ceiling claim.
    out["mfu_note"] = (
        "mfu/selection_matmul_mfu count ONLY the one-hot parent-selection "
        "matmul FLOPs — rank sort, PRNG, crossover/mutation, and fused "
        "evaluation are real kernel work the model excludes; gens/sec is "
        "the headline metric"
    )
    # Permanent serving + supervised + sharded + fleet arms (ISSUE
    # 4 / 5 / 7 / 8) — backend-agnostic, so they ride every bench run,
    # chip or CPU (the sharded arm skips itself below its device
    # requirement).
    out.update(serving_arm())
    out.update(supervised_arm())
    out.update(sharded_arm())
    out.update(fleet_arm())
    out.update(autotuned_arm())
    out.update(gp_arm())
    out.update(streaming_arm())
    print(json.dumps(out))


def serving_main() -> None:
    """``python bench.py --serving``: the serving arm alone — decision-
    grade on the CPU backend (runs/sec scaling needs no chip, unlike
    the kernel arms, whose setup raises off-TPU)."""
    cache_dir = enable_persistent_cache()
    out = {
        **provenance(cache_dir),
        "metric": "serving_runs_per_sec_16kx100",
        **serving_arm(),
    }
    print(json.dumps(out))


def supervised_main() -> None:
    """``python bench.py --supervised``: the supervisor-overhead arm
    alone — CPU-decision-grade like the serving arm."""
    cache_dir = enable_persistent_cache()
    out = {
        **provenance(cache_dir),
        "metric": "supervised_overhead_pct_16kx100",
        **supervised_arm(),
    }
    print(json.dumps(out))


def fleet_main() -> None:
    """``python bench.py --fleet``: the cross-process fleet arm alone
    (ISSUE 8) — CPU-decision-grade for the coordination-overhead and
    drain/resume figures (see fleet_note on the artifact)."""
    cache_dir = enable_persistent_cache()
    out = {
        **provenance(cache_dir),
        "metric": f"fleet_runs_per_sec_{FLEET_POP}x{FLEET_LEN}",
        **fleet_arm(),
    }
    print(json.dumps(out))


def autotuned_main() -> None:
    """``python bench.py --autotuned``: the self-tuning arm alone
    (ISSUE 10) — CPU-decision-grade as a null measurement of the
    tuner + resolution harness; the kernel-space verdict needs a
    chip (see autotuned_note on the artifact)."""
    cache_dir = enable_persistent_cache()
    out = {
        **provenance(cache_dir),
        "metric": f"tuned_vs_default_ratio_{AUTOTUNE_POP}x{AUTOTUNE_LEN}",
        **autotuned_arm(),
    }
    print(json.dumps(out))


def gp_main() -> None:
    """``python bench.py --gp``: the tree-GP symbolic-regression arm
    alone (ISSUE 11) — CPU-decision-grade for the interpreter path and
    the evaluator-share model; the fused-kernel figure needs a chip
    (see gp_note on the artifact)."""
    cache_dir = enable_persistent_cache()
    out = {
        **provenance(cache_dir),
        "metric": f"gp_gens_per_sec_{GP_POP}x{GP_NODES}nodes",
        **gp_arm(),
    }
    print(json.dumps(out))


def streaming_main() -> None:
    """``python bench.py --streaming``: the streaming evolution service
    arm alone (ISSUE 12) — CPU-decision-grade for the warm-pool
    compile-amortization, fold-overhead, and tenant-churn figures (see
    streaming_note on the artifact)."""
    cache_dir = enable_persistent_cache()
    out = {
        **provenance(cache_dir),
        "metric": f"streaming_first_ask_ms_{STREAM_POP}x{STREAM_LEN}",
        **streaming_arm(),
    }
    print(json.dumps(out))


def sharded_main() -> None:
    """``python bench.py --pop-shards [S]``: the population-sharding
    arm alone (ISSUE 7). On CPU hosts the multi-device platform is
    forced BEFORE backend init so the S-way mesh exists; the
    gens/sec figure is CPU-decision-grade for the OVERHEAD model
    (collective cost), not for cross-device scaling (all shards
    timeshare this host's core — see sharded_arm)."""
    import sys

    shards = SHARDED_SHARDS
    argv = sys.argv[1:]
    i = argv.index("--pop-shards")
    if i + 1 < len(argv) and argv[i + 1].isdigit():
        shards = int(argv[i + 1])
    from libpga_tpu.utils.compat import force_cpu_device_count

    force_cpu_device_count(max(shards, 1))
    cache_dir = enable_persistent_cache()
    out = {
        **provenance(cache_dir),
        "metric": f"sharded_gens_per_sec_{SHARDED_POP}x{SHARDED_LEN}",
        **sharded_arm(shards=shards),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    import sys

    if "--serving" in sys.argv[1:]:
        serving_main()
    elif "--supervised" in sys.argv[1:]:
        supervised_main()
    elif "--fleet" in sys.argv[1:]:
        fleet_main()
    elif "--autotuned" in sys.argv[1:]:
        autotuned_main()
    elif "--gp" in sys.argv[1:]:
        gp_main()
    elif "--streaming" in sys.argv[1:]:
        streaming_main()
    elif "--pop-shards" in sys.argv[1:]:
        sharded_main()
    else:
        main()
