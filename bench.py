"""Headline benchmark: generations/sec on 1M-population OneMax, one chip.

The workload is the reference's first driver scaled to the BASELINE.json
target: the reference runs pop 40,000 × 100 genes × 100 generations
(``/root/reference/test/test.cu:37,43,22``) as ~79 chunked kernel launches ×
3 operators × 100 generations, each followed by a full device sync
(``/root/reference/src/pga.cu:62-77,269``). Here the same GA — tournament-2
selection, uniform crossover, 0.01 point mutation — runs as ONE jitted XLA
program per whole run at pop 1,048,576 × 100.

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": "generations/sec", "vs_baseline": N}

``vs_baseline`` is measured against an analytic model of the reference on a
modern datacenter GPU (see BASELINE.md — the reference publishes no numbers,
so the baseline is its launch-bound execution model: ceil(pop/512) serialized
launches × 3 operators × ~3.5 µs launch+sync overhead per generation), i.e.
values > 1 mean faster than the reference's architecture could possibly go
regardless of its per-thread compute speed.
"""

from __future__ import annotations

import json
import math
import time


POP = 1 << 20  # 1,048,576
GENOME_LEN = 100
WARMUP_GENS = 10
BENCH_GENS = 200


def reference_floor_seconds_per_gen() -> float:
    """Analytic lower bound on the reference's per-generation wall time.

    The reference serializes ceil(pop/512) kernel launches per operator, 3
    operators per generation, each launch followed by cudaDeviceSynchronize
    (``src/pga.cu:62-77``: blocks=8 × threads=64 = 512 individuals/launch),
    plus one cuRAND pool refill. Taking ~3.5 µs as an optimistic
    launch+sync round-trip on a modern GPU and ignoring ALL compute and
    memory time, the floor is launches × 3.5 µs.
    """
    launches_per_op = math.ceil(POP / 512)
    return launches_per_op * 3 * 3.5e-6


def main() -> None:
    from libpga_tpu import PGA, PGAConfig

    pga = PGA(seed=42, config=PGAConfig(use_pallas=True))
    pga.create_population(POP, GENOME_LEN)
    pga.set_objective("onemax")

    pga.run(WARMUP_GENS)  # compile + warm caches
    # Best-of-3: the tunneled chip's throughput varies ~±15% between
    # process states; the max is the stable hardware-limited figure.
    # pga.run() itself blocks on device completion (it fetches the
    # executed-generation count), so the timed region is fully synchronous.
    gps = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        gens = pga.run(BENCH_GENS)
        dt = time.perf_counter() - t0
        gps = max(gps, gens / dt)
    baseline_gps = 1.0 / reference_floor_seconds_per_gen()
    print(
        json.dumps(
            {
                "metric": "onemax_1M_generations_per_sec",
                "value": round(gps, 2),
                "unit": "generations/sec",
                "vs_baseline": round(gps / baseline_gps, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
