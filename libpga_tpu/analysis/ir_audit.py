"""IR contract auditor: programmatic jaxpr/StableHLO invariants (ISSUE 13).

Single-sources the structural gates the test suite used to re-derive by
hand in six places:

- :func:`fingerprint` — the canonical StableHLO digest behind every
  byte-identity gate (telemetry-off, fallback, db=None, GP-import
  inertness, pbt-off, pop_shards=1). The lowering text is canonicalized
  (the ``module @jit_<name>`` line is the ONLY thing JAX derives from
  the traced function's *name*), so two structurally identical programs
  fingerprint equal regardless of what their Python functions are
  called — strictly stronger than the old copy-pasted
  ``as_text() == as_text()`` checks, which silently required the
  replica to shadow the engine function's name.
- :func:`collective_budget` — the sharded-run cost model
  ("exactly one ppermute + one all_gather per generation, nothing
  else") asserted on the while-loop body of any lowered run function,
  replacing ``test_shard_pop.py``'s hand-rolled jaxpr scan and
  extensible to the islands/streaming paths and to any future backend
  (the GPU port must re-prove exactly this contract).
- :func:`donation_check` — ``input_output_aliases`` actually present on
  the ping-pong/donated paths (``tf.aliasing_output`` in the lowered
  module). Donation was an unverified assumption before this: a
  refactor dropping ``donate_argnums`` would have doubled peak HBM
  silently.
- :func:`callback_free` — no host callbacks in hot loops (the
  round-15 deadlock class: a ``pure_callback`` inside a fused while
  loop serializes every generation on the host).

All checks raise :class:`IRContractError` with the offending counts /
a text excerpt, and return their evidence for callers that assert more.

JAX is imported lazily inside functions: importing this module costs
nothing, so the lint fast path can expose the whole analysis package.
"""

from __future__ import annotations

import hashlib
import re
from typing import Dict, Optional, Sequence, Tuple

__all__ = [
    "IRContractError",
    "canonical_text",
    "fingerprint",
    "count_primitives",
    "while_body_counts",
    "collective_budget",
    "donation_check",
    "callback_free",
]


class IRContractError(AssertionError):
    """A lowered program violates one of the repo's IR contracts."""


#: Cross-device collective primitives: the complete vocabulary the
#: budget accounts for. Anything here that is not explicitly budgeted
#: must appear zero times.
COLLECTIVE_PRIMS = (
    "ppermute", "all_gather", "all_to_all", "psum", "pmax", "pmin",
    "pmean", "reduce_scatter", "pgather", "axis_index",
)

#: Host-callback primitives (jaxpr names) + StableHLO custom-call
#: targets that round-trip through Python. Any of these inside a run
#: loop is the round-15 deadlock class.
CALLBACK_PRIMS = ("pure_callback", "io_callback", "debug_callback")
CALLBACK_CUSTOM_CALLS = (
    "xla_python_cpu_callback", "xla_python_gpu_callback",
    "xla_ffi_python_cpu_callback",
)

_MODULE_NAME_RE = re.compile(r"module @[\w.\-]+")


def _lowered(fn, *args, donate_argnums: Optional[Tuple[int, ...]] = None):
    """A ``Lowered`` for ``fn`` at ``args`` (concrete arrays or
    ShapeDtypeStructs). ``fn`` may be a plain callable, a jit wrapper,
    or anything with ``.lower``; plain callables are jitted here (with
    ``donate_argnums`` when given)."""
    import jax

    if hasattr(fn, "lower"):
        return fn.lower(*args)
    kw = {}
    if donate_argnums is not None:
        kw["donate_argnums"] = donate_argnums
    return jax.jit(fn, **kw).lower(*args)


def canonical_text(
    fn, *args, donate_argnums: Optional[Tuple[int, ...]] = None
) -> str:
    """The lowering's StableHLO text with the function-name-derived
    module id normalized away. Everything else — every op, every shape,
    every donation attribute — is preserved byte-for-byte, so equality
    of canonical texts is exactly "the same program"."""
    text = _lowered(fn, *args, donate_argnums=donate_argnums).as_text()
    return _MODULE_NAME_RE.sub("module @jit__canonical", text, count=1)


def fingerprint(
    fn, *args, donate_argnums: Optional[Tuple[int, ...]] = None
) -> str:
    """Canonical StableHLO digest (sha256 hex) of ``fn`` lowered at
    ``args`` — the one implementation behind every byte-identity gate.
    Stable across processes at a fixed seed (asserted by
    ``tests/test_analysis.py``); compare digests with ``==`` and diff
    :func:`canonical_text` when a gate trips."""
    text = canonical_text(fn, *args, donate_argnums=donate_argnums)
    return hashlib.sha256(text.encode("utf-8")).hexdigest()


# ------------------------------------------------------------ jaxpr walks


def _subjaxprs(eqn):
    from jax.core import ClosedJaxpr, Jaxpr

    for v in eqn.params.values():
        vals = v if isinstance(v, (list, tuple)) else (v,)
        for vv in vals:
            if isinstance(vv, ClosedJaxpr):
                yield vv.jaxpr
            elif isinstance(vv, Jaxpr):
                yield vv


def _count(jxp, counts: Dict[str, int]) -> Dict[str, int]:
    for eqn in jxp.eqns:
        counts[eqn.primitive.name] = counts.get(eqn.primitive.name, 0) + 1
        for sub in _subjaxprs(eqn):
            _count(sub, counts)
    return counts


def _find_eqns(jxp, name: str, acc: list) -> list:
    for eqn in jxp.eqns:
        if eqn.primitive.name == name:
            acc.append(eqn)
        for sub in _subjaxprs(eqn):
            _find_eqns(sub, name, acc)
    return acc


def _jaxpr(fn, *args):
    import jax

    # ``lambda *a: fn(*a)`` unwraps jit wrappers (make_jaxpr of a jitted
    # fn yields one opaque pjit eqn whose body the recursive walks then
    # open anyway — going through a plain call keeps one code path).
    return jax.make_jaxpr(lambda *a: fn(*a))(*args)


def count_primitives(fn, *args) -> Dict[str, int]:
    """Recursive primitive histogram of ``fn``'s whole jaxpr (control
    flow bodies included)."""
    return _count(_jaxpr(fn, *args).jaxpr, {})


def while_body_counts(fn, *args) -> Dict[str, int]:
    """Primitive histogram of the (single) while-loop body — i.e. one
    generation of a fused run loop. Raises when the program does not
    contain exactly one while loop (the fused-run-loop shape every
    engine path guarantees)."""
    whiles = _find_eqns(_jaxpr(fn, *args).jaxpr, "while", [])
    if len(whiles) != 1:
        raise IRContractError(
            f"expected exactly one while loop in the lowered run, "
            f"found {len(whiles)} — not a fused run loop?"
        )
    return _count(whiles[0].params["body_jaxpr"].jaxpr, {})


def collective_budget(
    fn,
    *args,
    ppermute: int = 1,
    all_gather: int = 1,
    others: int = 0,
    where: str = "while_body",
) -> Dict[str, int]:
    """Assert the per-generation cross-shard collective budget on a
    lowered run function: exactly ``ppermute`` ppermutes, exactly
    ``all_gather`` all_gathers, and at most ``others`` occurrences of
    any other collective (default: none at all) inside the fused while
    body (``where="while_body"``, the per-generation cost) or the whole
    program (``where="program"``). Returns the counted histogram.

    This is ISSUE 7's cost model as a library function: the shard_pop
    gate calls it with the defaults; a future islands/GPU path calls it
    with ITS budget — one implementation, every backend."""
    counts = (
        while_body_counts(fn, *args)
        if where == "while_body"
        else count_primitives(fn, *args)
    )
    problems = []
    if counts.get("ppermute", 0) != ppermute:
        problems.append(
            f"ppermute x{counts.get('ppermute', 0)} (budget {ppermute})"
        )
    if counts.get("all_gather", 0) != all_gather:
        problems.append(
            f"all_gather x{counts.get('all_gather', 0)} "
            f"(budget {all_gather})"
        )
    for prim in COLLECTIVE_PRIMS:
        if prim in ("ppermute", "all_gather"):
            continue
        if counts.get(prim, 0) > others:
            problems.append(
                f"{prim} x{counts[prim]} (budget {others})"
            )
    if problems:
        raise IRContractError(
            "collective budget violated in "
            f"{where}: {'; '.join(problems)}; full counts: "
            + str({
                k: v for k, v in sorted(counts.items())
                if k in COLLECTIVE_PRIMS
            })
        )
    return counts


def donation_check(
    fn, *args,
    min_donated: int = 1,
    donate_argnums: Optional[Tuple[int, ...]] = None,
) -> int:
    """Assert the lowered module actually carries input/output aliasing
    (``tf.aliasing_output`` on at least ``min_donated`` parameters) —
    i.e. the ping-pong donation the breed paths assume is REAL, not
    just requested. Returns the number of aliased parameters."""
    text = canonical_text(fn, *args, donate_argnums=donate_argnums)
    aliased = len(re.findall(r"tf\.aliasing_output", text))
    if aliased < min_donated:
        raise IRContractError(
            f"expected >= {min_donated} donated (aliased) parameters, "
            f"lowering carries {aliased} — donate_argnums dropped, or "
            "donation rejected (shape/dtype mismatch between input and "
            "output)?"
        )
    return aliased


def callback_free(fn, *args, where: str = "program") -> Dict[str, int]:
    """Assert no host-callback primitive appears in the lowered program
    (``where="program"``) or the fused while body only
    (``where="while_body"``). A callback inside a run loop serializes
    every generation on the host — the round-15 deadlock class.
    Returns the primitive histogram for further assertions."""
    counts = (
        while_body_counts(fn, *args)
        if where == "while_body"
        else count_primitives(fn, *args)
    )
    offending = {
        p: counts[p] for p in CALLBACK_PRIMS if counts.get(p, 0)
    }
    if offending:
        raise IRContractError(
            f"host callback(s) inside {where}: {offending} — hot loops "
            "must stay on-device (evaluate through a builtin/expression "
            "objective, or hoist the callback out of the loop)"
        )
    return counts


def text_callback_free(text: str) -> None:
    """StableHLO-text variant of :func:`callback_free` for already
    lowered programs: refuses python-callback custom-call targets."""
    hits = [t for t in CALLBACK_CUSTOM_CALLS if t in text]
    if hits:
        raise IRContractError(
            f"host-callback custom calls in lowered text: {hits}"
        )


# --------------------------------------------------------- repo contracts


def audit_repo(verbose: bool = False) -> list:
    """The CPU-lowerable IR contracts, audited on the LIVE engine — the
    ``tools/lint_pga.py --ir`` body. Returns a list of problem strings
    (empty = all contracts hold). Requires >= 4 visible devices for the
    sharded leg (the runner forces a simulated multi-device CPU
    platform before importing jax, as tests/conftest.py does)."""
    import jax
    import jax.numpy as jnp

    from libpga_tpu import PGA, PGAConfig, TelemetryConfig

    problems = []

    def note(msg):
        if verbose:
            print(f"  ir-audit: {msg}")

    def engine(**cfg):
        pga = PGA(seed=0, config=PGAConfig(use_pallas=False, **cfg))
        pga.create_population(64, 16)
        pga.set_objective("onemax")
        pop = pga._populations[0]
        args = (
            pop.genomes, jax.random.key(0), jnp.int32(3),
            jnp.float32(jnp.inf), pga._mutate_params(),
        )
        return pga._compiled_run(64, 16), args

    # 1. Host-config purity: the fallback policy (host-side robustness)
    #    must not reach the traced program.
    fn_default, args = engine()
    fn_raise, _ = engine(fallback="raise")
    fp_default = fingerprint(fn_default, *args)
    if fp_default != fingerprint(fn_raise, *args):
        problems.append(
            "fallback='raise' changed the lowered run program — the "
            "robustness layer leaked into the trace"
        )
    note("fallback purity OK")

    # 2. Telemetry: off-path carries no history machinery; on-path does
    #    (the auditor must SEE differences, not just equalities).
    fn_tel, _ = engine(telemetry=TelemetryConfig(history_gens=16))
    if fp_default == fingerprint(fn_tel, *args):
        problems.append(
            "telemetry-enabled run lowered identically to disabled — "
            "the history carry is not being traced"
        )
    if "dynamic_update_slice" in canonical_text(fn_default, *args):
        problems.append(
            "telemetry-off run contains dynamic_update_slice — history "
            "machinery leaked into the disabled path"
        )
    note("telemetry on/off structural split OK")

    # 3. Donation: the engine's ping-pong breed path really aliases its
    #    population buffer (config default donate_buffers=True).
    try:
        donation_check(fn_default, *args, min_donated=1)
        note("donation (input_output_aliases) OK")
    except IRContractError as e:
        problems.append(str(e))

    # 4. No host callbacks anywhere in the fused run.
    try:
        callback_free(fn_default, *args)
        note("callback-free run loop OK")
    except IRContractError as e:
        problems.append(str(e))

    # 5. The sharded collective budget on the real pop_shards=4
    #    lowering (skipped with a problem note when the platform has
    #    too few devices — the runner is expected to force 8).
    if len(jax.devices()) >= 4:
        pga = PGA(seed=7, config=PGAConfig(
            pop_shards=4, selection="truncation", mutation_rate=0.05,
            use_pallas=False,
        ))
        pga.create_population(256, 32)
        pga.set_objective("onemax_bits")
        sharded = pga._compiled_sharded_run(256, 32)
        pop = pga._populations[0]
        keys = jax.random.split(jax.random.key(0), 4)
        sargs = (
            pop.genomes, keys, jnp.int32(3), jnp.float32(jnp.inf),
            pga._mutate_params(),
        )
        try:
            collective_budget(
                sharded.jitted, *sargs, ppermute=1, all_gather=1
            )
            note("pop_shards=4 collective budget (1 ppermute + "
                 "1 all_gather) OK")
        except IRContractError as e:
            problems.append(str(e))
        # and the unsharded program must carry no collectives at all
        if "ppermute" in canonical_text(fn_default, *args):
            problems.append(
                "unsharded run program contains ppermute — cross-shard "
                "machinery leaked into pop_shards=1"
            )
    else:
        problems.append(
            f"ir-audit needs >= 4 devices for the sharded leg, have "
            f"{len(jax.devices())} (set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8)"
        )
    return problems
