"""Repo-specific static analysis: the invariant guard (ISSUE 13).

Twelve rounds of this codebase rest on conventions nothing enforced
mechanically: the atomic-rename spool discipline, event kinds that must
exist in ``telemetry.EVENT_FIELDS`` (the recurring bug class of rounds
9/12/13/14), StableHLO byte-identity gates copy-pasted across test
files, the hand-rolled "exactly 1 ppermute + 1 all_gather" jaxpr scan,
and a 3-way C ABI kept in sync by eyeball. This package turns those
implicit contracts into a checked analysis layer — the prerequisite for
the ROADMAP GPU port (every new backend must re-prove the same IR
contracts) and for letting fleet work touch the spool safely.

Three analyzers behind one runner (``tools/lint_pga.py``, CI stage 14):

- :mod:`~libpga_tpu.analysis.lint` — an AST visitor framework with
  repo-specific rules (``spool-atomic-write``, ``event-kind-registered``,
  ``no-wallclock-in-traced``, ``lock-guarded-registry``), each
  suppressible via a scoped ``# pga-lint: disable=<rule>`` comment with
  an unused-suppression check;
- :mod:`~libpga_tpu.analysis.ir_audit` — programmatic jaxpr/StableHLO
  contracts: :func:`fingerprint` (the canonical digest powering every
  byte-identity gate), :func:`collective_budget` (the sharded runs'
  1-ppermute + 1-all_gather cost model), :func:`donation_check`
  (``input_output_aliases`` actually present on donated paths) and
  :func:`callback_free` (no host callbacks in hot loops);
- :mod:`~libpga_tpu.analysis.abi_check` — the 3-way C ABI cross-check
  (``capi/pga_tpu.h`` prototypes ↔ ``capi/pga_tpu.cc`` marshal calls ↔
  ``capi_bridge.py`` defs ↔ the symbols ``capi/test_serving.c``
  exercises), including the retry-once sized-snapshot shape.

Import cost: ``lint`` and ``abi_check`` are pure-stdlib and
``ir_audit`` imports jax lazily, so the ANALYZERS cost nothing — but
importing them through this package pulls ``libpga_tpu/__init__``
(and therefore jax). ``tools/lint_pga.py`` loads the lint/ABI modules
standalone from their file paths for its jax-free fast path; test code
(which has jax anyway) imports from here.
"""

from libpga_tpu.analysis.lint import (  # noqa: F401
    Finding,
    RULES,
    lint_file,
    lint_paths,
    default_paths,
)
from libpga_tpu.analysis.ir_audit import (  # noqa: F401
    IRContractError,
    callback_free,
    canonical_text,
    collective_budget,
    count_primitives,
    donation_check,
    fingerprint,
)
from libpga_tpu.analysis.abi_check import check_abi, check_repo_abi  # noqa: F401

__all__ = [
    "Finding",
    "RULES",
    "lint_file",
    "lint_paths",
    "default_paths",
    "IRContractError",
    "fingerprint",
    "canonical_text",
    "collective_budget",
    "count_primitives",
    "donation_check",
    "callback_free",
    "check_abi",
    "check_repo_abi",
]
