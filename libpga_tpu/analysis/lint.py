"""AST lint pass: repo-specific rules over the Python tree (ISSUE 13).

A small visitor framework plus four rules encoding the conventions this
codebase actually relies on (each one a bug class that has already
happened, or an invariant a future backend port must not silently
break):

``spool-atomic-write``
    No bare ``open(path, "w")`` / ``np.savez(path)`` landing in durable
    state (spool / tuning DB / checkpoint files) inside ``libpga_tpu``:
    writes must route through the temp-file + ``os.replace``/``os.link``
    helpers (the discipline every crash-recovery proof in
    ``tools/chaos_smoke.py`` and ``tools/fleet_smoke.py`` rests on). A
    write is atomic-safe when its target is a temp name (the path
    expression — or the binding of the name it opens — mentions
    ``.tmp`` or comes from ``tempfile``). Append mode is allowed: the
    O_APPEND whole-line protocol is the spool's OTHER sanctioned write
    (trace/event logs).

``event-kind-registered``
    Every literal event kind at an ``_emit`` / ``emit`` /
    ``flight_note`` / ``note`` site must exist in
    ``telemetry.EVENT_FIELDS`` (parsed from the source, no import
    needed), and — where the call has no ``**kwargs`` — must pass every
    required field. Unknown kinds are the recurring bug: the schema
    validator allows them (forward compatibility), so a typo'd or
    unregistered kind ships silently and only fails when a consumer
    looks for its fields.

``no-wallclock-in-traced``
    No wall-clock reads (``time.time``/``monotonic``/...), host RNG
    (``np.random.*``, stdlib ``random``, ``os.urandom``, ``uuid``) or
    set-iteration nondeterminism inside functions that get traced —
    resolved by a call-graph walk from every function passed to
    ``jit``/``scan``/``while_loop``/``cond``/``fori_loop``/
    ``shard_map``/``pallas_call``/``vmap``. A wall-clock read inside a
    traced function is baked in at trace time (silently stale), and
    host RNG breaks the bit-identity guarantees every replay/recovery
    proof depends on.

``lock-guarded-registry``
    In any class that takes ``with self._lock:`` somewhere, an
    attribute the class mutates under that lock is a *protected*
    attribute — and every other mutation of it (outside ``__init__``)
    must also hold the lock. This is self-calibrating: classes without
    a lock, and attributes never locked, are untouched.

Suppression: append ``# pga-lint: disable=<rule>[,<rule>...]`` to the
flagged line. Suppressions are scoped to that line and CHECKED — one
that never fires is itself reported (``unused-suppression``), so stale
exemptions cannot accumulate.

This module is deliberately import-light (stdlib only) so the runner's
``--changed`` fast path never pays a JAX import.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Set, Tuple

# ----------------------------------------------------------------- model

#: Rule ids, in documentation order. ``unused-suppression`` is the
#: meta-rule emitted by the suppression checker itself.
RULES = (
    "spool-atomic-write",
    "event-kind-registered",
    "no-wallclock-in-traced",
    "lock-guarded-registry",
    "ring-framed-write",
    "unused-suppression",
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One diagnostic: ``path:line: [rule] message``."""

    path: str
    line: int
    rule: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


_SUPPRESS_RE = re.compile(r"#\s*pga-lint:\s*disable=([\w,\- ]+)")


def _suppressions(source: str) -> Dict[int, Set[str]]:
    """line -> suppressed rule set, from ``# pga-lint: disable=...``
    comments (found with the tokenizer, so a '#' inside a string can
    never be misread as a directive)."""
    out: Dict[int, Set[str]] = {}
    try:
        tokens = tokenize.generate_tokens(iter(source.splitlines(True)).__next__)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if m:
                rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
                out.setdefault(tok.start[0], set()).update(rules)
    except tokenize.TokenError:
        pass
    return out


# ----------------------------------------------------- shared AST helpers


def _dotted(node: ast.AST) -> Optional[str]:
    """`a.b.c` -> "a.b.c"; bare name -> "a"; anything else -> None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _call_name(call: ast.Call) -> Optional[str]:
    """Last component of the callee (``jax.lax.scan`` -> "scan")."""
    fn = call.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return ""


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class _Parents(ast.NodeVisitor):
    """Parent links + enclosing-function chains for a module tree."""

    def __init__(self, tree: ast.AST):
        self.parent: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parent[child] = node

    def enclosing_functions(self, node: ast.AST) -> List[ast.AST]:
        """Innermost-first chain of FunctionDef/Lambda containing node."""
        out = []
        cur = self.parent.get(node)
        while cur is not None:
            if isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                out.append(cur)
            cur = self.parent.get(cur)
        return out


# ------------------------------------------------- rule: spool-atomic-write

#: Write-intent open() modes. "a"/"ab" are exempt (the O_APPEND
#: whole-line protocol); "r+" is a read-modify that never lands durable
#: state here.
_WRITE_MODES = ("w", "x")

#: Path-expression markers that make a write atomic-safe.
_TMP_MARKERS = (".tmp", "tempfile", "mktemp", "TemporaryFile", "mkdtemp")

#: Path markers that pull the rule in even OUTSIDE libpga_tpu/ — writes
#: that name a spool/checkpoint location are durable state wherever
#: they live.
_SPOOL_MARKERS = (
    "spool", "pending", "claimed", "results", "leases", "ckpt",
    "checkpoint", "dead", "sessions",
)


def _binding_texts(
    name: str, scopes: List[ast.AST], module: ast.AST
) -> List[str]:
    """Unparsed value expressions of every visible binding of ``name``
    (enclosing functions innermost-first, then TOP-LEVEL module
    statements — another function's same-named local is not a
    binding)."""
    out = []

    def nodes_of(scope):
        if isinstance(scope, ast.Module):
            return list(scope.body)  # top level only: no descent
        return list(ast.walk(scope))

    for scope in list(scopes) + [module]:
        for node in nodes_of(scope):
            value = None
            if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == name
                for t in node.targets
            ):
                value = node.value
            elif isinstance(node, ast.AnnAssign) and isinstance(
                node.target, ast.Name
            ) and node.target.id == name:
                value = node.value
            elif isinstance(node, ast.NamedExpr) and isinstance(
                node.target, ast.Name
            ) and node.target.id == name:
                value = node.value
            if value is not None:
                out.append(_unparse(value))
    return out


def _path_texts(
    path_arg: ast.AST, parents: _Parents, module: ast.AST
) -> List[str]:
    """The path expression's source text plus the texts of every
    visible binding feeding it (one indirection level: the
    ``tmp = f"{path}.tmp"`` / ``meta = spool.path(...)`` idioms)."""
    texts = [_unparse(path_arg)]
    if isinstance(path_arg, ast.Name):
        scopes = parents.enclosing_functions(path_arg)
        texts += _binding_texts(path_arg.id, scopes, module)
    return texts


def rule_spool_atomic_write(ctx: "FileContext") -> List[Finding]:
    in_package = "libpga_tpu" in ctx.path.replace(os.sep, "/").split("/")
    findings = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        path_arg = None
        what = None
        if isinstance(node.func, ast.Name) and name == "open" and node.args:
            mode = None
            if len(node.args) >= 2:
                mode = _const_str(node.args[1])
            for kw in node.keywords:
                if kw.arg == "mode":
                    mode = _const_str(kw.value)
            if mode is None or not any(m in mode for m in _WRITE_MODES):
                continue
            path_arg = node.args[0]
            what = f'open(..., "{mode}")'
        elif name in ("savez", "savez_compressed", "save") and isinstance(
            node.func, ast.Attribute
        ):
            root = _dotted(node.func) or ""
            if not root.startswith(("np.", "numpy.")):
                continue
            if not node.args:
                continue
            path_arg = node.args[0]
            what = f"{root}(...)"
        else:
            continue
        texts = _path_texts(path_arg, ctx.parents, ctx.tree)
        spoolish = any(
            m in t.lower() for t in texts for m in _SPOOL_MARKERS
        )
        if not (in_package or spoolish):
            continue
        if any(m in t for t in texts for m in _TMP_MARKERS):
            continue
        findings.append(Finding(
            ctx.path, node.lineno, "spool-atomic-write",
            f"bare {what} on {texts[0]!r} — durable state must go "
            "through a temp file + os.replace/os.link (or append mode "
            "for whole-line logs)",
        ))
    return findings


# --------------------------------------------- rule: event-kind-registered

_EMIT_NAMES = ("_emit", "emit", "flight_note", "note")

#: Emitter names generic enough that only METHOD calls (``x.emit``,
#: ``self.note``) count — a local helper happening to be called
#: ``note(...)`` is not a telemetry site. ``_emit``/``flight_note`` are
#: repo-specific enough to match as bare names too.
_METHOD_ONLY_EMITTERS = ("emit", "note")

#: Emitter parameter names that carry a whole field dict (their field
#: sets are opaque to a static check — kind membership only).
_DICT_EMITTERS = ("flight_note", "note")


def load_event_fields(repo_root: str) -> Dict[str, Tuple[str, ...]]:
    """EVENT_FIELDS parsed out of ``utils/telemetry.py`` source — the
    single schema source, read without importing the package (the lint
    fast path must not pay a JAX import, and must keep working even
    when the package itself is broken)."""
    path = os.path.join(
        repo_root, "libpga_tpu", "utils", "telemetry.py"
    )
    with open(path, "r", encoding="utf-8") as fh:
        tree = ast.parse(fh.read())
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Name) and t.id == "EVENT_FIELDS":
                value = node.value
                if not isinstance(value, ast.Dict):
                    break
                out = {}
                for k, v in zip(value.keys, value.values):
                    kind = _const_str(k)
                    if kind is None:
                        continue
                    fields = tuple(
                        f for f in (
                            _const_str(e) for e in getattr(v, "elts", [])
                        ) if f is not None
                    )
                    out[kind] = fields
                return out
    raise ValueError(f"EVENT_FIELDS dict not found in {path}")


def rule_event_kind_registered(ctx: "FileContext") -> List[Finding]:
    fields = ctx.event_fields
    if fields is None:
        return []
    if ctx.path.replace(os.sep, "/").endswith("utils/telemetry.py"):
        return []  # the schema module itself (validators, doc examples)
    findings = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        if name not in _EMIT_NAMES:
            continue
        if name in _METHOD_ONLY_EMITTERS and not isinstance(
            node.func, ast.Attribute
        ):
            continue
        if not node.args:
            continue
        kind = _const_str(node.args[0])
        if kind is None:
            continue  # dynamic kind (e.g. re-emit of a parsed record)
        if kind not in fields:
            findings.append(Finding(
                ctx.path, node.lineno, "event-kind-registered",
                f"event kind {kind!r} is not registered in "
                "telemetry.EVENT_FIELDS — unknown kinds pass the schema "
                "validator silently; register the kind (with its "
                "required fields) instead",
            ))
            continue
        if name in _DICT_EMITTERS or any(
            kw.arg is None for kw in node.keywords
        ) or len(node.args) > 1:
            continue  # field dict / **kwargs: membership check only
        passed = {kw.arg for kw in node.keywords}
        missing = [f for f in fields[kind] if f not in passed]
        if missing:
            findings.append(Finding(
                ctx.path, node.lineno, "event-kind-registered",
                f"event {kind!r} emitted without required field(s) "
                f"{missing} (EVENT_FIELDS[{kind!r}] = "
                f"{list(fields[kind])})",
            ))
    return findings


# --------------------------------------------- rule: no-wallclock-in-traced

#: Call sites whose function-valued positional arguments get traced.
_TRACE_ENTRIES = (
    "jit", "while_loop", "scan", "fori_loop", "cond", "switch",
    "shard_map", "pallas_call", "vmap", "pmap", "checkpoint", "remat",
)

#: Attribute-chain patterns that read the host environment. Matched
#: against the dotted callee (aliases of the numpy/time/random modules
#: included below).
_WALLCLOCK_CALLS = {
    "time", "monotonic", "perf_counter", "time_ns", "monotonic_ns",
    "perf_counter_ns",
}
_HOST_RANDOM_ROOTS = ("np.random", "numpy.random", "random")
_BANNED_EXACT = {"os.urandom", "uuid.uuid1", "uuid.uuid4", "datetime.now",
                 "datetime.utcnow", "datetime.datetime.now",
                 "datetime.datetime.utcnow"}


class _ModuleIndex:
    """Per-module name resolution for the traced-call-graph walk."""

    def __init__(self, ctx: "FileContext"):
        self.ctx = ctx
        self.defs: Dict[str, ast.AST] = {}
        self.imports: Dict[str, str] = {}       # alias -> module
        self.from_imports: Dict[str, Tuple[str, str]] = {}  # name -> (mod, orig)
        for node in ctx.tree.body:
            self._index(node)
        # function defs at any nesting (for scope-chain resolution)
        self.all_defs: Dict[ast.AST, Dict[str, ast.AST]] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scope = self.all_defs.setdefault(node, {})
                for child in ast.walk(node):
                    if isinstance(
                        child, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ) and child is not node and self._directly_inside(
                        child, node
                    ):
                        scope[child.name] = child

    def _directly_inside(self, child: ast.AST, func: ast.AST) -> bool:
        cur = self.ctx.parents.parent.get(child)
        while cur is not None and not isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            cur = self.ctx.parents.parent.get(cur)
        return cur is func

    def _index(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.defs[node.name] = node
        elif isinstance(node, ast.Import):
            for alias in node.names:
                self.imports[alias.asname or alias.name.split(".")[0]] = (
                    alias.name
                )
        elif isinstance(node, ast.ImportFrom) and node.module:
            for alias in node.names:
                self.from_imports[alias.asname or alias.name] = (
                    node.module, alias.name
                )

    def resolve_local(
        self, name: str, site: ast.AST
    ) -> Optional[ast.AST]:
        """A FunctionDef for ``name`` visible from ``site`` (enclosing
        scopes innermost-first, then module level)."""
        for scope in self.ctx.parents.enclosing_functions(site):
            got = self.all_defs.get(scope, {}).get(name)
            if got is not None:
                return got
        return self.defs.get(name)


def _banned_call(dotted: str, index: _ModuleIndex) -> Optional[str]:
    """Why this dotted callee is banned inside traced code, or None."""
    if dotted in _BANNED_EXACT:
        return f"host-environment call {dotted}()"
    parts = dotted.split(".")
    root_alias = parts[0]
    root_module = index.imports.get(root_alias, root_alias)
    normalized = ".".join([root_module] + parts[1:])
    if (
        len(parts) == 2
        and root_module == "time"
        and parts[1] in _WALLCLOCK_CALLS
    ):
        return f"wall-clock read {dotted}()"
    for r in _HOST_RANDOM_ROOTS:
        if normalized == r or normalized.startswith(r + "."):
            # jax.random is fine; only numpy/stdlib random are host RNG
            return f"host RNG {dotted}()"
    return None


def _walk_traced(
    func: ast.AST,
    index: _ModuleIndex,
    root_desc: str,
    findings: List[Finding],
    seen: Set[int],
    depth: int = 0,
) -> None:
    if id(func) in seen or depth > 8:
        return
    seen.add(id(func))
    body = func.body if isinstance(func.body, list) else [func.body]
    for stmt in body:
        for node in ast.walk(stmt):
            # don't descend into nested defs unless they are called —
            # ast.walk does descend, but a nested def that is returned
            # (a factory pattern) IS usually the traced payload, so the
            # over-approximation errs on the safe side deliberately.
            if isinstance(node, ast.For):
                it = node.iter
                if isinstance(it, ast.Set) or (
                    isinstance(it, ast.Call)
                    and _call_name(it) == "set"
                ):
                    findings.append(Finding(
                        index.ctx.path, node.lineno,
                        "no-wallclock-in-traced",
                        "iteration over a set inside traced code "
                        f"(reached from {root_desc}) — set order is "
                        "nondeterministic across processes",
                    ))
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func)
            if dotted is not None:
                why = _banned_call(dotted, index)
                if why is not None:
                    findings.append(Finding(
                        index.ctx.path, node.lineno,
                        "no-wallclock-in-traced",
                        f"{why} inside traced code (reached from "
                        f"{root_desc}) — traced programs must be pure; "
                        "pass the value in as an argument instead",
                    ))
                    continue
            callee = None
            if isinstance(node.func, ast.Name):
                callee = index.resolve_local(node.func.id, node)
            if callee is not None:
                _walk_traced(
                    callee, index, root_desc, findings, seen, depth + 1
                )


def rule_no_wallclock_in_traced(ctx: "FileContext") -> List[Finding]:
    index = _ModuleIndex(ctx)
    findings: List[Finding] = []
    seen: Set[int] = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        entry = _call_name(node)
        if entry not in _TRACE_ENTRIES:
            continue
        # Only trust dotted jax-ish entries or bare names imported from
        # jax modules — a local helper that happens to be called
        # ``cond`` must not pull its arguments into the traced set.
        dotted = _dotted(node.func) or ""
        if "." not in dotted:
            src = ctx.module_index_fallback(dotted)
            if src is None or not src.startswith("jax"):
                continue
        for arg in node.args:
            root = None
            if isinstance(arg, ast.Lambda):
                root = arg
            elif isinstance(arg, ast.Name):
                root = index.resolve_local(arg.id, node)
            if root is not None:
                desc = (
                    f"{entry}() at line {node.lineno}"
                )
                _walk_traced(root, index, desc, findings, seen)
    return findings


# --------------------------------------------- rule: lock-guarded-registry

_MUTATOR_METHODS = {
    "append", "extend", "add", "update", "clear", "pop", "popleft",
    "remove", "discard", "insert", "setdefault",
}


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.X`` or ``self.X[...]`` -> "X"."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and isinstance(
        node.value, ast.Name
    ) and node.value.id == "self":
        return node.attr
    return None


def _is_lock_with(node: ast.With) -> bool:
    for item in node.items:
        expr = item.context_expr
        if isinstance(expr, ast.Attribute) and expr.attr.endswith("_lock"):
            if isinstance(expr.value, ast.Name) and expr.value.id == "self":
                return True
    return False


def _class_mutations(
    cls: ast.ClassDef,
) -> List[Tuple[str, ast.AST, bool, str]]:
    """(attr, node, under_lock, method_name) for every ``self.X``
    mutation in the class body."""
    out = []

    def visit(node: ast.AST, under: bool, method: str) -> None:
        if isinstance(node, ast.With):
            under2 = under or _is_lock_with(node)
            for child in node.body:
                visit(child, under2, method)
            return
        attrs: List[Tuple[str, ast.AST]] = []
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [
                node.target
            ]
            for t in targets:
                a = _self_attr(t)
                if a is not None:
                    attrs.append((a, node))
        if isinstance(node, ast.Delete):
            for t in node.targets:
                a = _self_attr(t)
                if a is not None:
                    attrs.append((a, node))
        if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
            call = node.value
            if isinstance(call.func, ast.Attribute) and (
                call.func.attr in _MUTATOR_METHODS
            ):
                a = _self_attr(call.func.value)
                if a is not None:
                    attrs.append((a, node))
        for a, n in attrs:
            out.append((a, n, under, method))
        for child in ast.iter_child_nodes(node):
            visit(child, under, method)

    for item in cls.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for stmt in item.body:
                visit(stmt, False, item.name)
    return out


def rule_lock_guarded_registry(ctx: "FileContext") -> List[Finding]:
    findings = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        muts = _class_mutations(node)
        protected = {a for a, _, under, m in muts if under}
        if not protected:
            continue
        for attr, site, under, method in muts:
            if under or method == "__init__" or attr not in protected:
                continue
            findings.append(Finding(
                ctx.path, site.lineno, "lock-guarded-registry",
                f"{node.name}.{attr} is mutated under self._lock "
                f"elsewhere but written here ({method}) without it — "
                "lock-protected state must stay lock-protected",
            ))
    return findings


# ------------------------------------------------ rule: ring-framed-write

#: Buffer-expression markers that make a write target "the shared ring
#: mapping": a direct mmap mention, or the repo's ring-mapping attribute
#: idiom. Bare names resolve one indirection level through their
#: visible bindings (``mm = mmap.mmap(...)``) — a plain ``bytearray``
#: staging image never matches.
_MMAP_MARKERS = ("mmap", "._mm")

#: Function-name prefix whose bodies are the SANCTIONED writers (the
#: seqlock/CRC framed-store helpers in ``serving/shm_ring.py``).
_FRAMED_PREFIX = "_framed"


def _mmapish(node: ast.AST, ctx: "FileContext") -> bool:
    texts = [_unparse(node)]
    if isinstance(node, ast.Name):
        scopes = ctx.parents.enclosing_functions(node)
        texts += _binding_texts(node.id, scopes, ctx.tree)
    return any(m in t for t in texts for m in _MMAP_MARKERS)


def _in_framed_writer(node: ast.AST, ctx: "FileContext") -> bool:
    return any(
        getattr(fn, "name", "").startswith(_FRAMED_PREFIX)
        for fn in ctx.parents.enclosing_functions(node)
    )


def rule_ring_framed_write(ctx: "FileContext") -> List[Finding]:
    """Every mutation of a shared mmap region must go through the
    framed seqlock writers (``_framed_*``): a raw slice-assign or
    ``pack_into`` onto a mapping is exactly the torn-read window the
    seqlock + CRC framing exists to close. Readers are never flagged
    (they validate), and building a staging ``bytearray`` image for an
    atomic file replace is not a shared-mapping write."""
    findings = []
    for node in ast.walk(ctx.tree):
        target = None
        what = None
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [
                node.target
            ]
            for t in targets:
                if isinstance(t, ast.Subscript) and _mmapish(
                    t.value, ctx
                ):
                    target = t.value
                    what = f"{_unparse(t)} = ..."
        elif isinstance(node, ast.Call) and _call_name(node) in (
            "pack_into",
        ):
            if len(node.args) >= 2 and _mmapish(node.args[1], ctx):
                target = node.args[1]
                what = f"pack_into(..., {_unparse(node.args[1])}, ...)"
        if target is None:
            continue
        if _in_framed_writer(node, ctx):
            continue
        findings.append(Finding(
            ctx.path, node.lineno, "ring-framed-write",
            f"raw mmap mutation {what} outside a {_FRAMED_PREFIX}* "
            "writer — shared-ring bytes must go through the seqlock/"
            "CRC framed-store helpers (serving/shm_ring.py) so readers "
            "can detect torn writes",
        ))
    return findings


# ----------------------------------------------------------------- driver


class FileContext:
    """Everything a rule needs about one file."""

    def __init__(
        self,
        path: str,
        source: str,
        event_fields: Optional[Dict[str, Tuple[str, ...]]],
    ):
        self.path = path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.parents = _Parents(self.tree)
        self.event_fields = event_fields
        self._bare_import_sources: Optional[Dict[str, str]] = None

    def module_index_fallback(self, name: str) -> Optional[str]:
        """Source module of a bare imported name (``from jax import
        jit`` -> "jax"); None for locals/builtins."""
        if self._bare_import_sources is None:
            out: Dict[str, str] = {}
            for node in ast.walk(self.tree):
                if isinstance(node, ast.ImportFrom) and node.module:
                    for alias in node.names:
                        out[alias.asname or alias.name] = node.module
            self._bare_import_sources = out
        return self._bare_import_sources.get(name)


_FILE_RULES = {
    "spool-atomic-write": rule_spool_atomic_write,
    "event-kind-registered": rule_event_kind_registered,
    "no-wallclock-in-traced": rule_no_wallclock_in_traced,
    "lock-guarded-registry": rule_lock_guarded_registry,
    "ring-framed-write": rule_ring_framed_write,
}


def repo_root_of(path: str) -> str:
    """Walk up from ``path`` to the directory containing libpga_tpu/."""
    cur = os.path.abspath(path if os.path.isdir(path) else os.path.dirname(path))
    while cur != os.path.dirname(cur):
        if os.path.isdir(os.path.join(cur, "libpga_tpu")):
            return cur
        cur = os.path.dirname(cur)
    return os.getcwd()


def lint_file(
    path: str,
    source: Optional[str] = None,
    rules: Optional[Iterable[str]] = None,
    event_fields: Optional[Dict[str, Tuple[str, ...]]] = None,
) -> List[Finding]:
    """Lint one Python file; returns surviving findings (suppressions
    applied, unused suppressions reported)."""
    if source is None:
        with open(path, "r", encoding="utf-8") as fh:
            source = fh.read()
    if event_fields is None:
        try:
            event_fields = load_event_fields(repo_root_of(path))
        except (OSError, ValueError):
            event_fields = None
    try:
        ctx = FileContext(path, source, event_fields)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 0, "parse-error", str(e))]
    selected = rules if rules is not None else _FILE_RULES.keys()
    raw: List[Finding] = []
    for rule in selected:
        fn = _FILE_RULES.get(rule)
        if fn is not None:
            raw.extend(fn(ctx))
    sup = _suppressions(source)
    used: Dict[int, Set[str]] = {}
    kept = []
    for f in raw:
        if f.rule in sup.get(f.line, ()):  # scoped, same-line
            used.setdefault(f.line, set()).add(f.rule)
            continue
        kept.append(f)
    for line, rules_here in sorted(sup.items()):
        for rule in sorted(rules_here - used.get(line, set())):
            if rules is not None and rule not in selected:
                continue  # a partial run can't prove a suppression dead
            kept.append(Finding(
                path, line, "unused-suppression",
                f"suppression for {rule!r} never fired on this line — "
                "remove it (or fix the directive)",
            ))
    kept.sort(key=lambda f: (f.path, f.line, f.rule))
    return kept


def default_paths(repo_root: str) -> List[str]:
    """The full-tree lint set: every .py under libpga_tpu/, tools/ and
    tests/ (fixtures excluded — they exist to violate the rules) plus
    the top-level scripts."""
    out = []
    for base in ("libpga_tpu", "tools", "tests"):
        root = os.path.join(repo_root, base)
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [
                d for d in dirnames
                if d not in ("__pycache__", "fixtures")
            ]
            for f in sorted(filenames):
                if f.endswith(".py"):
                    out.append(os.path.join(dirpath, f))
    for f in ("bench.py",):
        p = os.path.join(repo_root, f)
        if os.path.exists(p):
            out.append(p)
    return out


def lint_paths(
    paths: Iterable[str], rules: Optional[Iterable[str]] = None
) -> List[Finding]:
    findings: List[Finding] = []
    event_fields: Optional[Dict[str, Tuple[str, ...]]] = None
    for path in paths:
        if event_fields is None:
            try:
                event_fields = load_event_fields(repo_root_of(path))
            except (OSError, ValueError):
                event_fields = None
        findings.extend(
            lint_file(path, rules=rules, event_fields=event_fields)
        )
    return findings
