"""C-ABI cross-checker: pga_tpu.h ↔ pga_tpu.cc ↔ capi_bridge.py (ISSUE 13).

The improved C ABI is a 3-layer sandwich kept in sync — until now — by
eyeball: ``capi/pga_tpu.h`` declares the ``extern "C"`` surface,
``capi/pga_tpu.cc`` forwards each entry point to a named
``libpga_tpu.capi_bridge`` function through a ``Py_BuildValue`` format
string, and the bridge function's Python signature must accept exactly
what that format string marshals. A drift in any pairing (renamed
bridge function, added parameter, edited format string) compiles
cleanly and fails only at RUNTIME inside an embedded interpreter —
the worst possible place. This module pins all of it statically:

- every header prototype has a definition in the .cc (and vice versa);
- every bridge call inside a definition targets a real
  ``capi_bridge`` function, with a format-string arity the Python
  signature accepts (``y#`` pairs marshal ONE Python bytes argument);
- header functions whose definitions forward nothing are flagged (a
  stub that silently returns is drift, not an implementation);
- every ``pga_*`` symbol a C driver (``capi/test_serving.c``, ...)
  exercises must be declared in the header;
- the sized-snapshot entry points (``pga_*_snapshot``) keep the
  documented retry-once shape: ``long`` return, trailing
  ``(char *buf, unsigned long cap)``.

Pure stdlib (regex + ast over source text): runs without compiling C
or importing jax, so it belongs in the lint fast path whenever the ABI
files change.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, List, Optional, Tuple

from libpga_tpu.analysis.lint import Finding

__all__ = [
    "HeaderFn",
    "BridgeCall",
    "BridgeFn",
    "parse_header",
    "parse_cc",
    "parse_bridge",
    "parse_driver_symbols",
    "format_arg_count",
    "check_abi",
    "check_repo_abi",
]


@dataclasses.dataclass(frozen=True)
class HeaderFn:
    name: str
    ret: str
    args: Tuple[str, ...]
    line: int


@dataclasses.dataclass(frozen=True)
class BridgeCall:
    bridge_name: str
    fmt: str
    line: int


@dataclasses.dataclass(frozen=True)
class CcFn:
    name: str
    line: int
    calls: Tuple[BridgeCall, ...]


@dataclasses.dataclass(frozen=True)
class BridgeFn:
    name: str
    line: int
    min_args: int
    max_args: int
    has_varargs: bool


def _strip_c_comments(text: str) -> str:
    """Remove /* */ and // comments, preserving line numbers (each
    stripped character becomes a space or keeps its newline)."""
    out = []
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c == "/" and i + 1 < n and text[i + 1] == "*":
            j = text.find("*/", i + 2)
            j = n if j < 0 else j + 2
            out.append("".join(ch if ch == "\n" else " " for ch in text[i:j]))
            i = j
        elif c == "/" and i + 1 < n and text[i + 1] == "/":
            j = text.find("\n", i)
            j = n if j < 0 else j
            out.append(" " * (j - i))
            i = j
        elif c == '"':
            j = i + 1
            while j < n and text[j] != '"':
                j += 2 if text[j] == "\\" else 1
            j = min(j + 1, n)
            out.append(text[i:j])
            i = j
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _split_args(argtext: str) -> Tuple[str, ...]:
    argtext = " ".join(argtext.split())
    if not argtext or argtext == "void":
        return ()
    parts, depth, cur = [], 0, []
    for ch in argtext:
        if ch in "([":
            depth += 1
        elif ch in ")]":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur).strip())
    return tuple(p for p in parts if p)


_PROTO_RE = re.compile(
    r"(?P<ret>[A-Za-z_][\w \t\*]*?)\s*\**\s*\b(?P<name>pga_\w+)\s*"
    r"\((?P<args>[^;{}]*)\)\s*(?P<tail>[;{])",
    re.S,
)


def _line_of(text: str, pos: int) -> int:
    return text.count("\n", 0, pos) + 1


def parse_header(path: str) -> Dict[str, HeaderFn]:
    """``extern "C"`` prototypes (``...;``) of every pga_* function."""
    with open(path, "r", encoding="utf-8") as fh:
        text = _strip_c_comments(fh.read())
    out: Dict[str, HeaderFn] = {}
    for m in _PROTO_RE.finditer(text):
        if m.group("tail") != ";":
            continue
        name = m.group("name")
        ret = " ".join(m.group("ret").split())
        # the regex's ret group stops before '*'s; recover pointerness
        between = text[m.start():m.start("name")]
        if "*" in between:
            ret += " *"
        out[name] = HeaderFn(
            name=name,
            ret=ret,
            args=_split_args(m.group("args")),
            line=_line_of(text, m.start("name")),
        )
    return out


_CALL_RE = re.compile(
    r"\bcall(?:_long)?\s*\(\s*\"(?P<bridge>\w+)\"\s*,\s*"
    r"\"(?P<fmt>\([^\"]*\))\"",
    re.S,
)


def _body_span(text: str, brace_pos: int) -> int:
    """End index of the balanced {...} body starting at brace_pos."""
    depth = 0
    for i in range(brace_pos, len(text)):
        if text[i] == "{":
            depth += 1
        elif text[i] == "}":
            depth -= 1
            if depth == 0:
                return i + 1
    return len(text)


def parse_cc(path: str) -> Dict[str, CcFn]:
    """pga_* function DEFINITIONS in the .cc shim with the bridge calls
    each body makes (bridge function name + marshal format string)."""
    with open(path, "r", encoding="utf-8") as fh:
        text = _strip_c_comments(fh.read())
    out: Dict[str, CcFn] = {}
    for m in _PROTO_RE.finditer(text):
        if m.group("tail") != "{":
            continue
        name = m.group("name")
        start = m.end() - 1
        end = _body_span(text, start)
        body = text[start:end]
        calls = tuple(
            BridgeCall(
                bridge_name=c.group("bridge"),
                fmt=c.group("fmt"),
                line=_line_of(text, start + c.start()),
            )
            for c in _CALL_RE.finditer(body)
        )
        out[name] = CcFn(
            name=name, line=_line_of(text, m.start("name")), calls=calls
        )
    return out


def parse_bridge(path: str) -> Dict[str, BridgeFn]:
    """Module-level function signatures of the Python bridge."""
    with open(path, "r", encoding="utf-8") as fh:
        tree = ast.parse(fh.read(), filename=path)
    out: Dict[str, BridgeFn] = {}
    for node in tree.body:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        a = node.args
        npos = len(a.posonlyargs) + len(a.args)
        ndefaults = len(a.defaults)
        out[node.name] = BridgeFn(
            name=node.name,
            line=node.lineno,
            min_args=npos - ndefaults,
            max_args=npos,
            has_varargs=a.vararg is not None,
        )
    return out


_SYMBOL_RE = re.compile(r"\b(pga_\w+)\s*\(")


def parse_driver_symbols(path: str) -> Dict[str, int]:
    """pga_* symbols a C driver calls (first-use line each)."""
    with open(path, "r", encoding="utf-8") as fh:
        text = _strip_c_comments(fh.read())
    out: Dict[str, int] = {}
    for m in _SYMBOL_RE.finditer(text):
        out.setdefault(m.group(1), _line_of(text, m.start()))
    return out


def format_arg_count(fmt: str) -> int:
    """Python-argument count a ``Py_BuildValue`` format marshals.
    ``y#``/``s#`` pairs (pointer + length) marshal ONE Python bytes/str
    argument."""
    count = 0
    for ch in fmt:
        if ch in "()# ":
            continue
        if ch in "lLiIfdsykKbBhHnz":
            count += 1
        else:
            raise ValueError(f"unknown marshal unit {ch!r} in {fmt!r}")
    return count


_SNAPSHOT_RE = re.compile(r"_snapshot$")


def check_abi(
    header_path: str,
    cc_path: str,
    bridge_path: str,
    driver_paths: Tuple[str, ...] = (),
) -> List[Finding]:
    """Cross-check the three ABI layers (+ driver symbol coverage).
    Returns lint-style findings (empty = in sync)."""
    findings: List[Finding] = []
    header = parse_header(header_path)
    cc = parse_cc(cc_path)
    bridge = parse_bridge(bridge_path)

    def f(path, line, msg):
        findings.append(Finding(path, line, "abi-drift", msg))

    # Header ↔ .cc definition set equality.
    for name, proto in sorted(header.items()):
        if name not in cc:
            f(header_path, proto.line,
              f"{name} is declared in the header but has no definition "
              f"in {os.path.basename(cc_path)}")
    for name, impl in sorted(cc.items()):
        if name not in header:
            f(cc_path, impl.line,
              f"{name} is defined in the shim but has no prototype in "
              f"{os.path.basename(header_path)} — C callers cannot "
              "reach it")

    # Every definition forwards to the bridge; every bridge call
    # resolves, with a marshal arity the Python signature accepts.
    for name, impl in sorted(cc.items()):
        if name in header and not impl.calls:
            f(cc_path, impl.line,
              f"{name} forwards nothing to capi_bridge — a silent stub "
              "is ABI drift, not an implementation")
        for call in impl.calls:
            target = bridge.get(call.bridge_name)
            if target is None:
                f(cc_path, call.line,
                  f"{name} calls bridge function "
                  f"{call.bridge_name!r} which does not exist in "
                  f"{os.path.basename(bridge_path)}")
                continue
            try:
                n = format_arg_count(call.fmt)
            except ValueError as e:
                f(cc_path, call.line, f"{name}: {e}")
                continue
            if target.has_varargs:
                ok = n >= target.min_args
            else:
                ok = target.min_args <= n <= target.max_args
            if not ok:
                want = (
                    f">= {target.min_args}" if target.has_varargs
                    else f"{target.min_args}"
                    if target.min_args == target.max_args
                    else f"{target.min_args}..{target.max_args}"
                )
                f(cc_path, call.line,
                  f"{name} marshals {n} argument(s) via {call.fmt!r} "
                  f"to {call.bridge_name}() which takes {want} "
                  f"(capi_bridge.py:{target.line}) — signature drift")

    # Retry-once sized-snapshot shape.
    for name, proto in sorted(header.items()):
        if not _SNAPSHOT_RE.search(name):
            continue
        shape_ok = (
            proto.ret.strip() == "long"
            and len(proto.args) >= 2
            and "char" in proto.args[-2]
            and "unsigned long" in proto.args[-1]
        )
        if not shape_ok:
            f(header_path, proto.line,
              f"{name} must keep the documented retry-once snapshot "
              f"shape: `long {name}(..., char *buf, unsigned long "
              f"cap)` — found `{proto.ret} {name}"
              f"({', '.join(proto.args)})`")

    # Driver coverage: symbols a C test exercises must be declared.
    for dpath in driver_paths:
        for sym, line in sorted(parse_driver_symbols(dpath).items()):
            if sym not in header:
                f(dpath, line,
                  f"driver calls {sym} which "
                  f"{os.path.basename(header_path)} does not declare")
    findings.sort(key=lambda x: (x.path, x.line))
    return findings


def check_repo_abi(repo_root: str) -> List[Finding]:
    """The repo's own ABI file set (the ``lint_pga.py --abi`` body)."""
    capi = os.path.join(repo_root, "capi")
    return check_abi(
        os.path.join(capi, "pga_tpu.h"),
        os.path.join(capi, "pga_tpu.cc"),
        os.path.join(repo_root, "libpga_tpu", "capi_bridge.py"),
        driver_paths=(os.path.join(capi, "test_serving.c"),),
    )
