"""Device-speed custom objectives from a small expression language.

The reference's central extension point is a user-supplied objective
running AT DEVICE SPEED — a ``__device__`` function pointer installed via
``pga_set_objective_function`` (``/root/reference/include/pga.h:59,66``,
install idiom ``src/pga.cu:157-161``) and compiled into the evaluation
kernel. A host-language function pointer can't cross into a TPU program,
so the C ABI's raw-pointer path runs objectives on the HOST (batched,
but CPU-bound — ``capi_bridge.py``). This module closes that gap the
TPU-native way: the C (or Python) user supplies a small EXPRESSION over
the gene vector, which compiles to the same rowwise batched form the
builtin objectives use — eligible for in-kernel fusion, so a custom
objective scores children while they are still in VMEM, exactly like a
builtin.

The language (safe, no ``eval``; a ~100-line recursive-descent parser):

- ``g`` — the genome, a vector of ``L`` genes in [0, 1)
- ``i`` — the gene index vector ``0..L-1``; ``L`` — the genome length
- literals (``1.5``, ``2e-3``), ``pi``, ``e``
- named constants registered alongside the expression (scalars or
  length-``L`` vectors, broadcast elementwise)
- arithmetic ``+ - * / % **``, unary ``-``, parentheses
- comparisons ``< <= > >= ==`` (0/1-valued), ``where(c, a, b)``
- elementwise ``sin cos tan tanh exp log sqrt abs floor round``,
  two-argument ``min(a, b)`` / ``max(a, b)``
- reductions ``sum(x) mean(x) min(x) max(x)`` (one-argument min/max
  reduce), ``dot(a, b)`` = ``sum(a*b)``
- **v2 — indexed/adjacency primitives** (verdict round 4 item 4):

  - ``name = expr;`` statements before the final expression bind
    locals, so multi-stage objectives (decode, then look up, then
    reduce) are written once instead of inlined repeatedly;
  - ``roll(x, k)`` — circular shift along the gene axis by an INTEGER
    LITERAL ``k``: ``roll(x, k)[i] = x[(i+k) mod L]``. Lowers as a
    lane-axis concat of two static slices — the same Mosaic-friendly
    form the builtin NK objective uses (``classic.py make_nk_landscape``),
    no gather;
  - ``gather(t, idx)`` — bounded table lookup: ``t`` must be a
    REGISTERED CONSTANT, ``idx`` any per-gene value (floored and
    clipped into the table). A 1-D ``t`` of length n is a shared
    table (``t[idx[i]]``); a 2-D ``t`` of shape (n, L) is a
    per-locus table (``t[idx[i], i]`` — the NK form). Lowers as a
    masked accumulation over the n table entries (pure VPU compare+
    select — TPU gathers cost ~10 ns/element and do not lower in
    Mosaic), so n is capped at 512 entries.

The top-level expression must reduce to one scalar per genome. Higher
is better, as everywhere in the library.

Examples::

    from_expression("sum(g)")                          # OneMax
    from_expression("-sum((g*10.24-5.12)**2)")         # sphere
    from_expression("dot(v, g >= 0.5)", v=values)      # 0/1 knapsack value
    from_expression(
        "where(dot(w, floor(g*2)) <= cap,"
        " dot(v, floor(g*2)), cap - dot(w, floor(g*2)))",
        w=weights, v=values, cap=100.0)                # reference test2
    from_expression(                                   # NK landscape
        "b = g >= 0.5;"
        "codes = b + 2*roll(b, 1) + 4*roll(b, 2) + 8*roll(b, 3);"
        "mean(gather(T, codes))",
        T=table_t)                                     # (2^(k+1), n)
    from_expression(                                   # Euclidean tour cost
        "c = floor(g * L);"
        "x = gather(X, c); y = gather(Y, c);"
        "dx = roll(x, 1) - x; dy = roll(y, 1) - y;"
        "-sum(where(i < L - 1, sqrt(dx*dx + dy*dy + 1e-12), 0))",
        X=coords[:, 0], Y=coords[:, 1])
"""

from __future__ import annotations

import math
from typing import Callable, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class ExpressionError(ValueError):
    """Raised for any syntax, name, arity, or shape error — with a
    position and a human-readable explanation, so the C ABI can return
    -1 and print something actionable."""


_ELEMENTWISE = {
    "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan, "tanh": jnp.tanh,
    "exp": jnp.exp, "log": jnp.log, "sqrt": jnp.sqrt, "abs": jnp.abs,
    "floor": jnp.floor, "round": jnp.round,
}
_CONSTANTS = {"pi": math.pi, "e": math.e}
_KEYWORDS = (
    ["g", "i", "L", "where", "dot", "sum", "mean", "min", "max",
     "roll", "gather"]
    + list(_ELEMENTWISE) + list(_CONSTANTS)
)

# Masked-accumulation gather unrolls one compare+select per table entry;
# beyond this the kernel program size and VPU cost stop making sense —
# use a builtin objective (or a coords decomposition) instead.
_GATHER_MAX_ENTRIES = 512


# ------------------------------------------------------------------ lexer

_TWO_CHAR = ("**", "<=", ">=", "==")
_ONE_CHAR = "+-*/%(),<>=;"


def _tokenize(src: str) -> List[Tuple[str, str, int]]:
    """(kind, text, pos) tokens; kinds: num, name, op, end."""
    out = []
    n, k = len(src), 0
    while k < n:
        c = src[k]
        if c.isspace():
            k += 1
            continue
        if src[k : k + 2] in _TWO_CHAR:
            out.append(("op", src[k : k + 2], k))
            k += 2
            continue
        if c in _ONE_CHAR:
            out.append(("op", c, k))
            k += 1
            continue
        if c.isdigit() or c == ".":
            j = k
            while j < n and (src[j].isdigit() or src[j] == "."):
                j += 1
            if j < n and src[j] in "eE":
                j += 1
                if j < n and src[j] in "+-":
                    j += 1
                while j < n and src[j].isdigit():
                    j += 1
            try:
                float(src[k:j])
            except ValueError:
                raise ExpressionError(
                    f"bad number {src[k:j]!r} at position {k}"
                ) from None
            out.append(("num", src[k:j], k))
            k = j
            continue
        if c.isalpha() or c == "_":
            j = k
            while j < n and (src[j].isalnum() or src[j] == "_"):
                j += 1
            out.append(("name", src[k:j], k))
            k = j
            continue
        raise ExpressionError(f"unexpected character {c!r} at position {k}")
    out.append(("end", "", n))
    return out


# ------------------------------------------------------------------ parser
#
# AST nodes are tuples: ("num", x), ("var", name), ("const", name),
# ("un", op, a), ("bin", op, a, b), ("call", fname, [args]).


class _Parser:
    def __init__(self, src: str, const_names, var_names=("g", "i", "L")):
        self.src = src
        self.toks = _tokenize(src)
        self.k = 0
        self.const_names = const_names
        self.var_names = set(var_names)  # role-dependent: objectives see
        # g/i/L, breeding expressions their own sets (expr_breed.py)
        self.locals: List[str] = []  # ``name = expr;`` bindings, in order

    def peek(self):
        return self.toks[self.k]

    def next(self):
        t = self.toks[self.k]
        self.k += 1
        return t

    def expect(self, text):
        kind, tok, pos = self.next()
        if tok != text:
            raise ExpressionError(
                f"expected {text!r} at position {pos}, got {tok or 'end'!r}"
            )

    def parse(self):
        """``name = expr; ... ; final_expr`` — zero or more bindings,
        then the result expression (optionally semicolon-terminated).
        Bindings evaluate in order and are visible to everything after
        them; returns ``("prog", [(name, ast), ...], final_ast)`` (or
        just the final AST when there are no bindings)."""
        stmts = []
        while (
            self.peek()[0] == "name"
            and self.toks[self.k + 1][1] == "="
        ):
            _, name, pos = self.next()
            self.next()  # '='
            if name in _KEYWORDS or name in self.var_names:
                raise ExpressionError(
                    f"cannot bind {name!r} at position {pos}: it is a "
                    f"builtin name"
                )
            if name in self.const_names:
                raise ExpressionError(
                    f"cannot bind {name!r} at position {pos}: it is a "
                    f"registered constant"
                )
            if name in self.locals:
                raise ExpressionError(
                    f"{name!r} rebound at position {pos}; bindings are "
                    f"single-assignment"
                )
            rhs = self.comparison()
            self.expect(";")
            stmts.append((name, rhs))
            self.locals.append(name)
        node = self.comparison()
        if self.peek()[1] == ";":
            self.next()  # tolerate a trailing semicolon
        kind, tok, pos = self.peek()
        if kind != "end":
            raise ExpressionError(
                f"unexpected {tok!r} at position {pos}"
            )
        return ("prog", stmts, node) if stmts else node

    def comparison(self):
        node = self.addsub()
        kind, tok, _ = self.peek()
        if tok in ("<", "<=", ">", ">=", "=="):
            self.next()
            node = ("bin", tok, node, self.addsub())
        return node

    def addsub(self):
        node = self.muldiv()
        while self.peek()[1] in ("+", "-"):
            op = self.next()[1]
            node = ("bin", op, node, self.muldiv())
        return node

    def muldiv(self):
        node = self.unary()
        while self.peek()[1] in ("*", "/", "%"):
            op = self.next()[1]
            node = ("bin", op, node, self.unary())
        return node

    def unary(self):
        kind, tok, _ = self.peek()
        if tok in ("+", "-"):
            self.next()
            return ("un", tok, self.unary())
        return self.power()

    def power(self):
        node = self.atom()
        if self.peek()[1] == "**":
            self.next()
            node = ("bin", "**", node, self.unary())  # right-assoc
        return node

    def atom(self):
        kind, tok, pos = self.next()
        if kind == "num":
            return ("num", float(tok))
        if tok == "(":
            node = self.comparison()
            self.expect(")")
            return node
        if kind == "name":
            if self.peek()[1] == "(":
                self.next()
                args = [self.comparison()]
                while self.peek()[1] == ",":
                    self.next()
                    args.append(self.comparison())
                self.expect(")")
                return self._call(tok, args, pos)
            if tok in self.var_names:
                return ("var", tok)
            if tok in _CONSTANTS:
                return ("num", _CONSTANTS[tok])
            if tok in self.const_names:
                return ("const", tok)
            if tok in self.locals:
                return ("local", tok)
            names = ", ".join(sorted(self.var_names))
            raise ExpressionError(
                f"unknown name {tok!r} at position {pos}; available: "
                f"{names}, pi, e" + (
                    f", constants {sorted(self.const_names)}"
                    if self.const_names else
                    " (no constants registered)"
                ) + (
                    f", locals {self.locals}" if self.locals else ""
                )
            )
        raise ExpressionError(
            f"unexpected {tok or 'end of expression'!r} at position {pos}"
        )

    def _call(self, fname, args, pos):
        def need(n):
            if len(args) != n:
                raise ExpressionError(
                    f"{fname}() takes {n} argument(s), got {len(args)} "
                    f"at position {pos}"
                )

        if fname in _ELEMENTWISE:
            need(1)
        elif fname == "where":
            need(3)
        elif fname == "dot":
            need(2)
        elif fname in ("sum", "mean"):
            need(1)
        elif fname in ("min", "max"):
            if len(args) not in (1, 2):
                raise ExpressionError(
                    f"{fname}() takes 1 (reduction) or 2 (elementwise) "
                    f"arguments, got {len(args)} at position {pos}"
                )
        elif fname == "roll":
            need(2)
            k = _static_number(args[1])
            if k is None or k != int(k):
                raise ExpressionError(
                    f"roll() shift must be an integer literal at position "
                    f"{pos} (it sets the static slice layout)"
                )
            return ("roll", int(k), args[0])
        elif fname == "gather":
            need(2)
            if args[0][0] != "const":
                raise ExpressionError(
                    f"gather()'s first argument at position {pos} must be "
                    f"a registered constant (the lookup table)"
                )
            return ("gather", args[0][1], args[1])
        else:
            raise ExpressionError(
                f"unknown function {fname!r} at position {pos}; available: "
                f"{sorted(set(_ELEMENTWISE) | {'sum', 'mean', 'min', 'max', 'where', 'dot', 'roll', 'gather'})}"
            )
        return ("call", fname, args)


def _static_number(node):
    """Fold a numeric-literal subtree (numbers under unary +/- and the
    four basic operators) to a Python float, or None if it references
    anything runtime."""
    if node[0] == "num":
        return node[1]
    if node[0] == "un":
        v = _static_number(node[2])
        return None if v is None else (-v if node[1] == "-" else v)
    if node[0] == "bin" and node[1] in ("+", "-", "*", "/"):
        a, b = _static_number(node[2]), _static_number(node[3])
        if a is None or b is None:
            return None
        if node[1] == "+":
            return a + b
        if node[1] == "-":
            return a - b
        if node[1] == "*":
            return a * b
        return a / b if b else None
    return None


# --------------------------------------------------------------- compiler


def walk_ast(node, visit) -> None:
    """Call ``visit(node)`` on every AST node, parents before children —
    the ONE traversal the compile-time validators build on (a new node
    kind added to the parser gets threaded through every validator by
    updating this single function)."""
    visit(node)
    kind = node[0]
    if kind in ("un", "roll"):
        walk_ast(node[2], visit)
    elif kind == "gather":
        walk_ast(node[2], visit)
    elif kind == "bin":
        walk_ast(node[2], visit)
        walk_ast(node[3], visit)
    elif kind == "call":
        for a in node[2]:
            walk_ast(a, visit)
    elif kind == "prog":
        for _, rhs in node[1]:
            walk_ast(rhs, visit)
        walk_ast(node[2], visit)


def validate_const(name: str, value, *, allow_2d: bool, extra_reserved=()):
    """Shared constant validation for every expression surface: name
    hygiene plus the rank contract. Returns the float32 array."""
    if name in _KEYWORDS or name in extra_reserved:
        raise ExpressionError(
            f"constant name {name!r} shadows a builtin name"
        )
    arr = np.asarray(value, dtype=np.float32)
    if arr.ndim > (2 if allow_2d else 1):
        kinds = (
            "a scalar, 1-D vector, or 2-D gather table" if allow_2d
            else "a scalar or 1-D vector in a breeding expression"
        )
        raise ExpressionError(
            f"constant {name!r} must be {kinds}, got shape {arr.shape}"
        )
    return arr


def _emit(node, env) -> jax.Array:
    """Evaluate the AST over a (P, L) gene block ``env['g']``.
    Elementwise values carry shape (P, L) (or broadcastable); reductions
    keep a size-1 gene axis so everything composes by broadcasting.
    Every op class here (including %, ** with array exponents, tan,
    round — which no builtin objective uses) is verified to lower
    through Mosaic inside the fused breed kernel on real TPU:
    ``tools/tpu_kernel_checks.py`` runs the sweep."""
    kind = node[0]
    if kind == "num":
        return jnp.float32(node[1])
    if kind == "var":
        return env[node[1]]
    if kind == "const":
        return env["consts"][node[1]]
    if kind == "local":
        return env["locals"][node[1]]
    if kind == "prog":
        env = dict(env, locals=dict(env.get("locals", {})))
        for name, rhs in node[1]:
            env["locals"][name] = _emit(rhs, env)
        return _emit(node[2], env)
    if kind == "roll":
        # Circular shift on the gene axis by a static k: two static lane
        # slices + concat — the exact Mosaic-friendly form the builtin
        # NK objective lowers (classic.py make_nk_landscape), no gather.
        x = jnp.broadcast_to(_emit(node[2], env), env["shape"])
        k = node[1] % env["shape"][1]
        if k == 0:
            return x
        return jnp.concatenate([x[:, k:], x[:, :k]], axis=1)
    if kind == "gather":
        # Bounded table lookup as a masked accumulation over the table
        # entries (one compare+select per entry, all VPU): a 1-D table
        # (arriving (1, n)) is shared across loci, a 2-D (n, L) table is
        # per-locus (row c broadcasts against the gene axis) — the
        # builtin NK lookup's own lowering, generalized. Which kind a
        # table is follows its REGISTERED rank (``table_kinds``, fixed
        # at compile time) — the runtime shape is ambiguous: a (1, L)
        # per-locus table is indistinguishable from a shared L-entry
        # one. Indices floor+clip into the table like every decode in
        # the library.
        t = env["consts"][node[1]]
        per_locus = env["table_kinds"][node[1]] == "per_locus"
        if per_locus and t.shape[1] != env["shape"][1]:
            raise ExpressionError(
                f"per-locus gather table {node[1]!r} has width "
                f"{t.shape[1]} but the genome has {env['shape'][1]} genes"
            )
        idx = jnp.broadcast_to(_emit(node[2], env), env["shape"])
        n = t.shape[0] if per_locus else t.shape[1]
        codes = jnp.clip(jnp.floor(idx), 0.0, float(n - 1)).astype(jnp.int32)
        acc = jnp.zeros(env["shape"], dtype=jnp.float32)
        for c in range(n):
            entry = t[c : c + 1, :] if per_locus else t[:, c : c + 1]
            acc = acc + jnp.where(codes == c, entry, 0.0)
        return acc
    if kind == "un":
        v = _emit(node[2], env)
        return -v if node[1] == "-" else v
    if kind == "bin":
        op, a, b = node[1], _emit(node[2], env), _emit(node[3], env)
        if op == "+":
            return a + b
        if op == "-":
            return a - b
        if op == "*":
            return a * b
        if op == "/":
            return a / b
        if op == "%":
            return a % b
        if op == "**":
            return a ** b
        cmp = {"<": jnp.less, "<=": jnp.less_equal, ">": jnp.greater,
               ">=": jnp.greater_equal, "==": jnp.equal}[op]
        return cmp(a, b).astype(jnp.float32)
    fname, args = node[1], node[2]
    vals = [_emit(a, env) for a in args]
    if fname in _ELEMENTWISE:
        return _ELEMENTWISE[fname](vals[0])
    if fname == "where":
        return jnp.where(vals[0] != 0.0, vals[1], vals[2])
    # Reductions keep the gene axis as a size-1 dim so reduced values
    # compose with everything else by broadcasting — scalars/consts are
    # (1, 1), elementwise values (P, L), reductions (P, 1); the
    # top-level squeeze in ``rows`` produces the final (P,).
    if fname == "dot":
        return jnp.sum(
            jnp.broadcast_to(vals[0] * vals[1], env["shape"]),
            axis=1, keepdims=True,
        )
    reducers = {"sum": jnp.sum, "mean": jnp.mean,
                "min": jnp.min, "max": jnp.max}
    if fname in ("min", "max") and len(vals) == 2:
        return (jnp.minimum if fname == "min" else jnp.maximum)(*vals)
    v = jnp.broadcast_to(vals[0], env["shape"])
    return reducers[fname](v, axis=1, keepdims=True)


def from_expression(expr: str, **consts) -> Callable:
    """Compile an objective expression to the library's standard
    objective protocol: a per-genome callable whose ``kernel_rowwise``
    batched form fuses into the Pallas breed kernel (children scored
    in VMEM — device speed, no host callback), with any named constants
    riding along as kernel inputs (``kernel_rowwise_consts``), exactly
    like the builtin fusable objectives.

    ``consts``: scalars or 1-D float arrays (broadcast elementwise
    against the genome; a length-L vector pairs with each gene).
    Raises :class:`ExpressionError` with a position and an explanation
    for any syntax/name/arity problem, and for expressions that do not
    reduce to one scalar per genome.
    """
    const_vals: Dict[str, np.ndarray] = {
        name: validate_const(name, v, allow_2d=True)
        for name, v in consts.items()
    }

    ast = _Parser(expr, set(const_vals)).parse()
    # Keep only the constants the expression references: the C ABI
    # registers constants per solver across successive expressions, so
    # unused ones must not become dead kernel inputs, pin the probe
    # length, or trip the vector-length check below. The same walk
    # validates gather tables (registered, bounded, and the only legal
    # use of a 2-D constant — elementwise broadcast of an (n, L) table
    # would silently misalign against the gene axis).
    used: set = set()
    gather_tables: set = set()
    elementwise_consts: set = set()

    def visit(node):
        kind = node[0]
        if kind == "const":
            # A ("const",) node is an ELEMENTWISE use (gather tables are
            # stored by name on the ("gather",) node, never visited
            # here): it broadcasts against the gene axis, so a vector
            # shape pins the genome length below.
            used.add(node[1])
            elementwise_consts.add(node[1])
            if const_vals[node[1]].ndim == 2:
                raise ExpressionError(
                    f"2-D constant {node[1]!r} may only be used as "
                    f"gather()'s table"
                )
        elif kind == "gather":
            used.add(node[1])
            gather_tables.add(node[1])

    walk_ast(ast, visit)
    table_kinds: Dict[str, str] = {}
    for name in gather_tables:
        t = const_vals[name]
        if t.ndim == 0:
            raise ExpressionError(
                f"gather table {name!r} is a scalar; register a vector "
                f"or (n, L) matrix"
            )
        n = t.shape[0]  # 1-D: table length; 2-D: entry rows (n, L)
        if n > _GATHER_MAX_ENTRIES:
            raise ExpressionError(
                f"gather table {name!r} has {n} entries; the masked-"
                f"accumulation lowering caps at {_GATHER_MAX_ENTRIES}"
            )
        # The REGISTERED rank decides the lookup semantics, once: the
        # runtime (1, n) form of a 1-D table is shape-identical to a
        # single-entry (1, L) per-locus table.
        table_kinds[name] = "per_locus" if t.ndim == 2 else "shared"
    const_vals = {n: a for n, a in const_vals.items() if n in used}
    const_names = sorted(const_vals)
    defaults = tuple(
        jnp.atleast_2d(jnp.asarray(const_vals[n])) for n in const_names
    )

    def rows(m, *cargs):
        cargs = cargs or defaults
        env = {
            "g": m,
            "i": jax.lax.broadcasted_iota(jnp.int32, m.shape, 1).astype(
                jnp.float32
            ),
            "L": jnp.float32(m.shape[1]),
            "shape": m.shape,  # roll/gather broadcast target
            "table_kinds": table_kinds,
            # kernel consts arrive atleast_2d'd ((1, n) / (1, 1)) — the
            # row orientation broadcasts against (P, L) directly
            "consts": dict(zip(const_names, cargs)),
        }
        out = _emit(ast, env)
        if out.ndim == 2 and out.shape[-1] == 1:
            out = out[:, 0]
        elif out.ndim == 2:
            raise ExpressionError(
                "expression must reduce to one scalar per genome — wrap "
                "it in sum()/mean()/min()/max()"
            )
        return jnp.broadcast_to(out, (m.shape[0],)).astype(jnp.float32)

    # Validate eagerly: shape/arity/broadcast errors surface at
    # registration (→ -1 through the C ABI), not at first run. The
    # probe genome length follows the constants that pair with the gene
    # axis: ELEMENTWISE vector constants (length-n broadcast implies
    # L == n) and 2-D gather tables' per-locus width (an (n, L) table
    # implies L). A 1-D gather TABLE does not pin L — its length is the
    # index domain (e.g. C cities), unrelated to the genome.
    vec_lens = {
        const_vals[n].shape[0]
        for n in elementwise_consts
        if n in const_vals and const_vals[n].ndim == 1
    }
    vec_lens |= {
        const_vals[n].shape[1]
        for n in gather_tables
        if n in const_vals and const_vals[n].ndim == 2
    }
    if len(vec_lens) > 1:
        raise ExpressionError(
            f"vector constants disagree on genome length: {sorted(vec_lens)}"
        )
    pinned_len = vec_lens.pop() if vec_lens else None
    probe_len = pinned_len or 8
    try:
        probe = jax.eval_shape(
            rows, jax.ShapeDtypeStruct((2, probe_len), jnp.float32)
        )
    except ExpressionError:
        raise
    except Exception as exc:  # noqa: BLE001 — rewrap with the source expr
        raise ExpressionError(f"invalid expression {expr!r}: {exc}") from exc
    del probe

    rows.pad_ok = False  # e.g. cos(0) != 0: pad lanes would pollute
    per_genome = lambda genome: rows(genome[None, :])[0]  # noqa: E731
    per_genome.kernel_rowwise = rows
    per_genome.kernel_rowwise_consts = defaults
    per_genome.expression = expr
    # Genome length this expression's constants commit it to (None =
    # any): elementwise vector constants and per-locus gather tables pin
    # it; the C ABI checks population creation against this.
    per_genome.pinned_genome_len = pinned_len
    per_genome.__doc__ = f"Expression objective: {expr}"
    return per_genome
