"""Classic GA benchmark objectives.

Each is a pure per-genome function ``(L,) -> scalar`` over genes in [0,1),
higher-is-better, designed to trace cleanly under vmap/jit (no Python
control flow on traced values).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


# Fusable objectives are written ONCE in rowwise batched form
# (``(P, L) -> (P,)`` with axis=1 reductions) and the per-genome form is
# derived from it, so the two can never drift. The rowwise form is what
# lowers inside the Pallas breed kernel (a vmap'd per-genome form unrolls
# to P scalar reductions under Mosaic); the engine's fast path fuses it
# into the generation kernel so children are scored while still in VMEM.


def _rowwise(rows_fn, doc, pad_ok=False):
    def per_genome(genome: jax.Array) -> jax.Array:
        return rows_fn(genome[None, :])[0]

    # ``pad_ok``: the rowwise reduction is invariant to extra all-zero
    # gene columns, so the breed kernel may pass the full lane-aligned
    # (K, Lp) child instead of the misaligned (K, L) slice (which costs
    # a relayout per deme — see pallas_step's fused-evaluation note).
    rows_fn.pad_ok = pad_ok
    per_genome.kernel_rowwise = rows_fn
    per_genome.__doc__ = doc
    return per_genome


# ------------------------------------------------------------------ OneMax

onemax = _rowwise(
    lambda m: jnp.sum(m, axis=1),
    """Continuous OneMax: sum of genes. The reference's first driver
    objective (``test/test.cu:24-30``). Optimum = genome_len (genes → 1).""",
    pad_ok=True,  # sum of zero pads is zero
)

onemax_bits = _rowwise(
    lambda m: jnp.sum((m >= 0.5).astype(jnp.float32), axis=1),
    """Bitstring OneMax: count of genes that round to 1. Optimum = L.""",
    pad_ok=True,  # zero pads count as 0-bits
)


# ------------------------------------------------- real-coded test functions


def _to_box(genome: jax.Array, lo: float, hi: float) -> jax.Array:
    """Map genes from [0,1) to [lo, hi]."""
    return lo + genome * (hi - lo)


def _sphere_rows(m):
    x = _to_box(m, -5.12, 5.12)
    return -jnp.sum(x * x, axis=1)


def _rastrigin_rows(m):
    x = _to_box(m, -5.12, 5.12)
    return -(
        10.0 * m.shape[1]
        + jnp.sum(x * x - 10.0 * jnp.cos(2.0 * jnp.pi * x), axis=1)
    )


def _ackley_rows(m):
    x = _to_box(m, -32.768, 32.768)
    n = m.shape[1]
    a, b, c = 20.0, 0.2, 2.0 * jnp.pi
    s1 = jnp.sqrt(jnp.sum(x * x, axis=1) / n)
    s2 = jnp.sum(jnp.cos(c * x), axis=1) / n
    return -(-a * jnp.exp(-b * s1) - jnp.exp(s2) + a + jnp.e)


sphere = _rowwise(
    _sphere_rows,
    """Negated sphere function on [-5.12, 5.12]^L. Optimum 0 at x=0.""",
)

rastrigin = _rowwise(
    _rastrigin_rows,
    """Negated Rastrigin on [-5.12, 5.12]^L (BASELINE.json config
    "Rastrigin-30D real-valued GA"). Optimum 0 at x=0; highly multimodal.""",
)

ackley = _rowwise(
    _ackley_rows,
    """Negated Ackley on [-32.768, 32.768]^L. Optimum 0 at x=0.""",
)


# ---------------------------------------------------------------- knapsack


def make_knapsack(values, weights, capacity: float, max_item_count: int = 2):
    """Bounded knapsack with overweight penalty.

    Semantics of the reference's second driver (``test2/test.cu:28-36``):
    decode per-item count as ``int(g[i] * max_item_count)``; feasible →
    total value; infeasible → ``capacity - weight`` (negative overweight).
    """
    # numpy, not jnp: this factory runs at import time for
    # default_knapsack, and touching a device buffer here would
    # initialize the XLA backend before jax.distributed.initialize can
    # run in multi-host programs. The arrays convert under trace.
    values = np.asarray(values, dtype=np.float32)
    weights = np.asarray(weights, dtype=np.float32)

    values2 = values.reshape(1, -1)
    weights2 = weights.reshape(1, -1)

    def knapsack_rows(m: jax.Array, vals=None, wts=None) -> jax.Array:
        # ``vals``/``wts`` arrive as kernel inputs on the fused path
        # (Pallas forbids captured array constants); outside a kernel the
        # closure's host copies serve.
        vals = values2 if vals is None else vals
        wts = weights2 if wts is None else wts
        counts = jnp.floor(m * max_item_count).astype(jnp.float32)
        total_value = jnp.sum(vals * counts, axis=1)
        total_weight = jnp.sum(wts * counts, axis=1)
        return jnp.where(
            total_weight <= capacity, total_value, capacity - total_weight
        )

    def knapsack(genome: jax.Array) -> jax.Array:
        return knapsack_rows(genome[None, :])[0]

    # Pure elementwise + axis-1 reductions: lowers inside the Pallas
    # breed kernel, so knapsack children are scored in VMEM.
    knapsack.kernel_rowwise = knapsack_rows
    knapsack.kernel_rowwise_consts = (values2, weights2)
    return knapsack


# The exact instance the reference driver hardcodes (test2/test.cu:22-26).
default_knapsack = make_knapsack(
    values=[75, 150, 250, 35, 10, 100],
    weights=[7, 8, 6, 4, 3, 9],
    capacity=10.0,
    max_item_count=2,
)


# --------------------------------------------------------------------- TSP


def _chunked_rows(score_chunk, cities, B: int = 2048):
    """Shared chunking scaffold for the batched TSP forms: keep each
    chunk's (B, L, C)-scale one-hots tens of MB, not gigabytes, at
    framework-scale populations; a non-multiple tail pads up to the
    chunk size and is sliced away."""
    P = cities.shape[0]
    if P <= B:
        return score_chunk(cities)
    n_chunks = -(-P // B)
    padded = jnp.pad(cities, ((0, n_chunks * B - P), (0, 0)))
    out = jax.lax.map(
        score_chunk, padded.reshape(n_chunks, B, cities.shape[1])
    )
    return out.reshape(n_chunks * B)[:P]


def make_tsp(city_matrix, duplicate_penalty: float = 10_000.0):
    """TSP over a distance matrix with duplicate-city penalty.

    Semantics of the reference's third driver (``test3/test.cu:26-46``):
    city i = ``int(g[i] * L)``; fitness = −(path length + penalty per
    ordered duplicate pair). The O(L²) duplicate check is a vectorized
    comparison matrix here rather than the reference's nested loop.

    The batched form (``.rows``, used by :func:`ops.evaluate.evaluate`)
    is gather-free: edge costs come from a one-hot matmul (exact in f32
    — each output element selects exactly one matrix entry), and the
    duplicate count from per-city occupancy counts
    (``Σ_c n_c(n_c−1) = Σ_c n_c² − L``). TPU gathers cost ~10 ns/element,
    which made the indexed formulation dominate the whole TSP generation
    at large populations (6.6 ms/eval at 8192×100 vs ~0.5 ms for the
    matmul form).
    """
    city_matrix = jnp.asarray(city_matrix, dtype=jnp.float32)
    C = city_matrix.shape[0]

    def tsp(genome: jax.Array) -> jax.Array:
        L = genome.shape[0]
        cities = jnp.clip(jnp.floor(genome * L).astype(jnp.int32), 0, L - 1)
        length = jnp.sum(city_matrix[cities[:-1], cities[1:]])
        dup = cities[:, None] == cities[None, :]
        off_diag = dup & ~jnp.eye(L, dtype=bool)
        length = length + duplicate_penalty * jnp.sum(off_diag)
        return -length

    def tsp_rows(m: jax.Array) -> jax.Array:
        P, L = m.shape
        cities = jnp.clip(jnp.floor(m * L).astype(jnp.int32), 0, L - 1)
        # Duplicate counting must bucket the same values the per-genome
        # form compares (cities in [0, L)), while the matmul one-hot
        # must stay inside the matrix (clamped to C-1, matching the
        # clamped gather of the indexed form when L > C).
        CC = max(C, L)

        def score_chunk(c):
            B = c.shape[0]
            onehot = (
                c[:, :, None] == jnp.arange(CC, dtype=jnp.int32)
            ).astype(jnp.float32)  # (B, L, CC)
            if CC == C:  # cities already in-range: reuse slices
                src_oh, dst_oh = onehot[:, :-1], onehot[:, 1:]
            else:
                src_oh = (
                    jnp.clip(c[:, :-1], 0, C - 1)[:, :, None]
                    == jnp.arange(C, dtype=jnp.int32)
                ).astype(jnp.float32)
                dst_oh = (
                    jnp.clip(c[:, 1:], 0, C - 1)[:, :, None]
                    == jnp.arange(C, dtype=jnp.int32)
                ).astype(jnp.float32)
            # HIGHEST precision: the default TPU matmul downcasts the
            # matrix to bf16 (±0.4% per distance — tens of units over a
            # 99-edge tour, measured 28.5 max divergence from the exact
            # per-genome form; HIGHEST brings it to ~0.1 at ~2x the
            # matmul cost, still ~0.5 ms/eval at 8192×100).
            picked = jnp.matmul(
                src_oh.reshape(-1, C), city_matrix,
                precision=jax.lax.Precision.HIGHEST,
            ).reshape(B, L - 1, C)
            length = jnp.sum(picked * dst_oh, axis=(1, 2))
            counts = jnp.sum(onehot, axis=1)  # (B, CC)
            dups = jnp.sum(counts * counts, axis=1) - L
            return -(length + duplicate_penalty * dups)

        return _chunked_rows(score_chunk, cities)

    tsp.rows = tsp_rows
    return tsp


def make_tsp_coords(
    coords,
    duplicate_penalty: float = 10_000.0,
    duplicate_mode: str = "pairs",
):
    """Euclidean TSP over city COORDINATES — the scalable form for
    long tours.

    Same decode and penalty semantics as :func:`make_tsp`, but edge
    costs are computed from gathered (x, y) positions instead of a
    distance-matrix lookup: the batched form gathers each tour's
    coordinates with ONE (P·L, C)@(C, 2) one-hot matmul — O(P·L·C)
    FLOPs versus the matrix form's O(P·L·C²) — so a 1,000-city
    evaluation costs ~L/2× less than :func:`make_tsp` (measured: the
    matrix form's one-hot matmuls dominate whole generations beyond a
    few hundred cities; the reference itself caps at 110 cities,
    ``test3/test.cu:22-24``). Use :func:`make_tsp` for arbitrary
    (non-metric) matrices at reference scales.

    ``duplicate_mode``: how repeated cities are penalized. ``"pairs"``
    (default) counts ordered duplicate pairs — the reference driver's
    O(L²) loop semantics (``test3/test.cu:37-44``), matching
    :func:`make_tsp`. ``"genes"`` counts duplicate GENES
    (``Σ_c max(n_c−1, 0)`` = L − distinct cities) — linear in the
    duplicate count instead of quadratic, with the same zero set (valid
    tours score identically; any duplicate still eats ≥ one penalty).
    The "genes" mode additionally carries an IN-KERNEL gene-major
    evaluator (``kernel_gene_major``): with order crossover the fused
    breed kernel scores each child inside VMEM via a factorized
    one-hot coordinate gather and the walk's city-bitmask machinery —
    the long-genome TSP evaluation path (the XLA one-hot gather's HBM
    traffic dominates end-to-end generations at 1,000 cities).
    """
    coords = jnp.asarray(coords, dtype=jnp.float32)
    C = coords.shape[0]
    if duplicate_mode not in ("pairs", "genes"):
        raise ValueError(
            f"duplicate_mode must be 'pairs' or 'genes', got "
            f"{duplicate_mode!r}"
        )

    def edge_lengths(xy):
        # (..., L, 2) -> (...,) tour length over consecutive pairs
        d = xy[..., 1:, :] - xy[..., :-1, :]
        return jnp.sum(jnp.sqrt(jnp.sum(d * d, axis=-1) + 1e-12), axis=-1)

    def tsp(genome: jax.Array) -> jax.Array:
        L = genome.shape[0]
        # Decode in [0, L) exactly like make_tsp, so duplicate counting
        # ranks genomes identically when L != C; only the coordinate
        # LOOKUP clamps to the table (the matrix form's matmul clamps
        # the same way).
        cities = jnp.clip(jnp.floor(genome * L).astype(jnp.int32), 0, L - 1)
        xy = jnp.take(coords, jnp.clip(cities, 0, C - 1), axis=0)
        dup = cities[:, None] == cities[None, :]
        if duplicate_mode == "pairs":
            dups = jnp.sum(dup & ~jnp.eye(L, dtype=bool))
        else:  # "genes": position i is a duplicate if its city appeared
            # at any earlier position — exactly L − distinct cities.
            earlier = (
                jnp.arange(L, dtype=jnp.int32)[None, :]
                < jnp.arange(L, dtype=jnp.int32)[:, None]
            )
            dups = jnp.sum(jnp.any(dup & earlier, axis=1))
        return -(edge_lengths(xy) + duplicate_penalty * dups)

    def tsp_rows(m: jax.Array) -> jax.Array:
        P, L = m.shape
        cities = jnp.clip(jnp.floor(m * L).astype(jnp.int32), 0, L - 1)
        CC = max(C, L)  # duplicate buckets cover every decode (make_tsp)

        def score_chunk(c):
            B = c.shape[0]
            onehot = (
                c.reshape(-1)[:, None] == jnp.arange(CC, dtype=jnp.int32)
            ).astype(jnp.float32)  # (B*L, CC)
            counts = onehot.reshape(B, L, CC).sum(axis=1)  # (B, CC)
            if duplicate_mode == "pairs":
                dups = jnp.sum(counts * counts, axis=1) - L
            else:
                dups = L - jnp.sum((counts > 0).astype(jnp.float32), axis=1)
            if CC == C:
                gather_oh = onehot
            else:
                gather_oh = (
                    jnp.clip(c.reshape(-1), 0, C - 1)[:, None]
                    == jnp.arange(C, dtype=jnp.int32)
                ).astype(jnp.float32)
            xy = jnp.matmul(
                gather_oh, coords, precision=jax.lax.Precision.HIGHEST
            ).reshape(B, L, 2)
            return -(edge_lengths(xy) + duplicate_penalty * dups)

        return _chunked_rows(score_chunk, cities)

    tsp.rows = tsp_rows
    if duplicate_mode == "genes":
        # Factorized city id c = 32a + b. The kernel batches 8 gene
        # rows into ONE (128, A)@(A, 8K) one-hot matmul over the
        # a-digit (contracting A on sublanes — no per-step transposes),
        # then a 32-sublane b-digit select per row: O(K·(A/8 + 32))
        # work per gene position instead of the O(K·C) of a C-wide
        # masked accumulation. The table is a bf16 HI/LO SPLIT of the
        # coordinates (hi = bf16(c), lo = c − hi — the gene matmul's
        # own trick): Mosaic's MXU runs matmuls at bf16 operand
        # precision, and raw bf16 coordinates cost ~±2 units each
        # (~±100 on a 1,000-city tour, measured); the exact 0/1 one-hot
        # times hi+lo recovers f32 coordinates to ~1e-3. Layout:
        # rows 0..31 x_hi by b-digit, 32..63 y_hi, 64..95 x_lo,
        # 96..127 y_lo; a-digit on lanes.
        A = -(-C // 32)
        tableT = np.zeros((128, A), dtype=np.float32)
        cnp = np.asarray(coords)
        hi = np.asarray(
            jnp.asarray(cnp).astype(jnp.bfloat16).astype(jnp.float32)
        )
        lo = cnp - hi
        for c in range(C):
            tableT[c % 32, c // 32] = hi[c, 0]
            tableT[32 + c % 32, c // 32] = hi[c, 1]
            tableT[64 + c % 32, c // 32] = lo[c, 0]
            tableT[96 + c % 32, c // 32] = lo[c, 1]
        tsp.kernel_gene_major = {
            "table": tableT,
            "C": C,
            "penalty": float(duplicate_penalty),
        }
    return tsp


def random_tsp_coords(n_cities: int, seed: int = 0, scale: float = 1000.0):
    """Uniform-random city coordinates in a ``scale``-sized square — the
    Euclidean analog of :func:`random_tsp_matrix` for long-tour
    benchmarks. i.i.d. positions mean no tour order is special (unlike
    the matrix generator, which plants a cheap 0,1,…,L−1 path)."""
    rng = np.random.default_rng(seed)
    return (rng.random((n_cities, 2)) * scale).astype(np.float32)


def random_tsp_matrix(
    n_cities: int, seed: int = 0, low: float = 10.0, high: float = 1000.0
):
    """Random distance matrix with a planted cheap Hamiltonian path
    ``i → i+1 = low`` — the same construction as the reference's input
    generator (``test3/gen.c:27-38``), so the known-good tour is
    0,1,2,…,L−1 with length ``low * (L-1)``."""
    rng = np.random.default_rng(seed)
    m = rng.uniform(low, high, size=(n_cities, n_cities)).astype(np.float32)
    np.fill_diagonal(m, 0.0)
    idx = np.arange(n_cities - 1)
    m[idx, idx + 1] = low
    return m


# ----------------------------------------------------------- NK landscapes


def make_nk_landscape(n: int, k: int, seed: int = 0):
    """NK fitness landscape (epistatic; BASELINE.json "NK-landscape" config).

    Gene i's contribution depends on itself and its next k circular
    neighbors; contributions come from a fixed random table. Genes are
    thresholded to bits at 0.5. Fitness = mean contribution in [0, 1].

    Implemented with circular rolls instead of an explicit neighborhood
    gather: the (k+1)-bit code per locus is built by summing k+1 shifted
    copies of the bit vector, so the only per-locus intermediate is the
    (n,) code vector — under a multi-million-individual ``vmap`` the
    gather formulation materializes a ``(P, n, k+1)`` array (gigabytes at
    4M population, enough to OOM a 16 GB chip), the roll formulation never
    exceeds ``(P, n)``.
    """
    rng = np.random.default_rng(seed)
    table = jnp.asarray(
        rng.uniform(0.0, 1.0, size=(n, 2 ** (k + 1))).astype(np.float32)
    )

    n_codes = 2 ** (k + 1)
    code_iota = jnp.arange(n_codes, dtype=jnp.int32)

    def nk(genome: jax.Array) -> jax.Array:
        bits = (genome >= 0.5).astype(jnp.int32)
        codes = bits
        for j in range(1, k + 1):
            codes = codes + jnp.roll(bits, -j) * (2**j)
        if n_codes <= 64:
            # Masked sum over the small code axis instead of a row gather:
            # TPU gathers cost ~10 ns/element (≈3 s/generation at 4M×64),
            # while the (n, 2^(k+1)) compare+select+reduce fuses into pure
            # VPU work.
            contrib = jnp.sum(
                jnp.where(codes[:, None] == code_iota[None, :], table, 0.0),
                axis=1,
            )
        else:
            contrib = table[jnp.arange(n), codes]
        return jnp.mean(contrib)

    if n_codes <= 64:
        # Rowwise form for in-kernel fused evaluation: circular rolls
        # become lane-axis concats of two slices (Mosaic-friendly; no
        # gathers), the table lookup an accumulated per-code mask against
        # the (1, n) table rows. Separate-eval NK at 4M population spent
        # ~half the generation in the evaluation HBM pass. The transposed
        # table is declared as a kernel-input constant (Pallas forbids
        # captured arrays).
        table_t = np.ascontiguousarray(np.asarray(table).T)  # (2^(k+1), n)

        def nk_rows(m: jax.Array, tab_t=None) -> jax.Array:
            tab_t = table_t if tab_t is None else tab_t
            bits = (m >= 0.5).astype(jnp.int32)
            codes = bits
            for j in range(1, k + 1):
                rolled = jnp.concatenate([bits[:, j:], bits[:, :j]], axis=1)
                codes = codes + rolled * (2**j)
            contrib = jnp.zeros(m.shape, dtype=jnp.float32)
            for c in range(n_codes):
                contrib = contrib + jnp.where(codes == c, tab_t[c : c + 1, :], 0.0)
            return jnp.mean(contrib, axis=1)

        nk.kernel_rowwise = nk_rows
        nk.kernel_rowwise_consts = (table_t,)

    return nk


def make_deceptive_trap(trap_size: int = 5):
    """Concatenated deceptive trap (BASELINE.json "deceptive-trap" config).

    Genome splits into blocks of ``trap_size`` bits; a full block scores
    ``trap_size``, otherwise ``trap_size − 1 − ones`` — the gradient points
    away from the optimum. Global optimum = all ones = genome_len.
    """

    def trap_rows(m: jax.Array) -> jax.Array:
        # Written once in rowwise form (the per-genome form derives from
        # it — module convention, see header). Per-block bit counts come
        # from one small (L, nblocks) one-hot matmul instead of a 3-D
        # reshape (minor-dim reshapes don't lower in Mosaic), so the
        # same code serves CPU/XLA and the fused Pallas kernel.
        L = m.shape[1]
        nblocks = L // trap_size
        used = nblocks * trap_size
        bits = (m[:, :used] >= 0.5).astype(jnp.float32)
        block_of = jnp.arange(used, dtype=jnp.int32) // trap_size
        seg = (block_of[:, None] == jnp.arange(nblocks)[None, :]).astype(
            jnp.float32
        )
        ones = jnp.dot(bits, seg, preferred_element_type=jnp.float32)
        block_score = jnp.where(
            ones == trap_size, jnp.float32(trap_size), trap_size - 1.0 - ones
        )
        return jnp.sum(block_score, axis=1)

    return _rowwise(trap_rows, make_deceptive_trap.__doc__)
