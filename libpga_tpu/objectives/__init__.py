"""Builtin objective registry.

The reference has no builtin objectives — every driver supplies a
``__device__`` function pointer. Here the three reference driver workloads
(OneMax ``test/test.cu:24-30``, bounded knapsack ``test2/test.cu:28-36``,
TSP ``test3/test.cu:26-46``) plus the BASELINE.json benchmark configs
(Rastrigin, NK-landscape, deceptive trap) ship as named builtins. The
registry also backs the C-ABI shim, where TPU-side custom callables are
impossible and named objectives are the primary extension surface.

All objectives: ``(genome,) -> scalar`` on ``(L,)`` genes in [0,1);
HIGHER IS BETTER (the engine argmaxes, matching reference ``pga.cu:224``).
"""

from libpga_tpu.objectives.expr import ExpressionError, from_expression
from libpga_tpu.objectives.classic import (
    onemax,
    onemax_bits,
    sphere,
    rastrigin,
    ackley,
    make_knapsack,
    default_knapsack,
    make_tsp,
    make_tsp_coords,
    random_tsp_coords,
    random_tsp_matrix,
    make_nk_landscape,
    make_deceptive_trap,
)

_REGISTRY = {}


def register(name: str, fn=None):
    """Register an objective (usable as a decorator)."""
    if fn is None:
        return lambda f: register(name, f)
    _REGISTRY[name] = fn
    return fn


def get(name: str):
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"unknown objective {name!r}; registered: {sorted(_REGISTRY)}"
        ) from None


def names():
    return sorted(_REGISTRY)


register("onemax", onemax)
register("onemax_bits", onemax_bits)
register("sphere", sphere)
register("rastrigin", rastrigin)
register("ackley", ackley)
register("knapsack", default_knapsack)

__all__ = [
    "register",
    "get",
    "names",
    "from_expression",
    "ExpressionError",
    "onemax",
    "onemax_bits",
    "sphere",
    "rastrigin",
    "ackley",
    "make_knapsack",
    "default_knapsack",
    "make_tsp",
    "make_tsp_coords",
    "random_tsp_coords",
    "random_tsp_matrix",
    "make_nk_landscape",
    "make_deceptive_trap",
]
