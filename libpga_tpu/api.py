"""C-shaped parity API.

A function-for-function mirror of the reference's public C API
(``include/pga.h:53-150``) for users migrating from libpga: every
``pga_*`` entry point exists with the same call shape and the same
semantics — including the ones the reference declared but stubbed
(``pga_get_best_top``, ``pga_get_best_all``, ``pga_get_best_top_all``,
``pga_migrate``, ``pga_migrate_between``, ``pga_run_islands``, and
``pga_run``'s early termination), which are fully implemented here.

Pythonic differences, all deliberate:
- ``pga_init`` takes an optional seed/config (the reference seeds cuRAND
  with ``time(NULL)``, ``pga.cu:154``).
- Callback setters take Python callables (or builtin objective names)
  instead of ``__device__`` function pointers.
- Best-genome getters return numpy arrays instead of malloc'd ``gene*``.

The object API (:class:`libpga_tpu.engine.PGA`) is the primary surface;
this module is a thin veneer over it.
"""

from __future__ import annotations

from typing import Callable, Optional, Union

import numpy as np

from libpga_tpu.config import PGAConfig
from libpga_tpu.engine import PGA, PopulationHandle

# enum population_type (pga.h:31-34)
RANDOM_POPULATION = "random"
# enum crossover_selection_type (pga.h:39-42) — a placeholder in the
# reference ("this is pretty much just a placeholder", pga.h:37); tournament
# is the only strategy there (pga.cu:329) and the default here.
TOURNAMENT = "tournament"


def pga_init(seed: Optional[int] = None, config: Optional[PGAConfig] = None) -> PGA:
    """Create a solver instance (``pga.h:53``)."""
    if config is None:
        # Reference parity: at most 10 populations per instance (pga.h:44).
        config = PGAConfig(max_populations=10)
    return PGA(seed=seed, config=config)


def pga_deinit(pga: PGA) -> None:
    """Release the instance (``pga.h:58``). Device buffers are freed by JAX
    when unreferenced; this just drops them eagerly."""
    pga._populations.clear()
    pga._staged.clear()
    pga._compiled.clear()


def pga_create_population(
    pga: PGA, size: int, genome_len: int, type: str = RANDOM_POPULATION
) -> PopulationHandle:
    """Create a (sub)population (``pga.h:63``)."""
    return pga.create_population(size, genome_len, init=type)


def pga_set_objective_function(pga: PGA, fn: Union[Callable, str]) -> None:
    """Set the fitness function (``pga.h:72``)."""
    pga.set_objective(fn)


def pga_set_mutate_function(pga: PGA, fn: Optional[Callable]) -> None:
    """Set the mutation; ``None`` restores the default (``pga.h:78``)."""
    pga.set_mutate(fn)


def pga_set_crossover_function(pga: PGA, fn: Optional[Callable]) -> None:
    """Set the crossover; ``None`` restores the default (``pga.h:85``)."""
    pga.set_crossover(fn)


def pga_get_best(pga: PGA, pop: PopulationHandle) -> np.ndarray:
    """Best genome of a population (``pga.h:90``)."""
    return pga.get_best(pop)


def pga_get_best_top(pga: PGA, pop: PopulationHandle, length: int) -> np.ndarray:
    """Top-``length`` genomes (``pga.h:91``; stub in the reference)."""
    return pga.get_best_top(pop, length)


def pga_get_best_all(pga: PGA) -> np.ndarray:
    """Best genome across all populations (``pga.h:92``; stub in the
    reference)."""
    return pga.get_best_all()


def pga_get_best_top_all(pga: PGA, length: int) -> np.ndarray:
    """Global top-``length`` across populations (``pga.h:93``; stub in the
    reference)."""
    return pga.get_best_top_all(length)


def pga_evaluate(pga: PGA, pop: PopulationHandle) -> None:
    """Score the current generation (``pga.h:98``)."""
    pga.evaluate(pop)


def pga_evaluate_all(pga: PGA) -> None:
    """Score all populations (``pga.h:99``)."""
    pga.evaluate_all()


def pga_crossover(
    pga: PGA, pop: PopulationHandle, selection: str = TOURNAMENT
) -> None:
    """Stage the next generation from the current one (``pga.h:105``)."""
    pga.crossover(pop, selection)


def pga_crossover_all(pga: PGA, selection: str = TOURNAMENT) -> None:
    """Crossover every population (``pga.h:106``)."""
    pga.crossover_all(selection)


def pga_migrate(pga: PGA, pct: float) -> None:
    """Randomly migrate top ``pct`` between populations (``pga.h:111``;
    empty stub in the reference)."""
    pga.migrate(pct)


def pga_migrate_between(
    pga: PGA, src: PopulationHandle, dst: PopulationHandle, pct: float
) -> None:
    """Migrate top ``pct`` from ``src`` to ``dst`` (``pga.h:115``; empty
    stub in the reference)."""
    pga.migrate_between(src, dst, pct)


def pga_mutate(pga: PGA, pop: PopulationHandle) -> None:
    """Mutate the staged next generation (``pga.h:120``)."""
    pga.mutate(pop)


def pga_mutate_all(pga: PGA) -> None:
    """Mutate every staged generation (``pga.h:121``)."""
    pga.mutate_all()


def pga_swap_generations(pga: PGA, pop: PopulationHandle) -> None:
    """Promote staged → current (``pga.h:129``)."""
    pga.swap_generations(pop)


def pga_fill_random_values(pga: PGA, pop: PopulationHandle) -> None:
    """Advance the randomness stream (``pga.h:134``)."""
    pga.fill_random_values(pop)


def pga_run(
    pga: PGA, n: int, target: Optional[float] = None
) -> int:
    """Run the standard GA on the first population (``pga.h:143``) —
    including early termination at ``target``, which the reference header
    promises (``pga.h:141``) but its implementation lacks."""
    return pga.run(n, target=target)


def pga_run_islands(
    pga: PGA, n: int, m: int, pct: float, target: Optional[float] = None, mesh=None
) -> int:
    """Island GA with migration every ``m`` generations (``pga.h:150``;
    empty stub in the reference)."""
    return pga.run_islands(n, m, pct, target=target, mesh=mesh)
