"""HA coordinator primitives (ISSUE 20): leader election, epoch
fencing, and the durable intake journal.

The fleet survives any worker dying (round 13) and coordinates through
a crash-safe shm ring (round 22), but the coordinator itself was a
single point of failure — ROADMAP item 2(a). This module closes it
with the same spool discipline everything else uses: every transition
is one atomic filesystem operation, so a coordinator killed at ANY
instant (SIGKILL included) leaves only recoverable state.

Three cooperating pieces, all spool-resident:

- :class:`LeaderLease` — the leader election. Candidates race one
  ``os.link`` onto ``coord/leader.lease.json`` (first-writer-wins, the
  result-publication discipline); the winner heartbeats the lease file
  by ``os.utime`` every monitor tick (the round-13 worker-lease
  discipline, reused verbatim: heartbeat + ``lease_timeout_s`` expiry).
  A stale lease is SEIZED with one ``os.rename`` onto a tombstone name
  (exactly one of N racing standbys wins the rename), after which the
  seizer links its own lease. Every won election carries a
  monotonically increasing **epoch** — ``max(fence, stale lease
  epoch) + 1`` — and writes it to the durable fence file
  ``coord/epoch.json`` BEFORE the new leader authors any artifact.
- **Epoch fencing** — every leader-authored durable artifact (batch
  files, requeues, quarantines, the ring header) carries the author's
  epoch. Workers compare a claimed batch's epoch against the fence
  file and REJECT lower-epoch writes (``leader_fence`` event): a
  paused-then-resumed zombie leader (SIGSTOP past lease expiry) can
  keep writing, but nothing it writes after the takeover is ever
  executed. The unfenceable window — a zombie artifact adopted
  between the fence write and the new leader's re-stamp — degrades to
  a benign duplicate execution under the existing first-writer-wins
  result links: identical bits, never wrongness.
- :class:`IntakeJournal` — the durable intake. Pre-HA, the DRR
  scheduler's fair backlog and the ticket→result bookkeeping lived
  only in the leader's memory; a leader death lost every unformed
  ticket. In HA mode every submission is journaled FIRST: one atomic
  ticket file ``intake/<tid>.json`` (temp + rename) then one
  whole-line ``O_APPEND`` record in ``intake/admissions.jsonl`` (the
  admission ORDER — what makes the rebuilt fair queues deterministic).
  A new leader replays the journal from the spool alone: entries are
  deduped by ticket id (replaying twice admits each ticket exactly
  once), already-resulted and already-spooled tickets are skipped, and
  the leader retires a ticket's journal file when its result lands.

:class:`SpoolClient` is the client half: an external process submits
by journaling (the journal IS the leader rendezvous — whoever leads
admits it) and awaits the ticket's first-writer-wins result files, so
a failover is invisible to clients beyond the settle latency.

Fault sites (``robustness/faults.py``): ``coordinator.elect`` fires on
every acquisition attempt (a raise makes the candidate lose the round
and retry), ``coordinator.journal`` on every journal write/replay.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Dict, List, Optional

from libpga_tpu.robustness import faults as _faults

__all__ = [
    "COORD_DIR",
    "INTAKE_DIR",
    "LeaderLease",
    "IntakeJournal",
    "SpoolClient",
    "leadership_snapshot",
]

#: Spool subdirectories owned by this module. Deliberately NOT in
#: ``Spool.DIRS``: a single-coordinator fleet (``coordinators=1``, the
#: default) must keep byte-for-byte spool compatibility with round-23
#: spools, so these exist only once an HA fleet touches the spool.
COORD_DIR = "coord"
INTAKE_DIR = "intake"

LEASE_NAME = "leader.lease.json"
FENCE_NAME = "epoch.json"
ADMISSIONS_NAME = "admissions.jsonl"


def _fire(site: str) -> None:
    if _faults.PLAN is not None:
        _faults.PLAN.fire(site)


class LeaderLease:
    """The spool-resident leader lease + epoch fence for one fleet.

    ``spool`` is duck-typed (``path``/``read_json``/``write_json`` —
    the ``serving.fleet.Spool`` surface); keeping it duck-typed avoids
    a circular import and lets tests drive the election with a bare
    stand-in. One instance per candidate process."""

    def __init__(self, spool, owner: str, timeout_s: float):
        self.spool = spool
        self.owner = str(owner)
        self.timeout_s = float(timeout_s)
        os.makedirs(spool.path(COORD_DIR), exist_ok=True)

    # ------------------------------------------------------------ paths

    def lease_path(self) -> str:
        return self.spool.path(COORD_DIR, LEASE_NAME)

    def fence_path(self) -> str:
        return self.spool.path(COORD_DIR, FENCE_NAME)

    # ------------------------------------------------------------ fence

    def fence(self) -> int:
        """The durable fence epoch — the generation every worker and
        standby compares leader-authored artifacts against. 0 = no
        leader has ever won on this spool."""
        rec = self.spool.read_json(self.fence_path())
        if rec is None:
            return 0
        try:
            return int(rec.get("epoch", 0))
        except (TypeError, ValueError):
            return 0

    def _write_fence(self, epoch: int) -> None:
        # Durable BEFORE the winner authors anything: from this instant
        # every artifact the previous leader writes is below the fence.
        self.spool.write_json(self.fence_path(), {
            "epoch": int(epoch),
            "pid": os.getpid(),
            "owner": self.owner,
            "at": time.time(),
        })

    # --------------------------------------------------------- election

    def _lease_record(self, epoch: int) -> dict:
        return {
            "owner": self.owner,
            "pid": os.getpid(),
            "epoch": int(epoch),
            "acquired": time.time(),
        }

    def _link_lease(self, epoch: int) -> bool:
        """First-writer-wins lease publication (the ``Spool.publish``
        discipline): link a private temp record onto the lease name.
        Exactly one of N racing candidates succeeds."""
        path = self.lease_path()
        tmp = f"{path}.{os.getpid()}.{self.owner[-6:]}.tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(self._lease_record(epoch), fh)
        try:
            os.link(tmp, path)
            return True
        except OSError:
            return False
        finally:
            try:
                os.remove(tmp)
            except OSError:
                pass

    def _lease_age(self) -> Optional[float]:
        try:
            return max(time.time() - os.stat(self.lease_path()).st_mtime,
                       0.0)
        except OSError:
            return None  # no lease (or it just moved)

    def try_acquire(self) -> Optional[dict]:
        """One election attempt: ``{"epoch": E, "seized": bool}`` when
        this candidate now leads, None when a live leader holds the
        lease (or another candidate won the race — retry next tick).

        Fresh acquisition links a new lease at ``fence + 1``. A lease
        whose heartbeat is older than ``timeout_s`` is seized: ONE
        ``os.rename`` onto a tombstone name decides which standby may
        proceed (atomic — the losers' renames fail), the tombstone's
        epoch joins the max so the new epoch strictly exceeds the
        zombie's even if the zombie never wrote the fence."""
        _fire("coordinator.elect")
        lease = self.spool.read_json(self.lease_path())
        age = self._lease_age()
        if lease is None and age is None:
            if self._link_lease(self.fence() + 1):
                return self._won(seized=False)
            return None
        if age is not None and age <= self.timeout_s:
            return None  # live leader (possibly us — callers heartbeat)
        # Stale lease: seize it. The tombstone carries the loser's pid
        # so a crashed seizer leaves attributable debris, removed after
        # its epoch is folded in.
        stale_epoch = 0
        if lease is not None:
            try:
                stale_epoch = int(lease.get("epoch", 0))
            except (TypeError, ValueError):
                stale_epoch = 0
        tomb = (
            f"{self.lease_path()}.seized.{os.getpid()}"
            f".{self.owner[-6:]}"
        )
        try:
            os.rename(self.lease_path(), tomb)
        except OSError:
            return None  # another standby seized first (or leader woke)
        try:
            rec = self.spool.read_json(tomb)
            if rec is not None:
                try:
                    stale_epoch = max(stale_epoch, int(rec.get("epoch", 0)))
                except (TypeError, ValueError):
                    pass
        finally:
            try:
                os.remove(tomb)
            except OSError:
                pass
        if self._link_lease(max(self.fence(), stale_epoch) + 1):
            return self._won(seized=True)
        return None  # a third candidate linked between our rename+link

    def _won(self, seized: bool) -> dict:
        rec = self.spool.read_json(self.lease_path())
        epoch = self.fence() + 1
        if rec is not None and rec.get("owner") == self.owner:
            try:
                epoch = int(rec.get("epoch", epoch))
            except (TypeError, ValueError):
                pass
        self._write_fence(epoch)
        return {"epoch": epoch, "seized": bool(seized)}

    # -------------------------------------------------------- heartbeat

    def heartbeat(self) -> bool:
        """Refresh the lease (one ``os.utime`` — the worker-lease touch
        verbatim) and confirm this process still owns it. False means
        leadership is LOST (seized while we were paused, or the file is
        gone): the caller must stop authoring immediately. The
        ownership re-read makes a zombie's touch harmless — it may
        refresh the NEW leader's lease once, which only delays the next
        (unneeded) election."""
        path = self.lease_path()
        try:
            os.utime(path)
        except OSError:
            return False
        rec = self.spool.read_json(path)
        return rec is not None and rec.get("owner") == self.owner

    def release(self) -> None:
        """Clean abdication (``Fleet.close``): remove the lease so a
        standby takes over after one election attempt instead of a
        full timeout."""
        rec = self.spool.read_json(self.lease_path())
        if rec is not None and rec.get("owner") == self.owner:
            try:
                os.remove(self.lease_path())
            except OSError:
                pass


class IntakeJournal:
    """The durable intake: atomic per-ticket files + an ``O_APPEND``
    admission log, under ``<spool>/intake/``.

    Write path (``record``): the ticket file lands first (temp +
    rename — the batch-file discipline), then one whole-line append to
    the admission log. A crash between the two leaves an unlogged
    ticket file; replay appends unlogged files after the logged order
    (name-sorted), so nothing durable is ever lost. Replay
    (``entries``) is idempotent by construction: entries are deduped by
    ticket id and ordered by FIRST log occurrence, so replaying the
    log twice admits each ticket exactly once. A completed ticket's
    journal file is retired (``retire``) — its log line stays, ordering
    only."""

    def __init__(self, spool):
        self.spool = spool
        os.makedirs(spool.path(INTAKE_DIR), exist_ok=True)

    def entry_path(self, tid: str) -> str:
        return self.spool.path(INTAKE_DIR, f"{tid}.json")

    def log_path(self) -> str:
        return self.spool.path(INTAKE_DIR, ADMISSIONS_NAME)

    def record(
        self, tid: str, ticket: dict, tenant: str, priority: int,
        trace_id: Optional[str], epoch: int,
    ) -> None:
        """Make one submission durable. The ticket file is the payload
        (everything a new leader needs to re-admit), the log line the
        order."""
        _fire("coordinator.journal")
        self.spool.write_json(self.entry_path(tid), {
            "tid": tid,
            "epoch": int(epoch),
            "submitted_at": time.time(),
            "trace_id": trace_id,
            "tenant": tenant,
            "priority": int(priority),
            "ticket": dict(ticket),
        })
        line = json.dumps(
            {"tid": tid, "epoch": int(epoch), "ts": time.time()},
            separators=(",", ":"),
        ) + "\n"
        fd = os.open(
            self.log_path(), os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644
        )
        try:
            os.write(fd, line.encode("utf-8"))
        finally:
            os.close(fd)

    def _log_order(self) -> List[str]:
        """Ticket ids in FIRST-occurrence log order; torn trailing
        lines (a crash mid-append) are skipped, never fatal."""
        order: List[str] = []
        seen: set = set()
        try:
            with open(self.log_path(), "r", encoding="utf-8") as fh:
                for raw in fh:
                    raw = raw.strip()
                    if not raw:
                        continue
                    try:
                        tid = json.loads(raw).get("tid")
                    except (ValueError, AttributeError):
                        continue
                    if tid and tid not in seen:
                        seen.add(tid)
                        order.append(tid)
        except OSError:
            pass
        return order

    def entries(self) -> List[dict]:
        """Every LIVE journal entry (retired tickets are gone), deduped
        by tid, in admission order — logged tickets first (log order),
        then any unlogged files (crash between file and log line) in
        name order."""
        _fire("coordinator.journal")
        try:
            names = sorted(
                n for n in os.listdir(self.spool.path(INTAKE_DIR))
                if n.endswith(".json")
            )
        except OSError:
            names = []
        by_tid: Dict[str, dict] = {}
        for n in names:
            rec = self.spool.read_json(self.spool.path(INTAKE_DIR, n))
            if rec is None or not rec.get("tid"):
                continue
            by_tid.setdefault(rec["tid"], rec)
        out: List[dict] = []
        for tid in self._log_order():
            rec = by_tid.pop(tid, None)
            if rec is not None:
                out.append(rec)
        out.extend(by_tid[tid] for tid in sorted(by_tid))
        return out

    def depth(self) -> int:
        """Live (unretired) journal entries."""
        try:
            return sum(
                1 for n in os.listdir(self.spool.path(INTAKE_DIR))
                if n.endswith(".json")
            )
        except OSError:
            return 0

    def retire(self, tid: str) -> None:
        """Drop a completed ticket's journal file (its result is the
        durable record now)."""
        try:
            os.remove(self.entry_path(tid))
        except OSError:
            pass


class SpoolClient:
    """Submit-and-await against an HA fleet spool from ANY process.

    No coordinator connection: ``submit`` journals the ticket (the
    live leader — whoever that is, now or after a failover — admits it
    from the journal), ``result`` awaits the ticket's first-writer-wins
    result files. This is how ``Fleet`` client handles "transparently
    re-resolve the live leader": the spool is the rendezvous, so there
    is nothing to re-resolve."""

    def __init__(self, spool_dir: str):
        from libpga_tpu.serving.fleet import Spool

        self.spool = Spool(spool_dir)
        self.journal = IntakeJournal(self.spool)
        self._seq = 0
        self._token = f"{os.getpid():x}-{os.urandom(3).hex()}"

    def submit(self, ticket, tenant: Optional[str] = None,
               priority: int = 0) -> str:
        """Journal one ``FleetTicket``; returns its ticket id."""
        if tenant is not None:
            ticket = dataclasses.replace(ticket, tenant=tenant)
        self._seq += 1
        tid = f"t{self._seq:05d}-{self._token}"
        self.journal.record(
            tid=tid, ticket=dataclasses.asdict(ticket),
            tenant=ticket.tenant or "anon",
            priority=int(
                ticket.priority if ticket.priority is not None else priority
            ),
            trace_id=None, epoch=0,
        )
        return tid

    def poll(self, tid: str) -> bool:
        return (
            self.spool.read_json(self.spool.result_paths(tid)[1])
            is not None
        )

    def result(self, tid: str, timeout: Optional[float] = None,
               poll_s: float = 0.05):
        """Block for one ticket's result (a ``FleetResult``). Raises
        ``FleetDeadLetter`` on a dead-lettered ticket and
        ``TimeoutError`` on timeout."""
        from libpga_tpu.serving.fleet import FleetDeadLetter, FleetResult

        deadline = None if timeout is None else time.monotonic() + timeout
        npz_path, meta_path = self.spool.result_paths(tid)
        while True:
            meta = self.spool.read_json(meta_path)
            if meta is not None:
                break
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"ticket {tid} not completed within {timeout}s"
                )
            time.sleep(poll_s)
        if meta.get("error"):
            raise FleetDeadLetter(
                f"ticket {tid} dead-lettered: {meta['error']}"
            )
        import numpy as np

        from libpga_tpu.utils.checkpoint import _decode

        with np.load(npz_path) as data:
            genomes = _decode(
                data["genomes"], str(data["genomes_dtype"])
            ).copy()
            scores = data["scores"].copy()
            gens = int(data["generations"])
        return FleetResult(
            genomes, scores, gens, meta["best_score"], meta.get("worker")
        )


def leadership_snapshot(spool, payloads: List[dict]) -> dict:
    """The leadership block of ``fleet_status`` — spool alone, live or
    post-mortem: leader pid/liveness, fence epoch, lease age, standby
    count (coordinator metric flushes with a live pid that are not the
    leader), and the last-failover timestamp (the fence write time).
    ``{"enabled": False}`` on a non-HA spool (no ``coord/``)."""
    coord = spool.path(COORD_DIR)
    if not os.path.isdir(coord):
        return {"enabled": False}
    lease = spool.read_json(os.path.join(coord, LEASE_NAME))
    fence = spool.read_json(os.path.join(coord, FENCE_NAME))
    try:
        age = max(
            time.time() - os.stat(os.path.join(coord, LEASE_NAME)).st_mtime,
            0.0,
        )
    except OSError:
        age = None
    leader_pid = None if lease is None else lease.get("pid")
    standbys = 0
    for p in payloads:
        if not str(p.get("proc", "")).startswith("coordinator"):
            continue
        pid = p.get("pid")
        if pid == leader_pid:
            continue
        alive = _pid_alive(pid)
        if alive:
            standbys += 1
    return {
        "enabled": True,
        "leader_pid": leader_pid,
        "leader_alive": _pid_alive(leader_pid),
        "epoch": 0 if fence is None else int(fence.get("epoch", 0)),
        "lease_age_s": age,
        "standbys": standbys,
        "last_failover_ts": None if fence is None else fence.get("at"),
    }


def _pid_alive(pid) -> Optional[bool]:
    try:
        os.kill(int(pid), 0)
        return True
    except ProcessLookupError:
        return False
    except (OSError, TypeError, ValueError):
        return None
