"""Weighted-fair scheduling, admission control, and autoscaling policy
(ISSUE 15) — the coordinator's brain, split out of ``serving/fleet.py``
so every policy decision is a pure, process-free, unit-testable object.

The round-13 fleet intake was one global FIFO: tickets accumulated in
per-shape buckets and every full (or aged-out) bucket became a
claimable batch file immediately. BENCH_r10 showed the consequence —
throughput flat across 1/4/8 workers — and the FIFO has a worse
property under multi-tenant load: a burst tenant that spools 50 batches
first is served entirely before a steady tenant's next ticket, even
though the steady tenant's SLO is the one burning. This module replaces
that intake with three cooperating policies:

- :class:`FleetScheduler` — per-tenant DEFICIT ROUND-ROBIN over
  priority lanes. Tickets queue per (priority, tenant) in FIFO order;
  each scheduler rotation credits every backlogged tenant
  ``quantum x weight`` tickets of deficit, and the next batch is drawn
  from the first creditworthy tenant in ring order, filled with
  same-shape tickets across tenants in the same fair order (each taken
  ticket is CHARGED to its owner, driving a burst tenant's deficit
  negative so it pays for a full batch over the following rotations).
  Starvation-proof by construction: a tenant with queued work gains
  credit every rotation and the ring cursor advances past each served
  tenant, so tenants whose shapes never co-batch still alternate
  batches — the property ``tests/test_scheduler.py`` pins over random
  arrival patterns. The coordinator releases batches against a bounded
  spool window (``FleetConfig.sched_lookahead`` per live worker), which
  is what makes the ORDER matter: a late-arriving steady tenant
  competes against a bounded runway, not a fully spooled burst.
- :class:`QuotaExceeded` — per-tenant admission control
  (``TenantPolicy.max_pending``): deterministic shed semantics (always
  raises, never blocks — concurrent submitters see exactly the same
  verdict regardless of interleaving), one ``quota_reject`` event per
  shed.
- :class:`Autoscaler` — the closed-loop scale policy: a pure
  ``decide()`` over the signals the fleet already exports (claimable
  backlog, spool-wait p99, burn-rate alerts, straggler health) with
  hysteresis (scale-up at ``target_backlog`` per worker, scale-down
  only after ``idle_grace_s`` of COMPLETE idleness) and per-direction
  cooldowns, so oscillating load between the two thresholds produces
  zero decisions. The fleet's policy thread applies the returned delta;
  scale-down always drains (SIGTERM at a chunk boundary), never kills.

:class:`DirWatch` is the satellite: the coordinator monitor's
incremental-scan helper (directory mtime snapshots), which together
with the adaptive idle backoff removes the fixed-cadence full spool
re-scan BENCH_r10 measured as the flat-scaling overhead.
"""

from __future__ import annotations

import collections
import dataclasses
import math
import os
import time
from typing import Deque, Dict, List, Optional, Tuple

from libpga_tpu.config import AutoscaleConfig, FleetConfig, TenantPolicy
from libpga_tpu.serving.queue import QueueFull

__all__ = [
    "QuotaExceeded",
    "SchedEntry",
    "FleetScheduler",
    "Autoscaler",
    "DirWatch",
    "release_room",
]


def release_room(lookahead: int, live_workers: int, spooled: int) -> int:
    """Release-window headroom: how many more unclaimed batch files the
    coordinator may put on the spool before holding work back in the
    fair queues. ``spooled`` is the count of released-but-unclaimed
    batch files — a ``pending/`` listing in pure-spool mode, the ring's
    advertised live depth in ring mode (ISSUE 18), which is what lets
    the windowed release run without a listdir in the submit path. The
    window floor of one live worker keeps a worker-less fleet able to
    spool work for workers that arrive later."""
    return lookahead * max(live_workers, 1) - max(spooled, 0)


class QuotaExceeded(QueueFull):
    """A tenant's submission breached its ``TenantPolicy.max_pending``
    quota. Unlike the fleet-wide ``max_pending`` (which may block),
    quota breaches are DETERMINISTIC: the submit that finds the tenant
    at its cap raises, immediately and always — so N concurrent
    submitters racing a quota of k admit exactly k tickets whatever
    the interleaving, and a C-ABI caller sees a NULL ticket with the
    installed fleet state intact."""


@dataclasses.dataclass
class SchedEntry:
    """One queued ticket inside the coordinator's fair queues."""

    tid: str
    ticket: object  # FleetTicket (kept untyped: fleet imports us)
    bucket: tuple  # (size, genome_len, supervised)
    tenant: str
    priority: int
    admitted: float  # time.monotonic() at submit


class _TenantQueue:
    __slots__ = ("entries", "deficit")

    def __init__(self):
        self.entries: Deque[SchedEntry] = collections.deque()
        self.deficit: float = 0.0


class _Lane:
    """One priority level: a ring of tenant FIFO queues under DRR."""

    def __init__(self):
        self.tenants: Dict[str, _TenantQueue] = {}
        self.ring: List[str] = []  # service order; cursor rotates
        self.cursor: int = 0

    def push(self, entry: SchedEntry) -> None:
        q = self.tenants.get(entry.tenant)
        if q is None:
            q = self.tenants[entry.tenant] = _TenantQueue()
        if not q.entries:
            # (Re-)entering the ring: standard DRR resets the deficit
            # so an idle tenant cannot bank credit, but a tenant still
            # paying off a burst (negative deficit) keeps its debt.
            if entry.tenant not in self.ring:
                self.ring.append(entry.tenant)
            q.deficit = min(q.deficit, 0.0)
        q.entries.append(entry)

    def _retire_empty(self, tenant: str) -> None:
        q = self.tenants.get(tenant)
        if q is not None and not q.entries and q.deficit >= 0.0:
            # Fully served and debt-free: leave the ring (deficit is
            # reset on re-entry). Debtors stay so their debt keeps
            # aging against future credit.
            q.deficit = 0.0
            try:
                i = self.ring.index(tenant)
            except ValueError:
                return
            del self.ring[i]
            if i < self.cursor:
                self.cursor -= 1
            if self.ring:
                self.cursor %= len(self.ring)
            else:
                self.cursor = 0
            del self.tenants[tenant]

    def depth(self) -> int:
        return sum(len(q.entries) for q in self.tenants.values())


class FleetScheduler:
    """Per-tenant weighted-fair batch formation over priority lanes.

    The coordinator pushes every admitted ticket here and draws batches
    with :meth:`next_batch`; all state is in-memory (the spool stays
    the durable queue of RELEASED batches). Not thread-safe by itself —
    the ``Fleet`` calls it under its intake lock."""

    def __init__(
        self,
        fleet: Optional[FleetConfig] = None,
        policies: Optional[Dict[str, TenantPolicy]] = None,
        quantum: Optional[float] = None,
    ):
        fleet = fleet or FleetConfig()
        self.quantum = float(
            fleet.sched_quantum if quantum is None else quantum
        )
        self._policies: Dict[str, TenantPolicy] = dict(
            policies if policies is not None else (fleet.tenants or {})
        )
        self._default = TenantPolicy()
        self._lanes: Dict[int, _Lane] = {}
        self.drawn = 0  # tickets drawn into batches, lifetime

    # -------------------------------------------------------------- policy

    def policy(self, tenant: str) -> TenantPolicy:
        return self._policies.get(tenant, self._default)

    def set_policy(self, tenant: str, policy: TenantPolicy) -> None:
        if not isinstance(policy, TenantPolicy):
            raise ValueError("policy must be a TenantPolicy")
        self._policies[tenant] = policy

    # --------------------------------------------------------------- queue

    def push(self, entry: SchedEntry) -> None:
        lane = self._lanes.get(entry.priority)
        if lane is None:
            lane = self._lanes[entry.priority] = _Lane()
        lane.push(entry)

    def depth(self) -> int:
        return sum(lane.depth() for lane in self._lanes.values())

    def tenant_depth(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for lane in self._lanes.values():
            for tenant, q in lane.tenants.items():
                if q.entries:
                    out[tenant] = out.get(tenant, 0) + len(q.entries)
        return out

    def bucket_depth(self, priority: int, bucket: tuple) -> int:
        lane = self._lanes.get(priority)
        if lane is None:
            return 0
        return sum(
            1
            for q in lane.tenants.values()
            for e in q.entries
            if e.bucket == bucket
        )

    # ---------------------------------------------------------------- draw

    def _due_buckets(
        self, lane: _Lane, now: float, max_batch: int, max_wait_ms: float,
        urgent: bool,
    ) -> Dict[tuple, int]:
        """Bucket -> queued count, restricted to buckets DUE for
        release: full (``max_batch`` same-shape tickets queued), aged
        past the admission window, or anything at all under
        ``urgent``."""
        count: Dict[tuple, int] = {}
        oldest: Dict[tuple, float] = {}
        for q in lane.tenants.values():
            for e in q.entries:
                count[e.bucket] = count.get(e.bucket, 0) + 1
                if e.bucket not in oldest or e.admitted < oldest[e.bucket]:
                    oldest[e.bucket] = e.admitted
        deadline = now - max_wait_ms / 1000.0
        return {
            b: n
            for b, n in count.items()
            if urgent or n >= max_batch or oldest[b] <= deadline
        }

    def next_batch(
        self, now: float, max_batch: int, max_wait_ms: float,
        urgent: bool = False,
    ) -> Optional[Tuple[int, tuple, List[SchedEntry]]]:
        """Draw the next batch in weighted-fair order, or None when
        nothing is due. Returns ``(priority, bucket, entries)`` with
        at most ``max_batch`` same-bucket entries, co-batched across
        tenants in deficit order."""
        for priority in sorted(self._lanes, reverse=True):
            lane = self._lanes[priority]
            due = self._due_buckets(lane, now, max_batch, max_wait_ms,
                                    urgent)
            if not due:
                continue
            drawn = self._draw_from_lane(lane, due, max_batch)
            if drawn is not None:
                self._prune_lane(priority)
                return (priority, drawn[0], drawn[1])
        return None

    def _draw_from_lane(
        self, lane: _Lane, due: Dict[tuple, int], max_batch: int
    ) -> Optional[Tuple[tuple, List[SchedEntry]]]:
        # Phase 1 — pick the seed tenant/bucket by DRR: rotate the ring
        # from the cursor, crediting quantum x weight per visit, until
        # a creditworthy tenant whose HEAD entry's bucket is due turns
        # up. Bounded: total debt is bounded by max_batch per tenant,
        # so enough rotations always produce a creditworthy tenant.
        if not lane.ring:
            return None
        rotations = 0
        max_rotations = 2 + int(
            math.ceil(
                (max_batch + 1)
                / (self.quantum * min(
                    self.policy(t).weight for t in lane.ring
                ))
            )
        )
        seed_idx: Optional[int] = None
        while rotations <= max_rotations and seed_idx is None:
            any_due_head = False
            n = len(lane.ring)
            for step in range(n):
                i = (lane.cursor + step) % n
                tenant = lane.ring[i]
                q = lane.tenants[tenant]
                q.deficit = min(
                    q.deficit + self.quantum * self.policy(tenant).weight,
                    float(max_batch),
                )
                if not q.entries or q.entries[0].bucket not in due:
                    continue
                any_due_head = True
                if q.deficit >= 1.0:
                    seed_idx = i
                    break
            if not any_due_head:
                # Due tickets exist but every holder's head is queued
                # behind a not-due shape (FIFO per tenant) — nothing to
                # draw this pass.
                return None
            rotations += 1
        if seed_idx is None:
            return None
        bucket = lane.tenants[lane.ring[seed_idx]].entries[0].bucket
        # Phase 2 — fill the batch with same-bucket entries in ring
        # order starting at the seed. Each taken ticket is charged to
        # its owner (deficit may go negative: the tenant pays the batch
        # off over subsequent rotations); co-batching across tenants is
        # never blocked by debt, because utilization is decided here
        # and fairness is decided by the ORDER of batches.
        entries: List[SchedEntry] = []
        n = len(lane.ring)
        for step in range(n):
            i = (seed_idx + step) % n
            q = lane.tenants[lane.ring[i]]
            while q.entries and q.entries[0].bucket == bucket:
                if len(entries) >= max_batch:
                    break
                entries.append(q.entries.popleft())
                q.deficit -= 1.0
            if len(entries) >= max_batch:
                break
        # Advance the cursor past the seed so the next draw starts at
        # the following tenant — this is what alternates tenants whose
        # shapes never share a batch.
        lane.cursor = (seed_idx + 1) % n
        self.drawn += len(entries)
        return (bucket, entries)

    def _prune_lane(self, priority: int) -> None:
        lane = self._lanes[priority]
        for tenant in list(lane.ring):
            lane._retire_empty(tenant)
        if not lane.tenants:
            del self._lanes[priority]


# ----------------------------------------------------------- autoscaling


class Autoscaler:
    """The pure scale policy: signals in, worker delta out.

    Stateful only in its hysteresis bookkeeping (cooldown stamps, idle
    grace clock) — no threads, no processes — so
    ``tests/test_scheduler.py`` can drive years of oscillating load
    through it in microseconds. The fleet's policy thread feeds it real
    signals and applies the delta."""

    def __init__(self, cfg: AutoscaleConfig):
        self.cfg = cfg
        self._last_up = -math.inf
        self._last_down = -math.inf
        self._idle_since: Optional[float] = None

    def decide(
        self,
        now: float,
        alive: int,
        backlog: float,
        claimed: int,
        spool_wait_p99: Optional[float] = None,
        burn_alerts: int = 0,
        stragglers: int = 0,
    ) -> Tuple[int, str]:
        """One evaluation: ``(delta, reason)``. ``backlog`` counts
        claimable batches (spooled pending + coordinator-queued),
        ``claimed`` batches currently executing. Positive delta =
        spawn, negative = drain-retire, 0 = hold."""
        cfg = self.cfg
        busy = backlog > 0 or claimed > 0
        if busy:
            self._idle_since = None
        if alive < cfg.min_workers:
            # Below the floor (a retired-then-needed fleet, or workers
            # died): restore it regardless of cooldowns.
            self._idle_since = None
            return (cfg.min_workers - alive, "floor")
        up_reason = ""
        if backlog > cfg.target_backlog * max(alive, 1):
            up_reason = "backlog"
        elif (
            cfg.spool_wait_p99_ms is not None
            and spool_wait_p99 is not None
            and spool_wait_p99 > cfg.spool_wait_p99_ms
            and busy
        ):
            up_reason = "spool_wait"
        elif burn_alerts > 0 and busy:
            up_reason = "slo_burn"
        elif stragglers > 0 and backlog > 0:
            up_reason = "straggler"
        if (
            up_reason
            and alive < cfg.max_workers
            and now - self._last_up >= cfg.up_cooldown_s
        ):
            self._last_up = now
            return (min(cfg.step, cfg.max_workers - alive), up_reason)
        if not busy and alive > cfg.min_workers:
            if self._idle_since is None:
                self._idle_since = now
            elif (
                now - self._idle_since >= cfg.idle_grace_s
                and now - self._last_down >= cfg.down_cooldown_s
            ):
                self._last_down = now
                return (-min(cfg.step, alive - cfg.min_workers), "idle")
        return (0, "")


# -------------------------------------------------------- incremental scan


class DirWatch:
    """Directory-mtime change detection for the coordinator monitor
    (ISSUE 15 satellite): ``poll()`` is True when any watched
    directory's mtime changed since the previous poll — i.e. an entry
    was created, renamed in/out, or removed — so the monitor re-scans
    spool directories only when a transition actually happened instead
    of re-listing them on every fixed-cadence tick. The first poll
    reports changed (no baseline yet)."""

    def __init__(self, *paths: str):
        self.paths = tuple(paths)
        self._snap: Dict[str, Optional[int]] = {}

    @staticmethod
    def _stamp(path: str) -> Optional[int]:
        try:
            return os.stat(path).st_mtime_ns
        except OSError:
            return None

    def poll(self) -> bool:
        changed = False
        for p in self.paths:
            stamp = self._stamp(p)
            if self._snap.get(p, "∅") != stamp:
                changed = True
            self._snap[p] = stamp
        return changed


def monotonic() -> float:
    return time.monotonic()
