"""Same-host shared-memory ticket ring (ISSUE 18): the fleet's
coordination fast path.

The spool protocol (``serving/fleet.py``) is the fleet's durable spine:
every ticket transition is an atomic rename and SIGKILL at any instant
leaves only recoverable state. It is also the fleet's measured
coordination floor — BENCH_r15 showed throughput *falling* from 28.8 to
22.3 runs/sec between 1 and 8 workers, because every transition costs a
directory scan on the other side of the spool. This module adds the
same-host accelerator: one mmap'd file under the spool root carrying
ticket *metadata* — submit / claim / heartbeat / publish / result-ready
notifications — so workers and the coordinator wake on a shared-memory
counter instead of polling directories, and a lease heartbeat is one
framed slot store instead of a file touch.

The ring is NEVER the source of truth. Every reader treats a torn,
stale, CRC-bad, overflowed, or absent record as "consult the spool":
the fallback path is exactly the pre-ring behavior, bit-for-bit, and a
bounded fallback scan cadence is kept even when the ring looks healthy
so a SIGKILL'd or wedged peer can never stall the fleet.

Layout (all little-endian, one file, default ``ring.shm`` under the
spool root, created atomically by the coordinator via temp + rename)::

    [fixed header][mutable record][worker slots][event frames]

- **fixed header** (offset 0, written once at create): magic
  ``PGARING1``, layout version, geometry (slot/frame counts and sizes),
  the coordinator pid and creation wall time — what :meth:`ShmRing.attach`
  validates and what stale-ring detection reads on restart.
- **mutable record** (offset 256, seqlock+CRC framed, coordinator is
  the single writer): the frame ``head`` sequence, the advertised
  ``pending_depth`` (released-but-unclaimed batch files), and a
  ``coord_alive`` wall-clock touch refreshed every monitor tick.
- **worker slots** (one per worker, seqlock+CRC framed, each slot's
  spawned worker is its single writer): worker id, pid, last heartbeat
  wall time, and monotone ``notify``/``claims``/``publishes`` counters.
  The coordinator's monitor wakes on the sum of ``notify`` counters;
  lease freshness reads the heartbeat stamp instead of a lease-file
  mtime.
- **event frames** (a ring of fixed-size frames, coordinator is the
  single writer): JSON payloads validated by a per-frame global
  sequence number + CRC32. Frame ``s`` lives at index ``(s-1) % N``;
  a reader that has fallen more than ``N`` frames behind sees the
  overflow and falls back to a spool scan — the ring never blocks and
  never drops work, it only stops accelerating.

Single-writer-per-region discipline is what makes the seqlock protocol
sufficient: no CAS, no cross-process locks, no futexes — just framed
stores (odd sequence while writing, even+CRC when committed) and
validating readers. All raw mmap mutations in this module live in the
``_framed_*`` helpers; ``tools/lint_pga.py``'s ``ring-framed-write``
rule enforces that nothing else in the repo mutates an mmap directly.
"""

from __future__ import annotations

import json
import mmap
import os
import struct
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

from libpga_tpu.robustness import faults as _faults

__all__ = ["ShmRing", "RingError", "RING_FILENAME"]

RING_FILENAME = "ring.shm"

MAGIC = b"PGARING1"
#: v2 (ISSUE 20): the fixed header grew a trailing coordinator-epoch
#: field — the HA leader-election fence generation stamped at create.
#: Rings are ephemeral (each coordinator atomically rebuilds its own at
#: start), so a v1 ring under a v2 reader is simply "stale, rebuild".
LAYOUT_VERSION = 2

#: Geometry defaults. Stored in the fixed header at create time, so
#: attachers compute offsets from the file, not from these constants.
HDR_SIZE = 4096
MUT_OFF = 256
HB_SLOTS = 64
SLOT_SIZE = 128
N_FRAMES = 512
FRAME_SIZE = 256

_FIXED_FMT = "<8sIIIIIQdQ"  # magic, version, slots, frames, fsize, ssize, pid, created, epoch
_MUT_FMT = "<QQd"  # head, pending_depth, coord_alive
_SLOT_FMT = "<16sQdQQQ"  # wid, pid, hb, notify, claims, publishes
_FRAME_HDR_FMT = "<QII"  # seqno, length, crc32

_MUT_SIZE = struct.calcsize(_MUT_FMT)
_SLOT_PAYLOAD = struct.calcsize(_SLOT_FMT)
_FRAME_HDR = struct.calcsize(_FRAME_HDR_FMT)


class RingError(RuntimeError):
    """The ring could not be created, attached, or written. Callers
    degrade to the pure-spool path — never propagate this into fleet
    correctness."""


# ------------------------------------------------------- framed writers
#
# THE sanctioned mmap mutations (lint rule ``ring-framed-write``): a
# seqlock+CRC framed store for fixed-size records, a sequence-stamped
# store for ring frames, and the create-time image write. Everything
# else in the repo goes through ShmRing's public methods.


def _framed_store(mm, off: int, payload: bytes) -> None:
    """Seqlock+CRC framed store: bump the 32-bit sequence to odd (write
    in progress), lay down the payload and its CRC32, bump to even
    (committed). A reader that observes an odd or unstable sequence, or
    a CRC mismatch, discards the read."""
    (seq,) = struct.unpack_from("<I", mm, off)
    struct.pack_into("<I", mm, off, (seq + 1) & 0xFFFFFFFF)
    mm[off + 4:off + 4 + len(payload)] = payload
    struct.pack_into(
        "<I", mm, off + 4 + len(payload), zlib.crc32(payload) & 0xFFFFFFFF
    )
    struct.pack_into("<I", mm, off, (seq + 2) & 0xFFFFFFFF)


def _framed_store_frame(mm, off: int, seqno: int, payload: bytes) -> None:
    """Ring-frame store: invalidate the frame's sequence stamp, lay
    down length + CRC + payload, then commit the global sequence
    number. Readers require the stamp to equal the exact sequence they
    expect at this index, before AND after reading the payload."""
    struct.pack_into("<Q", mm, off, 0)
    struct.pack_into(
        "<II", mm, off + 8, len(payload), zlib.crc32(payload) & 0xFFFFFFFF
    )
    mm[off + _FRAME_HDR:off + _FRAME_HDR + len(payload)] = payload
    struct.pack_into("<Q", mm, off, seqno)


# ------------------------------------------------------ validating reads


def _framed_load(mm, off: int, size: int) -> Optional[bytes]:
    """Validating read of a seqlock+CRC framed record; None on a torn
    or corrupt frame (caller falls back to the spool)."""
    for _ in range(4):
        (s1,) = struct.unpack_from("<I", mm, off)
        if s1 & 1:
            continue
        payload = bytes(mm[off + 4:off + 4 + size])
        (crc,) = struct.unpack_from("<I", mm, off + 4 + size)
        (s2,) = struct.unpack_from("<I", mm, off)
        if s1 == s2 and zlib.crc32(payload) & 0xFFFFFFFF == crc:
            return payload
    return None


def _load_frame(mm, off: int, expect: int, capacity: int) -> Optional[bytes]:
    """Validating read of ring frame ``expect``; None when the frame
    was overwritten, is mid-write, or fails its CRC."""
    (s1,) = struct.unpack_from("<Q", mm, off)
    if s1 != expect:
        return None
    length, crc = struct.unpack_from("<II", mm, off + 8)
    if not 0 < length <= capacity:
        return None
    payload = bytes(mm[off + _FRAME_HDR:off + _FRAME_HDR + length])
    (s2,) = struct.unpack_from("<Q", mm, off)
    if s2 != expect or zlib.crc32(payload) & 0xFFFFFFFF != crc:
        return None
    return payload


class ShmRing:
    """One attached (or created) shared-memory ticket ring.

    The coordinator calls :meth:`create` (atomic temp + rename under
    the spool root, replacing any stale predecessor); workers and
    observers call :meth:`attach`. Write methods are partitioned by the
    single-writer discipline: the coordinator owns the mutable record
    and the event frames, a worker owns exactly the slot it was bound
    to at spawn. All write methods may raise :class:`RingError` (and
    fire the ``ring.publish`` fault site) — callers degrade to the
    spool. All read methods return ``None``/flags instead of raising.
    """

    def __init__(self, path: str, fd: int, mm, geom: dict,
                 owner: bool = False):
        self.path = path
        self._fd = fd
        self._mm = mm
        self._geom = geom
        self._owner = owner
        self._wlock = threading.Lock()
        self._slot_idx: Optional[int] = None
        self._slot_state: Optional[dict] = None
        # Coordinator-side cache of the mutable record (it is the
        # single writer, so its cache is authoritative).
        self._head = 0
        self._depth = 0

    # ----------------------------------------------------------- lifecycle

    @classmethod
    def create(cls, path: str, hb_slots: int = HB_SLOTS,
               n_frames: int = N_FRAMES,
               epoch: int = 0) -> Tuple["ShmRing", dict]:
        """Create (or atomically replace) the ring at ``path``; returns
        ``(ring, prior)`` where ``prior`` describes any pre-existing
        ring file — ``{"existed": bool, "stale": bool, "prev_pid": int}``
        — so the coordinator can report a stale ring left by a
        SIGKILL'd predecessor being rebuilt. ``epoch`` (ISSUE 20) is
        the creating coordinator's leader-election fence generation,
        stamped into the fixed header: a zombie leader's ring is
        recognizable by its lower epoch (0 = single-coordinator fleet,
        no fencing)."""
        prior = {"existed": False, "stale": False, "prev_pid": 0}
        old = cls.peek(path)
        if old is not None:
            prior["existed"] = True
            prior["prev_pid"] = int(old.get("pid", 0))
            prior["stale"] = not _pid_alive(prior["prev_pid"])
        elif os.path.exists(path):
            prior["existed"] = True  # unreadable/corrupt counts as stale
            prior["stale"] = True
        size = HDR_SIZE + hb_slots * SLOT_SIZE + n_frames * FRAME_SIZE
        buf = bytearray(size)
        struct.pack_into(
            _FIXED_FMT, buf, 0, MAGIC, LAYOUT_VERSION, hb_slots, n_frames,
            FRAME_SIZE, SLOT_SIZE, os.getpid(), time.time(), int(epoch),
        )
        mut = struct.pack(_MUT_FMT, 0, 0, time.time())
        # Seqlock-frame the initial mutable record inside the image so
        # the very first reader sees a committed (even, CRC-valid) one.
        struct.pack_into("<I", buf, MUT_OFF, 0)
        buf[MUT_OFF + 4:MUT_OFF + 4 + len(mut)] = mut
        struct.pack_into(
            "<I", buf, MUT_OFF + 4 + len(mut), zlib.crc32(mut) & 0xFFFFFFFF
        )
        # Frame every (unbound, pid=0) slot the same way — readers must
        # see "empty", never "torn", for slots no worker has bound yet.
        empty = bytes(_SLOT_PAYLOAD)
        empty_crc = struct.pack("<I", zlib.crc32(empty) & 0xFFFFFFFF)
        for i in range(hb_slots):
            off = HDR_SIZE + i * SLOT_SIZE
            buf[off + 4:off + 4 + _SLOT_PAYLOAD] = empty
            buf[off + 4 + _SLOT_PAYLOAD:off + 8 + _SLOT_PAYLOAD] = empty_crc
        tmp = f"{path}.{os.getpid()}.tmp"
        try:
            with open(tmp, "wb") as fh:
                fh.write(buf)
            os.replace(tmp, path)
        except OSError as exc:
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise RingError(f"ring create failed: {exc}") from exc
        ring = cls._open(path, writable=True, owner=True)
        return ring, prior

    @classmethod
    def attach(cls, path: str, slot: Optional[int] = None,
               worker_id: str = "") -> "ShmRing":
        """Attach to an existing ring; validates magic/version/geometry
        and (when ``slot`` is given) binds this process as the single
        writer of that worker slot."""
        ring = cls._open(path, writable=True, owner=False)
        if slot is not None:
            if not 0 <= slot < ring._geom["hb_slots"]:
                ring.close()
                raise RingError(f"slot {slot} out of range")
            ring._slot_idx = slot
            ring._slot_state = {
                "wid": worker_id, "pid": os.getpid(), "hb": time.time(),
                "notify": 0, "claims": 0, "publishes": 0,
            }
            ring._store_slot()
        return ring

    @classmethod
    def _open(cls, path: str, writable: bool, owner: bool) -> "ShmRing":
        try:
            fd = os.open(path, os.O_RDWR if writable else os.O_RDONLY)
        except OSError as exc:
            raise RingError(f"ring open failed: {exc}") from exc
        try:
            size = os.fstat(fd).st_size
            if size < HDR_SIZE:
                raise RingError(f"ring file truncated ({size} bytes)")
            mm = mmap.mmap(
                fd, size,
                access=mmap.ACCESS_WRITE if writable else mmap.ACCESS_READ,
            )
        except (OSError, ValueError) as exc:
            os.close(fd)
            raise RingError(f"ring mmap failed: {exc}") from exc
        try:
            (magic, version, hb_slots, n_frames, fsize, ssize, pid,
             created, epoch) = struct.unpack_from(_FIXED_FMT, mm, 0)
        except struct.error as exc:
            mm.close()
            os.close(fd)
            raise RingError(f"ring header unreadable: {exc}") from exc
        geom = {
            "hb_slots": hb_slots, "n_frames": n_frames,
            "frame_size": fsize, "slot_size": ssize,
            "pid": pid, "created": created, "epoch": epoch,
        }
        expect = HDR_SIZE + hb_slots * ssize + n_frames * fsize
        if (magic != MAGIC or version != LAYOUT_VERSION
                or n_frames < 1 or hb_slots < 1
                or fsize < _FRAME_HDR + 1 or ssize < _SLOT_PAYLOAD + 8
                or size < expect):
            mm.close()
            os.close(fd)
            raise RingError(
                f"ring header invalid (magic={magic!r} version={version} "
                f"size={size})"
            )
        return cls(path, fd, mm, geom, owner=owner)

    def close(self, unlink: bool = False) -> None:
        try:
            self._mm.close()
        except (BufferError, ValueError):
            pass
        try:
            os.close(self._fd)
        except OSError:
            pass
        if unlink and self._owner:
            try:
                os.remove(self.path)
            except OSError:
                pass

    # ------------------------------------------------------------- offsets

    def _slot_off(self, idx: int) -> int:
        return HDR_SIZE + idx * self._geom["slot_size"]

    def _frame_off(self, seqno: int) -> int:
        n = self._geom["n_frames"]
        return (HDR_SIZE + self._geom["hb_slots"] * self._geom["slot_size"]
                + ((seqno - 1) % n) * self._geom["frame_size"])

    def frame_capacity(self) -> int:
        return self._geom["frame_size"] - _FRAME_HDR

    # ------------------------------------------------- coordinator writers

    def _store_mutable(self) -> None:
        payload = struct.pack(_MUT_FMT, self._head, self._depth, time.time())
        try:
            _framed_store(self._mm, MUT_OFF, payload)
        except (ValueError, struct.error, IndexError) as exc:
            raise RingError(f"mutable store failed: {exc}") from exc

    def advertise(self, kind: str, name: str = "", **extra) -> int:
        """Publish one notification frame (``submit``/``result`` style)
        and bump the head; workers waiting on the head wake. Returns
        the frame's global sequence number."""
        if _faults.PLAN is not None:
            _faults.PLAN.fire("ring.publish")
        payload = json.dumps(
            {"kind": kind, "name": name, **extra},
            separators=(",", ":"),
        ).encode("utf-8")
        if len(payload) > self.frame_capacity():
            raise RingError(f"frame payload too large ({len(payload)}B)")
        with self._wlock:
            seqno = self._head + 1
            try:
                _framed_store_frame(
                    self._mm, self._frame_off(seqno), seqno, payload
                )
            except (ValueError, struct.error, IndexError) as exc:
                raise RingError(f"frame store failed: {exc}") from exc
            self._head = seqno
            self._store_mutable()
        return seqno

    def set_pending_depth(self, depth: int) -> None:
        """Advertise the live released-but-unclaimed batch depth (the
        scheduler's release window reads this instead of a listdir)."""
        if _faults.PLAN is not None:
            _faults.PLAN.fire("ring.publish")
        with self._wlock:
            self._depth = max(int(depth), 0)
            self._store_mutable()

    def touch_coordinator(self) -> None:
        """Refresh ``coord_alive`` (called every monitor tick) — the
        liveness stamp observers use to tell a live ring from the
        leftovers of a SIGKILL'd coordinator."""
        with self._wlock:
            self._store_mutable()

    # ------------------------------------------------------- worker writers

    def _store_slot(self) -> None:
        st = self._slot_state
        payload = struct.pack(
            _SLOT_FMT, st["wid"].encode("utf-8")[:16].ljust(16, b"\0"),
            st["pid"], st["hb"], st["notify"], st["claims"],
            st["publishes"],
        )
        try:
            _framed_store(self._mm, self._slot_off(self._slot_idx), payload)
        except (ValueError, struct.error, IndexError) as exc:
            raise RingError(f"slot store failed: {exc}") from exc

    def _slot_update(self, **bumps) -> None:
        if self._slot_idx is None:
            raise RingError("no slot bound (read-only attach)")
        if _faults.PLAN is not None:
            _faults.PLAN.fire("ring.publish")
        with self._wlock:
            st = self._slot_state
            st["hb"] = time.time()
            for key, delta in bumps.items():
                st[key] += delta
            self._store_slot()

    def heartbeat(self) -> None:
        """One framed slot store — the ring-mode replacement for the
        lease-file ``os.utime`` touch."""
        self._slot_update()

    def note_claim(self) -> None:
        self._slot_update(claims=1, notify=1)

    def note_publish(self) -> None:
        """Result-ready notification: the coordinator's monitor wakes
        on the notify sum and scans ``results/``."""
        self._slot_update(publishes=1, notify=1)

    # --------------------------------------------------------------- reads

    def mutable(self) -> Optional[dict]:
        payload = _framed_load(self._mm, MUT_OFF, _MUT_SIZE)
        if payload is None:
            return None
        head, depth, alive = struct.unpack(_MUT_FMT, payload)
        return {"head": head, "pending_depth": depth, "coord_alive": alive}

    def slot(self, idx: int) -> Optional[dict]:
        payload = _framed_load(self._mm, self._slot_off(idx), _SLOT_PAYLOAD)
        if payload is None:
            return None
        wid, pid, hb, notify, claims, publishes = struct.unpack(
            _SLOT_FMT, payload
        )
        return {
            "wid": wid.rstrip(b"\0").decode("utf-8", "replace"),
            "pid": pid, "hb": hb, "notify": notify,
            "claims": claims, "publishes": publishes,
        }

    def slots(self) -> List[dict]:
        """Every bound (pid != 0) worker slot's latest stable record."""
        out = []
        for i in range(self._geom["hb_slots"]):
            rec = self.slot(i)
            if rec is not None and rec["pid"] != 0:
                rec["slot"] = i
                out.append(rec)
        return out

    def notify_sum(self) -> Optional[Tuple[int, int]]:
        """``(sum of notify counters, torn slot count)`` across bound
        slots — the coordinator's wake signal. None when the mutable
        record itself is unreadable."""
        torn = 0
        total = 0
        for i in range(self._geom["hb_slots"]):
            payload = _framed_load(
                self._mm, self._slot_off(i), _SLOT_PAYLOAD
            )
            if payload is None:
                torn += 1
                continue
            _, pid, _, notify, _, _ = struct.unpack(_SLOT_FMT, payload)
            if pid:
                total += notify
        return total, torn

    def counters(self) -> dict:
        """Summed worker-slot counters — the coordinator's per-tick
        observation: ``{"notify", "claims", "publishes", "torn"}``.
        Torn slots are skipped (their next stable read is a change the
        monitor wakes on anyway)."""
        out = {"notify": 0, "claims": 0, "publishes": 0, "torn": 0}
        for i in range(self._geom["hb_slots"]):
            payload = _framed_load(
                self._mm, self._slot_off(i), _SLOT_PAYLOAD
            )
            if payload is None:
                out["torn"] += 1
                continue
            _, pid, _, notify, claims, publishes = struct.unpack(
                _SLOT_FMT, payload
            )
            if pid:
                out["notify"] += notify
                out["claims"] += claims
                out["publishes"] += publishes
        return out

    def frames_since(self, last_seq: int) -> dict:
        """Frames published after ``last_seq``: ``{"frames": [payload
        dicts], "head": int, "overflowed": bool, "torn": bool}``.
        ``overflowed`` means the reader fell more than a ring's worth
        behind (missed frames — do a spool scan); ``torn`` means a
        frame or the head failed validation (same remedy)."""
        out = {"frames": [], "head": last_seq, "overflowed": False,
               "torn": False}
        mut = self.mutable()
        if mut is None:
            out["torn"] = True
            return out
        head = mut["head"]
        out["head"] = head
        if head < last_seq:
            # The ring was rebuilt under us (coordinator restart).
            out["overflowed"] = True
            return out
        n = self._geom["n_frames"]
        if head - last_seq > n:
            out["overflowed"] = True
            last_seq = head - n
        for s in range(last_seq + 1, head + 1):
            payload = _load_frame(
                self._mm, self._frame_off(s), s, self.frame_capacity()
            )
            if payload is None:
                out["torn"] = True
                continue
            try:
                out["frames"].append(json.loads(payload.decode("utf-8")))
            except (ValueError, UnicodeDecodeError):
                out["torn"] = True
        return out

    # --------------------------------------------------------------- waits

    def wait_pending(self, last_head: int, last_depth: int, timeout: float,
                     stop: Optional[threading.Event] = None,
                     spin_s: float = 0.002) -> Tuple[str, int, int]:
        """Worker-side wait: ``(reason, head, depth)`` with reason
        ``"head"`` when new frames were published, ``"depth"`` when the
        advertised released depth GREW past ``last_depth`` (growth
        only: an unchanged stale depth must not busy-wake a worker
        that already failed to claim), ``"stop"``/``"timeout"``
        otherwise, ``"torn"`` when the ring stopped validating. The
        timeout IS the bounded fallback poll: expiry sends the caller
        to a spool scan."""
        if _faults.PLAN is not None:
            _faults.PLAN.fire("ring.wake")
        deadline = time.monotonic() + timeout
        while True:
            mut = self.mutable()
            if mut is None:
                return ("torn", last_head, last_depth)
            head, depth = mut["head"], mut["pending_depth"]
            if head != last_head:
                return ("head", head, depth)
            if depth > last_depth:
                return ("depth", head, depth)
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return ("timeout", head, depth)
            if stop is not None:
                if stop.wait(min(spin_s, remaining)):
                    return ("stop", head, depth)
            else:
                time.sleep(min(spin_s, remaining))

    def wait_activity(self, last_sum: int, timeout: float,
                      stop: Optional[threading.Event] = None,
                      spin_s: float = 0.005) -> Tuple[str, int]:
        """Coordinator-side wait: ``("notify", new_sum)`` when any
        worker bumped its notify counter (claim or publish happened),
        ``("stop", ...)`` when the in-process wake event fired,
        ``("timeout", ...)`` at the bounded fallback expiry."""
        if _faults.PLAN is not None:
            _faults.PLAN.fire("ring.wake")
        deadline = time.monotonic() + timeout
        while True:
            res = self.notify_sum()
            if res is not None and res[0] != last_sum:
                return ("notify", res[0])
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return ("timeout", last_sum)
            if stop is not None:
                if stop.wait(min(spin_s, remaining)):
                    return ("stop", last_sum)
            else:
                time.sleep(min(spin_s, remaining))

    # ------------------------------------------------------------ observers

    @staticmethod
    def peek(path: str) -> Optional[dict]:
        """Read-only health snapshot for ``fleet_status``/``fleet_top``:
        geometry, coordinator pid/liveness, head, advertised depth, and
        bound worker slots. None when absent or unreadable."""
        try:
            ring = ShmRing._open(path, writable=False, owner=False)
        except RingError:
            return None
        try:
            mut = ring.mutable()
            slots = ring.slots()
            geom = ring._geom
            out = {
                "pid": geom["pid"],
                "created": geom["created"],
                "epoch": geom["epoch"],
                "n_frames": geom["n_frames"],
                "hb_slots": geom["hb_slots"],
                "coordinator_alive": _pid_alive(geom["pid"]),
                "workers_bound": len(slots),
                "slots": slots,
            }
            if mut is not None:
                out.update(mut)
            else:
                out["torn"] = True
            return out
        finally:
            ring.close()


def _pid_alive(pid: int) -> bool:
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    except OSError:
        return False
    return True
