"""Multi-tenant batched run engine.

Three layers (see ISSUE 4 / README "Serving"):

- :mod:`libpga_tpu.serving.batch` — :class:`BatchedRuns`, the executor
  packing N same-signature runs into ONE compiled mega-run over a
  leading run axis, bit-identical per run to standalone ``PGA.run``;
- :mod:`libpga_tpu.serving.cache` — the module-level shape-bucket
  program cache with AOT warm-up and hit/miss/evict counters;
- :mod:`libpga_tpu.serving.queue` — the async front door:
  ``submit() -> RunTicket``, accumulation per bucket, launch at
  ``max_batch`` or ``max_wait_ms``.
"""

from libpga_tpu.config import ServingConfig
from libpga_tpu.serving.batch import BatchedRuns, RunRequest, RunResult
from libpga_tpu.serving.cache import COUNTERS, PROGRAM_CACHE, ProgramCache
from libpga_tpu.serving.queue import RunQueue, RunTicket

__all__ = [
    "BatchedRuns",
    "RunRequest",
    "RunResult",
    "RunQueue",
    "RunTicket",
    "ServingConfig",
    "ProgramCache",
    "PROGRAM_CACHE",
    "COUNTERS",
]
