"""Multi-tenant batched run engine.

Three layers (see ISSUE 4 / README "Serving"):

- :mod:`libpga_tpu.serving.batch` — :class:`BatchedRuns`, the executor
  packing N same-signature runs into ONE compiled mega-run over a
  leading run axis, bit-identical per run to standalone ``PGA.run``;
- :mod:`libpga_tpu.serving.cache` — the module-level shape-bucket
  program cache with AOT warm-up and hit/miss/evict counters;
- :mod:`libpga_tpu.serving.queue` — the async front door:
  ``submit() -> RunTicket``, accumulation per bucket, launch at
  ``max_batch`` or ``max_wait_ms``.

Failure semantics (ISSUE 5 — the contracts a serving operator leans on):

- **Per-ticket failure isolation.** A failing run inside a mega-batch
  fails ONLY its own ticket. When a launch raises, the queue
  pre-validates every co-batched request (``BatchedRuns.validate``) —
  statically invalid ones dead-letter immediately with their diagnosis —
  and requeues the survivors ONCE as solo launches; a request that then
  fails alone is itself the poison. Poisoned requests land on
  ``RunQueue.dead_letters`` (a :class:`~libpga_tpu.serving.queue.DeadLetter`
  each: request + bucket + error) and emit a ``dead_letter`` telemetry
  event; every innocent ticket completes normally.
- **Bounded-queue backpressure.** ``ServingConfig(max_pending=N)``
  bounds admitted-but-incomplete tickets; at the bound ``submit``
  follows ``overflow``: ``"block"`` (wait for a completion) or
  ``"raise"`` (:class:`~libpga_tpu.serving.queue.QueueFull` — load
  shedding). Default is unbounded, the pre-robustness behavior.
- **Deterministic teardown.** ``RunQueue.close()`` wakes and JOINS the
  background flusher before the final flush — no flusher iteration can
  race a post-close launch, and post-close ``submit`` always raises.
  A flusher thread that dies mid-run (crash, injected
  ``serving.flusher`` fault) is replaced on the next submit.
- ``ticket.result(timeout=...)`` raising ``TimeoutError`` leaves the
  ticket re-awaitable — call ``result()`` again to keep waiting.
- ``RunQueue.close()`` is idempotent under CONCURRENT closers: one
  caller tears down, every other close() waits for it and no-ops.

Fleet layer (ISSUE 8 — ``serving/fleet.py`` + ``serving/worker.py``):
:class:`Fleet` lifts all of the above across PROCESSES — a coordinator
owns ticket intake and N supervised worker processes claim shape-bucket
batches under time-bounded heartbeat leases, with fleet-level
dead-lettering (:class:`FleetDeadLetter` after ``max_worker_deaths``),
fleet-wide ``max_pending`` backpressure, and preemption-safe SIGTERM
draining through the supervisor's checkpoint machinery. A worker killed
mid-batch (SIGKILL included) has its lease expire and its batch re-run
bit-identically on a survivor: seeds and runtime parameters travel with
the ticket, never with the worker.

Scheduling layer (ISSUE 15 — ``serving/scheduler.py``): the fleet's
FIFO intake is replaced by per-tenant deficit-round-robin batch
formation over priority lanes (:class:`~libpga_tpu.config.TenantPolicy`
weights/quotas/priorities in ``FleetConfig.tenants``), deterministic
per-tenant admission control (:class:`QuotaExceeded`), chunk-boundary
preemption of lower-priority supervised batches, and a closed-loop
:class:`~libpga_tpu.config.AutoscaleConfig` worker autoscaler that
follows offered load up and down without changing a single result bit.
"""

from libpga_tpu.config import (
    AutoscaleConfig,
    FleetConfig,
    ServingConfig,
    SLOConfig,
    TenantPolicy,
)
from libpga_tpu.serving.batch import BatchedRuns, RunRequest, RunResult
from libpga_tpu.serving.cache import COUNTERS, PROGRAM_CACHE, ProgramCache
from libpga_tpu.serving.fleet import (
    FLEET_SPANS,
    Fleet,
    FleetDeadLetter,
    FleetHandle,
    FleetResult,
    FleetTicket,
    fleet_status,
    merge_spool_metrics,
)
from libpga_tpu.serving.queue import (
    DeadLetter,
    QueueFull,
    RunQueue,
    RunTicket,
    TicketTiming,
)
from libpga_tpu.serving.scheduler import (
    Autoscaler,
    FleetScheduler,
    QuotaExceeded,
)

__all__ = [
    "BatchedRuns",
    "RunRequest",
    "RunResult",
    "RunQueue",
    "RunTicket",
    "TicketTiming",
    "DeadLetter",
    "QueueFull",
    "ServingConfig",
    "SLOConfig",
    "FleetConfig",
    "TenantPolicy",
    "AutoscaleConfig",
    "FleetScheduler",
    "Autoscaler",
    "QuotaExceeded",
    "Fleet",
    "FleetTicket",
    "FleetHandle",
    "FleetResult",
    "FleetDeadLetter",
    "FLEET_SPANS",
    "fleet_status",
    "merge_spool_metrics",
    "ProgramCache",
    "PROGRAM_CACHE",
    "COUNTERS",
]
