"""Shape-bucketed mega-runs: N independent GA runs as ONE program.

``PGA.run`` is one synchronous host dispatch of one run. A serving host
handling N concurrent requests as N engine instances pays N full
trace+compile+dispatch pipelines for what is — whenever the requests
share a shape signature — the SAME program over different runtime
inputs. This module packs such requests into one compiled **mega-run**
over a leading run axis:

- anything that shapes the traced program (population size, genome
  length, gene dtype, objective, operator kinds, selection config,
  telemetry depth) forms the **bucket signature** — requests in one
  bucket share one compilation, cached process-wide (``cache.py``);
- anything that is already a runtime input of the fused run loop stays
  per-run: the PRNG seed, the generation budget ``n``, the early-stop
  ``target``, and the mutation rate/sigma (via
  ``ops/step.make_param_breed``, which reads them from the ``mparams``
  input instead of baking them in);
- results are **bit-identical per run** to a standalone same-seed
  ``PGA.run`` — the mega-run reuses the engine's exact
  ``make_run_loop`` body per run slice, and the request-state
  derivation replays the engine's key chain (``key(seed)`` → split for
  the population → split for the run).

Two run-axis layouts (``ServingConfig.layout``):

- ``run_major`` — ``lax.scan`` over runs, each executing its own fused
  ``while_loop``. Every run's ~pop×len working set stays cache-resident
  across its generations and an early-terminating run simply stops.
  The measured winner on CPU hosts (the 1M-per-generation lockstep
  layout thrashes the cache: ~330 ms/run vs ~135 ms/run at 32×16k×100).
- ``lockstep`` — ``vmap`` over runs: one wide program stepping every
  run per iteration, with the branchless per-run early-termination
  freeze that vmapped ``while_loop`` provides (finished runs' carries
  are frozen by select). The layout for accelerators, where the run
  axis buys arithmetic intensity instead of cache misses.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from libpga_tpu.config import PGAConfig, ServingConfig
from libpga_tpu.engine import make_run_loop
from libpga_tpu.ops.crossover import uniform_crossover
from libpga_tpu.ops.step import make_param_breed
from libpga_tpu.population import create_population
from libpga_tpu.robustness import faults as _faults
from libpga_tpu.serving import cache as _cache
from libpga_tpu.utils import metrics as _metrics
from libpga_tpu.utils import telemetry as _tl


@dataclasses.dataclass(frozen=True)
class RunRequest:
    """One GA run to serve.

    ``size``/``genome_len`` place the request in a shape bucket;
    ``seed`` (or an explicit ``key``/``genomes`` pair) makes it
    reproducible — a seed-only request is served bit-identically to
    ``PGA(seed=seed)`` + ``create_population(size, genome_len)`` +
    ``run(n, target=target)`` with the same operator parameters.
    ``mutation_rate``/``mutation_sigma`` default to the executor
    config's values; they are runtime inputs of the bucket's shared
    program, so requests with different rates (e.g. an annealing
    sweep's phases) still share one compilation.
    """

    size: int
    genome_len: int
    n: int
    seed: Optional[int] = None
    key: Optional[jax.Array] = None
    genomes: Optional[jax.Array] = None
    target: Optional[float] = None
    mutation_rate: Optional[float] = None
    mutation_sigma: Optional[float] = None

    def __post_init__(self):
        if self.seed is None and self.key is None:
            raise ValueError("RunRequest needs a seed or an explicit key")
        if self.n < 0:
            raise ValueError("n must be >= 0")


class RunResult:
    """One run's slice of a completed mega-run.

    Device buffers stay unmaterialized until read — launching batch
    k+1 overlaps with reading batch k back (``jax.block_until_ready``
    deferral; the queue relies on this). ``generations`` and
    ``best_score`` block; ``genomes``/``scores`` return device arrays.
    """

    def __init__(self, genomes, scores, gens, history_buf, history_gens):
        self._genomes = genomes
        self._scores = scores
        self._gens = gens
        self._history_buf = history_buf
        self._history_gens = history_gens

    @property
    def genomes(self) -> jax.Array:
        return self._genomes

    @property
    def scores(self) -> jax.Array:
        return self._scores

    @property
    def generations(self) -> int:
        return int(self._gens)

    @property
    def history(self) -> Optional[_tl.History]:
        if self._history_buf is None:
            return None
        return _tl.History(self._history_buf, self.generations)

    @property
    def best_score(self) -> float:
        return float(jnp.max(self._scores))

    def best(self) -> np.ndarray:
        """Best genome (host array)."""
        idx = int(jnp.argmax(self._scores))
        return np.asarray(self._genomes[idx])

    def block(self) -> "RunResult":
        jax.block_until_ready((self._genomes, self._scores, self._gens))
        return self


def request_state(
    req: RunRequest, dtype=jnp.float32
) -> tuple:
    """``(genomes, run_key)`` for a request, replaying the engine's key
    chain for seed-only requests so the serving path is bit-identical
    to the engine path: ``PGA(seed=s)`` consumes ``split(key(s))[1]``
    for ``create_population`` and the next split for ``run``."""
    if req.genomes is not None:
        genomes = jnp.asarray(req.genomes, dtype=dtype)
        if genomes.shape != (req.size, req.genome_len):
            raise ValueError(
                f"request genomes {genomes.shape} != "
                f"({req.size}, {req.genome_len})"
            )
        if req.key is not None:
            return genomes, req.key
        k = jax.random.key(req.seed)
        k, run_key = jax.random.split(k)
        return genomes, run_key
    if req.key is not None:
        # Explicit key + generated population: one further split pair,
        # mirroring create_population-then-run on an engine whose key
        # state is `key`.
        k, pop_key = jax.random.split(req.key)
        k, run_key = jax.random.split(k)
    else:
        k = jax.random.key(req.seed)
        k, pop_key = jax.random.split(k)
        k, run_key = jax.random.split(k)
    genomes = create_population(
        pop_key, req.size, req.genome_len, init="random", dtype=dtype
    ).genomes
    return genomes, run_key


def _pad_width(n: int, max_batch: int) -> int:
    """Round a ragged batch up to the next power of two (capped at
    ``max_batch``) so ragged flushes reuse a handful of compiled widths
    instead of one program per batch size. Pad runs carry ``n = 0`` —
    in the run_major layout they cost one evaluation each."""
    width = 1
    while width < n:
        width *= 2
    return min(width, max_batch) if max_batch >= n else n


class BatchedRuns:
    """Executor packing same-signature runs into one compiled mega-run.

    One executor serves one tenant configuration (objective + operator
    kinds + ``PGAConfig``); the bucket signature additionally carries
    the request shape, so one executor still produces distinct buckets
    for distinct shapes. Executors with equal signatures share compiled
    programs through the module-level ``serving.cache.PROGRAM_CACHE``.
    """

    def __init__(
        self,
        objective,
        config: Optional[PGAConfig] = None,
        serving: Optional[ServingConfig] = None,
        crossover: Optional[Callable] = None,
        mutate_kind: str = "point",
        events=None,
    ):
        if isinstance(objective, str):
            from libpga_tpu import objectives

            objective = objectives.get(objective)
        self.objective = objective
        self.config = config or PGAConfig()
        self.serving = serving or ServingConfig()
        self.crossover = crossover or uniform_crossover
        self.mutate_kind = mutate_kind
        self.events = events
        # Tuning-DB resolution per shape (ISSUE 10): cached so bucket
        # admission costs one dict lookup, not a DB walk per request.
        self._tuned_cache: dict = {}

    # ------------------------------------------------------------ bucketing

    def _tuning_for(self, size: int, genome_len: int):
        """``(knobs, provenance)`` of the tuning-DB resolution for one
        request shape — precedence user knob > DB entry > default
        (``tuning.db.resolve_config_knobs``). Provenance is None when
        no DB is installed or no entry matches: the bucket signature
        then carries ``("tuned", None)`` and nothing else changes —
        untuned serving is byte-identical to pre-tuning serving."""
        from libpga_tpu.ops import crossover as _c
        from libpga_tpu.tuning import db as _tdb

        # Keyed on the active DB path too: a long-lived executor picks
        # up a set_tuning_db() swap instead of serving stale knobs.
        # active_db() first — it may install the env-provided DB.
        tdb = _tdb.active_db()
        mark = (_tdb.active_path(), size, genome_len)
        hit = self._tuned_cache.get(mark)
        if hit is not None:
            return hit
        entry = None
        if tdb is not None:
            cross_names = {
                _c.uniform_crossover: "uniform",
                _c.order_preserving_crossover: "order",
                _c.one_point_crossover: "one_point",
                _c.arithmetic_crossover: "arithmetic",
            }
            entry = tdb.lookup(_tdb.current_key(
                size, genome_len, self.config.gene_dtype,
                self.objective,
                cross_names.get(self.crossover, self.crossover),
                self.mutate_kind,
            ))
        knobs, prov = _tdb.resolve_config_knobs(self.config, entry)
        out = (knobs, prov)
        self._tuned_cache[mark] = out
        return out

    def signature(self, req: RunRequest) -> tuple:
        """The exact shape-bucket signature: everything baked into the
        traced program. Two requests share a program iff their
        signatures are equal; seeds, n, targets, and mutation
        parameters are runtime inputs and deliberately absent.
        ``config.serving_signature_fields()`` carries ``pop_shards``
        (ISSUE 7), so sharded and unsharded tenants never share a
        compiled program — and since the cache key
        (:meth:`_program`'s ``prog_key``) extends this signature, the
        separation holds in ``cache.py`` too (collision test in
        tests/test_shard_pop.py). The trailing ``("tuned", ...)`` pair
        (ISSUE 10) is the DB-resolved knob tuple when a tuning-DB entry
        matched this shape (None otherwise), so a tuned bucket can
        never collide with an untuned one — the AOT warm-up compiles,
        and the cache keys, exactly the best-known config."""
        from libpga_tpu.engine import _kind_key

        knobs, prov = self._tuning_for(req.size, req.genome_len)
        tuned = (
            tuple(sorted(knobs.items())) if prov is not None else None
        )
        return (
            "serving/run",
            req.size,
            req.genome_len,
            self.objective,
            _kind_key(self.crossover),
            # Builtin kinds key by name; CALLABLE kinds (the GP
            # structural mutations) by their compiled semantics
            # (kernel_cache_key), exactly like crossovers — so two
            # executors over the same GP encoding share a program and
            # distinct encodings never collide.
            _kind_key(self.mutate_kind),
            self.config.serving_signature_fields(),
            ("tuned", tuned),
        )

    def _emit(self, event: str, **fields) -> None:
        _tl.flight_note(event, fields)  # post-mortem ring, always on
        if self.events is not None:
            self.events.emit(event, **fields)

    # ---------------------------------------------------------- validation

    def validate(self, req: RunRequest) -> Optional[Exception]:
        """Pre-validate one request's static parameters; returns the
        diagnosis (an exception instance) or None when the request looks
        launchable. The queue's failure isolation (``serving/queue.py``)
        uses this to split a failed mega-run into poisoned requests
        (dead-lettered with their error) and innocent survivors
        (requeued) — cheap, host-only checks, no device work."""
        try:
            if req.size < 1 or req.genome_len < 1:
                raise ValueError(
                    f"invalid shape ({req.size}, {req.genome_len})"
                )
            if req.genomes is not None:
                shape = tuple(np.shape(req.genomes))
                if shape != (req.size, req.genome_len):
                    raise ValueError(
                        f"request genomes {shape} != "
                        f"({req.size}, {req.genome_len})"
                    )
            if req.mutation_rate is not None and not (
                0.0 <= req.mutation_rate <= 1.0
            ):
                raise ValueError(
                    f"mutation_rate {req.mutation_rate} not in [0, 1]"
                )
            if req.mutation_sigma is not None and req.mutation_sigma < 0:
                raise ValueError(
                    f"mutation_sigma {req.mutation_sigma} < 0"
                )
        except Exception as e:
            return e
        return None

    # ------------------------------------------------------- program build

    def _history_gens(self) -> Optional[int]:
        t = self.config.telemetry
        return t.history_gens if t is not None and t.history_gens > 0 else None

    def _build_mega(self, N: int, size: int, genome_len: int, layout: str):
        """Compile the N-wide mega-run for one bucket (AOT when
        configured). Returns ``fn(genomes (N,P,L), key_data (N,2)u32,
        n (N,)i32, target (N,)f32, mparams (N,1,2)f32) -> (genomes,
        scores, gens[, history])`` stacked along the run axis."""
        cfg = self.config
        hist = self._history_gens()
        breed = make_param_breed(
            self.crossover,
            self.mutate_kind,
            tournament_size=cfg.tournament_size,
            selection_kind=cfg.selection,
            selection_param=cfg.selection_param,
            elitism=cfg.elitism,
        )
        run_loop = make_run_loop(self.objective, breed, hist)

        if layout == "lockstep":

            def mega(genomes, key_data, n, target, mparams):
                keys = jax.random.wrap_key_data(key_data)
                return jax.vmap(run_loop)(genomes, keys, n, target, mparams)

        else:

            def mega(genomes, key_data, n, target, mparams):
                keys = jax.random.wrap_key_data(key_data)

                def one(carry, xs):
                    return carry, run_loop(*xs)

                _, out = jax.lax.scan(
                    one, 0, (genomes, keys, n, target, mparams)
                )
                return out

        donate = (0,) if self.serving.donate_buffers else ()
        jitted = jax.jit(mega, donate_argnums=donate)
        if not self.serving.aot_warmup:
            return jitted
        dtype = cfg.gene_dtype
        shapes = (
            jax.ShapeDtypeStruct((N, size, genome_len), dtype),
            jax.ShapeDtypeStruct((N, 2), jnp.uint32),
            jax.ShapeDtypeStruct((N,), jnp.int32),
            jax.ShapeDtypeStruct((N,), jnp.float32),
            jax.ShapeDtypeStruct((N, 1, 2), jnp.float32),
        )
        return jitted.lower(*shapes).compile()

    def _program(self, sig: tuple, N: int, layout: str):
        size, genome_len = sig[1], sig[2]
        prog_key = sig + ("layout", layout, "width", N,
                          "donate", self.serving.donate_buffers)
        # AOT warm-up consults the tuning DB (ISSUE 10): the resolved
        # knobs already ride ``sig`` (so they're part of prog_key);
        # here the PROVENANCE is attached to the cached program
        # (cache.stats()) and announced once per actual build.
        knobs, prov = self._tuning_for(size, genome_len)
        tuned = None
        if prov is not None:
            from libpga_tpu.tuning import db as _tdb

            tuned = {
                "population_size": size, "genome_len": genome_len,
                "knobs": dict(knobs), "provenance": dict(prov),
                "db": _tdb.active_path(),
            }

        def on_compile():
            self._emit(
                "compile", what="serving_mega_run", batch_width=N,
                population_size=size, genome_len=genome_len,
                layout=layout,
            )
            if tuned is not None:
                self._emit(
                    "tuned_config", population_size=size,
                    genome_len=genome_len, knobs=dict(knobs),
                    provenance=dict(tuned["provenance"]),
                    db=tuned["db"], where="serving_warmup",
                )

        return _cache.PROGRAM_CACHE.get_or_build(
            prog_key,
            lambda: self._build_mega(N, size, genome_len, layout),
            on_compile=on_compile,
            tuned=tuned,
        )

    # -------------------------------------------------------------- execute

    def _mparams(self, req: RunRequest) -> np.ndarray:
        rate = (
            self.config.mutation_rate
            if req.mutation_rate is None else req.mutation_rate
        )
        sigma = 0.0 if req.mutation_sigma is None else req.mutation_sigma
        return np.asarray([[rate, sigma]], dtype=np.float32)

    def run(
        self, requests: Sequence[RunRequest], layout: Optional[str] = None
    ) -> List[RunResult]:
        """Execute a bucket of same-signature requests as one mega-run.

        Mixed signatures raise — routing mismatched shapes into
        separate buckets is the queue's job (``serving/queue.py``).
        Returns one lazy :class:`RunResult` per request, in order.
        """
        if not requests:
            return []
        # Fault-injection site (robustness/faults): a raise here is a
        # mega-run launch failure the queue's isolation must contain.
        if _faults.PLAN is not None:
            _faults.PLAN.fire("serving.launch")
        sigs = {self.signature(r) for r in requests}
        if len(sigs) != 1:
            raise ValueError(
                f"mixed bucket: {len(sigs)} distinct signatures in one "
                "run() call — shape-route requests through RunQueue"
            )
        sig = sigs.pop()
        layout = layout or self.serving.resolve_layout()
        N = len(requests)
        width = _pad_width(N, max(self.serving.max_batch, N))
        dtype = self.config.gene_dtype

        states = [request_state(r, dtype) for r in requests]
        genomes = jnp.stack([g for g, _ in states])
        key_data = jnp.stack(
            [jax.random.key_data(k) for _, k in states]
        ).astype(jnp.uint32)
        n = np.fromiter((r.n for r in requests), np.int32, N)
        target = np.asarray(
            [np.inf if r.target is None else r.target for r in requests],
            np.float32,
        )
        mparams = np.stack([self._mparams(r) for r in requests])
        if width > N:
            pad = width - N
            genomes = jnp.concatenate(
                [genomes, jnp.broadcast_to(genomes[:1], (pad,) + genomes.shape[1:])]
            )
            key_data = jnp.concatenate(
                [key_data, jnp.broadcast_to(key_data[:1], (pad, key_data.shape[1]))]
            )
            n = np.concatenate([n, np.zeros(pad, np.int32)])
            target = np.concatenate([target, np.full(pad, np.inf, np.float32)])
            mparams = np.concatenate(
                [mparams, np.repeat(mparams[:1], pad, axis=0)]
            )

        fn = self._program(sig, width, layout)
        t0 = time.perf_counter()
        out = fn(
            genomes, key_data, jnp.asarray(n), jnp.asarray(target),
            jnp.asarray(mparams),
        )
        # Host-side dispatch span only (JAX async dispatch returns
        # before the device finishes) — the device-complete span is the
        # ticket's execute_ms, stamped by the queue at _complete.
        _metrics.REGISTRY.histogram(
            "serving.megarun.dispatch_seconds"
        ).observe(time.perf_counter() - t0)
        g, s, gens = out[:3]
        hist_gens = self._history_gens()
        buf = out[3] if len(out) > 3 else None
        return [
            RunResult(
                g[i], s[i], gens[i],
                None if buf is None else buf[i], hist_gens,
            )
            for i in range(N)
        ]
