"""Standalone fleet-coordinator process (ISSUE 20).

Runs ONE coordinator candidate against an existing spool until
SIGTERM. With ``FleetConfig.coordinators > 1`` the process joins the
spool's leader election: exactly one candidate holds the leader lease
and schedules work; the rest stand by, watch the lease, and take over
(bumping the epoch) when it goes stale. Intake arrives through the
durable spool journal (``serving/ha.py``; submit from any process via
``SpoolClient``), so a failover loses nothing — the new leader
rebuilds scheduler state, tenant quota debts, and in-flight leases
from the spool alone.

Used by ``tools/ha_smoke.py`` and the failover chaos matrix; the same
env transports as the worker apply (``PGA_FAULT_SPEC`` fault plans,
plus the coordinator-side ``PGA_COORD_CHAOS`` kill points).
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading
from typing import List, Optional

from libpga_tpu.config import FleetConfig, PGAConfig
from libpga_tpu.robustness import faults as _faults


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--spool", required=True)
    ap.add_argument("--objective", default="onemax")
    ap.add_argument("--coordinators", type=int, default=2,
                    help="candidate count on this spool; > 1 enables "
                         "the leader election + intake journal")
    ap.add_argument("--n-workers", type=int, default=2)
    ap.add_argument("--max-batch", type=int, default=2)
    ap.add_argument("--max-wait-ms", type=float, default=20.0)
    ap.add_argument("--lease-timeout-s", type=float, default=3.0)
    ap.add_argument("--heartbeat-s", type=float, default=0.5)
    ap.add_argument("--poll-s", type=float, default=0.05)
    ap.add_argument("--metrics-flush-s", type=float, default=1.0)
    ap.add_argument("--ring-fallback-s", type=float, default=1.0)
    ap.add_argument("--no-ring", action="store_true",
                    help="pure-spool coordination (no shm ticket ring)")
    ap.add_argument("--autoscale", action="store_true",
                    help="enable a one-worker-headroom autoscaler "
                         "(the chaos matrix's autoscale kill point "
                         "needs a live scale loop)")
    ap.add_argument("--use-pallas", action="store_true",
                    help="engine Pallas kernels (off by default: this "
                         "CLI is exercised on CPU CI)")
    args = ap.parse_args(argv)

    # Same env transport as the worker: install the fault plan before
    # the Fleet constructor runs its first election attempt.
    spec = os.environ.get("PGA_FAULT_SPEC", "")
    if spec:
        _faults.install_spec(spec)

    from libpga_tpu.config import AutoscaleConfig
    from libpga_tpu.serving.fleet import Fleet

    autoscale = None
    if args.autoscale:
        autoscale = AutoscaleConfig(
            min_workers=args.n_workers, max_workers=args.n_workers + 1,
            target_backlog=1.0, up_cooldown_s=0.3, down_cooldown_s=0.5,
            idle_grace_s=0.8, check_s=0.1,
        )
    fleet = Fleet(
        args.spool, args.objective,
        config=PGAConfig(use_pallas=args.use_pallas),
        fleet=FleetConfig(
            n_workers=args.n_workers, max_batch=args.max_batch,
            max_wait_ms=args.max_wait_ms,
            lease_timeout_s=args.lease_timeout_s,
            heartbeat_s=args.heartbeat_s, poll_s=args.poll_s,
            metrics_flush_s=args.metrics_flush_s,
            ring=not args.no_ring, ring_fallback_s=args.ring_fallback_s,
            coordinators=args.coordinators, autoscale=autoscale,
        ),
    )
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    # A standby's start() spawns nothing — the monitor watches the
    # lease and spawns workers only on takeover.
    fleet.start()
    print(
        f"coordinator pid={os.getpid()} leader={fleet.is_leader} "
        f"epoch={fleet.epoch}",
        flush=True,
    )
    try:
        while not stop.wait(0.2):
            pass
    finally:
        fleet.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
