"""Cross-process serving fleet: coordinator, spool protocol, leases.

The round-9/10 serving stack is single-process: one ``RunQueue`` in one
interpreter — a worker crash kills every pending ticket, and there is no
notion of a fleet surviving preemption. This module is the coordinator
half of the fleet (ISSUE 8; ROADMAP item 1 — the distributed
master/worker execution model the Beagle framework treats as
first-class, and the reference's aspirational "+MPI" made real): ticket
intake, shape-bucket batch formation, time-bounded leases, fleet-level
dead-lettering, fleet-wide backpressure, and preemption-safe draining.
``serving/worker.py`` is the worker half.

**Spool protocol.** All cross-process state lives in one spool
directory; every transition is an atomic filesystem operation, so a
process killed at ANY instant (SIGKILL included) leaves the spool in a
recoverable state — the same durability stance as
``utils/checkpoint``'s temp-write + rename:

- ``pending/<batch>.json`` — claimable batch files the coordinator
  writes (temp + ``os.replace``). A batch carries the executor spec,
  the ticket list, and the ``attempts`` record of workers that lost
  their lease on it.
- ``claimed/<batch>.json`` — a worker claims a batch with ONE
  ``os.rename(pending/x, claimed/x)``: atomic, so exactly one of N
  racing workers wins.
- ``leases/<batch>.lease.json`` — written by the claiming worker
  (owner + pid), then touched every ``FleetConfig.heartbeat_s`` by its
  heartbeat thread. The lease IS the liveness contract: a heartbeat
  older than ``lease_timeout_s`` — worker wedged, SIGSTOPped, or its
  heartbeat thread killed — expires the lease and the coordinator
  requeues the batch; a worker PROCESS that exits while holding a
  lease is requeued immediately (the coordinator watches the processes
  it spawned).
- ``results/<tid>.npz`` + ``results/<tid>.json`` — per-ticket results,
  published FIRST-WRITER-WINS (``os.link``, which fails atomically on
  an existing target). Seeds and runtime parameters travel with the
  ticket, never with the worker, so a batch re-run after a worker
  death lands bit-identical — a late duplicate publication from a
  SIGSTOP-resumed worker is therefore identical bits, and the link
  race is benign whoever wins.
- ``ckpt/<tid>.npz`` (+ supervisor sidecar) — drain checkpoints of
  supervised tickets; a re-claiming worker resumes from the last
  durable checkpoint at the ticket's recorded cadence.
- ``dead/`` — quarantined batches: a batch that cost
  ``max_worker_deaths`` DISTINCT workers their lease is moved here
  with a flight-recorder dump instead of being retried forever, and
  its unfinished tickets fail with :class:`FleetDeadLetter`.
- ``logs/`` — per-worker stdout, JSONL event logs, and a Prometheus
  snapshot each worker writes on exit.
- ``traces/<batch>.trace.jsonl`` — the batch's cross-process span log
  (ISSUE 9): coordinator intake spans per ticket, worker claim /
  lease-held markers, requeue records. Appended whole-line (O_APPEND)
  by whichever process observes the transition; per-ticket execute/
  publish spans travel in the result meta instead, so a ticket's
  assembled trace (``FleetHandle.trace()``) shows EVERY attempt —
  including the claim of a worker that then died.
- ``metrics/<proc>.json`` — periodic ``MetricsRegistry`` snapshot
  flushes (atomic rename, ``FleetConfig.metrics_flush_s`` cadence)
  from every worker plus the coordinator. :func:`merge_spool_metrics`
  folds them — through the associative ``HistogramSnapshot.merge`` —
  into ONE fleet snapshot with per-process labels; the feed of
  ``Fleet.merged_prometheus()``, ``Fleet.status()``, straggler
  detection, and ``tools/fleet_top.py`` (which works from the spool
  alone, live fleet or post-mortem).

**Bit-identity.** Plain tickets (``checkpoint_every == 0``) execute as
shape-bucketed mega-runs through the worker's ``RunQueue``/
``BatchedRuns`` engine — per-run bit-identical to standalone
``PGA.run`` (the round-9 contract), so a killed-and-requeued batch
re-runs to the same bits. Supervised tickets (``checkpoint_every >
0``) execute under ``robustness.supervised_run`` at the ticket's
cadence; SIGTERM drains them at a chunk boundary via the supervisor's
``stop`` hook, and the per-process contract — a resumed run is
bit-identical to an uninterrupted same-seed run at the same cadence —
lifts unchanged to the fleet.

**Coordinator HA (ISSUE 20).** With ``FleetConfig.coordinators > 1``
the coordinator itself stops being a single point of failure: N
``Fleet`` instances run against ONE spool, elect a leader through the
spool-resident lease in ``serving/ha.py`` (first-writer-wins link +
heartbeat + ``lease_timeout_s`` expiry — the worker-lease discipline,
one level up), and fence every leader-authored artifact with a
monotonically increasing election epoch. Submissions become durable in
the intake journal BEFORE they are scheduled, so a new leader rebuilds
the fair backlog, quota debts, and ticket bookkeeping from the spool
alone; workers reject batch files below the fence epoch, so a
SIGSTOP-resumed zombie leader can never make a deposed write execute.
``coordinators=1`` (the default) takes none of these paths and keeps
byte-for-byte spool compatibility with round-23 fleets.
"""

from __future__ import annotations

import dataclasses
import errno
import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from libpga_tpu.config import FleetConfig, PGAConfig, TenantPolicy
from libpga_tpu.robustness import faults as _faults
from libpga_tpu.serving import ha as _ha
from libpga_tpu.serving.queue import QueueFull, TenantBurnTracker
from libpga_tpu.serving.scheduler import (
    Autoscaler,
    DirWatch,
    FleetScheduler,
    QuotaExceeded,
    SchedEntry,
    release_room,
)
from libpga_tpu.serving.shm_ring import RING_FILENAME, RingError, ShmRing
from libpga_tpu.utils import metrics as _metrics
from libpga_tpu.utils import telemetry as _tl
from libpga_tpu.utils.tenancy import ANON, validate_tenant
from libpga_tpu.utils.telemetry import TelemetryConfig


class FleetDeadLetter(RuntimeError):
    """Raised by ``FleetHandle.result`` for a ticket whose batch was
    quarantined after ``max_worker_deaths`` distinct workers lost their
    lease on it (the fleet-level dead-letter policy)."""


# ------------------------------------------------------------------- spool


def _jax_env_knobs() -> Dict[str, str]:
    """JAX settings that must MATCH across the process boundary, as the
    environment a spawned worker needs (ISSUE 12 satellite).

    Env-var settings already inherit through ``dict(os.environ)`` — the
    gap is knobs the parent flipped PROGRAMMATICALLY via
    ``jax.config.update`` (e.g. the test harness sets threefry
    partitionability in-process): a worker left on the default would
    derive DIFFERENT random streams from the very same ticket seed,
    silently voiding the fleet's bit-identity contract. Collected here
    for every spawn site: threefry partitionability, x64 mode, the
    platform list, and the default PRNG implementation.
    """
    out: Dict[str, str] = {}
    try:
        import jax

        out["JAX_THREEFRY_PARTITIONABLE"] = (
            "1" if jax.config.jax_threefry_partitionable else "0"
        )
        out["JAX_ENABLE_X64"] = "1" if jax.config.jax_enable_x64 else "0"
        platforms = getattr(jax.config, "jax_platforms", None)
        if platforms:
            out["JAX_PLATFORMS"] = str(platforms)
        prng_impl = getattr(jax.config, "jax_default_prng_impl", None)
        if prng_impl:
            out["JAX_DEFAULT_PRNG_IMPL"] = str(prng_impl)
    except Exception:
        pass
    return out


class Spool:
    """Path layout + atomic-write helpers for one fleet spool directory.

    Shared by the coordinator and the worker so the protocol cannot
    drift between the two halves. Every mutation is a single atomic
    filesystem operation (``os.replace`` / ``os.rename`` / ``os.link``).
    """

    DIRS = ("pending", "claimed", "leases", "results", "dead", "ckpt",
            "logs", "traces", "metrics", "sessions")

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        for d in self.DIRS:
            os.makedirs(os.path.join(self.root, d), exist_ok=True)

    def path(self, *parts: str) -> str:
        return os.path.join(self.root, *parts)

    # ---------------------------------------------------------- json files

    @staticmethod
    def read_json(path: str) -> Optional[dict]:
        """The parsed file, or None when it is gone or torn mid-read
        (both are normal under concurrent rename — callers retry or
        skip)."""
        try:
            with open(path, "r", encoding="utf-8") as fh:
                return json.load(fh)
        except (OSError, json.JSONDecodeError):
            return None

    @staticmethod
    def write_json(path: str, obj: dict) -> None:
        """Atomic write: temp file + ``os.replace``."""
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(obj, fh)
        os.replace(tmp, path)

    @staticmethod
    def publish(tmp: str, final: str) -> bool:
        """First-writer-wins publication: link ``tmp`` to ``final``;
        True when this process's copy won, False when a result already
        existed (ours is discarded). ``tmp`` is removed either way."""
        try:
            os.link(tmp, final)
            return True
        except OSError as e:
            if e.errno != errno.EEXIST:
                raise
            return False
        finally:
            try:
                os.remove(tmp)
            except OSError:
                pass

    # --------------------------------------------------------------- names

    def pending_batches(self) -> List[str]:
        try:
            names = os.listdir(self.path("pending"))
        except OSError:
            return []
        return sorted(n for n in names if n.endswith(".json"))

    def claimed_batches(self) -> List[str]:
        try:
            names = os.listdir(self.path("claimed"))
        except OSError:
            return []
        return sorted(n for n in names if n.endswith(".json"))

    def lease_path(self, batch_name: str) -> str:
        return self.path("leases", f"{batch_name}.lease.json")

    def result_paths(self, tid: str) -> Tuple[str, str]:
        """(npz, meta-json) result paths for one ticket."""
        return (
            self.path("results", f"{tid}.npz"),
            self.path("results", f"{tid}.json"),
        )

    def ckpt_path(self, tid: str) -> str:
        return self.path("ckpt", f"{tid}.npz")

    def preempt_path(self, batch_name: str) -> str:
        """The batch's preemption marker (ISSUE 15): written by the
        coordinator when a higher-priority batch needs the slot; the
        worker's supervised stop hook checks it every chunk boundary
        (the SIGTERM-drain discipline, without losing the process)."""
        return self.path("leases", f"{batch_name}.preempt.json")

    @staticmethod
    def name_priority(batch_name: str) -> int:
        """The scheduling priority encoded in a batch file name.
        Priority rides the name as ``p<9-priority>`` so the plain
        name sort workers claim by IS the priority order; pre-ISSUE-15
        names (no prefix) read as priority 0."""
        if (
            len(batch_name) > 1 and batch_name[0] == "p"
            and batch_name[1].isdigit()
        ):
            return 9 - int(batch_name[1])
        return 0

    def trace_path(self, batch_name: str) -> str:
        """The batch's span-log file (``telemetry.append_trace`` /
        ``read_trace`` format)."""
        return self.path("traces", f"{batch_name}.trace.jsonl")

    def metrics_files(self) -> List[str]:
        """Per-process metric-snapshot files, sorted by process name."""
        try:
            names = os.listdir(self.path("metrics"))
        except OSError:
            return []
        return [
            self.path("metrics", n) for n in sorted(names)
            if n.endswith(".json")
        ]

    def metrics_path(self, proc: str) -> str:
        return self.path("metrics", f"{proc}.json")


# --------------------------------------------------- fleet metric merging

#: Version of the on-disk per-process metric snapshot files
#: (``metrics/<proc>.json``). Bump on any breaking layout change;
#: :func:`load_spool_metrics` REFUSES other versions so a mixed-version
#: fleet fails loudly instead of silently mis-merging (the same stance
#: as ``HistogramSnapshot.merge``'s bounds refusal).
METRICS_FILE_SCHEMA = 1


def write_metrics_file(
    spool: Spool, proc: str, snapshot: dict, **extra
) -> None:
    """Flush one process's registry snapshot to the spool — atomic
    temp-write + rename (the batch-file crash-safety discipline), so a
    process SIGKILLed mid-flush leaves the previous valid file, never a
    torn one."""
    payload = {
        "schema_version": METRICS_FILE_SCHEMA,
        "proc": str(proc),
        "pid": os.getpid(),
        "ts": _tl.anchored_wall(),
        "snapshot": snapshot,
    }
    payload.update(extra)
    spool.write_json(spool.metrics_path(proc), payload)


def load_spool_metrics(spool: Spool) -> Tuple[List[dict], List[str]]:
    """Read every per-process snapshot in the spool. Returns
    ``(payloads, skipped)``: unreadable/torn files land in ``skipped``
    (a crash can leave garbage; the atomic-rename flushes themselves
    never tear) — but a PARSEABLE file from another
    :data:`METRICS_FILE_SCHEMA` version raises ValueError, the
    mixed-version refusal path."""
    payloads: List[dict] = []
    skipped: List[str] = []
    for path in spool.metrics_files():
        payload = Spool.read_json(path)
        if payload is None:
            skipped.append(os.path.basename(path))
            continue
        ver = payload.get("schema_version")
        if ver != METRICS_FILE_SCHEMA:
            raise ValueError(
                f"{path}: metrics snapshot schema_version {ver!r} != "
                f"supported {METRICS_FILE_SCHEMA} — refusing to merge "
                "across fleet versions"
            )
        if not isinstance(payload.get("snapshot"), dict) or not isinstance(
            payload.get("proc"), str
        ):
            skipped.append(os.path.basename(path))
            continue
        payloads.append(payload)
    return payloads, skipped


def merge_spool_metrics(
    spool: Spool, live: Optional[Dict[str, dict]] = None
) -> dict:
    """One fleet-wide snapshot from the spool's per-process flushes,
    merged via ``metrics.merge_snapshots`` (per-``proc`` labels +
    associatively merged aggregate histograms). ``live`` maps process
    names to in-memory snapshots that OVERRIDE the on-disk file of the
    same name (the coordinator passes its own registry so its view is
    current, not flush-cadence stale)."""
    payloads, skipped = load_spool_metrics(spool)
    live = dict(live or {})
    parts: List[Tuple[str, dict]] = [
        (p["proc"], p["snapshot"]) for p in payloads
        if p.get("proc") not in live
    ]
    parts += sorted(live.items())
    merged = _metrics.merge_snapshots(parts)
    if skipped:
        merged["skipped_files"] = skipped
    return merged


def _merged_hist(merged: dict, name: str) -> Optional[dict]:
    """The AGGREGATE (proc-label-free) histogram record for one series
    name in a merged snapshot, or None."""
    for rec in merged.get("histograms", ()):
        if rec["name"] == name and "proc" not in rec.get("labels", {}):
            return rec
    return None


def _counter_total(merged: dict, name: str) -> int:
    return sum(
        int(rec["value"]) for rec in merged.get("counters", ())
        if rec["name"] == name
    )


def _tenant_counter_totals(merged: dict, name: str) -> Dict[str, int]:
    """Per-tenant totals of one tenant-labeled counter across all
    processes of a merged snapshot."""
    out: Dict[str, int] = {}
    for rec in merged.get("counters", ()):
        if rec["name"] != name:
            continue
        tenant = rec.get("labels", {}).get("tenant")
        if tenant is not None:
            out[tenant] = out.get(tenant, 0) + int(rec["value"])
    return out


def _tenant_hists(merged: dict, name: str) -> Dict[str, dict]:
    """AGGREGATE (proc-free) tenant-labeled histogram records of one
    series, keyed by tenant."""
    out: Dict[str, dict] = {}
    for rec in merged.get("histograms", ()):
        labels = rec.get("labels", {})
        if (
            rec["name"] == name and "proc" not in labels
            and "tenant" in labels
        ):
            out[labels["tenant"]] = rec
    return out


def _pid_alive(pid) -> Optional[bool]:
    try:
        os.kill(int(pid), 0)
        return True
    except ProcessLookupError:
        return False
    except (OSError, TypeError, ValueError):
        return None  # unknowable (permissions, bad pid)


def fleet_status(
    spool_dir: str, live: Optional[Dict[str, dict]] = None
) -> dict:
    """Introspect one fleet spool — live fleet or post-mortem of a dead
    one (ISSUE 9): queue depths, batch states, per-worker lease age /
    health / throughput, and the merged latency percentiles, computed
    from the SPOOL ALONE. ``Fleet.status()`` wraps this with the
    coordinator's in-memory view; ``tools/fleet_top.py`` renders it."""
    spool = Spool(spool_dir)
    now_wall = _tl.anchored_wall()
    pending = []
    tenant_depth: Dict[str, Dict[str, int]] = {}

    def _tally(batch: Optional[dict], state: str) -> None:
        for t in () if batch is None else batch.get("tickets", ()):
            tenant = t.get("tenant", ANON)
            d = tenant_depth.setdefault(
                tenant, {"pending": 0, "claimed": 0}
            )
            d[state] += 1

    for name in spool.pending_batches():
        batch = Spool.read_json(spool.path("pending", name))
        formed = None if batch is None else batch.get("formed_at")
        _tally(batch, "pending")
        pending.append({
            "batch": name,
            "tickets": 0 if batch is None else len(batch.get("tickets", ())),
            "attempts": 0 if batch is None else len(
                set(batch.get("attempts", ()))
            ),
            "age_s": None if formed is None else max(
                now_wall - float(formed), 0.0
            ),
        })
    claimed = []
    for name in spool.claimed_batches():
        _tally(Spool.read_json(spool.path("claimed", name)), "claimed")
        lease = Spool.read_json(spool.lease_path(name))
        try:
            age = max(time.time() - os.stat(spool.lease_path(name)).st_mtime,
                      0.0)
        except OSError:
            age = None
        claimed.append({
            "batch": name,
            "worker": None if lease is None else lease.get("worker"),
            "lease_age_s": age,
        })
    try:
        dead = sorted(
            n for n in os.listdir(spool.path("dead")) if n.endswith(".json")
        )
    except OSError:
        dead = []
    try:
        results = sum(
            1 for n in os.listdir(spool.path("results"))
            if n.endswith(".json")
        )
    except OSError:
        results = 0

    payloads, skipped = load_spool_metrics(spool)
    merged = merge_spool_metrics(spool, live=live)
    lease_by_worker = {
        c["worker"]: c for c in claimed if c["worker"] is not None
    }
    workers = []
    for p in payloads:
        proc = p["proc"]
        # HA fleets flush coordinator snapshots under qualified names
        # ("coordinator.<token>"), one per candidate — none are workers.
        if proc.startswith("coordinator"):
            continue
        snap = p["snapshot"]
        exec_rec = None
        published = 0
        for rec in snap.get("histograms", ()):
            if rec["name"] == "serving.ticket.execute_ms" and not rec.get(
                "labels"
            ):
                exec_rec = rec
        for rec in snap.get("counters", ()):
            if rec["name"] == "worker.tickets.published":
                published += int(rec["value"])
        health = None
        for name in ("fleet.worker.health",):
            for rec in merged.get("gauges", ()):
                if rec["name"] == name and rec["labels"].get("worker") == proc:
                    health = float(rec["value"])
        lease = lease_by_worker.get(proc)
        workers.append({
            "worker": proc,
            "pid": p.get("pid"),
            "alive": _pid_alive(p.get("pid")),
            "flush_age_s": max(now_wall - float(p.get("ts", 0.0)), 0.0),
            "batches_done": p.get("batches_done"),
            "tickets_published": published,
            "lease": None if lease is None else lease["batch"],
            "lease_age_s": None if lease is None else lease["lease_age_s"],
            "health": health,
            "execute_p50_ms": None if exec_rec is None else exec_rec["p50"],
            "execute_p95_ms": None if exec_rec is None else exec_rec["p95"],
            "execute_count": 0 if exec_rec is None else exec_rec["count"],
        })

    latency = {}
    for key, series in (
        ("e2e", "fleet.ticket.e2e_ms"),
        ("spool_wait", "fleet.ticket.spool_wait_ms"),
        ("execute", "fleet.ticket.execute_ms"),
    ):
        rec = _merged_hist(merged, series)
        if rec is not None and rec["count"]:
            latency[key] = {
                "p50_ms": rec["p50"], "p95_ms": rec["p95"],
                "p99_ms": rec["p99"], "count": rec["count"],
            }

    # Per-tenant view (ISSUE 14) — assembled from the spool alone:
    # queue depth from the batch files' ticket tenants, completions /
    # dead letters from the merged tenant-labeled counters, latency
    # percentiles from the merged tenant-labeled histograms, and the
    # burn-rate gauges from the coordinator's latest flush. Live fleet
    # or dead-spool post-mortem, same math.
    tenants: Dict[str, dict] = {}

    def _trec(tenant: str) -> dict:
        return tenants.setdefault(tenant, {
            "pending": 0, "claimed": 0, "submitted": 0, "completed": 0,
            "dead_letters": 0, "e2e": None, "spool_wait": None,
            "burn": {}, "burn_alerts": 0,
        })

    for tenant, d in tenant_depth.items():
        _trec(tenant).update(d)
    for field, series in (
        ("submitted", "fleet.tenant.submissions"),
        ("completed", "fleet.tenant.completions"),
        ("dead_letters", "fleet.tenant.dead_letters"),
        ("burn_alerts", "fleet.slo_burn_alerts"),
    ):
        for tenant, total in _tenant_counter_totals(merged, series).items():
            _trec(tenant)[field] = total
    for key, series in (
        ("e2e", "fleet.tenant.e2e_ms"),
        ("spool_wait", "fleet.tenant.spool_wait_ms"),
    ):
        for tenant, rec in _tenant_hists(merged, series).items():
            if rec["count"]:
                _trec(tenant)[key] = {
                    "p50_ms": rec["p50"], "p95_ms": rec["p95"],
                    "p99_ms": rec["p99"], "count": rec["count"],
                }
    for rec in merged.get("gauges", ()):
        labels = rec.get("labels", {})
        if (
            rec["name"] == "fleet.tenant.slo_burn"
            and str(labels.get("proc", "")).startswith("coordinator")
        ):
            _trec(labels["tenant"])["burn"][labels.get("window", "?")] = (
                float(rec["value"])
            )

    # Ring health (ISSUE 18) — read-only peek at the shared-memory
    # fast path, same spool-alone discipline (works post-mortem).
    ring_info = ShmRing.peek(spool.path(RING_FILENAME))
    ring = {"present": False} if ring_info is None else dict(
        ring_info, present=True
    )

    return {
        "spool": spool.root,
        "ts": now_wall,
        "ring": ring,
        # Coordinator HA (ISSUE 20): leader pid/liveness, fence epoch,
        # lease age, standby count, last-failover timestamp — spool
        # alone, so it works on a post-mortem of a dead fleet too.
        "leadership": _ha.leadership_snapshot(spool, payloads),
        "queue": {
            "pending_batches": pending,
            "claimed_batches": claimed,
            "dead_batches": dead,
            "results": results,
        },
        "workers": workers,
        "latency": latency,
        "tenants": tenants,
        "counters": {
            "worker_deaths": _counter_total(merged, "fleet.worker.deaths"),
            "lease_requeues": _counter_total(merged, "fleet.lease.requeues"),
            "straggler_alerts": _counter_total(
                merged, "fleet.straggler_alerts"
            ),
            "dead_letters": _counter_total(merged, "fleet.dead_letters"),
            "tickets_completed": _counter_total(
                merged, "fleet.tickets.completed"
            ),
        },
        "metrics_skipped_files": skipped,
    }


# ---------------------------------------------------- config serialization

#: PGAConfig fields that cross the process boundary verbatim. gene_dtype
#: and telemetry need encoding and are handled separately.
_CONFIG_FIELDS = (
    "tournament_size", "selection", "selection_param", "mutation_rate",
    "elitism", "max_populations", "migration_topology", "use_pallas",
    "pallas_deme_size", "pallas_generations_per_launch", "pallas_layout",
    "pallas_subblock", "pop_shards", "donate_buffers", "validate",
    "fallback", "seed",
)


def config_to_json(cfg: PGAConfig) -> dict:
    """A JSON-safe encoding of the program-shaping config fields — what
    a worker needs to rebuild a bit-identical executor. Event-log paths
    are deliberately NOT carried (each worker logs into the spool)."""
    out = {f: getattr(cfg, f) for f in _CONFIG_FIELDS}
    out["gene_dtype"] = np.dtype(cfg.gene_dtype).name
    t = cfg.telemetry
    out["telemetry_history_gens"] = None if t is None else t.history_gens
    return out


def config_from_json(data: dict) -> PGAConfig:
    """Inverse of :func:`config_to_json`."""
    kw = {f: data[f] for f in _CONFIG_FIELDS if f in data}
    name = data.get("gene_dtype", "float32")
    if name == "bfloat16":
        import jax.numpy as jnp

        kw["gene_dtype"] = jnp.bfloat16
    else:
        kw["gene_dtype"] = np.dtype(name)
    hist = data.get("telemetry_history_gens")
    if hist is not None:
        kw["telemetry"] = TelemetryConfig(history_gens=int(hist))
    return PGAConfig(**kw)


# ----------------------------------------------------------------- tickets


@dataclasses.dataclass(frozen=True)
class FleetTicket:
    """One GA run submitted to the fleet.

    Everything a worker needs travels here (never with the worker):
    shape, budget, seed, runtime parameters, and the supervision
    cadence. ``checkpoint_every == 0`` is a PLAIN ticket — executed as
    part of a shape-bucketed mega-run, recovered after a worker death
    by re-running the batch (bit-identical, the round-9 contract).
    ``checkpoint_every > 0`` is a SUPERVISED ticket — executed under
    ``robustness.supervised_run`` at that cadence with its durable
    checkpoint in the spool, so drains and deaths resume from the last
    chunk boundary. ``max_retries`` bounds the supervisor's in-worker
    retries; failures beyond it escalate to a worker death and the
    fleet's lease-requeue path.

    ``tenant`` (ISSUE 14) attributes the ticket: it rides the batch
    file to the worker (so worker-side serving metrics are
    tenant-labeled), comes back in the result meta and every trace
    span, and drives the coordinator's per-tenant latency/burn
    accounting. ``None`` → the default ``anon`` tenant; explicit ids
    are validated label-safe here, at the submit boundary.

    ``priority`` (ISSUE 15) picks the scheduling lane explicitly;
    ``None`` (default) inherits the tenant's ``TenantPolicy.priority``.
    Higher lanes form and claim first, and may preempt a worker busy
    on a lower-priority supervised batch."""

    size: int
    genome_len: int
    n: int
    seed: int
    target: Optional[float] = None
    mutation_rate: Optional[float] = None
    mutation_sigma: Optional[float] = None
    checkpoint_every: int = 0
    max_retries: int = 1
    tenant: Optional[str] = None
    priority: Optional[int] = None

    def __post_init__(self):
        if self.size < 1 or self.genome_len < 1:
            raise ValueError(
                f"invalid shape ({self.size}, {self.genome_len})"
            )
        if self.n < 0:
            raise ValueError("n must be >= 0")
        if self.checkpoint_every < 0:
            raise ValueError("checkpoint_every must be >= 0")
        if self.max_retries < 0:
            raise ValueError("max_retries must be >= 0")
        if self.priority is not None and not (
            0 <= int(self.priority) <= 9
        ):
            raise ValueError("priority must be in [0, 9] or None")
        object.__setattr__(self, "tenant", validate_tenant(self.tenant))


class FleetResult:
    """One completed ticket, loaded from the spool (host arrays).

    ``latency`` is the ticket's cross-process breakdown dict (ISSUE 9,
    same content as ``FleetHandle.latency()``), ``trace`` its assembled
    span-record list — both None when the fleet ran with tracing off.
    """

    def __init__(self, genomes, scores, generations, best_score, worker,
                 latency=None, trace=None):
        self.genomes = genomes
        self.scores = scores
        self.generations = int(generations)
        self.best_score = float(best_score)
        self.worker = worker  # which worker published it
        self.latency = latency
        self.trace = trace

    def best(self) -> np.ndarray:
        return np.asarray(self.genomes[int(np.argmax(self.scores))])


#: Cross-process latency spans, in breakdown order. The spans TILE the
#: ticket's life (each one's end is the next one's start), so their sum
#: telescopes to the end-to-end time regardless of per-process clock
#: anchors: intake (submit -> batch file durable, coordinator), spool
#: wait (batch durable -> winning worker claim), execute (claim -> run
#: complete, worker — wraps the worker-local ``TicketTiming`` and the
#: ``pga/<stage>`` spans), publish (complete -> result durable, worker),
#: readback (result durable -> coordinator loaded it).
FLEET_SPANS = ("intake", "spool_wait", "execute", "publish", "readback")


class FleetHandle:
    """Handle for one submitted fleet ticket (``Fleet.submit``)."""

    def __init__(self, fleet: "Fleet", tid: str, ticket: FleetTicket):
        self.tid = tid
        self.ticket = ticket
        self.trace_id = _tl.new_trace_id()
        self._fleet = fleet
        self._submit_wall = _tl.anchored_wall()
        self._formed_wall: Optional[float] = None
        self._batch: Optional[str] = None
        self._breakdown: Optional[dict] = None
        self._read_wall: Optional[float] = None

    def poll(self) -> bool:
        """True once a result (or a dead-letter verdict) is durable."""
        return self._fleet._meta(self.tid) is not None

    def result(self, timeout: Optional[float] = None) -> FleetResult:
        """Block for the ticket's result. Raises
        :class:`FleetDeadLetter` when its batch was quarantined, and
        ``TimeoutError`` (handle stays re-awaitable) on timeout."""
        return self._fleet._await(self.tid, timeout)

    def latency(self) -> dict:
        """The ticket's TRUE cross-process latency breakdown (ms):
        ``<span>_ms`` for each of :data:`FLEET_SPANS` plus ``e2e_ms``
        (submit -> coordinator readback complete). Spans whose
        transitions haven't happened (or that tracing-off suppressed)
        read None. Unlike the worker-local ``TicketTiming`` this
        composes timestamps from BOTH processes — the spans tile, so
        they sum to e2e up to per-process clock-anchor error."""
        if self._breakdown is not None:
            return dict(self._breakdown)
        return {f"{s}_ms": None for s in FLEET_SPANS} | {"e2e_ms": None}

    def trace(self) -> List[dict]:
        """The ticket's assembled span log: coordinator intake, every
        claim/requeue/lease record of its batch (ALL attempts — a
        requeued ticket's trace shows each worker that tried), the
        winning worker's execute/publish spans, and the coordinator
        readback. Records are schema-valid ``trace_span`` events."""
        recs: List[dict] = []
        if self._formed_wall is not None:
            recs.append(_tl.trace_span_record(
                "intake", self._submit_wall, self._formed_wall,
                tid=self.tid, trace_id=self.trace_id, role="coordinator",
            ))
        if self._batch is not None:
            recs += [
                r for r in _tl.read_trace(
                    self._fleet.spool.trace_path(self._batch)
                )
                if r.get("tid") in (None, self.tid)
                and r.get("span") != "intake"  # synthesized above
            ]
        meta = self._fleet._meta(self.tid)
        tr = (meta or {}).get("trace") or {}
        recs += list(tr.get("spans", ()))
        if self._read_wall is not None and tr.get("published_at") is not None:
            recs.append(_tl.trace_span_record(
                "readback", float(tr["published_at"]), self._read_wall,
                tid=self.tid, trace_id=self.trace_id, role="coordinator",
            ))
        return recs


def _now() -> float:
    return time.monotonic()


def _parse_coord_chaos(spec: str) -> List[tuple]:
    """``PGA_COORD_CHAOS`` — the coordinator twin of the worker's
    ``PGA_WORKER_CHAOS`` self-signal hook: comma-separated
    ``<signal>@<site>:<n>`` directives make the coordinator send ITSELF
    the real signal at its n-th arrival at a named protocol point, so
    the HA chaos matrix (``tools/ha_smoke.py``) can kill -9 a leader at
    exact instants. Sites: ``batch_form`` (tickets drawn from the fair
    scheduler, batch file NOT yet durable — recovery is pure journal
    replay), ``requeue`` (lease removed, re-release not yet durable),
    ``ring_write`` (before a ring frame advertise — batch durable but
    unannounced), ``autoscale`` (top of a scale evaluation). Unknown
    entries raise — a chaos driver must never silently test nothing."""
    sites = ("batch_form", "requeue", "ring_write", "autoscale")
    out = []
    for part in filter(None, (p.strip() for p in spec.split(","))):
        try:
            signame, rest = part.split("@", 1)
            site, n = rest.split(":", 1)
            if site not in sites:
                raise ValueError(site)
            out.append(
                (getattr(signal, signame.upper()), site, int(n))
            )
        except (ValueError, AttributeError):
            raise ValueError(f"bad PGA_COORD_CHAOS directive {part!r}")
    return out


# ------------------------------------------------------------- coordinator


class Fleet:
    """Coordinator of a cross-process serving fleet.

    One ``Fleet`` owns one tenant configuration (objective name +
    ``PGAConfig``) and one spool directory; shape buckets still form per
    ticket shape. Usage::

        fleet = Fleet(spool_dir, "onemax", config=PGAConfig(...))
        fleet.start()                       # spawn N worker processes
        h = fleet.submit(FleetTicket(size=4096, genome_len=64, n=50,
                                     seed=7))
        res = h.result(timeout=120)         # bit-identical to PGA.run
        fleet.drain()                       # SIGTERM: checkpoint + exit
        fleet.start()                       # fresh workers resume
        fleet.close()

    The objective must be a NAMED builtin (``libpga_tpu.objectives``):
    it crosses a process boundary, so it must be reconstructible by
    name — the same constraint the C ABI's serving path has.
    """

    def __init__(
        self,
        spool_dir: str,
        objective: str,
        config: Optional[PGAConfig] = None,
        fleet: Optional[FleetConfig] = None,
        mutate_kind: str = "point",
        events=None,
        registry: Optional[_metrics.MetricsRegistry] = None,
        slo=None,
    ):
        if not isinstance(objective, str):
            raise ValueError(
                "Fleet needs a NAMED objective (it crosses process "
                "boundaries) — pass a libpga_tpu.objectives name"
            )
        from libpga_tpu import objectives

        objectives.get(objective)  # fail fast on unknown names
        self.spool = Spool(spool_dir)
        self.objective = objective
        self.config = config or PGAConfig()
        self.fleet = fleet or FleetConfig()
        self.mutate_kind = mutate_kind
        self.events = events
        self.slo = slo  # fleet-level SLOConfig (check_slo / readback)
        self.registry = registry if registry is not None else _metrics.REGISTRY
        self._lock = threading.RLock()
        # Scheduling layer (ISSUE 15): tickets queue in the weighted-
        # fair scheduler and are released to the spool as batch files
        # against a bounded window (sched_lookahead per live worker) —
        # the spool stays the durable queue of RELEASED work, the
        # scheduler holds the fair backlog.
        self.sched = FleetScheduler(self.fleet)
        self._handles: Dict[str, FleetHandle] = {}
        self._meta_cache: Dict[str, dict] = {}
        self._counted: set = set()  # tids folded into self.completed
        self._workers: Dict[str, subprocess.Popen] = {}
        self._worker_gone: set = set()  # exits already accounted
        self._hb_seen: Dict[str, float] = {}  # batch -> last lease mtime
        self._tid_seq = 0
        self._batch_seq = 0
        # Coordinator instance token: batch names must never collide
        # with a previous coordinator's leftovers on the same spool
        # (a restarted fleet resumes pending work, it never overwrites
        # it).
        self._token = f"{os.getpid():x}-{os.urandom(3).hex()}"
        self._closed = False
        self._monitor: Optional[threading.Thread] = None
        self._stop_monitor = threading.Event()
        self._cv = threading.Condition()  # completion/backpressure wakeups
        # Incremental monitor scan (ISSUE 15 satellite): directory
        # watches gate the spool re-scans, the wake event short-cuts
        # the adaptive idle backoff on new submissions.
        self._wake = threading.Event()
        self._wait_s = self.fleet.poll_s
        self._results_watch = DirWatch(self.spool.path("results"))
        self._claimed_watch = DirWatch(
            self.spool.path("claimed"), self.spool.path("leases")
        )
        self._have_claimed = True  # scan once before trusting the watch
        # Autoscaler (ISSUE 15): policy thread state. _draining pauses
        # scale decisions across an explicit drain()/start() cycle so
        # the scaler never fights a deliberate preemption drain.
        self.autoscaler = (
            None if self.fleet.autoscale is None
            else Autoscaler(self.fleet.autoscale)
        )
        self._scaler: Optional[threading.Thread] = None
        self._stop_scaler = threading.Event()
        self._retiring: set = set()
        self._draining = False
        self._preempted_batches: set = set()  # markers outstanding
        self.submitted = 0
        self.completed = 0
        self.requeues = 0
        self.worker_deaths = 0
        self.quarantined: List[str] = []  # batch names moved to dead/
        # Fleet observability state (ISSUE 9): coordinator metric-flush
        # cadence bookkeeping, the workers currently holding a lease
        # (for lease-age gauge resets), and the workers currently
        # flagged as stragglers (alerts fire on the TRANSITION, not
        # every scan).
        self._last_flush = 0.0
        self._lease_gauged: set = set()
        self._stragglers: set = set()
        # Tenant attribution (ISSUE 14): ids seen (one tenant_admit
        # each), per-tenant submitted/completed tallies behind the
        # fleet.tenant.outstanding gauges (the fairness signal ROADMAP
        # item 1 consumes), and the fleet-level error-budget burn
        # tracker over coordinator readbacks.
        self._tenants_seen: set = set()
        self._tenant_submitted: Dict[str, int] = {}
        self._tenant_completed: Dict[str, int] = {}
        self.burn = TenantBurnTracker(
            self.slo, self.registry, self._emit, "fleet"
        )
        # Shared-memory ticket ring (ISSUE 18): created before any
        # worker spawn so every worker attaches a live ring. All ring
        # writes degrade (never raise) — the spool stays authoritative.
        self._ring: Optional[ShmRing] = None
        self._ring_notify = 0  # last observed worker notify sum
        self._ring_depth = 0  # released-but-unclaimed estimate
        self._ring_claims_seen = 0
        self._ring_reconcile_next = 0.0  # monotonic; 0 => reconcile now
        self._ring_slots: Dict[str, int] = {}  # wid -> bound slot index
        # Coordinator HA (ISSUE 20): with coordinators > 1 this
        # instance is a CANDIDATE — it leads only while it holds the
        # spool's leader lease, every durable artifact it authors
        # carries its election epoch, and every submission is journaled
        # before it is scheduled. coordinators=1 (the default) skips
        # all of it: no coord/ or intake/ directories, no epoch field
        # in batch files — byte-for-byte the round-23 spool.
        self._ha_enabled = self.fleet.coordinators > 1
        self.epoch = 0
        self.is_leader = not self._ha_enabled
        self.failovers = 0
        self._lease: Optional[_ha.LeaderLease] = None
        self._journal: Optional[_ha.IntakeJournal] = None
        self._journal_seen: set = set()  # tids admitted to the sched
        # Journal tids skipped at replay because a pre-failover batch
        # already carried them. If that batch was a zombie write that
        # lands fenced (a worker removes it), _reclaim_stranded
        # re-admits these within half a lease timeout.
        self._journal_inflight: set = set()
        self._reclaim_next = 0.0  # monotonic throttle for the rescan
        self._intake_watch: Optional[DirWatch] = None
        self._ha_worker_env: Optional[Dict[int, dict]] = None
        self._proc_name = "coordinator"
        self._coord_chaos = _parse_coord_chaos(
            os.environ.get("PGA_COORD_CHAOS", "")
        )
        self._coord_chaos_calls: Dict[str, int] = {}
        if self._ha_enabled:
            # Qualified identities: N candidates on one spool must not
            # collide on the metrics flush file or on worker ids.
            self._proc_name = f"coordinator.{self._token[-6:]}"
            self._lease = _ha.LeaderLease(
                self.spool, owner=self._token,
                timeout_s=self.fleet.lease_timeout_s,
            )
            self._journal = _ha.IntakeJournal(self.spool)
            self._intake_watch = DirWatch(self.spool.path(_ha.INTAKE_DIR))
            try:
                won = self._lease.try_acquire()
            except _faults.InjectedFault:
                won = None  # injected election loss: boot as standby
            if won is not None:
                self._become_leader(won, during_init=True)
        elif self.fleet.ring:
            self._ring_create()
        self.registry.gauge("fleet.coordinator.epoch").set(self.epoch)
        self.registry.gauge("fleet.coordinator.is_leader").set(
            1 if self.is_leader else 0
        )

    # ----------------------------------------------------------------- ring

    def _ring_create(self) -> None:
        path = self.spool.path(RING_FILENAME)
        try:
            # The ring header carries the author's election epoch
            # (ISSUE 20): a failover rebuilds the ring atomically under
            # the new epoch, and status tooling can tell whose ring it
            # is looking at.
            self._ring, prior = ShmRing.create(path, epoch=self.epoch)
        except RingError as exc:
            self._ring_degrade(f"create: {exc}")
            return
        stale = bool(prior["existed"] and prior["stale"])
        if stale:
            # A SIGKILL'd predecessor's ring: detected (dead pid or
            # unreadable header) and atomically rebuilt — workers of
            # the old fleet are gone, so nothing maps the stale inode.
            self.registry.counter("fleet.ring.stale_rebuilt").bump()
        self._emit(
            "ring_attach", role="coordinator", path=path,
            stale_replaced=stale,
        )

    def _ring_degrade(self, reason: str) -> None:
        """Drop this coordinator to pure-spool coordination (one-way):
        the monitor wait, lease freshness, claim advertisements, and
        the release-window depth all revert to the pre-ring spool scan
        paths, bit-for-bit. Workers keep their mapping and simply stop
        seeing new frames — their bounded fallback scans carry them."""
        ring, self._ring = self._ring, None
        if ring is not None:
            try:
                ring.close()
            except Exception:
                pass
        self.registry.counter("fleet.ring.degraded").bump()
        self._emit("ring_degraded", role="coordinator", reason=reason)

    def _ring_advertise(self, name: str) -> None:
        """Advertise one released batch file as a ``submit`` frame (the
        ring-advertised claim reservation — workers try this name
        before falling back to a pending listing) and grow the live
        depth. The durable release already happened via the atomic
        spool write; this is only the wake."""
        ring = self._ring
        if ring is None:
            return
        self._coord_chaos_check("ring_write")
        try:
            ring.advertise("submit", name)
        except Exception as exc:
            self._ring_degrade(f"advertise: {exc}")
            return
        self._ring_set_depth(self._ring_depth + 1)

    def _ring_set_depth(self, depth: int) -> None:
        self._ring_depth = max(int(depth), 0)
        ring = self._ring
        if ring is None:
            return
        try:
            ring.set_pending_depth(self._ring_depth)
        except Exception as exc:
            self._ring_degrade(f"depth: {exc}")

    def _ring_observe(self) -> None:
        """Once per monitor tick: fold the workers' claim counters into
        the live depth estimate (claims consume released batch files).
        Counter REGRESSIONS (a slot rebound by a respawned worker)
        just resync the baseline — the periodic reconcile against a
        real listing bounds any drift either way."""
        ring = self._ring
        if ring is None:
            return
        counters = ring.counters()
        delta = counters["claims"] - self._ring_claims_seen
        self._ring_claims_seen = counters["claims"]
        if delta > 0:
            self._ring_set_depth(self._ring_depth - delta)

    def _ring_hb_map(self) -> Dict[str, float]:
        """wid -> last ring-heartbeat wall time, for lease freshness
        (ring mode replaces the lease-file touch; the lease scan takes
        ``max(file mtime, ring heartbeat)`` so a degraded ring can
        only ever make expiry MORE conservative, never less)."""
        ring = self._ring
        if ring is None:
            return {}
        return {rec["wid"]: rec["hb"] for rec in ring.slots()}

    # --------------------------------------------------------------- events

    def _emit(self, event: str, **fields) -> None:
        _tl.flight_note(event, fields)  # post-mortem ring, always on
        if self.events is not None:
            self.events.emit(event, **fields)

    # ------------------------------------------------- HA roles (ISSUE 20)

    def _coord_chaos_check(self, site: str) -> None:
        """Self-signal at the n-th arrival at a protocol point (see
        :func:`_parse_coord_chaos`) — the chaos matrix's scalpel."""
        if not self._coord_chaos:
            return
        n = self._coord_chaos_calls.get(site, 0) + 1
        self._coord_chaos_calls[site] = n
        for sig, s, at in self._coord_chaos:
            if s == site and at == n:
                os.kill(os.getpid(), sig)

    def _ha_tick(self) -> bool:
        """Per-tick role management: heartbeat the lease while leading
        (a failed heartbeat means we were SEIZED while paused — step
        down instantly), attempt election while standing by. Returns
        True when this instance leads after the tick."""
        if self.is_leader:
            if self._lease.heartbeat():
                return True
            # Zombie path: our lease was seized (we were SIGSTOPped or
            # wedged past lease_timeout_s). Stop authoring NOW —
            # anything already written below the new fence is rejected
            # by workers; in-flight worker results stand (first-writer-
            # wins, bit-identical to the new leader's re-run).
            self._step_down("lease_lost")
            return False
        try:
            won = self._lease.try_acquire()
        except _faults.InjectedFault:
            won = None  # injected election loss: retry next tick
        if won is not None:
            self._become_leader(won)
            return True
        return False

    def _step_down(self, reason: str) -> None:
        """Deposed leader → standby: drop every leader duty (schedule,
        requeue, autoscale, metrics-of-record) but keep the monitor
        watching our own handles — their results arrive from the new
        leader's fleet. Our workers are NOT killed: they hold valid
        leases and publish first-writer-wins results either way."""
        with self._lock:
            self.is_leader = False
        self.registry.gauge("fleet.coordinator.is_leader").set(0)
        self.registry.counter("fleet.coordinator.step_downs").bump()
        self._emit(
            "leader_fence", what=reason, epoch=self.epoch,
            fence=self._lease.fence(),
        )

    def _become_leader(self, won: dict, during_init: bool = False) -> None:
        """Win the fleet: fence the predecessor (the lease already
        wrote ``coord/epoch.json`` before returning), rebuild the ring
        under the new epoch, adopt the spool's pending work, replay the
        intake journal, and top the worker pool back up."""
        takeover = bool(won.get("seized")) or not during_init
        with self._lock:
            self.epoch = int(won["epoch"])
            self.is_leader = True
        self.registry.gauge("fleet.coordinator.epoch").set(self.epoch)
        self.registry.gauge("fleet.coordinator.is_leader").set(1)
        self._emit("leader_elect", epoch=self.epoch, takeover=takeover)
        if self.fleet.ring:
            # Leader-authored ring: atomic full-image replace stamps
            # the new epoch in the header; surviving workers reattach
            # on the inode change within ring_fallback_s.
            self._ring_slots.clear()
            self._ring_create()
        adopted = self._adopt_spool()
        with self._lock:
            readmitted, skipped = self._replay_intake()
        if readmitted or skipped:
            self._emit(
                "intake_journal_replay", epoch=self.epoch,
                admitted=readmitted, skipped=skipped,
            )
        if takeover:
            self.failovers += 1
            self._emit(
                "coordinator_failover", epoch=self.epoch,
                readmitted=readmitted, adopted=adopted,
            )
        if self._ring is not None:
            # Fresh ring, fresh reservations: re-advertise the adopted
            # runway so surviving workers see it event-driven (their
            # bounded fallback scan covers the reattach window anyway).
            self._ring_set_depth(0)
            for name in self.spool.pending_batches():
                self._ring_advertise(name)
        if not during_init and not self._closed:
            live = self._foreign_live_workers()
            need = max(self.fleet.n_workers - live, 0)
            if need and not self._draining:
                self._spawn_workers(need, worker_env=self._ha_worker_env)
            self._ensure_scaler()
            self._schedule(urgent=True)
            self._wake.set()
        with self._cv:
            self._cv.notify_all()

    def _adopt_spool(self) -> int:
        """Re-stamp lower-epoch pending batch files to this leader's
        epoch — in place (atomic rewrite, name unchanged, so the
        priority name sort and worker claims are undisturbed). Claimed
        batches are left alone: a live worker holds their lease, and
        its results are never fenced (first-writer-wins publication is
        epoch-free by design). A batch the zombie releases into the
        adoption window degrades to a benign duplicate execution."""
        adopted = 0
        for name in self.spool.pending_batches():
            path = self.spool.path("pending", name)
            batch = self.spool.read_json(path)
            if batch is None:
                continue  # claimed under us — the worker owns it now
            if int(batch.get("epoch", 0)) >= self.epoch:
                continue
            batch["epoch"] = self.epoch
            self.spool.write_json(path, batch)
            adopted += 1
        return adopted

    def _spooled_tids(self) -> set:
        """Tickets already released into a pending/claimed batch file —
        the journal entries replay must NOT re-admit."""
        tids: set = set()
        for dirname, names in (
            ("pending", self.spool.pending_batches()),
            ("claimed", self.spool.claimed_batches()),
        ):
            for name in names:
                batch = self.spool.read_json(self.spool.path(dirname, name))
                for t in () if batch is None else batch.get("tickets", ()):
                    if t.get("tid"):
                        tids.add(t["tid"])
        return tids

    def _replay_intake(self) -> Tuple[int, int]:
        """Admit every live journal entry not already admitted in this
        process (takes the reentrant lock itself — callers may already
        hold it). Idempotent by the
        ``_journal_seen`` set + the journal's own tid dedupe: replaying
        twice admits each ticket exactly once. Entries whose result is
        durable or that already ride a spooled batch are SKIPPED (the
        readback/lease machinery owns them); foreign entries (another
        candidate's clients, ``SpoolClient`` submitters) get a handle
        and count into the tenant quota debts, so fairness and
        backpressure survive the failover. Returns
        ``(admitted, skipped)``."""
        if self._journal is None:
            return 0, 0
        entries = self._journal.entries()
        if not entries:
            return 0, 0
        admitted = skipped = 0
        with self._lock:
            spooled = self._spooled_tids()
            for e in entries:
                tid = e.get("tid")
                if not tid or tid in self._journal_seen:
                    continue
                self._journal_seen.add(tid)
                if tid not in self._handles:
                    try:
                        ticket = FleetTicket(**dict(e.get("ticket") or {}))
                    except (TypeError, ValueError):
                        skipped += 1
                        continue  # unreadable foreign entry: never admit
                    handle = FleetHandle(self, tid, ticket)
                    if e.get("trace_id"):
                        handle.trace_id = e["trace_id"]
                    self._handles[tid] = handle
                    t_id = ticket.tenant
                    self.submitted += 1
                    self._tenant_submitted[t_id] = (
                        self._tenant_submitted.get(t_id, 0) + 1
                    )
                    self.registry.counter(
                        "fleet.tenant.submissions", tenant=t_id
                    ).bump()
                if self._meta(tid) is not None:
                    skipped += 1  # result already durable
                    continue
                if tid in spooled:
                    # Riding a pre-failover batch. Track it: if that
                    # batch turns out to be a fenced zombie write (a
                    # worker removes it instead of serving it),
                    # _reclaim_stranded re-admits the ticket within
                    # half a lease timeout.
                    self._journal_inflight.add(tid)
                    skipped += 1
                    continue
                ticket = self._handles[tid].ticket
                prio = e.get("priority")
                if prio is None:
                    prio = (
                        self.sched.policy(ticket.tenant).priority
                        if ticket.priority is None else ticket.priority
                    )
                self.sched.push(SchedEntry(
                    tid=tid, ticket=ticket,
                    bucket=self._bucket_key(ticket),
                    tenant=ticket.tenant, priority=int(prio),
                    admitted=_now(),
                ))
                admitted += 1
        return admitted, skipped

    def _scan_intake(self) -> bool:
        """Leader-only, DirWatch-gated: admit journal entries other
        candidates (or external ``SpoolClient`` s) made durable since
        the last tick."""
        if (
            not self.is_leader or self._intake_watch is None
            or not self._intake_watch.poll()
        ):
            return False
        with self._lock:
            admitted, skipped = self._replay_intake()
        if admitted or skipped:
            self._emit(
                "intake_journal_replay", epoch=self.epoch,
                admitted=admitted, skipped=skipped,
            )
        return bool(admitted)

    def _reclaim_stranded(self) -> bool:
        """Safety net for the adoption race (ISSUE 20): a zombie
        leader's batch that lands in the window between
        ``_adopt_spool`` and the journal replay is skipped as
        in-flight — then a worker fences it (removes the lower-epoch
        file), leaving its tickets with neither a batch nor a lease.
        Re-admit every tracked in-flight tid whose batch vanished
        without a durable result. Cheap: ``_journal_inflight`` is
        empty except right after a takeover, and the spool rescan is
        throttled to half the lease timeout."""
        if not self._journal_inflight:
            return False
        now = time.monotonic()
        if now < self._reclaim_next:
            return False
        self._reclaim_next = now + self.fleet.lease_timeout_s / 2.0
        pushed = 0
        with self._lock:
            self._journal_inflight = {
                tid for tid in self._journal_inflight
                if tid in self._handles and self._meta(tid) is None
            }
            if not self._journal_inflight:
                return False
            spooled = self._spooled_tids()
            for tid in sorted(self._journal_inflight - spooled):
                ticket = self._handles[tid].ticket
                prio = (
                    self.sched.policy(ticket.tenant).priority
                    if ticket.priority is None else ticket.priority
                )
                self.sched.push(SchedEntry(
                    tid=tid, ticket=ticket, bucket=self._bucket_key(ticket),
                    tenant=ticket.tenant, priority=int(prio),
                    admitted=_now(),
                ))
                self._journal_inflight.discard(tid)
                pushed += 1
        if pushed:
            self.registry.counter("fleet.coordinator.reclaimed").bump(pushed)
        return bool(pushed)

    def _foreign_live_workers(self) -> int:
        """Workers of a previous leader still alive on this spool,
        counted from their metric flushes (pid + liveness probe) — a
        takeover tops the pool up to ``n_workers`` instead of doubling
        it. Workers that never flushed are invisible and may be
        double-covered: benign (extra capacity, identical bits)."""
        try:
            payloads, _ = load_spool_metrics(self.spool)
        except ValueError:
            return 0
        with self._lock:
            own = set(self._workers)
        n = 0
        for p in payloads:
            proc = str(p.get("proc", ""))
            if proc.startswith("coordinator") or proc in own:
                continue
            if _pid_alive(p.get("pid")):
                n += 1
        return n

    # -------------------------------------------------------------- workers

    def start(self, worker_env: Optional[Dict[int, dict]] = None) -> List[str]:
        """Spawn ``FleetConfig.n_workers`` worker processes against the
        spool and start the monitor. Safe to call again after
        :meth:`drain` — fresh workers pick up pending and checkpointed
        work. ``worker_env`` maps worker INDEX to extra environment
        variables (the chaos hooks ``PGA_FAULT_SPEC`` /
        ``PGA_WORKER_CHAOS`` ride here in tests). Returns worker ids."""
        if self._closed:
            raise RuntimeError("fleet is closed")
        self._draining = False
        self._ha_worker_env = worker_env
        if self._ha_enabled and not self.is_leader:
            # Standby (ISSUE 20): no workers, no scaler — just the
            # monitor (election retry + own-handle completion watch).
            # Workers spawn on takeover (_become_leader).
            self._ensure_monitor()
            return []
        spawned = self._spawn_workers(
            self.fleet.n_workers, worker_env=worker_env
        )
        self._ensure_monitor()
        self._ensure_scaler()
        return spawned

    def _spawn_workers(
        self, n: int, worker_env: Optional[Dict[int, dict]] = None
    ) -> List[str]:
        """Spawn ``n`` fresh worker processes (used by :meth:`start`
        and the autoscaler's scale-up path). ``worker_env`` indexes
        are relative to this spawn group."""
        spawned = []
        jax_knobs = _jax_env_knobs()
        with self._lock:
            base = len(self._workers)
            for i in range(n):
                wid = f"w{base + i}"
                if self._ha_enabled:
                    # Coordinator-qualified: two leaders' spawn groups
                    # on one spool must never collide on a worker id
                    # (leases, metric files, and logs all key on it).
                    wid = f"w{base + i}.{self._token[-6:]}"
                out = open(  # worker stdout/stderr, for post-mortems
                    self.spool.path("logs", f"{wid}.out"), "ab"
                )
                env = dict(os.environ)
                env.update(jax_knobs)
                if self.fleet.tuning_db:
                    # Workers inherit the fleet's kernel tuning DB the
                    # same way faults travel: one env var (ISSUE 10).
                    env["PGA_TUNING_DB"] = self.fleet.tuning_db
                if worker_env and i in worker_env:
                    env.update(worker_env[i])
                proc = subprocess.Popen(
                    [
                        sys.executable, "-m", "libpga_tpu.serving.worker",
                        "--spool", self.spool.root,
                        "--worker-id", wid,
                        "--heartbeat-s", str(self.fleet.heartbeat_s),
                        "--poll-s", str(self.fleet.poll_s),
                        "--metrics-flush-s", str(self.fleet.metrics_flush_s),
                        "--ring-slot", str(self._ring_slot_for(wid)),
                        "--ring-fallback-s", str(self.fleet.ring_fallback_s),
                    ],
                    stdout=out, stderr=subprocess.STDOUT, env=env,
                )
                out.close()  # the child holds its own descriptor
                self._workers[wid] = proc
                spawned.append(wid)
                self._emit("worker_spawn", worker=wid, pid=proc.pid)
                self.registry.gauge("fleet.worker.up", worker=wid).set(1)
        self._alive_gauge()
        return spawned

    def session_store(self):
        """The fleet's spool-resident streaming session directory
        (ISSUE 12): suspended :class:`~libpga_tpu.streaming
        .EvolutionSession` states any worker process (or the
        coordinator) can resume — same shared-filesystem, atomic-rename
        contract as every other spool subdirectory."""
        from libpga_tpu.streaming.store import SessionStore

        return SessionStore(self.spool.path("sessions"))

    def _ring_slot_for(self, wid: str) -> int:
        """Assign the lowest free ring slot to a spawning worker (the
        coordinator is the slot allocator — slot assignment at spawn is
        what keeps every slot single-writer). -1 = no ring / exhausted
        (the worker then runs pure-spool)."""
        if self._ring is None:
            return -1
        used = set(self._ring_slots.values())
        from libpga_tpu.serving import shm_ring as _shm

        for idx in range(_shm.HB_SLOTS):
            if idx not in used:
                self._ring_slots[wid] = idx
                return idx
        return -1

    def workers_alive(self) -> List[str]:
        with self._lock:
            return [
                wid for wid, p in self._workers.items()
                if p.poll() is None
            ]

    def _alive_gauge(self) -> None:
        self.registry.gauge("fleet.workers.alive").set(
            len(self.workers_alive())
        )

    # ---------------------------------------------------------------- admit

    def _outstanding(self) -> int:
        return self.submitted - self.completed

    def _admit_slot(self) -> None:
        limit = self.fleet.max_pending
        if limit is None:
            return
        with self._cv:
            while self._outstanding() >= limit:
                if self._closed:
                    raise RuntimeError("fleet is closed")
                if self.fleet.overflow == "raise":
                    raise QueueFull(
                        f"{self._outstanding()} outstanding fleet tickets"
                        f" >= max_pending={limit}"
                    )
                self._cv.wait(timeout=0.05)

    def _bucket_key(self, t: FleetTicket) -> tuple:
        # Supervised tickets never co-batch with plain ones: the plain
        # half of a batch is ONE mega-run, the supervised half is
        # per-ticket engines — mixing them would couple a drainable
        # ticket's latency to an undrainable dispatch.
        return (t.size, t.genome_len, t.checkpoint_every > 0)

    def submit(
        self, ticket: FleetTicket, tenant: Optional[str] = None
    ) -> FleetHandle:
        """Admit one ticket; returns its handle. Admission order
        (ISSUE 15): per-tenant quota first (``TenantPolicy.max_pending``
        — a breach raises :class:`QuotaExceeded` deterministically and
        emits ``quota_reject``), then the fleet-wide backpressure
        policy, then the ticket queues in the weighted-fair scheduler
        under its tenant and priority lane (``ticket.priority``,
        defaulting to the tenant policy's). Batches release to the
        spool in deficit-round-robin order against the
        ``sched_lookahead`` window, at ``max_batch`` same-shape tickets
        or ``max_wait_ms`` after the oldest admission. ``tenant``
        (ISSUE 14) overrides the ticket's own tenant field — either
        way the id is validated label-safe and rides the batch file,
        result meta, spans, and every per-tenant metric series."""
        if self._closed:
            raise RuntimeError("fleet is closed")
        if tenant is not None:
            ticket = dataclasses.replace(
                ticket, tenant=validate_tenant(tenant)
            )
        t_id = ticket.tenant
        policy = self.sched.policy(t_id)
        self._admit_slot()
        prio = int(
            policy.priority if ticket.priority is None else ticket.priority
        )
        with self._lock:
            # Quota check-and-admit is ATOMIC under the intake lock:
            # N concurrent submitters racing a quota of k admit
            # exactly k, whatever the interleaving.
            limit = policy.max_pending
            if limit is not None:
                outstanding = (
                    self._tenant_submitted.get(t_id, 0)
                    - self._tenant_completed.get(t_id, 0)
                )
                if outstanding >= limit:
                    self.registry.counter(
                        "fleet.sched.quota_rejects", tenant=t_id
                    ).bump()
                    self._emit(
                        "quota_reject", tenant=t_id,
                        outstanding=outstanding, limit=limit,
                    )
                    raise QuotaExceeded(
                        f"tenant {t_id!r}: {outstanding} outstanding "
                        f"tickets >= TenantPolicy.max_pending={limit}"
                    )
            self._tid_seq += 1
            # Token-qualified: a fresh coordinator on a reused spool
            # must never see a previous run's results as its own.
            tid = f"t{self._tid_seq:05d}-{self._token}"
            handle = FleetHandle(self, tid, ticket)
            self._handles[tid] = handle
            if self._ha_enabled:
                # Durable FIRST (ISSUE 20): the journal is what a new
                # leader replays, so nothing admitted may exist only in
                # this process's memory. A journal failure unwinds the
                # admission — the caller sees the error, nothing half-
                # submitted remains.
                try:
                    self._journal.record(
                        tid=tid, ticket=dataclasses.asdict(ticket),
                        tenant=t_id, priority=prio,
                        trace_id=handle.trace_id, epoch=self.epoch,
                    )
                except BaseException:
                    self._handles.pop(tid, None)
                    raise
            key = self._bucket_key(ticket)
            if self.is_leader:
                self._journal_seen.add(tid)
                self.sched.push(SchedEntry(
                    tid=tid, ticket=ticket, bucket=key, tenant=t_id,
                    priority=prio, admitted=_now(),
                ))
            # else: standby — the live leader admits it from the
            # journal (its intake watch); our handle resolves from the
            # shared results directory like any other.
            self.submitted += 1
            if t_id not in self._tenants_seen:
                self._tenants_seen.add(t_id)
                self._emit("tenant_admit", tenant=t_id, where="fleet")
            self._tenant_submitted[t_id] = (
                self._tenant_submitted.get(t_id, 0) + 1
            )
            self.registry.counter(
                "fleet.tenant.submissions", tenant=t_id
            ).bump()
            self.registry.gauge(
                "fleet.sched.queued", tenant=t_id
            ).set(self.sched.tenant_depth().get(t_id, 0))
            self._emit(
                "batch_admit", bucket=f"{ticket.size}x{ticket.genome_len}",
                pending=self.sched.bucket_depth(prio, key),
                population_size=ticket.size,
                genome_len=ticket.genome_len, tenant=t_id, priority=prio,
            )
            full = (
                self.sched.bucket_depth(prio, key) >= self.fleet.max_batch
            )
        if full:
            self._schedule()
        self.registry.gauge("fleet.tickets.outstanding").set(
            self._outstanding()
        )
        self._tenant_outstanding_gauge(ticket.tenant)
        self._wake.set()
        self._ensure_monitor()
        return handle

    def set_tenant_policy(self, tenant: str, policy: TenantPolicy) -> None:
        """Install or replace one tenant's scheduling policy on the
        LIVE fleet (weight, quota, priority lane) — the Python face of
        the C ABI's ``pga_fleet_tenant_policy``. Takes effect on the
        next submit/draw; already-queued tickets keep the lane they
        were admitted into."""
        self.sched.set_policy(validate_tenant(tenant), policy)

    def _tenant_outstanding_gauge(self, tenant: str) -> None:
        """Refresh one tenant's pending-work gauge — the per-tenant
        depth signal the elastic-fleet fairness work (ROADMAP item 1)
        schedules against."""
        with self._lock:
            n = (
                self._tenant_submitted.get(tenant, 0)
                - self._tenant_completed.get(tenant, 0)
            )
        self.registry.gauge(
            "fleet.tenant.outstanding", tenant=tenant
        ).set(max(n, 0))

    def flush(self) -> int:
        """Release every queued ticket to the spool as batch files now
        (returns batches formed) — overrides BOTH the admission window
        (max_batch / max_wait_ms) and the fair scheduler's
        ``sched_lookahead`` release window. Single-tenant drains and
        ``close()`` want this; latency-sensitive awaits use the
        windowed release so a burst tenant cannot pre-spool past the
        fairness runway."""
        return self._schedule(drain=True)

    def _pending_room(self) -> int:
        """Release-window headroom: how many more unclaimed batch
        files the coordinator will put on the spool before holding
        work back in the fair queues. Ring mode reads the live depth
        from the ring's advertised estimate instead of a ``pending/``
        listing (reconciled against a real listing every
        ``ring_fallback_s``)."""
        return release_room(
            self.fleet.sched_lookahead, len(self.workers_alive()),
            self._spooled_depth(),
        )

    def _spooled_depth(self) -> int:
        """Released-but-unclaimed batch files on the spool."""
        if self._ring is None:
            return len(self.spool.pending_batches())
        now = time.monotonic()
        if now >= self._ring_reconcile_next:
            self._ring_reconcile_next = now + self.fleet.ring_fallback_s
            self.registry.counter("fleet.ring.fallback_scans").bump()
            depth = len(self.spool.pending_batches())
            self._ring_set_depth(depth)
            return depth
        return self._ring_depth

    def _schedule(self, urgent: bool = False, drain: bool = False) -> int:
        """Draw due batches from the weighted-fair scheduler and write
        them to the spool in deficit order. ``urgent`` overrides the
        admission window (a lone ticket must not wait out max_wait_ms);
        ``drain`` additionally overrides the release window. Returns
        batches formed."""
        if self._ha_enabled and not self.is_leader:
            return 0  # only the leader authors batch files
        formed = 0
        with self._lock:
            room = None if drain else self._pending_room()
            while self.sched.depth() > 0:
                if room is not None and room <= 0:
                    break
                nb = self.sched.next_batch(
                    _now(), self.fleet.max_batch, self.fleet.max_wait_ms,
                    urgent=urgent or drain,
                )
                if nb is None:
                    break
                self._write_batch(*nb)
                formed += 1
                if room is not None:
                    room -= 1
            queued = self.sched.depth()
            for tenant, depth in self.sched.tenant_depth().items():
                self.registry.gauge(
                    "fleet.sched.queued", tenant=tenant
                ).set(depth)
        if formed:
            self.registry.counter("fleet.sched.rounds").bump()
            self._emit("sched_round", batches=formed, queued=queued)
            self.registry.gauge("fleet.batches.pending").set(
                self._spooled_depth() if self._ring is not None
                else len(self.spool.pending_batches())
            )
            self._wake.set()
        return formed

    def _write_batch(
        self, priority: int, key: tuple, entries: List[SchedEntry]
    ) -> None:
        """Turn one drawn batch into a claimable batch file (caller
        holds the lock). The priority rides the NAME (``p<9-prio>``
        prefix) so the plain name sort workers claim by serves higher
        lanes first."""
        tickets = [(e.tid, e.ticket) for e in entries]
        # Chaos point "batch_form": tickets drawn, nothing durable yet
        # — the hardest kill, recovered purely by journal replay.
        self._coord_chaos_check("batch_form")
        self._batch_seq += 1
        size, genome_len, supervised = key
        name = (
            f"p{9 - priority}b{self._batch_seq:05d}-{self._token}"
            f"-{size}x{genome_len}"
            f"{'-sup' if supervised else ''}.json"
        )
        formed = _tl.anchored_wall()
        batch = {
            "batch": name,
            "formed_at": formed,
            "priority": priority,
            "trace": bool(self.fleet.trace),
            "spec": {
                "objective": self.objective,
                "mutate_kind": self.mutate_kind,
                "config": config_to_json(self.config),
            },
            "attempts": [],
            "tickets": [
                {
                    "tid": tid,
                    "trace_id": getattr(
                        self._handles.get(tid), "trace_id", None
                    ),
                    **dataclasses.asdict(t),
                }
                for tid, t in tickets
            ],
        }
        if self._ha_enabled:
            # Epoch fence (ISSUE 20): workers reject batches below the
            # durable fence, so a deposed zombie's writes never
            # execute. Non-HA batches stay byte-identical to round 23.
            batch["epoch"] = self.epoch
        self.spool.write_json(self.spool.path("pending", name), batch)
        if self.fleet.trace:
            # The span log opens with one intake span per ticket —
            # durable BEFORE any worker can claim, so a post-mortem of
            # a fleet that died right here still has the trace head.
            tp = self.spool.trace_path(name)
            for tid, t in tickets:
                h = self._handles.get(tid)
                if h is None:
                    continue
                h._formed_wall = formed
                h._batch = name
                _tl.append_trace(tp, _tl.trace_span_record(
                    "intake", h._submit_wall, formed, tid=tid,
                    trace_id=h.trace_id, batch=name, role="coordinator",
                    tenant=t.tenant,
                ))
        else:
            for tid, _ in tickets:
                h = self._handles.get(tid)
                if h is not None:
                    h._formed_wall = formed
                    h._batch = name
        self._emit(
            "batch_launch", bucket=name, batch_size=len(tickets),
            fill_ratio=round(len(tickets) / self.fleet.max_batch, 4),
            priority=priority,
        )
        # Wake the workers: the durable release above is the truth,
        # this frame is the reservation they try to claim first.
        self._ring_advertise(name)

    # -------------------------------------------------------------- results

    def _meta(self, tid: str) -> Optional[dict]:
        meta = self._meta_cache.get(tid)
        if meta is not None:
            return meta
        meta = self.spool.read_json(self.spool.result_paths(tid)[1])
        if meta is not None:
            self._meta_cache[tid] = meta
        return meta

    def _await(self, tid: str, timeout: Optional[float]) -> FleetResult:
        deadline = None if timeout is None else _now() + timeout
        # A lone ticket must not wait out max_wait_ms — but release
        # WINDOWED (not a full drain), so an awaiting burst tenant
        # cannot pre-spool past the fairness runway; the monitor keeps
        # releasing as claims free the window.
        self._schedule(urgent=True)
        while True:
            meta = self._meta(tid)
            if meta is not None:
                break
            if deadline is not None and _now() > deadline:
                raise TimeoutError(
                    f"fleet ticket {tid} not completed within {timeout}s"
                )
            with self._cv:
                self._cv.wait(timeout=self.fleet.poll_s)
        if meta.get("error"):
            raise FleetDeadLetter(
                f"ticket {tid} dead-lettered: {meta['error']}"
            )
        npz_path = self.spool.result_paths(tid)[0]
        from libpga_tpu.utils.checkpoint import _decode

        with np.load(npz_path) as data:
            genomes = _decode(
                data["genomes"], str(data["genomes_dtype"])
            ).copy()
            scores = data["scores"].copy()
            gens = int(data["generations"])
        latency, trace = self._observe_readback(tid, meta)
        return FleetResult(
            genomes, scores, gens, meta["best_score"], meta.get("worker"),
            latency=latency, trace=trace,
        )

    def _observe_readback(self, tid: str, meta: dict):
        """Close a completed ticket's trace (the coordinator-readback
        span), assemble its cross-process breakdown, fold it into the
        fleet latency histograms, and emit ``fleet_ticket_done`` —
        exactly once per ticket; later ``result()`` calls reuse the
        stored breakdown. Returns ``(latency, trace)`` (None, None with
        tracing off or when the meta carries no trace)."""
        handle = self._handles.get(tid)
        if handle is None:
            return None, None
        tr = meta.get("trace") or None
        if tr is None:
            return None, None
        ver = tr.get("schema_version")
        if ver != _tl.TRACE_SCHEMA_VERSION:
            raise ValueError(
                f"ticket {tid}: result trace schema_version {ver!r} != "
                f"supported {_tl.TRACE_SCHEMA_VERSION} — mixed-version "
                "fleet (refusing to compose spans)"
            )
        if handle._breakdown is not None:
            return dict(handle._breakdown), handle.trace()
        read_done = _tl.anchored_wall()
        handle._read_wall = read_done
        edges = (
            handle._submit_wall, handle._formed_wall, tr.get("claimed_at"),
            tr.get("completed_at"), tr.get("published_at"), read_done,
        )

        def ms(a, b):
            return (
                None if a is None or b is None
                else max((float(b) - float(a)) * 1e3, 0.0)
            )

        breakdown = {
            f"{span}_ms": ms(edges[i], edges[i + 1])
            for i, span in enumerate(FLEET_SPANS)
        }
        breakdown["e2e_ms"] = ms(edges[0], edges[-1])
        handle._breakdown = breakdown
        tenant = handle.ticket.tenant
        for span in FLEET_SPANS:
            v = breakdown[f"{span}_ms"]
            if v is not None:
                self.registry.histogram(f"fleet.ticket.{span}_ms").observe(v)
        if breakdown["e2e_ms"] is not None:
            self.registry.histogram("fleet.ticket.e2e_ms").observe(
                breakdown["e2e_ms"]
            )
        # Tenant-labeled twins (ISSUE 14): e2e + spool_wait per tenant —
        # the latency and queueing signals a per-tenant SLO needs. The
        # aggregate series above stay label-free for every round-14
        # consumer.
        for name, v in (
            ("fleet.tenant.e2e_ms", breakdown["e2e_ms"]),
            ("fleet.tenant.spool_wait_ms", breakdown["spool_wait_ms"]),
        ):
            if v is not None:
                self.registry.histogram(name, tenant=tenant).observe(v)
        self.registry.counter("fleet.tickets.traced").bump()
        self._emit(
            "fleet_ticket_done", trace_id=handle.trace_id, tid=tid,
            worker=meta.get("worker"), tenant=tenant,
            **{k: None if v is None else round(v, 3)
               for k, v in breakdown.items()},
        )
        slo = self.slo
        tslo = None if slo is None else slo.for_tenant(tenant)
        wait = (
            None
            if breakdown["intake_ms"] is None
            or breakdown["spool_wait_ms"] is None
            else breakdown["intake_ms"] + breakdown["spool_wait_ms"]
        )
        if (
            tslo is not None
            and tslo.max_queue_wait_ms is not None
            and wait is not None
            and wait > tslo.max_queue_wait_ms
        ):
            self.registry.counter("fleet.slo_violations").bump()
            self._emit(
                "slo_violation", what="fleet_queue_wait",
                value_ms=round(wait, 3), limit_ms=tslo.max_queue_wait_ms,
                trace_id=handle.trace_id, tenant=tenant,
            )
        self.burn.observe(tenant, breakdown["e2e_ms"])
        return dict(breakdown), handle.trace()

    # -------------------------------------------------------------- monitor

    def _ensure_monitor(self) -> None:
        with self._lock:
            if self._monitor is not None and self._monitor.is_alive():
                return
            if self._closed:
                return
            self._stop_monitor.clear()
            self._monitor = threading.Thread(
                target=self._monitor_loop, name="pga-fleet-monitor",
                daemon=True,
            )
            self._monitor.start()

    def _monitor_loop(self) -> None:
        # Adaptive cadence (ISSUE 15 satellite): an idle fleet's wait
        # doubles from poll_s up to poll_idle_max_s; a submit (or any
        # batch release) sets the wake event and snaps it back. Ring
        # mode (ISSUE 18) replaces the blind sleep with an event wait
        # on the workers' notify counters — claims and publishes wake
        # the monitor within spin_s instead of at the next poll edge.
        while not self._stop_monitor.is_set():
            self._monitor_wait()
            if self._stop_monitor.is_set():
                return
            # Fault site (robustness/faults): fires per LEADER monitor
            # tick, OUTSIDE the recovery try below — a raise kills this
            # thread, the injected analog of a wedged leader whose
            # lease goes stale under it (a standby then takes over).
            if self.is_leader and _faults.PLAN is not None:
                _faults.PLAN.fire("coordinator.monitor")
            try:
                self._tick()
            except Exception:
                # The monitor is the fleet's recovery engine — one bad
                # scan (e.g. a file racing a rename) must not stop it.
                pass

    def _monitor_wait(self) -> None:
        """One monitor sleep: ring event wait when attached, plain
        wake-event wait otherwise. The adaptive ``_wait_s`` stays the
        bounded fallback either way."""
        ring = self._ring
        if ring is None:
            if self._wake.wait(timeout=self._wait_s):
                self._wake.clear()
            return
        try:
            reason, new_sum = ring.wait_activity(
                self._ring_notify, self._wait_s, stop=self._wake
            )
        except Exception as exc:  # ring.wake fault / torn mapping
            self._ring_degrade(f"wait_activity: {exc}")
            if self._wake.wait(timeout=self._wait_s):
                self._wake.clear()
            return
        if reason == "stop":
            self._wake.clear()
        elif reason == "notify":
            self._ring_notify = new_sum
            self.registry.counter("fleet.ring.wakes").bump()

    def _tick(self) -> None:
        t0 = time.perf_counter()
        now = _now()
        active = False
        # HA role management first (ISSUE 20): lease heartbeat while
        # leading, election attempt while standing by. A standby runs
        # only the half-tick below — no scheduling, no requeues, no
        # autoscale — but keeps watching results so its own submitted
        # handles (served by the live leader) still resolve.
        if self._ha_enabled and not self._ha_tick():
            if self._results_watch.poll():
                self._scan_completions()
            if now - self._last_flush >= self.fleet.metrics_flush_s:
                self._last_flush = now
                self.flush_metrics()
            self.registry.histogram("fleet.coordinator.scan_ms").observe(
                (time.perf_counter() - t0) * 1e3
            )
            # Cadence stays at most one heartbeat: the next election
            # attempt must come before a stale lease ages further.
            self._wait_s = (
                self.fleet.poll_s if self._outstanding() > 0
                else min(self._wait_s * 2.0, self._wait_cap())
            )
            return
        # HA intake: admit journal entries other candidates or
        # external SpoolClients made durable since the last tick.
        if self._ha_enabled and self._scan_intake():
            active = True
        if self._ha_enabled and self._reclaim_stranded():
            active = True
        # Ring bookkeeping first: fold the workers' claim counters into
        # the advertised pending-depth estimate and refresh the
        # coordinator-liveness stamp that stale-ring detection reads.
        self._ring_observe()
        if self._ring is not None:
            try:
                self._ring.touch_coordinator()
            except Exception as exc:
                self._ring_degrade(f"touch: {exc}")
        # 1. Admission + release windows: draw due batches from the
        # fair scheduler into the spool's claimable runway.
        if self.sched.depth() > 0:
            active = True
            self._schedule()
        # 2. Completions: new result metas wake blocked
        # result()/submit(). Scanned only when the results directory
        # actually CHANGED (DirWatch) — the incremental-scan satellite;
        # counted via a dedicated set, NOT meta-cache presence — a
        # result() call that reads the meta first would otherwise hide
        # the completion from this accounting (undercounting
        # ``completed`` and over-tightening max_pending backpressure).
        if self._results_watch.poll():
            active = self._scan_completions() or active
        # 3+4. Claim/lease scan, gated: skipped entirely while there
        # are no claimed batches AND the claimed/leases directories
        # did not change (lease AGING needs periodic rescans, but only
        # while something is claimed).
        if self._claimed_watch.poll() or self._have_claimed:
            lease_owner = self._scan_leases()
            self._have_claimed = bool(lease_owner) or bool(
                self.spool.claimed_batches()
            )
            active = active or self._have_claimed
        else:
            lease_owner = {}
        self._scan_workers(lease_owner)
        # 5. Priority preemption (ISSUE 15).
        self._preempt_scan(lease_owner)
        # 6. Observability flush (ISSUE 9): at metrics_flush_s cadence,
        # persist the coordinator's own registry snapshot to the spool
        # (so post-mortems and fleet_top see the fleet-level series)
        # and run the straggler scan over the workers' flushes.
        if now - self._last_flush >= self.fleet.metrics_flush_s:
            self._last_flush = now
            self.flush_metrics()
            self.detect_stragglers()
        if self._outstanding() > 0:
            active = True
        self.registry.histogram("fleet.coordinator.scan_ms").observe(
            (time.perf_counter() - t0) * 1e3
        )
        self._wait_s = (
            self.fleet.poll_s if active
            else min(self._wait_s * 2.0, self._wait_cap())
        )

    def _wait_cap(self) -> float:
        """Idle-backoff ceiling. HA candidates cap at the heartbeat
        cadence: a leader that napped past ``lease_timeout_s`` would be
        seized, and a standby must keep its election attempts timely."""
        if self._ha_enabled:
            return min(self.fleet.poll_idle_max_s, self.fleet.heartbeat_s)
        return self.fleet.poll_idle_max_s

    def _scan_completions(self) -> bool:
        fresh = False
        fresh_tenants: set = set()
        for tid in list(self._handles):
            if tid in self._counted:
                continue
            meta = self._meta(tid)
            if meta is not None:
                fresh = True
                self._counted.add(tid)
                self.completed += 1
                if self.is_leader and self._journal is not None:
                    # Retire the intake journal file: the result is
                    # the durable record now (the admission-log line
                    # stays — it carries order, not state).
                    self._journal.retire(tid)
                self.registry.counter("fleet.tickets.completed").bump()
                tenant = self._handles[tid].ticket.tenant
                fresh_tenants.add(tenant)
                with self._lock:
                    self._tenant_completed[tenant] = (
                        self._tenant_completed.get(tenant, 0) + 1
                    )
                self.registry.counter(
                    "fleet.tenant.completions", tenant=tenant
                ).bump()
        if fresh:
            self.registry.gauge("fleet.tickets.outstanding").set(
                self._outstanding()
            )
            for tenant in fresh_tenants:
                self._tenant_outstanding_gauge(tenant)
            with self._cv:
                self._cv.notify_all()
        return fresh

    def _scan_workers(self, lease_owner: Dict[str, str]) -> None:
        """Worker liveness (cheap ``Popen.poll`` per worker, every
        tick): a worker that EXITED while holding a lease is requeued
        immediately (no need to wait out the lease)."""
        with self._lock:
            workers = dict(self._workers)
        for wid, proc in workers.items():
            rc = proc.poll()
            if rc is None or wid in self._worker_gone:
                continue
            self._worker_gone.add(wid)
            self._retiring.discard(wid)
            self._ring_slots.pop(wid, None)  # slot is reusable now
            self.registry.gauge("fleet.worker.up", worker=wid).set(0)
            if rc == 0:
                self._emit("worker_exit", worker=wid, returncode=0)
            else:
                self.worker_deaths += 1
                self.registry.counter(
                    "fleet.worker.deaths", worker=wid
                ).bump()
                self._emit("worker_death", worker=wid, returncode=rc)
                for name, owner in lease_owner.items():
                    if owner == wid:
                        self._requeue(name, wid, "worker_died")
            self._alive_gauge()

    def _scan_leases(self) -> Dict[str, str]:
        """Lease expiry + age gauges over the claimed batches; returns
        the batch -> owning-worker map. Stale heartbeats (SIGSTOP,
        wedged worker, dead heartbeat thread) requeue the batch onto a
        survivor. Lease ages double as per-worker gauges (ISSUE 9):
        the merged exposition and fleet_top read how long each worker
        has gone without touching its lease."""
        lease_owner: Dict[str, str] = {}
        claimed_names = self.spool.claimed_batches()
        for name in claimed_names:
            lease = self.spool.read_json(self.spool.lease_path(name))
            if lease is not None:
                lease_owner[name] = lease.get("worker", "?")
        # Ring-mode workers heartbeat into their slot, not the lease
        # file — merge the slot stamps so a healthy worker is never
        # expired off a stale mtime. max() keeps this strictly more
        # conservative: a degraded/absent ring leaves mtime semantics
        # exactly as they were pre-ring.
        ring_hb = self._ring_hb_map()
        gauged_now: set = set()
        for name in claimed_names:
            lease_path = self.spool.lease_path(name)
            try:
                mtime = os.stat(lease_path).st_mtime
            except OSError:
                # Claimed but no lease yet: age from the claim itself.
                try:
                    mtime = os.stat(
                        self.spool.path("claimed", name)
                    ).st_ctime
                except OSError:
                    continue  # finished/requeued under us
            hb = ring_hb.get(lease_owner.get(name, ""))
            if hb is not None and hb > mtime:
                mtime = hb
            last = self._hb_seen.get(name)
            if last is not None and mtime > last:
                self.registry.counter("fleet.lease.heartbeats").bump()
            self._hb_seen[name] = mtime
            age = max(time.time() - mtime, 0.0)
            owner = lease_owner.get(name)
            if owner is not None:
                gauged_now.add(owner)
                self.registry.gauge(
                    "fleet.lease.age_s", worker=owner
                ).set(round(age, 3))
            if age > self.fleet.lease_timeout_s:
                self._requeue(
                    name, lease_owner.get(name, "?"), "lease_expired"
                )
        for owner in self._lease_gauged - gauged_now:
            self.registry.gauge("fleet.lease.age_s", worker=owner).set(0.0)
        self._lease_gauged = gauged_now
        # Preempt markers whose batch left the claimed state are
        # stale — the worker removes its own on finish, this sweeps
        # markers orphaned by deaths.
        for name in self._preempted_batches - set(claimed_names):
            self._preempted_batches.discard(name)
            try:
                os.remove(self.spool.preempt_path(name))
            except OSError:
                pass
        return lease_owner

    # ----------------------------------------------- preemption (ISSUE 15)

    def _preempt_scan(self, lease_owner: Dict[str, str]) -> None:
        """Priority lanes with preemption: when a higher-priority batch
        is waiting, every worker is busy, and a strictly lower-priority
        SUPERVISED batch is executing, mark that batch for preemption.
        The worker's supervised stop hook observes the marker at the
        next chunk boundary and returns the batch's remainder to the
        spool — the round-13 SIGTERM-drain discipline without losing
        the process — then claims the higher-priority batch (the name
        sort puts it first). Resume is bit-identical: the checkpoint +
        sidecar machinery is exactly the drain path's."""
        pending = self.spool.pending_batches()
        if not pending:
            return
        claimed = self.spool.claimed_batches()
        if not claimed:
            return
        if len(self.workers_alive()) > len(claimed):
            return  # an idle worker will pick the high-prio batch up
        best_waiting = max(Spool.name_priority(n) for n in pending)
        victims = [
            n for n in claimed
            if n.endswith("-sup.json")
            and n not in self._preempted_batches
            and Spool.name_priority(n) < best_waiting
        ]
        if not victims:
            return
        victim = min(victims, key=Spool.name_priority)
        high = max(pending, key=Spool.name_priority)
        owner = lease_owner.get(victim, "?")
        self.spool.write_json(self.spool.preempt_path(victim), {
            "batch": victim, "for": high, "worker": owner,
            "at": _tl.anchored_wall(),
        })
        self._preempted_batches.add(victim)
        self.registry.counter("fleet.sched.preemptions").bump()
        self._emit("preempt", batch=victim, by=high, worker=owner)
        if self.fleet.trace:
            now_w = _tl.anchored_wall()
            _tl.append_trace(
                self.spool.trace_path(victim),
                _tl.trace_span_record(
                    "preempt", now_w, now_w, batch=victim, by=high,
                    worker=owner, role="coordinator",
                ),
            )

    # ----------------------------------------------- autoscaler (ISSUE 15)

    def _ensure_scaler(self) -> None:
        if self.autoscaler is None:
            return
        with self._lock:
            if self._scaler is not None and self._scaler.is_alive():
                return
            if self._closed:
                return
            self._stop_scaler.clear()
            self._scaler = threading.Thread(
                target=self._scaler_loop, name="pga-fleet-autoscaler",
                daemon=True,
            )
            self._scaler.start()

    def _scaler_loop(self) -> None:
        cfg = self.fleet.autoscale
        while not self._stop_scaler.wait(cfg.check_s):
            try:
                self._autoscale_tick()
            except Exception:
                pass  # one bad evaluation must not stop the policy

    def _autoscale_tick(self) -> None:
        """One closed-loop evaluation: feed the pure policy the signals
        the fleet already exports (claimable backlog, spool-wait p99,
        per-tenant burn alerts, straggler flags) and apply the delta —
        spawn on scale-up, SIGTERM-drain (never kill) on scale-down."""
        if self._draining or self._closed or self.autoscaler is None:
            return
        if self._ha_enabled and not self.is_leader:
            return  # deposed mid-cycle: scaling is a leader duty
        self._coord_chaos_check("autoscale")
        cfg = self.fleet.autoscale
        # Retiring workers (SIGTERM sent, drain in progress) are no
        # longer capacity: counting them would let the policy retire a
        # second worker before the first finishes draining and dip
        # below the floor.
        alive = [
            w for w in self.workers_alive() if w not in self._retiring
        ]
        import math as _math

        backlog = len(self.spool.pending_batches()) + _math.ceil(
            self.sched.depth() / self.fleet.max_batch
        )
        claimed = len(self.spool.claimed_batches())
        p99 = None
        if cfg.spool_wait_p99_ms is not None:
            snap = self.registry.histogram(
                "fleet.ticket.spool_wait_ms"
            ).snapshot()
            if snap.count:
                p99 = snap.percentile(99.0)
        burn_alerts = sum(
            1 for t, m in list(self.burn.monitors.items())
            if m.alerting(t)
        )
        delta, reason = self.autoscaler.decide(
            _now(), len(alive), backlog, claimed, spool_wait_p99=p99,
            burn_alerts=burn_alerts, stragglers=len(self._stragglers),
        )
        self.registry.gauge("fleet.autoscale.workers").set(len(alive))
        if delta > 0:
            spawned = self._spawn_workers(delta)
            self.registry.counter("fleet.autoscale.ups").bump()
            self._emit(
                "autoscale_up", workers=delta, reason=reason,
                alive=len(alive) + delta, backlog=backlog,
                spawned=",".join(spawned),
            )
            self._wake.set()
        elif delta < 0:
            self._retire_workers(-delta, reason)

    def _retire_workers(self, n: int, reason: str) -> None:
        """Scale-down by DRAINING: SIGTERM ``n`` workers (idle ones
        first) — each checkpoints any in-flight supervised chunk,
        returns its lease, and exits 0, so results stay bit-identical
        to a fixed-size fleet. Never SIGKILL from here."""
        with self._lock:
            candidates = [
                wid for wid, p in self._workers.items()
                if p.poll() is None and wid not in self._retiring
            ]
        if not candidates:
            return
        owners = set()
        for name in self.spool.claimed_batches():
            lease = self.spool.read_json(self.spool.lease_path(name))
            if lease is not None:
                owners.add(lease.get("worker"))
        # Idle workers first; among equals, the newest (highest id) —
        # the floor keeps the longest-warmed caches.
        def _rank(wid: str):
            try:
                idx = int(wid[1:])
            except ValueError:
                idx = 0
            return (wid in owners, -idx)

        candidates.sort(key=_rank)
        for wid in candidates[:n]:
            with self._lock:
                proc = self._workers.get(wid)
            if proc is None or proc.poll() is not None:
                continue
            try:
                proc.send_signal(signal.SIGTERM)
            except OSError:
                continue
            self._retiring.add(wid)
            self.registry.counter("fleet.autoscale.downs").bump()
            self._emit(
                "autoscale_down", workers=1, reason=reason, worker=wid
            )

    # -------------------------------------------------- requeue / quarantine

    def _requeue(self, name: str, worker: str, reason: str) -> None:
        """Recover one claimed batch whose worker lost its lease:
        requeue it for a surviving worker, or quarantine it once
        ``max_worker_deaths`` distinct workers have failed on it."""
        claimed = self.spool.path("claimed", name)
        batch = self.spool.read_json(claimed)
        if batch is None:
            return  # already finished or requeued
        # Invalidate the lease FIRST: a SIGSTOP-resumed worker notices
        # the missing lease (heartbeat utime fails) and abandons the
        # batch instead of racing the re-run.
        try:
            os.remove(self.spool.lease_path(name))
        except OSError:
            pass
        # A pending preemption marker dies with the lease: the re-run
        # starts unpreempted (the scan re-marks it if the high-priority
        # batch is still waiting).
        self._preempted_batches.discard(name)
        try:
            os.remove(self.spool.preempt_path(name))
        except OSError:
            pass
        self._hb_seen.pop(name, None)
        # Chaos point "requeue": lease gone, re-release not yet durable
        # — the new leader's lease scan ages the claimed file itself.
        self._coord_chaos_check("requeue")
        attempts = list(batch.get("attempts", []))
        attempts.append(worker)
        batch["attempts"] = attempts
        if self._ha_enabled:
            # The requeued file is a fresh leader-authored artifact:
            # re-stamp it so it clears the current fence.
            batch["epoch"] = self.epoch
        distinct = len(set(attempts))
        unfinished = [
            t for t in batch["tickets"] if self._meta(t["tid"]) is None
        ]
        if not unfinished:
            # Every ticket's result landed before the worker lost its
            # lease (death between publish and cleanup) — nothing to
            # re-run, just retire the batch file.
            try:
                os.remove(claimed)
            except OSError:
                pass
            return
        if distinct >= self.fleet.max_worker_deaths:
            self._quarantine(name, claimed, batch, unfinished)
            return
        self.spool.write_json(claimed, batch)
        try:
            os.rename(claimed, self.spool.path("pending", name))
        except OSError:
            return  # raced a concurrent transition; next tick re-scans
        self.requeues += 1
        self.registry.counter("fleet.lease.requeues").bump()
        self._ring_advertise(name)  # requeued work is claimable work
        if batch.get("trace", False):
            now_w = _tl.anchored_wall()
            _tl.append_trace(
                self.spool.trace_path(name),
                _tl.trace_span_record(
                    "requeue", now_w, now_w, batch=name, worker=worker,
                    reason=reason, attempts=distinct, role="coordinator",
                ),
            )
        self._emit(
            "lease_requeue", batch=name, worker=worker, reason=reason,
            attempts=distinct,
        )

    def _quarantine(
        self, name: str, claimed: str, batch: dict, unfinished: List[dict]
    ) -> None:
        """Fleet-level dead-letter: the batch has now cost
        ``max_worker_deaths`` distinct workers their lease — park it in
        ``dead/`` with a flight-recorder dump and fail its unfinished
        tickets instead of feeding it more workers."""
        dead = self.spool.path("dead", name)
        # The dead batch's span log rides into both post-mortem
        # artifacts (ISSUE 9): embedded in the dead batch file AND in
        # the flight dump, so "which workers touched this batch, when"
        # survives even if the traces/ directory is swept.
        trace_log: List[dict] = []
        try:
            trace_log = _tl.read_trace(self.spool.trace_path(name))
        except ValueError:
            pass  # a mixed-version trace must not block quarantine
        if trace_log:
            batch["trace_log"] = trace_log
        self.spool.write_json(claimed, batch)
        try:
            os.rename(claimed, dead)
        except OSError:
            return
        self.quarantined.append(name)
        error = (
            f"batch {name} quarantined: {len(set(batch['attempts']))} "
            f"distinct workers lost their lease on it "
            f"(attempts: {batch['attempts']})"
        )
        for t in unfinished:
            self._publish_error(t["tid"], error)
            self.registry.counter(
                "fleet.tenant.dead_letters",
                tenant=t.get("tenant", ANON),
            ).bump()
        self.registry.counter("fleet.dead_letters").bump()
        self._emit("dead_letter", bucket=name, error=error)
        _tl.FLIGHT.dump(
            path=self.spool.path("dead", f"{name}.flight.jsonl"),
            reason="fleet_dead_letter",
            extra=trace_log,
        )
        with self._cv:
            self._cv.notify_all()

    def _publish_error(self, tid: str, error: str) -> None:
        """Durable per-ticket failure verdict — first-writer-wins, so a
        ticket whose result landed before quarantine keeps it."""
        _, meta_path = self.spool.result_paths(tid)
        tmp = f"{meta_path}.{os.getpid()}.err.tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump({"tid": tid, "error": error}, fh)
        self.spool.publish(tmp, meta_path)

    # ------------------------------------------- fleet observability (9)

    def flush_metrics(self) -> None:
        """Persist the coordinator's registry snapshot to the spool's
        ``metrics/`` directory (atomic rename) — called by the monitor
        at ``metrics_flush_s`` cadence and by ``close()``, so the
        fleet-level series survive the coordinator for post-mortems."""
        try:
            write_metrics_file(
                self.spool, self._proc_name, self.registry.snapshot(),
                submitted=self.submitted, completed=self.completed,
                role="leader" if self.is_leader else "standby",
                epoch=self.epoch,
            )
        except OSError:
            pass  # a full disk must not take down the monitor

    def merged_snapshot(self) -> dict:
        """ONE fleet-wide metrics snapshot: every worker's latest spool
        flush merged with the coordinator's LIVE registry through the
        associative histogram merge, per-process labels on every
        series (``metrics.merge_snapshots``)."""
        return merge_spool_metrics(
            self.spool, live={self._proc_name: self.registry.snapshot()}
        )

    def merged_prometheus(self) -> str:
        """The merged fleet snapshot in Prometheus text exposition
        format — one scrape target for the whole fleet."""
        return _metrics.prometheus_text(self.merged_snapshot())

    def detect_stragglers(self) -> List[dict]:
        """Flag workers whose execute-latency p95 exceeds the fleet
        median of worker p95s by ``FleetConfig.straggler_factor``
        (needs >= 2 reporting workers and ``straggler_min_samples``
        observations each). A NEWLY slow worker emits one schema-valid
        ``straggler_alert`` event, bumps ``fleet.straggler_alerts``,
        and drops its ``fleet.worker.health`` gauge to 0; recovery
        restores it to 1. Returns the alerts raised this scan."""
        import statistics

        try:
            payloads, _ = load_spool_metrics(self.spool)
        except ValueError:
            raise  # mixed-version fleet: fail loudly, not silently
        stats: List[Tuple[str, float]] = []
        for p in payloads:
            if p["proc"].startswith("coordinator"):
                continue
            for rec in p["snapshot"].get("histograms", ()):
                if (
                    rec["name"] == "serving.ticket.execute_ms"
                    and not rec.get("labels")
                    and rec["count"] >= self.fleet.straggler_min_samples
                    and rec.get("p95") is not None
                ):
                    stats.append((p["proc"], float(rec["p95"])))
        alerts: List[dict] = []
        if len(stats) < 2:
            return alerts
        median = statistics.median(p95 for _, p95 in stats)
        for wid, p95 in stats:
            slow = median > 0 and p95 > self.fleet.straggler_factor * median
            self.registry.gauge("fleet.worker.health", worker=wid).set(
                0.0 if slow else 1.0
            )
            if slow and wid not in self._stragglers:
                self._stragglers.add(wid)
                self.registry.counter(
                    "fleet.straggler_alerts", worker=wid
                ).bump()
                alert = {
                    "worker": wid,
                    "p95_ms": round(p95, 3),
                    "fleet_p95_ms": round(median, 3),
                    "factor": self.fleet.straggler_factor,
                }
                self._emit("straggler_alert", **alert)
                alerts.append(alert)
            elif not slow:
                self._stragglers.discard(wid)
        return alerts

    def check_slo(self, slo=None, tenant: Optional[str] = None) -> List[dict]:
        """Fleet-level aggregate SLO check: the coordinator's merged
        end-to-end ticket latency histogram's p99 against
        ``slo.p99_latency_ms`` (skipped below ``min_samples``), the
        same contract as ``RunQueue.check_slo`` one level up. With
        ``tenant`` given (ISSUE 14), checks that tenant's LABELED
        latency histogram against its resolved override and counts an
        active burn-rate excursion as a violation. Returns violation
        dicts; each emits one ``slo_violation`` event."""
        slo = slo or self.slo
        if slo is None:
            return []
        violations: List[dict] = []
        if tenant is not None:
            tenant = validate_tenant(tenant)
            slo = slo.for_tenant(tenant)
            snap = self.registry.histogram(
                "fleet.tenant.e2e_ms", tenant=tenant
            ).snapshot()
            what = "fleet_tenant_p99_latency"
        else:
            snap = self.registry.histogram("fleet.ticket.e2e_ms").snapshot()
            what = "fleet_p99_latency"
        if slo.p99_latency_ms is not None and snap.count >= slo.min_samples:
            p99 = snap.percentile(99.0)
            if p99 > slo.p99_latency_ms:
                v = {
                    "what": what,
                    "value_ms": round(p99, 3),
                    "limit_ms": slo.p99_latency_ms,
                    "samples": snap.count,
                }
                if tenant is not None:
                    v["tenant"] = tenant
                violations.append(v)
        if tenant is not None:
            mon = self.burn.monitors.get(tenant)
            if mon is not None and mon.alerting(tenant):
                b = mon.burn(tenant)
                violations.append({
                    "what": "fleet_tenant_burn_rate", "tenant": tenant,
                    "value_ms": round(b["fast_burn"], 4),
                    "limit_ms": mon.threshold,
                })
        for v in violations:
            self.registry.counter("fleet.slo_violations").bump()
            self._emit("slo_violation", **v)
        return violations

    def status(self) -> dict:
        """The live fleet console feed: :func:`fleet_status` over this
        fleet's spool (queue depths, per-worker lease age / health /
        throughput, merged latency percentiles) plus the coordinator's
        in-memory view (workers alive, outstanding tickets,
        quarantines). ``tools/fleet_top.py`` renders the same dict for
        spools whose coordinator is gone."""
        st = fleet_status(
            self.spool.root,
            live={self._proc_name: self.registry.snapshot()},
        )
        st["coordinator"] = {
            "pid": os.getpid(),
            # Coordinator HA (ISSUE 20): this instance's role + epoch.
            "coordinators": self.fleet.coordinators,
            "is_leader": self.is_leader,
            "epoch": self.epoch,
            "failovers": self.failovers,
            "workers_alive": self.workers_alive(),
            "submitted": self.submitted,
            "completed": self.completed,
            "outstanding": self._outstanding(),
            "requeues": self.requeues,
            "worker_deaths": self.worker_deaths,
            "quarantined": list(self.quarantined),
            # Scheduling layer (ISSUE 15): the held-back fair backlog
            # per tenant, the current monitor cadence (adaptive idle
            # backoff), and the autoscaler's retire set.
            "sched_queued": self.sched.depth(),
            "sched_queued_by_tenant": self.sched.tenant_depth(),
            "monitor_poll_s": self._wait_s,
            "retiring": sorted(self._retiring),
            "preempted_batches": sorted(self._preempted_batches),
            # Ring fast path (ISSUE 18): attached == still on the fast
            # path; a degraded coordinator runs pure-spool from then on.
            "ring_enabled": self.fleet.ring,
            "ring_attached": self._ring is not None,
            "ring_depth_estimate": self._ring_depth,
        }
        return st

    # ------------------------------------------------------- drain / close

    def drain(self, timeout: Optional[float] = None) -> int:
        """Preemption-safe drain: SIGTERM every live worker and wait for
        it to exit. Workers checkpoint in-flight supervised runs at the
        next chunk boundary (atomic checkpoint + sidecar), return their
        leases by writing unfinished work back to ``pending/``, and
        exit cleanly; a worker that overruns ``drain_timeout_s`` is
        SIGKILLed (its batch is then recovered by the normal
        lease-expiry path). Pending work and handles survive —
        :meth:`start` afterwards resumes the fleet. Returns the number
        of workers that exited."""
        timeout = self.fleet.drain_timeout_s if timeout is None else timeout
        # Pause autoscaling across an explicit drain: the policy thread
        # must not respawn workers the operator just retired (start()
        # resumes it).
        self._draining = True
        with self._lock:
            procs = {
                wid: p for wid, p in self._workers.items()
                if p.poll() is None
            }
        for p in procs.values():
            try:
                p.send_signal(signal.SIGTERM)
            except OSError:
                pass
        deadline = _now() + timeout
        for wid, p in procs.items():
            try:
                p.wait(timeout=max(deadline - _now(), 0.1))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(timeout=10)
        self._alive_gauge()
        return len(procs)

    def close(self) -> None:
        """Drain the workers, persist unformed buckets to the spool
        (nothing in memory only), and stop the monitor. Unfinished work
        stays claimable — a later ``Fleet`` on the same spool directory
        can pick it up."""
        if self._closed:
            return
        self._stop_scaler.set()
        if self._scaler is not None:
            self._scaler.join(timeout=5)
        self.flush()
        self.drain()
        self.flush_metrics()  # final coordinator snapshot for post-mortems
        self._closed = True
        self._stop_monitor.set()
        self._wake.set()  # snap the monitor out of an idle backoff wait
        if self._monitor is not None:
            self._monitor.join(timeout=5)
        if self._lease is not None and self.is_leader:
            # Clean abdication: a standby wins its next election
            # attempt instead of waiting out lease_timeout_s.
            self._lease.release()
        if self._ring is not None:
            try:
                self._ring.close(unlink=True)
            except OSError:
                pass
            self._ring = None
        with self._cv:
            self._cv.notify_all()

    def __enter__(self) -> "Fleet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
