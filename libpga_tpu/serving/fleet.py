"""Cross-process serving fleet: coordinator, spool protocol, leases.

The round-9/10 serving stack is single-process: one ``RunQueue`` in one
interpreter — a worker crash kills every pending ticket, and there is no
notion of a fleet surviving preemption. This module is the coordinator
half of the fleet (ISSUE 8; ROADMAP item 1 — the distributed
master/worker execution model the Beagle framework treats as
first-class, and the reference's aspirational "+MPI" made real): ticket
intake, shape-bucket batch formation, time-bounded leases, fleet-level
dead-lettering, fleet-wide backpressure, and preemption-safe draining.
``serving/worker.py`` is the worker half.

**Spool protocol.** All cross-process state lives in one spool
directory; every transition is an atomic filesystem operation, so a
process killed at ANY instant (SIGKILL included) leaves the spool in a
recoverable state — the same durability stance as
``utils/checkpoint``'s temp-write + rename:

- ``pending/<batch>.json`` — claimable batch files the coordinator
  writes (temp + ``os.replace``). A batch carries the executor spec,
  the ticket list, and the ``attempts`` record of workers that lost
  their lease on it.
- ``claimed/<batch>.json`` — a worker claims a batch with ONE
  ``os.rename(pending/x, claimed/x)``: atomic, so exactly one of N
  racing workers wins.
- ``leases/<batch>.lease.json`` — written by the claiming worker
  (owner + pid), then touched every ``FleetConfig.heartbeat_s`` by its
  heartbeat thread. The lease IS the liveness contract: a heartbeat
  older than ``lease_timeout_s`` — worker wedged, SIGSTOPped, or its
  heartbeat thread killed — expires the lease and the coordinator
  requeues the batch; a worker PROCESS that exits while holding a
  lease is requeued immediately (the coordinator watches the processes
  it spawned).
- ``results/<tid>.npz`` + ``results/<tid>.json`` — per-ticket results,
  published FIRST-WRITER-WINS (``os.link``, which fails atomically on
  an existing target). Seeds and runtime parameters travel with the
  ticket, never with the worker, so a batch re-run after a worker
  death lands bit-identical — a late duplicate publication from a
  SIGSTOP-resumed worker is therefore identical bits, and the link
  race is benign whoever wins.
- ``ckpt/<tid>.npz`` (+ supervisor sidecar) — drain checkpoints of
  supervised tickets; a re-claiming worker resumes from the last
  durable checkpoint at the ticket's recorded cadence.
- ``dead/`` — quarantined batches: a batch that cost
  ``max_worker_deaths`` DISTINCT workers their lease is moved here
  with a flight-recorder dump instead of being retried forever, and
  its unfinished tickets fail with :class:`FleetDeadLetter`.
- ``logs/`` — per-worker stdout, JSONL event logs, and a Prometheus
  snapshot each worker writes on exit.

**Bit-identity.** Plain tickets (``checkpoint_every == 0``) execute as
shape-bucketed mega-runs through the worker's ``RunQueue``/
``BatchedRuns`` engine — per-run bit-identical to standalone
``PGA.run`` (the round-9 contract), so a killed-and-requeued batch
re-runs to the same bits. Supervised tickets (``checkpoint_every >
0``) execute under ``robustness.supervised_run`` at the ticket's
cadence; SIGTERM drains them at a chunk boundary via the supervisor's
``stop`` hook, and the per-process contract — a resumed run is
bit-identical to an uninterrupted same-seed run at the same cadence —
lifts unchanged to the fleet.
"""

from __future__ import annotations

import dataclasses
import errno
import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from libpga_tpu.config import FleetConfig, PGAConfig
from libpga_tpu.serving.queue import QueueFull
from libpga_tpu.utils import metrics as _metrics
from libpga_tpu.utils import telemetry as _tl
from libpga_tpu.utils.telemetry import TelemetryConfig


class FleetDeadLetter(RuntimeError):
    """Raised by ``FleetHandle.result`` for a ticket whose batch was
    quarantined after ``max_worker_deaths`` distinct workers lost their
    lease on it (the fleet-level dead-letter policy)."""


# ------------------------------------------------------------------- spool


class Spool:
    """Path layout + atomic-write helpers for one fleet spool directory.

    Shared by the coordinator and the worker so the protocol cannot
    drift between the two halves. Every mutation is a single atomic
    filesystem operation (``os.replace`` / ``os.rename`` / ``os.link``).
    """

    DIRS = ("pending", "claimed", "leases", "results", "dead", "ckpt",
            "logs")

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        for d in self.DIRS:
            os.makedirs(os.path.join(self.root, d), exist_ok=True)

    def path(self, *parts: str) -> str:
        return os.path.join(self.root, *parts)

    # ---------------------------------------------------------- json files

    @staticmethod
    def read_json(path: str) -> Optional[dict]:
        """The parsed file, or None when it is gone or torn mid-read
        (both are normal under concurrent rename — callers retry or
        skip)."""
        try:
            with open(path, "r", encoding="utf-8") as fh:
                return json.load(fh)
        except (OSError, json.JSONDecodeError):
            return None

    @staticmethod
    def write_json(path: str, obj: dict) -> None:
        """Atomic write: temp file + ``os.replace``."""
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(obj, fh)
        os.replace(tmp, path)

    @staticmethod
    def publish(tmp: str, final: str) -> bool:
        """First-writer-wins publication: link ``tmp`` to ``final``;
        True when this process's copy won, False when a result already
        existed (ours is discarded). ``tmp`` is removed either way."""
        try:
            os.link(tmp, final)
            return True
        except OSError as e:
            if e.errno != errno.EEXIST:
                raise
            return False
        finally:
            try:
                os.remove(tmp)
            except OSError:
                pass

    # --------------------------------------------------------------- names

    def pending_batches(self) -> List[str]:
        try:
            names = os.listdir(self.path("pending"))
        except OSError:
            return []
        return sorted(n for n in names if n.endswith(".json"))

    def claimed_batches(self) -> List[str]:
        try:
            names = os.listdir(self.path("claimed"))
        except OSError:
            return []
        return sorted(n for n in names if n.endswith(".json"))

    def lease_path(self, batch_name: str) -> str:
        return self.path("leases", f"{batch_name}.lease.json")

    def result_paths(self, tid: str) -> Tuple[str, str]:
        """(npz, meta-json) result paths for one ticket."""
        return (
            self.path("results", f"{tid}.npz"),
            self.path("results", f"{tid}.json"),
        )

    def ckpt_path(self, tid: str) -> str:
        return self.path("ckpt", f"{tid}.npz")


# ---------------------------------------------------- config serialization

#: PGAConfig fields that cross the process boundary verbatim. gene_dtype
#: and telemetry need encoding and are handled separately.
_CONFIG_FIELDS = (
    "tournament_size", "selection", "selection_param", "mutation_rate",
    "elitism", "max_populations", "migration_topology", "use_pallas",
    "pallas_deme_size", "pallas_generations_per_launch", "pallas_layout",
    "pallas_subblock", "pop_shards", "donate_buffers", "validate",
    "fallback", "seed",
)


def config_to_json(cfg: PGAConfig) -> dict:
    """A JSON-safe encoding of the program-shaping config fields — what
    a worker needs to rebuild a bit-identical executor. Event-log paths
    are deliberately NOT carried (each worker logs into the spool)."""
    out = {f: getattr(cfg, f) for f in _CONFIG_FIELDS}
    out["gene_dtype"] = np.dtype(cfg.gene_dtype).name
    t = cfg.telemetry
    out["telemetry_history_gens"] = None if t is None else t.history_gens
    return out


def config_from_json(data: dict) -> PGAConfig:
    """Inverse of :func:`config_to_json`."""
    kw = {f: data[f] for f in _CONFIG_FIELDS if f in data}
    name = data.get("gene_dtype", "float32")
    if name == "bfloat16":
        import jax.numpy as jnp

        kw["gene_dtype"] = jnp.bfloat16
    else:
        kw["gene_dtype"] = np.dtype(name)
    hist = data.get("telemetry_history_gens")
    if hist is not None:
        kw["telemetry"] = TelemetryConfig(history_gens=int(hist))
    return PGAConfig(**kw)


# ----------------------------------------------------------------- tickets


@dataclasses.dataclass(frozen=True)
class FleetTicket:
    """One GA run submitted to the fleet.

    Everything a worker needs travels here (never with the worker):
    shape, budget, seed, runtime parameters, and the supervision
    cadence. ``checkpoint_every == 0`` is a PLAIN ticket — executed as
    part of a shape-bucketed mega-run, recovered after a worker death
    by re-running the batch (bit-identical, the round-9 contract).
    ``checkpoint_every > 0`` is a SUPERVISED ticket — executed under
    ``robustness.supervised_run`` at that cadence with its durable
    checkpoint in the spool, so drains and deaths resume from the last
    chunk boundary. ``max_retries`` bounds the supervisor's in-worker
    retries; failures beyond it escalate to a worker death and the
    fleet's lease-requeue path."""

    size: int
    genome_len: int
    n: int
    seed: int
    target: Optional[float] = None
    mutation_rate: Optional[float] = None
    mutation_sigma: Optional[float] = None
    checkpoint_every: int = 0
    max_retries: int = 1

    def __post_init__(self):
        if self.size < 1 or self.genome_len < 1:
            raise ValueError(
                f"invalid shape ({self.size}, {self.genome_len})"
            )
        if self.n < 0:
            raise ValueError("n must be >= 0")
        if self.checkpoint_every < 0:
            raise ValueError("checkpoint_every must be >= 0")
        if self.max_retries < 0:
            raise ValueError("max_retries must be >= 0")


class FleetResult:
    """One completed ticket, loaded from the spool (host arrays)."""

    def __init__(self, genomes, scores, generations, best_score, worker):
        self.genomes = genomes
        self.scores = scores
        self.generations = int(generations)
        self.best_score = float(best_score)
        self.worker = worker  # which worker published it

    def best(self) -> np.ndarray:
        return np.asarray(self.genomes[int(np.argmax(self.scores))])


class FleetHandle:
    """Handle for one submitted fleet ticket (``Fleet.submit``)."""

    def __init__(self, fleet: "Fleet", tid: str, ticket: FleetTicket):
        self.tid = tid
        self.ticket = ticket
        self._fleet = fleet

    def poll(self) -> bool:
        """True once a result (or a dead-letter verdict) is durable."""
        return self._fleet._meta(self.tid) is not None

    def result(self, timeout: Optional[float] = None) -> FleetResult:
        """Block for the ticket's result. Raises
        :class:`FleetDeadLetter` when its batch was quarantined, and
        ``TimeoutError`` (handle stays re-awaitable) on timeout."""
        return self._fleet._await(self.tid, timeout)


def _now() -> float:
    return time.monotonic()


# ------------------------------------------------------------- coordinator


class _Bucket:
    __slots__ = ("tickets", "oldest")

    def __init__(self):
        self.tickets: List[Tuple[str, FleetTicket]] = []
        self.oldest: float = _now()


class Fleet:
    """Coordinator of a cross-process serving fleet.

    One ``Fleet`` owns one tenant configuration (objective name +
    ``PGAConfig``) and one spool directory; shape buckets still form per
    ticket shape. Usage::

        fleet = Fleet(spool_dir, "onemax", config=PGAConfig(...))
        fleet.start()                       # spawn N worker processes
        h = fleet.submit(FleetTicket(size=4096, genome_len=64, n=50,
                                     seed=7))
        res = h.result(timeout=120)         # bit-identical to PGA.run
        fleet.drain()                       # SIGTERM: checkpoint + exit
        fleet.start()                       # fresh workers resume
        fleet.close()

    The objective must be a NAMED builtin (``libpga_tpu.objectives``):
    it crosses a process boundary, so it must be reconstructible by
    name — the same constraint the C ABI's serving path has.
    """

    def __init__(
        self,
        spool_dir: str,
        objective: str,
        config: Optional[PGAConfig] = None,
        fleet: Optional[FleetConfig] = None,
        mutate_kind: str = "point",
        events=None,
        registry: Optional[_metrics.MetricsRegistry] = None,
    ):
        if not isinstance(objective, str):
            raise ValueError(
                "Fleet needs a NAMED objective (it crosses process "
                "boundaries) — pass a libpga_tpu.objectives name"
            )
        from libpga_tpu import objectives

        objectives.get(objective)  # fail fast on unknown names
        self.spool = Spool(spool_dir)
        self.objective = objective
        self.config = config or PGAConfig()
        self.fleet = fleet or FleetConfig()
        self.mutate_kind = mutate_kind
        self.events = events
        self.registry = registry if registry is not None else _metrics.REGISTRY
        self._lock = threading.RLock()
        self._buckets: Dict[tuple, _Bucket] = {}
        self._handles: Dict[str, FleetHandle] = {}
        self._meta_cache: Dict[str, dict] = {}
        self._workers: Dict[str, subprocess.Popen] = {}
        self._worker_gone: set = set()  # exits already accounted
        self._hb_seen: Dict[str, float] = {}  # batch -> last lease mtime
        self._tid_seq = 0
        self._batch_seq = 0
        # Coordinator instance token: batch names must never collide
        # with a previous coordinator's leftovers on the same spool
        # (a restarted fleet resumes pending work, it never overwrites
        # it).
        self._token = f"{os.getpid():x}-{os.urandom(3).hex()}"
        self._closed = False
        self._monitor: Optional[threading.Thread] = None
        self._stop_monitor = threading.Event()
        self._cv = threading.Condition()  # completion/backpressure wakeups
        self.submitted = 0
        self.completed = 0
        self.requeues = 0
        self.worker_deaths = 0
        self.quarantined: List[str] = []  # batch names moved to dead/

    # --------------------------------------------------------------- events

    def _emit(self, event: str, **fields) -> None:
        _tl.flight_note(event, fields)  # post-mortem ring, always on
        if self.events is not None:
            self.events.emit(event, **fields)

    # -------------------------------------------------------------- workers

    def start(self, worker_env: Optional[Dict[int, dict]] = None) -> List[str]:
        """Spawn ``FleetConfig.n_workers`` worker processes against the
        spool and start the monitor. Safe to call again after
        :meth:`drain` — fresh workers pick up pending and checkpointed
        work. ``worker_env`` maps worker INDEX to extra environment
        variables (the chaos hooks ``PGA_FAULT_SPEC`` /
        ``PGA_WORKER_CHAOS`` ride here in tests). Returns worker ids."""
        if self._closed:
            raise RuntimeError("fleet is closed")
        spawned = []
        # PRNG semantics must MATCH across the process boundary or the
        # fleet's bit-identity contract is void: the coordinator may
        # have flipped threefry partitionability via jax.config (not
        # the environment — e.g. the test harness), and a worker left
        # on the default would derive different random streams from
        # the very same ticket seed.
        try:
            import jax

            threefry = "1" if jax.config.jax_threefry_partitionable else "0"
        except Exception:
            threefry = None
        with self._lock:
            base = len(self._workers)
            for i in range(self.fleet.n_workers):
                wid = f"w{base + i}"
                out = open(  # worker stdout/stderr, for post-mortems
                    self.spool.path("logs", f"{wid}.out"), "ab"
                )
                env = dict(os.environ)
                if threefry is not None:
                    env["JAX_THREEFRY_PARTITIONABLE"] = threefry
                if worker_env and i in worker_env:
                    env.update(worker_env[i])
                proc = subprocess.Popen(
                    [
                        sys.executable, "-m", "libpga_tpu.serving.worker",
                        "--spool", self.spool.root,
                        "--worker-id", wid,
                        "--heartbeat-s", str(self.fleet.heartbeat_s),
                        "--poll-s", str(self.fleet.poll_s),
                    ],
                    stdout=out, stderr=subprocess.STDOUT, env=env,
                )
                out.close()  # the child holds its own descriptor
                self._workers[wid] = proc
                spawned.append(wid)
                self._emit("worker_spawn", worker=wid, pid=proc.pid)
                self.registry.gauge("fleet.worker.up", worker=wid).set(1)
        self._alive_gauge()
        self._ensure_monitor()
        return spawned

    def workers_alive(self) -> List[str]:
        with self._lock:
            return [
                wid for wid, p in self._workers.items()
                if p.poll() is None
            ]

    def _alive_gauge(self) -> None:
        self.registry.gauge("fleet.workers.alive").set(
            len(self.workers_alive())
        )

    # ---------------------------------------------------------------- admit

    def _outstanding(self) -> int:
        return self.submitted - self.completed

    def _admit_slot(self) -> None:
        limit = self.fleet.max_pending
        if limit is None:
            return
        with self._cv:
            while self._outstanding() >= limit:
                if self._closed:
                    raise RuntimeError("fleet is closed")
                if self.fleet.overflow == "raise":
                    raise QueueFull(
                        f"{self._outstanding()} outstanding fleet tickets"
                        f" >= max_pending={limit}"
                    )
                self._cv.wait(timeout=0.05)

    def _bucket_key(self, t: FleetTicket) -> tuple:
        # Supervised tickets never co-batch with plain ones: the plain
        # half of a batch is ONE mega-run, the supervised half is
        # per-ticket engines — mixing them would couple a drainable
        # ticket's latency to an undrainable dispatch.
        return (t.size, t.genome_len, t.checkpoint_every > 0)

    def submit(self, ticket: FleetTicket) -> FleetHandle:
        """Admit one ticket; returns its handle. Applies the fleet-wide
        backpressure policy first, then buckets the ticket; the bucket
        becomes a claimable batch file at ``max_batch`` tickets or
        ``max_wait_ms`` after its oldest admission."""
        if self._closed:
            raise RuntimeError("fleet is closed")
        self._admit_slot()
        with self._lock:
            self._tid_seq += 1
            # Token-qualified: a fresh coordinator on a reused spool
            # must never see a previous run's results as its own.
            tid = f"t{self._tid_seq:05d}-{self._token}"
            handle = FleetHandle(self, tid, ticket)
            self._handles[tid] = handle
            key = self._bucket_key(ticket)
            bucket = self._buckets.get(key)
            if bucket is None:
                bucket = self._buckets[key] = _Bucket()
            if not bucket.tickets:
                bucket.oldest = _now()
            bucket.tickets.append((tid, ticket))
            self.submitted += 1
            self._emit(
                "batch_admit", bucket=f"{ticket.size}x{ticket.genome_len}",
                pending=len(bucket.tickets), population_size=ticket.size,
                genome_len=ticket.genome_len,
            )
            if len(bucket.tickets) >= self.fleet.max_batch:
                self._form_batch(key)
        self.registry.gauge("fleet.tickets.outstanding").set(
            self._outstanding()
        )
        self._ensure_monitor()
        return handle

    def flush(self) -> int:
        """Write every non-empty bucket out as a pending batch file now
        (returns batches formed) — the admission-window override."""
        formed = 0
        with self._lock:
            for key in list(self._buckets):
                if self._buckets[key].tickets:
                    self._form_batch(key)
                    formed += 1
        return formed

    def _form_batch(self, key: tuple) -> None:
        """Turn one bucket's tickets into a claimable batch file
        (caller holds the lock)."""
        bucket = self._buckets[key]
        tickets, bucket.tickets = bucket.tickets, []
        self._batch_seq += 1
        size, genome_len, supervised = key
        name = (
            f"b{self._batch_seq:05d}-{self._token}-{size}x{genome_len}"
            f"{'-sup' if supervised else ''}.json"
        )
        batch = {
            "batch": name,
            "spec": {
                "objective": self.objective,
                "mutate_kind": self.mutate_kind,
                "config": config_to_json(self.config),
            },
            "attempts": [],
            "tickets": [
                {"tid": tid, **dataclasses.asdict(t)}
                for tid, t in tickets
            ],
        }
        self.spool.write_json(self.spool.path("pending", name), batch)
        self._emit(
            "batch_launch", bucket=name, batch_size=len(tickets),
            fill_ratio=round(len(tickets) / self.fleet.max_batch, 4),
        )
        self.registry.gauge("fleet.batches.pending").set(
            len(self.spool.pending_batches())
        )

    # -------------------------------------------------------------- results

    def _meta(self, tid: str) -> Optional[dict]:
        meta = self._meta_cache.get(tid)
        if meta is not None:
            return meta
        meta = self.spool.read_json(self.spool.result_paths(tid)[1])
        if meta is not None:
            self._meta_cache[tid] = meta
        return meta

    def _await(self, tid: str, timeout: Optional[float]) -> FleetResult:
        deadline = None if timeout is None else _now() + timeout
        self.flush()  # a lone ticket must not wait out max_wait_ms
        while True:
            meta = self._meta(tid)
            if meta is not None:
                break
            if deadline is not None and _now() > deadline:
                raise TimeoutError(
                    f"fleet ticket {tid} not completed within {timeout}s"
                )
            with self._cv:
                self._cv.wait(timeout=self.fleet.poll_s)
        if meta.get("error"):
            raise FleetDeadLetter(
                f"ticket {tid} dead-lettered: {meta['error']}"
            )
        npz_path = self.spool.result_paths(tid)[0]
        from libpga_tpu.utils.checkpoint import _decode

        with np.load(npz_path) as data:
            genomes = _decode(
                data["genomes"], str(data["genomes_dtype"])
            ).copy()
            scores = data["scores"].copy()
            gens = int(data["generations"])
        return FleetResult(
            genomes, scores, gens, meta["best_score"], meta.get("worker")
        )

    # -------------------------------------------------------------- monitor

    def _ensure_monitor(self) -> None:
        with self._lock:
            if self._monitor is not None and self._monitor.is_alive():
                return
            if self._closed:
                return
            self._stop_monitor.clear()
            self._monitor = threading.Thread(
                target=self._monitor_loop, name="pga-fleet-monitor",
                daemon=True,
            )
            self._monitor.start()

    def _monitor_loop(self) -> None:
        while not self._stop_monitor.wait(self.fleet.poll_s):
            try:
                self._tick()
            except Exception:
                # The monitor is the fleet's recovery engine — one bad
                # scan (e.g. a file racing a rename) must not stop it.
                pass

    def _tick(self) -> None:
        now = _now()
        # 1. Admission window: flush buckets past max_wait_ms.
        with self._lock:
            deadline = now - self.fleet.max_wait_ms / 1000.0
            for key, b in list(self._buckets.items()):
                if b.tickets and b.oldest <= deadline:
                    self._form_batch(key)
        # 2. Completions: new result metas wake blocked result()/submit().
        fresh = False
        for tid in list(self._handles):
            if tid in self._meta_cache:
                continue
            meta = self._meta(tid)
            if meta is not None:
                fresh = True
                self.completed += 1
                self.registry.counter("fleet.tickets.completed").bump()
        if fresh:
            self.registry.gauge("fleet.tickets.outstanding").set(
                self._outstanding()
            )
            with self._cv:
                self._cv.notify_all()
        # 3. Worker liveness: a worker that EXITED while holding a lease
        # is requeued immediately (no need to wait out the lease).
        lease_owner: Dict[str, str] = {}
        for name in self.spool.claimed_batches():
            lease = self.spool.read_json(self.spool.lease_path(name))
            if lease is not None:
                lease_owner[name] = lease.get("worker", "?")
        with self._lock:
            workers = dict(self._workers)
        for wid, proc in workers.items():
            rc = proc.poll()
            if rc is None or wid in self._worker_gone:
                continue
            self._worker_gone.add(wid)
            self.registry.gauge("fleet.worker.up", worker=wid).set(0)
            if rc == 0:
                self._emit("worker_exit", worker=wid, returncode=0)
            else:
                self.worker_deaths += 1
                self.registry.counter(
                    "fleet.worker.deaths", worker=wid
                ).bump()
                self._emit("worker_death", worker=wid, returncode=rc)
                for name, owner in lease_owner.items():
                    if owner == wid:
                        self._requeue(name, wid, "worker_died")
            self._alive_gauge()
        # 4. Lease expiry: stale heartbeats (SIGSTOP, wedged worker,
        # dead heartbeat thread) requeue the batch onto a survivor.
        for name in self.spool.claimed_batches():
            lease_path = self.spool.lease_path(name)
            try:
                mtime = os.stat(lease_path).st_mtime
            except OSError:
                # Claimed but no lease yet: age from the claim itself.
                try:
                    mtime = os.stat(
                        self.spool.path("claimed", name)
                    ).st_ctime
                except OSError:
                    continue  # finished/requeued under us
            last = self._hb_seen.get(name)
            if last is not None and mtime > last:
                self.registry.counter("fleet.lease.heartbeats").bump()
            self._hb_seen[name] = mtime
            if time.time() - mtime > self.fleet.lease_timeout_s:
                self._requeue(
                    name, lease_owner.get(name, "?"), "lease_expired"
                )

    # -------------------------------------------------- requeue / quarantine

    def _requeue(self, name: str, worker: str, reason: str) -> None:
        """Recover one claimed batch whose worker lost its lease:
        requeue it for a surviving worker, or quarantine it once
        ``max_worker_deaths`` distinct workers have failed on it."""
        claimed = self.spool.path("claimed", name)
        batch = self.spool.read_json(claimed)
        if batch is None:
            return  # already finished or requeued
        # Invalidate the lease FIRST: a SIGSTOP-resumed worker notices
        # the missing lease (heartbeat utime fails) and abandons the
        # batch instead of racing the re-run.
        try:
            os.remove(self.spool.lease_path(name))
        except OSError:
            pass
        self._hb_seen.pop(name, None)
        attempts = list(batch.get("attempts", []))
        attempts.append(worker)
        batch["attempts"] = attempts
        distinct = len(set(attempts))
        unfinished = [
            t for t in batch["tickets"] if self._meta(t["tid"]) is None
        ]
        if not unfinished:
            # Every ticket's result landed before the worker lost its
            # lease (death between publish and cleanup) — nothing to
            # re-run, just retire the batch file.
            try:
                os.remove(claimed)
            except OSError:
                pass
            return
        if distinct >= self.fleet.max_worker_deaths:
            self._quarantine(name, claimed, batch, unfinished)
            return
        self.spool.write_json(claimed, batch)
        try:
            os.rename(claimed, self.spool.path("pending", name))
        except OSError:
            return  # raced a concurrent transition; next tick re-scans
        self.requeues += 1
        self.registry.counter("fleet.lease.requeues").bump()
        self._emit(
            "lease_requeue", batch=name, worker=worker, reason=reason,
            attempts=distinct,
        )

    def _quarantine(
        self, name: str, claimed: str, batch: dict, unfinished: List[dict]
    ) -> None:
        """Fleet-level dead-letter: the batch has now cost
        ``max_worker_deaths`` distinct workers their lease — park it in
        ``dead/`` with a flight-recorder dump and fail its unfinished
        tickets instead of feeding it more workers."""
        dead = self.spool.path("dead", name)
        self.spool.write_json(claimed, batch)
        try:
            os.rename(claimed, dead)
        except OSError:
            return
        self.quarantined.append(name)
        error = (
            f"batch {name} quarantined: {len(set(batch['attempts']))} "
            f"distinct workers lost their lease on it "
            f"(attempts: {batch['attempts']})"
        )
        for t in unfinished:
            self._publish_error(t["tid"], error)
        self.registry.counter("fleet.dead_letters").bump()
        self._emit("dead_letter", bucket=name, error=error)
        _tl.FLIGHT.dump(
            path=self.spool.path("dead", f"{name}.flight.jsonl"),
            reason="fleet_dead_letter",
        )
        with self._cv:
            self._cv.notify_all()

    def _publish_error(self, tid: str, error: str) -> None:
        """Durable per-ticket failure verdict — first-writer-wins, so a
        ticket whose result landed before quarantine keeps it."""
        _, meta_path = self.spool.result_paths(tid)
        tmp = f"{meta_path}.{os.getpid()}.err.tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump({"tid": tid, "error": error}, fh)
        self.spool.publish(tmp, meta_path)

    # ------------------------------------------------------- drain / close

    def drain(self, timeout: Optional[float] = None) -> int:
        """Preemption-safe drain: SIGTERM every live worker and wait for
        it to exit. Workers checkpoint in-flight supervised runs at the
        next chunk boundary (atomic checkpoint + sidecar), return their
        leases by writing unfinished work back to ``pending/``, and
        exit cleanly; a worker that overruns ``drain_timeout_s`` is
        SIGKILLed (its batch is then recovered by the normal
        lease-expiry path). Pending work and handles survive —
        :meth:`start` afterwards resumes the fleet. Returns the number
        of workers that exited."""
        timeout = self.fleet.drain_timeout_s if timeout is None else timeout
        with self._lock:
            procs = {
                wid: p for wid, p in self._workers.items()
                if p.poll() is None
            }
        for p in procs.values():
            try:
                p.send_signal(signal.SIGTERM)
            except OSError:
                pass
        deadline = _now() + timeout
        for wid, p in procs.items():
            try:
                p.wait(timeout=max(deadline - _now(), 0.1))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(timeout=10)
        self._alive_gauge()
        return len(procs)

    def close(self) -> None:
        """Drain the workers, persist unformed buckets to the spool
        (nothing in memory only), and stop the monitor. Unfinished work
        stays claimable — a later ``Fleet`` on the same spool directory
        can pick it up."""
        if self._closed:
            return
        self.flush()
        self.drain()
        self._closed = True
        self._stop_monitor.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5)
        with self._cv:
            self._cv.notify_all()

    def __enter__(self) -> "Fleet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
