"""Fleet worker process: claim → lease → execute → publish.

The worker half of the cross-process serving fleet (ISSUE 8; the
coordinator and spool protocol live in ``serving/fleet.py``). One
worker is one OS process running this module's :func:`main` —
``python -m libpga_tpu.serving.worker --spool DIR --worker-id w0`` —
wrapping the existing round-9/10 execution engines in a
``robustness.supervisor``-style harness:

- **claim**: one atomic ``os.rename(pending/x, claimed/x)`` per batch
  (exactly one of N racing workers wins), followed by the lease file
  and a heartbeat thread touching it every ``--heartbeat-s``;
- **plain tickets** (``checkpoint_every == 0``) run as ONE
  shape-bucketed mega-run through a worker-local
  ``RunQueue``/``BatchedRuns`` — the round-9 engine unchanged, with its
  per-ticket failure isolation: a statically poisoned ticket
  dead-letters locally (its error is published as the ticket's
  verdict) while every co-batched ticket completes. The worker's
  AOT program cache (``serving/cache.PROGRAM_CACHE``) is per-process,
  so repeated same-bucket batches compile once per worker — the
  fleet's cache warm-up story;
- **supervised tickets** (``checkpoint_every > 0``) run under
  ``robustness.supervised_run`` at the ticket's cadence with their
  durable checkpoint in the spool (``ckpt/<tid>.npz`` + sidecar). A
  ticket whose checkpoint already exists RESUMES from it — that is the
  recovery path for both drains and worker deaths, and the
  per-process bit-identity contract (resumed == uninterrupted at the
  same cadence) carries the fleet's;
- **drain** (SIGTERM): the supervisor's ``stop`` hook ends the
  in-flight supervised run at the next chunk boundary — checkpointed
  via the existing atomic temp-write + rename + sidecar machinery —
  unfinished tickets are written back to ``pending/`` and the lease is
  returned; the worker then exits 0;
- **publish**: per-ticket results land first-writer-wins (``os.link``)
  — a worker that lost its lease (SIGSTOP + requeue) may finish late
  and publish bits identical to the re-run's, so the race is benign;
  before retiring the batch file it re-checks lease ownership and
  abandons cleanup if the coordinator reassigned the batch;
- **observability** (ISSUE 9): when the batch rides with tracing on,
  the worker appends durable claim / lease-held markers to the
  batch's span log (``traces/``) and publishes each ticket's
  spool_wait / execute / publish spans (+ the worker-local
  ``TicketTiming`` breakdown) inside the result meta — the
  coordinator composes them with its own intake/readback spans into
  the cross-process latency breakdown. A background flusher also
  writes this process's ``MetricsRegistry`` snapshot to
  ``metrics/<wid>.json`` every ``--metrics-flush-s`` seconds (atomic
  rename), feeding the merged fleet exposition, straggler detection,
  and ``tools/fleet_top.py``;
- **ring fast path** (ISSUE 18): when the coordinator spawned this
  worker with ``--ring-slot``, the worker attaches the spool's
  shared-memory ticket ring (``serving/shm_ring.py``): claims try the
  ring-advertised batch names first, idle waits are event-driven off
  the ring head (with a bounded ``--ring-fallback-s`` pending re-scan
  so a quiet or wedged ring can never hide work), the lease heartbeat
  becomes one framed slot store, and each claim/publish bumps the
  slot's notify counter to wake the coordinator. Any ring failure
  emits ``ring_degraded`` and drops this worker back to the pure-spool
  path above — behavior (and result bits) unchanged.

Chaos hooks (environment, set per worker by the coordinator's
``start(worker_env=...)`` in tests and ``tools/chaos_smoke.py`` /
``tools/fleet_smoke.py``):

- ``PGA_FAULT_SPEC``: a ``robustness.faults.install_spec`` JSON —
  deterministic in-process faults, including the fleet sites
  ``worker.execute`` (a raise kills the worker process mid-batch) and
  ``worker.heartbeat`` (a raise kills only the heartbeat thread, so
  the lease expires under a still-computing worker);
- ``PGA_WORKER_CHAOS``: comma-separated ``<signal>@execute:<n>``
  directives (``sigkill``/``sigstop``) — the worker sends ITSELF the
  real signal at the start of its n-th batch execution, giving tests a
  deterministic kill -9 / preemption-pause mid-batch.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from libpga_tpu.robustness import faults as _faults
from libpga_tpu.serving.fleet import Spool, config_from_json
from libpga_tpu.serving.shm_ring import (
    HB_SLOTS,
    RING_FILENAME,
    RingError,
    ShmRing,
)
from libpga_tpu.utils import metrics as _metrics
from libpga_tpu.utils import telemetry as _tl


def _parse_chaos(spec: str) -> List[tuple]:
    """``"sigkill@execute:2,sigstop@execute:1"`` → [(SIGKILL,
    "execute", 2), ...]. Unknown entries raise — a chaos driver must
    never silently test nothing."""
    out = []
    for part in filter(None, (p.strip() for p in spec.split(","))):
        try:
            signame, rest = part.split("@", 1)
            site, n = rest.split(":", 1)
            out.append(
                (getattr(signal, signame.upper()), site, int(n))
            )
        except (ValueError, AttributeError):
            raise ValueError(f"bad PGA_WORKER_CHAOS directive {part!r}")
    return out


class WorkerHarness:
    """One fleet worker's claim/execute/publish loop."""

    def __init__(
        self,
        spool_dir: str,
        worker_id: str,
        heartbeat_s: float = 0.5,
        poll_s: float = 0.05,
        metrics_flush_s: float = 1.0,
        ring_slot: int = -1,
        ring_fallback_s: float = 1.0,
    ):
        self.spool = Spool(spool_dir)
        self.wid = worker_id
        self.heartbeat_s = heartbeat_s
        self.poll_s = poll_s
        self.metrics_flush_s = metrics_flush_s
        self.drain_evt = threading.Event()
        self._lease_lost = threading.Event()
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        self._engines: Dict[str, tuple] = {}  # spec key -> (ex, queue)
        self._exec_calls = 0
        self._chaos = _parse_chaos(os.environ.get("PGA_WORKER_CHAOS", ""))
        self.batches_done = 0
        # Cross-process tracing (ISSUE 9): the anchored-wall claim time
        # and trace flag of the batch currently held, so every published
        # ticket's meta carries its spool-composable span edges.
        self._claim_wall: Dict[str, float] = {}
        self._trace_on: Dict[str, bool] = {}
        self._started_wall = _tl.anchored_wall()
        self._mf_stop = threading.Event()
        self._mf_thread: Optional[threading.Thread] = None
        # Flight-recorder attribution (ISSUE 8 satellite): dumps from
        # this process carry the worker id + pid in their trailer and
        # land inside the spool for fleet post-mortems.
        _tl.FLIGHT.worker_id = worker_id
        _tl.FLIGHT.dump_dir = self.spool.path("logs")
        self.events = _tl.EventLog(
            self.spool.path("logs", f"{worker_id}.events.jsonl")
        )
        # Shared-memory ticket ring (ISSUE 18): attach the slot the
        # coordinator assigned at spawn. An attach failure is a
        # degradation, not an error — this worker simply runs the
        # pure-spool path.
        self.ring_fallback_s = ring_fallback_s
        self._ring: Optional[ShmRing] = None
        self._ring_head = 0
        self._ring_depth = 0
        self._ring_torn = 0
        self._ring_fallback_next = 0.0  # monotonic; 0 => scan due now
        # Coordinator failover (ISSUE 20): a new leader rebuilds the
        # ring file in place, which orphans every surviving worker's
        # mapping. Remember the path + inode so the claim loop and
        # heartbeat can notice the swap and reattach.
        self._ring_slot = ring_slot
        self._ring_path = self.spool.path(RING_FILENAME)
        self._ring_ino: Optional[int] = None
        if ring_slot >= 0:
            ring_path = self._ring_path
            try:
                self._ring = ShmRing.attach(
                    ring_path, slot=ring_slot, worker_id=worker_id
                )
            except RingError as exc:
                self._ring_degrade(f"attach: {exc}")
            else:
                try:
                    self._ring_ino = os.stat(ring_path).st_ino
                except OSError:
                    self._ring_ino = None
                self._emit(
                    "ring_attach", role="worker", path=ring_path,
                    stale_replaced=False,
                )

    # ----------------------------------------------------------------- ring

    def _ring_degrade(self, reason: str) -> None:
        """Drop to pure-spool coordination (one-way for this process):
        close the mapping, emit the ``ring_degraded`` event, and let
        every caller's fallback branch take over. Behavior from here on
        is the pre-ring worker, bit-for-bit."""
        ring, self._ring = self._ring, None
        if ring is not None:
            try:
                ring.close()
            except Exception:
                pass
        _metrics.REGISTRY.counter("fleet.ring.degraded").bump()
        self._emit("ring_degraded", role="worker", reason=reason)

    def _ring_note(self, what: str) -> None:
        """Best-effort notify-counter bump (claim/publish) — wakes the
        coordinator's monitor; never worker correctness."""
        ring = self._ring
        if ring is None:
            return
        try:
            if what == "claim":
                ring.note_claim()
            else:
                ring.note_publish()
        except Exception as exc:
            self._ring_degrade(f"{what} note: {exc}")

    def _ring_check_rebuilt(self) -> None:
        """Coordinator failover (ISSUE 20): when a new leader won the
        lease it rebuilt the ring file in place (``create`` is an
        atomic replace), so this worker's mapping points at a deleted
        inode — heartbeats and frame reads land in a file nobody
        reads. Detect the inode swap and reattach to the fresh ring.

        The old mapping is deliberately NOT closed: the heartbeat
        thread may be mid-call on it, and an unmapped buffer under a
        live reader is a crash. One leaked (small) mapping per
        failover is the price of lock-freedom here.

        Slot choice: surviving workers probe for a free slot from the
        TOP of the slot table while the coordinator assigns spawn
        slots from the bottom, so the two populations only collide
        once the table is nearly full — and even then a collision is
        benign (last-writer-wins attribution; at worst one spurious
        requeue whose re-execution is bit-identical under
        first-writer-wins results)."""
        if self._ring is None:
            return
        try:
            ino = os.stat(self._ring_path).st_ino
        except OSError:
            return  # leaderless window: keep the old mapping for now
        if self._ring_ino is not None and ino == self._ring_ino:
            return
        slot = self._ring_slot
        try:
            probe = ShmRing.attach(self._ring_path)
            try:
                bound = {rec["slot"] for rec in probe.slots()}
            finally:
                probe.close()
            for idx in range(HB_SLOTS - 1, -1, -1):
                if idx not in bound:
                    slot = idx
                    break
            fresh = ShmRing.attach(
                self._ring_path, slot=slot, worker_id=self.wid
            )
        except RingError as exc:
            self._ring_degrade(f"reattach: {exc}")
            return
        self._ring = fresh
        self._ring_ino = ino
        self._ring_slot = slot
        self._ring_head = 0
        self._ring_depth = 0
        self._ring_fallback_next = 0.0  # force a spool scan right away
        _metrics.REGISTRY.counter("fleet.ring.reattaches").bump()
        self._emit(
            "ring_attach", role="worker", path=self._ring_path,
            stale_replaced=True,
        )

    # --------------------------------------------------------------- events

    def _emit(self, event: str, **fields) -> None:
        _tl.flight_note(event, fields)
        try:
            self.events.emit(event, **fields)
        except Exception:
            pass  # a full disk must not take down the worker

    # ---------------------------------------------------------------- lease

    def _start_heartbeat(self, batch_name: str) -> None:
        self._hb_stop.clear()
        self._lease_lost.clear()
        lease = self.spool.lease_path(batch_name)

        def beat():
            while not self._hb_stop.wait(self.heartbeat_s):
                # Fault site (robustness/faults): a raise kills THIS
                # thread only — the lease then expires under a live,
                # still-computing worker (the injected lease-expiry
                # scenario).
                if _faults.PLAN is not None:
                    _faults.PLAN.fire("worker.heartbeat")
                if self._ring is not None:
                    # Failover (ISSUE 20): a new leader rebuilt the
                    # ring — heartbeat into the fresh one, not the
                    # orphaned inode.
                    self._ring_check_rebuilt()
                ring = self._ring
                if ring is not None:
                    # Ring mode (ISSUE 18): the heartbeat is one framed
                    # slot store instead of a lease-file touch. A
                    # vanished lease (coordinator requeued us) must
                    # still be noticed before publishing, so keep the
                    # existence check — a stat, not a write.
                    try:
                        ring.heartbeat()
                    except Exception as exc:
                        self._ring_degrade(f"heartbeat: {exc}")
                    else:
                        _metrics.REGISTRY.counter(
                            "worker.heartbeats"
                        ).bump()
                        if not os.path.exists(lease):
                            self._lease_lost.set()
                            return
                        continue
                try:
                    os.utime(lease)
                    _metrics.REGISTRY.counter("worker.heartbeats").bump()
                except OSError:
                    # Lease invalidated (coordinator requeued us):
                    # signal the main loop to abandon the batch.
                    self._lease_lost.set()
                    return

        self._hb_thread = threading.Thread(
            target=beat, name=f"pga-hb-{self.wid}", daemon=True
        )
        self._hb_thread.start()

    def _stop_heartbeat(self) -> None:
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2 * self.heartbeat_s + 1)
            self._hb_thread = None

    def _owns_lease(self, batch_name: str) -> bool:
        lease = self.spool.read_json(self.spool.lease_path(batch_name))
        return lease is not None and lease.get("worker") == self.wid

    def _preempt_requested(self, batch_name: str) -> bool:
        """Coordinator preemption marker (ISSUE 15): a higher-priority
        batch wants this slot. Checked by the supervised stop hook at
        every chunk boundary — exactly the SIGTERM-drain discipline,
        but the PROCESS survives: the batch's remainder returns to the
        spool and the claim loop picks the high-priority batch next
        (the name sort puts it first)."""
        return os.path.exists(self.spool.preempt_path(batch_name))

    # -------------------------------------------------------------- metrics

    def _flush_metrics(self) -> None:
        """One atomic registry-snapshot flush into the spool's
        ``metrics/`` directory — the coordinator merges these into the
        fleet exposition and straggler scan (ISSUE 9)."""
        from libpga_tpu.serving.fleet import write_metrics_file

        try:
            write_metrics_file(
                self.spool, self.wid, _metrics.REGISTRY.snapshot(),
                worker=self.wid, batches_done=self.batches_done,
                started_at=self._started_wall,
            )
        except Exception:
            pass  # flushing is observability, never worker correctness

    def _start_metrics_flusher(self) -> None:
        self._flush_metrics()  # first file durable before any claim

        def flush_loop():
            while not self._mf_stop.wait(self.metrics_flush_s):
                self._flush_metrics()

        self._mf_thread = threading.Thread(
            target=flush_loop, name=f"pga-metrics-{self.wid}", daemon=True
        )
        self._mf_thread.start()

    # ---------------------------------------------------------------- claim

    def _claim_candidates(self) -> List[str]:
        """Batch names to attempt, in claim-priority order. Ring mode
        reads the ring-advertised reservations (new ``submit`` frames
        since the last look) instead of listing ``pending/``; any
        overflow, torn frame, or the bounded ``ring_fallback_s``
        cadence falls back to the full name-sorted spool listing — the
        pre-ring behavior, so nothing can hide behind a quiet ring."""
        if self._ring is not None:
            self._ring_check_rebuilt()
        ring = self._ring
        if ring is None:
            return self.spool.pending_batches()
        now = time.monotonic()
        try:
            res = ring.frames_since(self._ring_head)
        except Exception as exc:
            self._ring_degrade(f"frames: {exc}")
            return self.spool.pending_batches()
        self._ring_head = res["head"]
        if res["torn"]:
            _metrics.REGISTRY.counter("fleet.ring.frames_torn").bump()
        names = [
            f["name"] for f in res["frames"]
            if f.get("kind") == "submit" and f.get("name")
        ]
        if res["overflowed"] or res["torn"] or now >= self._ring_fallback_next:
            self._ring_fallback_next = now + self.ring_fallback_s
            _metrics.REGISTRY.counter("fleet.ring.fallback_scans").bump()
            listing = self.spool.pending_batches()
            known = set(listing)
            # The spool listing is the superset and already
            # priority-sorted; advertised names not yet visible in the
            # listing (rename racing the readdir) still get a try.
            return listing + [n for n in names if n not in known]
        return names

    def _fence_epoch(self) -> int:
        """The spool's leader-epoch fence (ISSUE 20). 0 when the fence
        file does not exist — i.e. single-coordinator spools, where no
        batch carries an epoch and nothing is ever fenced."""
        rec = self.spool.read_json(self.spool.path("coord", "epoch.json"))
        if rec is None:
            return 0
        try:
            return int(rec.get("epoch", 0))
        except (TypeError, ValueError):
            return 0

    def claim(self) -> Optional[str]:
        """Claim the oldest pending batch via atomic rename; None when
        nothing is claimable."""
        for name in self._claim_candidates():
            src = self.spool.path("pending", name)
            dst = self.spool.path("claimed", name)
            t0 = _tl.anchored_wall()
            try:
                os.rename(src, dst)
            except OSError:
                continue  # another worker won this one
            batch = self.spool.read_json(dst)
            # Epoch fencing (ISSUE 20): a batch stamped by a deposed
            # leader (epoch below the spool's fence) is a zombie write
            # — drop it on the floor BEFORE taking a lease, so the
            # live leader's re-stamped copy is the only one served.
            # Non-HA batches carry no "epoch" key and skip this
            # entirely.
            bep = None if batch is None else batch.get("epoch")
            if bep is not None:
                fence = self._fence_epoch()
                if int(bep) < fence:
                    try:
                        os.remove(dst)
                    except OSError:
                        pass
                    _metrics.REGISTRY.counter(
                        "fleet.leader.fenced_writes"
                    ).bump()
                    self._emit(
                        "leader_fence", what="batch", epoch=int(bep),
                        fence=fence, batch=name,
                    )
                    continue
            self.spool.write_json(
                self.spool.lease_path(name),
                {"worker": self.wid, "pid": os.getpid(),
                 "claimed": time.time()},
            )
            claimed = _tl.anchored_wall()
            self._claim_wall[name] = claimed
            trace_on = bool(batch.get("trace", False)) if batch else False
            self._trace_on[name] = trace_on
            if trace_on:
                # Durable BEFORE execution starts: a worker that dies
                # mid-batch still leaves its claim in the span log, so
                # the re-run ticket's trace shows BOTH attempts.
                _tl.append_trace(
                    self.spool.trace_path(name),
                    _tl.trace_span_record(
                        "claim", t0, claimed, batch=name, worker=self.wid,
                        role="worker",
                    ),
                )
            self._start_heartbeat(name)
            self._ring_note("claim")
            self._emit("lease_claim", worker=self.wid, batch=name)
            return name
        return None

    # -------------------------------------------------------------- engines

    def _engine(self, spec: dict):
        """Worker-local ``BatchedRuns`` + ``RunQueue`` for one executor
        spec — cached per process, so every same-spec batch after the
        first reuses the warm AOT program cache."""
        import json as _json

        key = _json.dumps(spec, sort_keys=True)
        cached = self._engines.get(key)
        if cached is not None:
            return cached
        from libpga_tpu.config import ServingConfig
        from libpga_tpu.serving.batch import BatchedRuns
        from libpga_tpu.serving.queue import RunQueue

        cfg = config_from_json(spec["config"])
        ex = BatchedRuns(
            spec["objective"], config=cfg,
            mutate_kind=spec.get("mutate_kind", "point"),
        )
        # max_wait_ms=0: the worker flushes explicitly per batch — no
        # background flusher racing the claim loop. max_batch is a
        # ceiling, never an admission trigger here.
        queue = RunQueue(
            ex, serving=ServingConfig(max_batch=4096, max_wait_ms=0)
        )
        self._engines[key] = (ex, queue)
        return ex, queue

    # -------------------------------------------------------------- publish

    def _publish(
        self, tid: str, genomes, scores, gens,
        trace: Optional[dict] = None, tenant: str = "anon",
    ) -> None:
        from libpga_tpu.utils.checkpoint import _encode

        npz_path, meta_path = self.spool.result_paths(tid)
        g = np.asarray(genomes)
        s = np.asarray(scores)
        enc, dtype_name = _encode(g)
        tmp = f"{npz_path}.{os.getpid()}.tmp.npz"
        np.savez(
            tmp, genomes=enc, genomes_dtype=np.asarray(dtype_name),
            scores=s, generations=np.asarray(int(gens)),
        )
        self.spool.publish(tmp, npz_path)
        import json as _json

        meta = {"tid": tid, "generations": int(gens),
                "best_score": float(np.max(s)), "worker": self.wid,
                "pid": os.getpid(), "error": None, "tenant": tenant}
        if trace is not None:
            # The span log travels WITH the result: stamp the publish
            # edge now (the npz above is already durable), close the
            # publish span, and version the whole trace block so a
            # mixed-version coordinator refuses instead of mis-reading.
            published = _tl.anchored_wall()
            trace = dict(trace)
            trace["schema_version"] = _tl.TRACE_SCHEMA_VERSION
            trace["published_at"] = published
            completed = trace.get("completed_at")
            if completed is not None:
                trace.setdefault("spans", []).append(
                    _tl.trace_span_record(
                        "publish", completed, published, tid=tid,
                        trace_id=trace.get("trace_id"), worker=self.wid,
                        role="worker",
                    )
                )
            meta["trace"] = trace
        with open(mtmp := f"{meta_path}.{os.getpid()}.tmp", "w",
                  encoding="utf-8") as fh:
            _json.dump(meta, fh)
        self.spool.publish(mtmp, meta_path)
        self._ring_note("publish")
        _metrics.REGISTRY.counter("worker.tickets.published").bump()

    def _trace_base(self, name: str, batch: dict, t: dict,
                    completed: float, local=None) -> Optional[dict]:
        """The per-ticket trace block published with its result: the
        anchored claim/complete edges plus the worker-side span records
        (spool_wait and execute; publish is appended at publish time).
        None when the batch rode with tracing off."""
        if not self._trace_on.get(name, False):
            return None
        claimed = self._claim_wall.get(name)
        formed = batch.get("formed_at")
        tid, trace_id = t["tid"], t.get("trace_id")
        tenant = t.get("tenant", "anon")
        spans = []
        if formed is not None and claimed is not None:
            spans.append(_tl.trace_span_record(
                "spool_wait", float(formed), claimed, tid=tid,
                trace_id=trace_id, worker=self.wid, role="worker",
                tenant=tenant,
            ))
        if claimed is not None:
            spans.append(_tl.trace_span_record(
                "execute", claimed, completed, tid=tid, trace_id=trace_id,
                worker=self.wid, role="worker", tenant=tenant,
            ))
        base = {
            "trace_id": trace_id,
            "worker": self.wid,
            "tenant": tenant,
            "claimed_at": claimed,
            "completed_at": completed,
            "spans": spans,
        }
        if local is not None:
            # Link to the worker-LOCAL lifecycle (round-11 TicketTiming
            # on this process's RunQueue ticket): the breakdown dict
            # plus its anchored sub-spans, which nest inside the
            # cross-process execute span.
            base["worker_timing"] = local.latency()
            spans += local.timing.trace_spans(
                tid=tid, trace_id=trace_id, worker=self.wid, role="worker",
            )
        return base

    def _publish_error(self, tid: str, error: BaseException) -> None:
        import json as _json

        _, meta_path = self.spool.result_paths(tid)
        mtmp = f"{meta_path}.{os.getpid()}.tmp"
        with open(mtmp, "w", encoding="utf-8") as fh:
            _json.dump(
                {"tid": tid, "worker": self.wid, "pid": os.getpid(),
                 "error": f"{type(error).__name__}: {error}"},
                fh,
            )
        self.spool.publish(mtmp, meta_path)
        self._ring_note("publish")

    # -------------------------------------------------------------- execute

    def _chaos_check(self) -> None:
        for sig, site, n in self._chaos:
            if site == "execute" and n == self._exec_calls:
                os.kill(os.getpid(), sig)

    def execute(self, name: str) -> None:
        """Execute one claimed batch. On completion the batch file and
        lease are retired; on drain the unfinished remainder returns to
        ``pending/``; on a lost lease the batch is abandoned (results
        already published stand — they are bit-identical to the
        re-run's)."""
        self._exec_calls += 1
        self._chaos_check()
        # Fault site (robustness/faults): a raise here propagates out of
        # main() — the worker PROCESS dies mid-batch, which is exactly
        # the failure the coordinator's liveness watch must recover.
        if _faults.PLAN is not None:
            _faults.PLAN.fire("worker.execute")
        batch = self.spool.read_json(self.spool.path("claimed", name))
        if batch is None:  # requeued/quarantined before we could start
            self._stop_heartbeat()
            return
        done: set = set()
        drained = False
        plain = [
            t for t in batch["tickets"]
            if t["checkpoint_every"] == 0 and not self._has_result(t["tid"])
        ]
        supervised = [
            t for t in batch["tickets"]
            if t["checkpoint_every"] > 0 and not self._has_result(t["tid"])
        ]
        try:
            # The profiler-visible envelope of this batch: the fleet
            # "execute" trace span brackets the same interval, so a
            # jax.profiler capture nests the engine's pga/<stage> spans
            # under pga/fleet_execute (the cross-layer link, ISSUE 9).
            with _tl.span("fleet_execute"):
                if plain and not self._abandoned():
                    done |= self._run_plain(name, batch, plain)
                for t in supervised:
                    if self._abandoned():
                        break
                    if self.drain_evt.is_set():
                        drained = True
                        break
                    if self._run_supervised(name, batch, t):
                        done.add(t["tid"])
                    else:
                        drained = True  # stopped at a chunk boundary
                        break
        except BaseException:
            # The worker is about to die mid-batch (injected fault,
            # unexpected error): leave the claimed file AND the lease
            # exactly as they are — the coordinator's death/lease
            # recovery owns them now, and retiring either here would
            # orphan the batch's unfinished tickets.
            self._hb_stop.set()
            raise
        else:
            self._finish_batch(name, batch, done, drained)

    def _abandoned(self) -> bool:
        return self._lease_lost.is_set()

    def _has_result(self, tid: str) -> bool:
        return (
            self.spool.read_json(self.spool.result_paths(tid)[1])
            is not None
        )

    def _run_plain(self, name: str, batch: dict,
                   tickets: List[dict]) -> set:
        """All plain tickets of the batch as ONE mega-run through the
        worker-local RunQueue — per-ticket isolation included: a
        poisoned ticket's error becomes its published verdict, innocent
        co-batched tickets complete. With tracing on, each published
        result carries its span block (spool_wait/execute edges + the
        worker-local TicketTiming breakdown)."""
        from libpga_tpu.serving.batch import RunRequest

        _, queue = self._engine(batch["spec"])
        handles = []
        by_tid = {t["tid"]: t for t in tickets}
        for t in tickets:
            req = RunRequest(
                size=t["size"], genome_len=t["genome_len"], n=t["n"],
                seed=t["seed"], target=t["target"],
                mutation_rate=t["mutation_rate"],
                mutation_sigma=t["mutation_sigma"],
            )
            # Tenant identity rides the batch file (ISSUE 14): submit
            # under it, so this worker's serving.tenant.* series — and
            # therefore the merged fleet exposition — attribute the
            # work correctly.
            handles.append((t["tid"], queue.submit(
                req, tenant=t.get("tenant")
            )))
        queue.drain()
        done = set()
        for tid, ticket in handles:
            try:
                res = ticket.result(timeout=None)
            except BaseException as e:
                self._publish_error(tid, e)
            else:
                self._publish(
                    tid, res.genomes, res.scores, res.generations,
                    trace=self._trace_base(
                        name, batch, by_tid[tid], _tl.anchored_wall(),
                        local=ticket,
                    ),
                    tenant=by_tid[tid].get("tenant", "anon"),
                )
            done.add(tid)
        return done

    def _run_supervised(self, name: str, batch: dict, t: dict) -> bool:
        """One supervised ticket at its cadence; True when it finished
        (result published), False when the drain hook stopped it at a
        chunk boundary (checkpoint durable, ticket stays unfinished).

        The stop hook also re-checks LEASE OWNERSHIP each chunk: a
        worker whose lease expired mid-run (stalled heartbeats) stops
        at the next boundary instead of racing the re-claiming
        survivor on the shared checkpoint for the rest of the run."""
        import dataclasses as _dc

        from libpga_tpu.engine import PGA
        from libpga_tpu.robustness.supervisor import (
            RetryPolicy,
            supervised_run,
        )

        spec = batch["spec"]
        cfg = config_from_json(spec["config"])
        if t["mutation_rate"] is not None:
            cfg = _dc.replace(cfg, mutation_rate=t["mutation_rate"])
        ckpt = self.spool.ckpt_path(t["tid"])
        resume = os.path.exists(ckpt)
        pga = PGA(seed=t["seed"], config=cfg)
        pga.set_objective(spec["objective"])
        if not resume:
            pga.create_population(t["size"], t["genome_len"])
        report = supervised_run(
            pga, t["n"], target=t["target"], checkpoint_path=ckpt,
            checkpoint_every=t["checkpoint_every"],
            retry=RetryPolicy(max_retries=t.get("max_retries", 1)),
            resume=resume,
            stop=lambda: (
                self.drain_evt.is_set()
                or self._lease_lost.is_set()
                or self._preempt_requested(name)
                or not self._owns_lease(name)
            ),
        )
        if report.stopped:
            return False
        pop = pga.populations[0]
        self._publish(
            t["tid"], pop.genomes, pop.scores, report.generations,
            trace=self._trace_base(name, batch, t, _tl.anchored_wall()),
            tenant=t.get("tenant", "anon"),
        )
        return True

    def _finish_batch(
        self, name: str, batch: dict, done: set, drained: bool
    ) -> None:
        """Retire, return, or abandon the claimed batch file."""
        self._stop_heartbeat()
        claimed = self.spool.path("claimed", name)
        if not self._owns_lease(name):
            # The coordinator invalidated our lease (expiry after a
            # stalled heartbeat, SIGSTOP pause) — possibly another
            # worker holds the batch now. Whatever we published is
            # bit-identical to the re-run's, but the batch file and
            # lease are no longer ours to touch.
            self._emit(
                "lease_requeue", batch=name, worker=self.wid,
                reason="lost_lease_abandoned",
            )
            return
        remaining = [
            t for t in batch["tickets"]
            if t["tid"] not in done and not self._has_result(t["tid"])
        ]
        if remaining and drained:
            batch["tickets"] = remaining
            self.spool.write_json(claimed, batch)
            try:
                os.rename(claimed, self.spool.path("pending", name))
            except OSError:
                pass
        else:
            try:
                os.remove(claimed)
            except OSError:
                pass
        try:
            os.remove(self.spool.lease_path(name))
        except OSError:
            pass
        try:
            # Consume any preemption marker with the batch: the
            # returned remainder must re-claim unpreempted later.
            os.remove(self.spool.preempt_path(name))
        except OSError:
            pass
        if self._trace_on.pop(name, False):
            claimed = self._claim_wall.get(name)
            if claimed is not None:
                _tl.append_trace(
                    self.spool.trace_path(name),
                    _tl.trace_span_record(
                        "lease_held", claimed, _tl.anchored_wall(),
                        batch=name, worker=self.wid, role="worker",
                        drained=bool(drained),
                    ),
                )
        self._claim_wall.pop(name, None)
        self.batches_done += 1
        _metrics.REGISTRY.counter("worker.batches.done").bump()

    # ----------------------------------------------------------------- loop

    def run_forever(self) -> int:
        """Claim/execute until drained (SIGTERM). Returns the exit
        code: 0 for a clean drain."""
        self._emit("worker_spawn", worker=self.wid, pid=os.getpid())
        self._start_metrics_flusher()
        clean = False
        try:
            while not self.drain_evt.is_set():
                name = self.claim()
                if name is None:
                    if self._idle_wait():
                        break
                    continue
                self.execute(name)
            if self.drain_evt.is_set():
                self._emit(
                    "worker_drain", worker=self.wid,
                    batches_done=self.batches_done,
                )
            clean = True
        finally:
            self._shutdown(clean)
        return 0

    def _idle_wait(self) -> bool:
        """Block until there may be claimable work (or drain). True =
        drain requested. Ring mode waits event-driven on the ring head
        / advertised depth for up to ``ring_fallback_s`` (the bounded
        fallback: a timeout forces the next claim through a full spool
        listing, so a SIGKILL'd coordinator or wedged ring can never
        stall this worker); spool mode is the classic ``poll_s`` nap."""
        ring = self._ring
        if ring is None:
            return self.drain_evt.wait(self.poll_s)
        try:
            reason, head, depth = ring.wait_pending(
                self._ring_head, self._ring_depth, self.ring_fallback_s,
                stop=self.drain_evt,
            )
        except Exception as exc:
            self._ring_degrade(f"wake: {exc}")
            return self.drain_evt.wait(self.poll_s)
        self._ring_depth = depth
        if reason == "stop":
            return True
        if reason in ("head", "depth"):
            self._ring_torn = 0
            _metrics.REGISTRY.counter("fleet.ring.wakes").bump()
        elif reason == "torn":
            _metrics.REGISTRY.counter("fleet.ring.frames_torn").bump()
            self._ring_torn += 1
            self._ring_fallback_next = 0.0  # next claim: full listing
            if self._ring_torn >= 5:
                self._ring_degrade("mutable record repeatedly torn")
            elif self.drain_evt.wait(self.poll_s):
                return True
        else:  # timeout — bounded fallback scan on the next claim
            self._ring_fallback_next = 0.0
        return False

    def _shutdown(self, clean: bool = True) -> None:
        self._stop_heartbeat()
        self._mf_stop.set()
        if self._mf_thread is not None:
            self._mf_thread.join(timeout=2 * self.metrics_flush_s + 1)
            self._mf_thread = None
        for _, queue in self._engines.values():
            try:
                queue.close()
            except Exception:
                pass
        # Final registry flush (the post-mortem file the coordinator's
        # merge and fleet_top read) + per-worker Prometheus exposition
        # for the CI lint (tools/fleet_smoke.py), both written at exit.
        self._flush_metrics()
        try:
            snap = _metrics.REGISTRY.snapshot()
            prom_path = self.spool.path("logs", f"{self.wid}.prom")
            tmp = f"{prom_path}.{os.getpid()}.tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                fh.write(_metrics.prometheus_text(snap))
            os.replace(tmp, prom_path)
        except Exception:
            pass
        if clean:
            self._emit("worker_exit", worker=self.wid, returncode=0)
        if self._ring is not None:
            try:
                self._ring.close()
            except Exception:
                pass
            self._ring = None
        try:
            self.events.close()
        except Exception:
            pass


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--spool", required=True)
    ap.add_argument("--worker-id", required=True)
    ap.add_argument("--heartbeat-s", type=float, default=0.5)
    ap.add_argument("--poll-s", type=float, default=0.05)
    ap.add_argument("--metrics-flush-s", type=float, default=1.0)
    ap.add_argument("--ring-slot", type=int, default=-1,
                    help="shared-memory ring slot index assigned by the "
                         "coordinator; -1 = pure-spool coordination")
    ap.add_argument("--ring-fallback-s", type=float, default=1.0)
    args = ap.parse_args(argv)

    spec = os.environ.get("PGA_FAULT_SPEC", "")
    if spec:
        _faults.install_spec(spec)

    # Kernel tuning DB (ISSUE 10): same env transport as faults —
    # installed eagerly so a bad DB surfaces in the worker log at
    # startup; a worker is still serviceable untuned, so warn, don't
    # die (the engine-side env fallback would otherwise retry lazily).
    db_path = os.environ.get("PGA_TUNING_DB", "")
    if db_path:
        try:
            from libpga_tpu.tuning import set_tuning_db

            set_tuning_db(db_path)
        except Exception as exc:
            import warnings

            warnings.warn(
                f"PGA_TUNING_DB={db_path!r} is unusable ({exc}) — "
                "worker running untuned"
            )

    harness = WorkerHarness(
        args.spool, args.worker_id,
        heartbeat_s=args.heartbeat_s, poll_s=args.poll_s,
        metrics_flush_s=args.metrics_flush_s,
        ring_slot=args.ring_slot, ring_fallback_s=args.ring_fallback_s,
    )
    # SIGTERM = preemption notice: finish/checkpoint the current chunk,
    # return the lease, exit 0. Installed on the main thread before any
    # batch work begins.
    signal.signal(
        signal.SIGTERM, lambda *_: harness.drain_evt.set()
    )
    return harness.run_forever()


if __name__ == "__main__":
    sys.exit(main())
