"""Async submission queue: accumulate → bucket → launch mega-runs.

The serving front door. ``submit()`` returns immediately with a
:class:`RunTicket`; requests accumulate per shape bucket and a bucket
launches when it reaches ``ServingConfig.max_batch`` or when its oldest
request has waited ``max_wait_ms`` (the continuous-batching admission
window — the same request-packing tradeoff as LLM serving schedulers;
see PAPERS.md). Mismatched shapes can never share a program: the bucket
key IS the executor's exact signature tuple.

Pipelining: a launch only DISPATCHES the mega-run — results come back
as unmaterialized device arrays (``serving/batch.RunResult``), and
host-side readback happens in ``ticket.result()``. With JAX's async
dispatch this overlaps the readback of batch k with the device
execution of batch k+1; nothing in the queue ever calls
``jax.block_until_ready`` on behalf of a caller that hasn't asked.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from libpga_tpu.config import ServingConfig
from libpga_tpu.serving.batch import BatchedRuns, RunRequest, RunResult


def _bucket_id(sig: tuple) -> str:
    """Short stable-within-process label for a signature (event logs
    need a JSON-friendly name, not a tuple full of function objects)."""
    return f"b{abs(hash(sig)) & 0xFFFFFFFF:08x}"


class RunTicket:
    """Handle for one submitted run.

    ``poll()`` is non-blocking; ``result()`` blocks until the run's
    bucket has launched and the mega-run finished, force-flushing the
    bucket first so a lone ticket never waits out ``max_wait_ms``.
    """

    def __init__(self, queue: "RunQueue", bucket: str):
        self.bucket = bucket
        self._queue = queue
        self._event = threading.Event()
        self._result: Optional[RunResult] = None
        self._error: Optional[BaseException] = None

    def _complete(self, result: Optional[RunResult], error=None) -> None:
        self._result = result
        self._error = error
        self._event.set()

    def poll(self) -> bool:
        """True once the run's mega-run has been launched and assigned
        (the result may still be device-lazy — ``result()`` reads it
        back)."""
        return self._event.is_set()

    done = poll

    def result(self, timeout: Optional[float] = None) -> RunResult:
        if not self._event.is_set():
            self._queue.flush(bucket=self.bucket)
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"run in bucket {self.bucket} not completed "
                f"within {timeout}s"
            )
        if self._error is not None:
            raise self._error
        return self._result.block()


class _Bucket:
    __slots__ = ("executor", "items", "oldest")

    def __init__(self, executor: BatchedRuns):
        self.executor = executor
        self.items: List[tuple] = []  # (RunRequest, RunTicket)
        self.oldest: float = time.monotonic()


class RunQueue:
    """Accumulating async front end over :class:`BatchedRuns` executors.

    One queue can serve many tenants: pass a default ``executor`` at
    construction and/or a per-call executor to :meth:`submit`. Requests
    land in the bucket named by ``executor.signature(request)``, so two
    tenants with identical configuration share buckets (and compiled
    programs) automatically, while any difference in shape, objective,
    operators, or config splits them.
    """

    def __init__(
        self,
        executor: Optional[BatchedRuns] = None,
        serving: Optional[ServingConfig] = None,
        events=None,
    ):
        self.executor = executor
        self.serving = serving or (
            executor.serving if executor is not None else ServingConfig()
        )
        self.events = events if events is not None else (
            executor.events if executor is not None else None
        )
        self._buckets: Dict[tuple, _Bucket] = {}
        self._bucket_names: Dict[str, tuple] = {}
        self._lock = threading.RLock()
        self._closed = False
        self._flusher: Optional[threading.Thread] = None
        self.launches = 0
        self.submitted = 0

    # --------------------------------------------------------------- events

    def _emit(self, event: str, **fields) -> None:
        if self.events is not None:
            self.events.emit(event, **fields)

    # ---------------------------------------------------------------- admit

    def submit(
        self, request: RunRequest, executor: Optional[BatchedRuns] = None
    ) -> RunTicket:
        """Admit a run; returns its ticket. Launches the request's
        bucket inline when it reaches ``max_batch``."""
        if self._closed:
            raise RuntimeError("queue is closed")
        ex = executor or self.executor
        if ex is None:
            raise ValueError("no executor: pass one here or at init")
        sig = ex.signature(request)
        name = _bucket_id(sig)
        launch = None
        with self._lock:
            bucket = self._buckets.get(sig)
            if bucket is None:
                bucket = self._buckets[sig] = _Bucket(ex)
                self._bucket_names[name] = sig
            if not bucket.items:
                bucket.oldest = time.monotonic()
            ticket = RunTicket(self, name)
            bucket.items.append((request, ticket))
            self.submitted += 1
            self._emit(
                "batch_admit", bucket=name, pending=len(bucket.items),
                population_size=request.size,
                genome_len=request.genome_len,
            )
            if len(bucket.items) >= self.serving.max_batch:
                launch = self._take(sig)
            self._ensure_flusher()
        if launch is not None:
            self._launch(sig, *launch)
        return ticket

    # --------------------------------------------------------------- launch

    def _take(self, sig: tuple):
        """Detach a bucket's pending items (lock held by caller)."""
        bucket = self._buckets.get(sig)
        if bucket is None or not bucket.items:
            return None
        items, bucket.items = bucket.items, []
        return bucket.executor, items

    def _launch(self, sig: tuple, executor: BatchedRuns, items) -> None:
        name = _bucket_id(sig)
        self._emit("batch_launch", bucket=name, batch_size=len(items))
        self.launches += 1
        try:
            results = executor.run([req for req, _ in items])
        except BaseException as e:  # propagate to every waiter
            for _, ticket in items:
                ticket._complete(None, error=e)
            return
        for (_, ticket), result in zip(items, results):
            ticket._complete(result)

    def flush(self, bucket: Optional[str] = None) -> int:
        """Launch pending buckets now (all of them, or just the named
        one). Returns the number of mega-runs launched."""
        with self._lock:
            if bucket is not None:
                sig = self._bucket_names.get(bucket)
                sigs = [] if sig is None else [sig]
            else:
                sigs = list(self._buckets)
            taken = [(s, self._take(s)) for s in sigs]
        count = 0
        for sig, launch in taken:
            if launch is not None:
                self._launch(sig, *launch)
                count += 1
        return count

    def drain(self) -> int:
        """Flush everything pending; returns launches performed. After
        drain() every previously returned ticket is completed (its
        result may still be device-lazy until read)."""
        return self.flush()

    # -------------------------------------------------------- timed flusher

    def _ensure_flusher(self) -> None:
        if (
            self._flusher is not None and self._flusher.is_alive()
        ) or self.serving.max_wait_ms <= 0 or self._closed:
            # max_wait_ms == 0 → flush on ticket.result()/drain() only
            # (pure size-triggered batching, fully deterministic: no
            # background thread races the test's own flushes).
            return
        self._flusher = threading.Thread(
            target=self._flush_loop, name="pga-serving-flusher", daemon=True
        )
        self._flusher.start()

    def _flush_loop(self) -> None:
        interval = min(max(self.serving.max_wait_ms / 4000.0, 0.001), 0.05)
        while not self._closed:
            time.sleep(interval)
            deadline = time.monotonic() - self.serving.max_wait_ms / 1000.0
            with self._lock:
                expired = [
                    (sig, self._take(sig))
                    for sig, b in self._buckets.items()
                    if b.items and b.oldest <= deadline
                ]
            for sig, launch in expired:
                if launch is not None:
                    self._launch(sig, *launch)

    def close(self) -> None:
        """Flush pending work and stop the background flusher."""
        self._closed = True
        self.flush()

    def __enter__(self) -> "RunQueue":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
