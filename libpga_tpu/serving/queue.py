"""Async submission queue: accumulate → bucket → launch mega-runs.

The serving front door. ``submit()`` returns immediately with a
:class:`RunTicket`; requests accumulate per shape bucket and a bucket
launches when it reaches ``ServingConfig.max_batch`` or when its oldest
request has waited ``max_wait_ms`` (the continuous-batching admission
window — the same request-packing tradeoff as LLM serving schedulers;
see PAPERS.md). Mismatched shapes can never share a program: the bucket
key IS the executor's exact signature tuple.

Pipelining: a launch only DISPATCHES the mega-run — results come back
as unmaterialized device arrays (``serving/batch.RunResult``), and
host-side readback happens in ``ticket.result()``. With JAX's async
dispatch this overlaps the readback of batch k with the device
execution of batch k+1; nothing in the queue ever calls
``jax.block_until_ready`` on behalf of a caller that hasn't asked.

Failure isolation (ISSUE 5): a failing run inside a mega-batch fails
only its own ticket. A launch that raises is split by
``BatchedRuns.validate`` — statically invalid requests dead-letter
immediately with their diagnosis — and the surviving requests are
requeued ONCE as solo launches; a request that fails alone is itself
poisoned and joins :attr:`RunQueue.dead_letters` with its error, while
every innocent co-batched ticket completes normally. Bounded-queue
backpressure (``ServingConfig.max_pending`` + ``overflow``) makes an
unserviceable burst degrade predictably: ``submit`` blocks, or raises
:class:`QueueFull`, instead of accumulating without limit.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional

from libpga_tpu.config import ServingConfig
from libpga_tpu.robustness import faults as _faults
from libpga_tpu.serving.batch import BatchedRuns, RunRequest, RunResult


class QueueFull(RuntimeError):
    """``submit`` under ``overflow="raise"`` with ``max_pending``
    admitted-but-incomplete tickets already in flight."""


@dataclasses.dataclass(frozen=True)
class DeadLetter:
    """One poisoned request: what was submitted, where it was bucketed,
    and why it failed. Kept on :attr:`RunQueue.dead_letters` so an
    operator can inspect/replay instead of losing the diagnosis."""

    request: RunRequest
    bucket: str
    error: BaseException


def _bucket_id(sig: tuple) -> str:
    """Short stable-within-process label for a signature (event logs
    need a JSON-friendly name, not a tuple full of function objects)."""
    return f"b{abs(hash(sig)) & 0xFFFFFFFF:08x}"


class RunTicket:
    """Handle for one submitted run.

    ``poll()`` is non-blocking; ``result()`` blocks until the run's
    bucket has launched and the mega-run finished, force-flushing the
    bucket first so a lone ticket never waits out ``max_wait_ms``. A
    ``result(timeout=...)`` that raises ``TimeoutError`` leaves the
    ticket intact — call ``result()`` again to keep waiting.
    """

    def __init__(self, queue: "RunQueue", bucket: str):
        self.bucket = bucket
        self._queue = queue
        self._event = threading.Event()
        self._result: Optional[RunResult] = None
        self._error: Optional[BaseException] = None

    def _complete(self, result: Optional[RunResult], error=None) -> None:
        self._result = result
        self._error = error
        self._event.set()
        self._queue._ticket_done()

    def poll(self) -> bool:
        """True once the run's mega-run has been launched and assigned
        (the result may still be device-lazy — ``result()`` reads it
        back)."""
        return self._event.is_set()

    done = poll

    def result(self, timeout: Optional[float] = None) -> RunResult:
        if not self._event.is_set():
            self._queue.flush(bucket=self.bucket)
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"run in bucket {self.bucket} not completed "
                f"within {timeout}s"
            )
        if self._error is not None:
            raise self._error
        return self._result.block()


class _Bucket:
    __slots__ = ("executor", "items", "oldest")

    def __init__(self, executor: BatchedRuns):
        self.executor = executor
        self.items: List[tuple] = []  # (RunRequest, RunTicket)
        self.oldest: float = time.monotonic()


class RunQueue:
    """Accumulating async front end over :class:`BatchedRuns` executors.

    One queue can serve many tenants: pass a default ``executor`` at
    construction and/or a per-call executor to :meth:`submit`. Requests
    land in the bucket named by ``executor.signature(request)``, so two
    tenants with identical configuration share buckets (and compiled
    programs) automatically, while any difference in shape, objective,
    operators, or config splits them.
    """

    def __init__(
        self,
        executor: Optional[BatchedRuns] = None,
        serving: Optional[ServingConfig] = None,
        events=None,
    ):
        self.executor = executor
        self.serving = serving or (
            executor.serving if executor is not None else ServingConfig()
        )
        self.events = events if events is not None else (
            executor.events if executor is not None else None
        )
        self._buckets: Dict[tuple, _Bucket] = {}
        self._bucket_names: Dict[str, tuple] = {}
        self._lock = threading.RLock()
        self._closed = False
        self._flusher: Optional[threading.Thread] = None
        self._wake = threading.Event()  # close() interrupts the flusher nap
        # Backpressure accounting: tickets admitted but not completed.
        self._pending = 0
        self._pending_cv = threading.Condition()
        self.launches = 0
        self.submitted = 0
        self.requeues = 0
        self.dead_letters: List[DeadLetter] = []

    # --------------------------------------------------------------- events

    def _emit(self, event: str, **fields) -> None:
        if self.events is not None:
            self.events.emit(event, **fields)

    # --------------------------------------------------------- backpressure

    def _ticket_done(self) -> None:
        with self._pending_cv:
            self._pending -= 1
            self._pending_cv.notify_all()

    @property
    def pending(self) -> int:
        """Admitted-but-incomplete tickets (the backpressure quantity)."""
        with self._pending_cv:
            return self._pending

    def _admit_slot(self) -> None:
        """Reserve a pending slot, blocking or raising per the overflow
        policy at the ``max_pending`` bound. Called OUTSIDE the bucket
        lock (a blocked submit must not stall completions)."""
        limit = self.serving.max_pending
        with self._pending_cv:
            while limit is not None and self._pending >= limit:
                if self._closed:
                    raise RuntimeError("queue is closed")
                if self.serving.overflow == "raise":
                    raise QueueFull(
                        f"{self._pending} pending tickets >= "
                        f"max_pending={limit}"
                    )
                self._pending_cv.wait(timeout=0.05)
            self._pending += 1

    def _unadmit(self) -> None:
        """Roll back a slot reserved by :meth:`_admit_slot` when the
        admission itself fails (closed race, executor error)."""
        self._ticket_done()

    # ---------------------------------------------------------------- admit

    def submit(
        self, request: RunRequest, executor: Optional[BatchedRuns] = None
    ) -> RunTicket:
        """Admit a run; returns its ticket. Launches the request's
        bucket inline when it reaches ``max_batch``. With
        ``max_pending`` set, applies the overflow policy first."""
        if self._closed:
            raise RuntimeError("queue is closed")
        ex = executor or self.executor
        if ex is None:
            raise ValueError("no executor: pass one here or at init")
        self._admit_slot()
        try:
            sig = ex.signature(request)
            name = _bucket_id(sig)
            launch = None
            with self._lock:
                if self._closed:
                    raise RuntimeError("queue is closed")
                bucket = self._buckets.get(sig)
                if bucket is None:
                    bucket = self._buckets[sig] = _Bucket(ex)
                    self._bucket_names[name] = sig
                if not bucket.items:
                    bucket.oldest = time.monotonic()
                ticket = RunTicket(self, name)
                bucket.items.append((request, ticket))
                self.submitted += 1
                self._emit(
                    "batch_admit", bucket=name, pending=len(bucket.items),
                    population_size=request.size,
                    genome_len=request.genome_len,
                )
                if len(bucket.items) >= self.serving.max_batch:
                    launch = self._take(sig)
                self._ensure_flusher()
        except BaseException:
            self._unadmit()
            raise
        if launch is not None:
            self._launch(sig, *launch)
        return ticket

    # --------------------------------------------------------------- launch

    def _take(self, sig: tuple):
        """Detach a bucket's pending items (lock held by caller)."""
        bucket = self._buckets.get(sig)
        if bucket is None or not bucket.items:
            return None
        items, bucket.items = bucket.items, []
        return bucket.executor, items

    def _launch(self, sig: tuple, executor: BatchedRuns, items) -> None:
        name = _bucket_id(sig)
        self._emit("batch_launch", bucket=name, batch_size=len(items))
        self.launches += 1
        try:
            results = executor.run([req for req, _ in items])
        except BaseException as e:
            self._isolate(name, executor, items, e)
            return
        for (_, ticket), result in zip(items, results):
            ticket._complete(result)

    def _isolate(self, name: str, executor: BatchedRuns, items, error) -> None:
        """A failed mega-run fails only the tickets that are actually
        poisoned. Statically invalid requests (per
        ``executor.validate``) dead-letter immediately with their
        diagnosis; the survivors are requeued ONCE as solo launches — a
        request that then fails alone is itself the poison and
        dead-letters with its error, everything else completes. Bounded:
        one extra pass, no recursion."""
        survivors = []
        for req, ticket in items:
            diag = executor.validate(req)
            if diag is not None:
                self._dead_letter(name, req, ticket, diag)
            else:
                survivors.append((req, ticket))
        if not survivors:
            return
        if len(items) == 1:
            # The failed launch WAS a solo run of a statically valid
            # request: the failure is its own (objective raise,
            # poisoned params) — dead-letter rather than loop.
            req, ticket = survivors[0]
            self._dead_letter(name, req, ticket, error)
            return
        self.requeues += 1
        self._emit(
            "retry", attempt=1, bucket=name, batch_size=len(survivors),
            error=str(error), where="serving_launch",
        )
        for req, ticket in survivors:
            try:
                (result,) = executor.run([req])
            except BaseException as e:
                self._dead_letter(name, req, ticket, e)
            else:
                ticket._complete(result)

    def _dead_letter(self, name: str, req, ticket, error) -> None:
        self.dead_letters.append(
            DeadLetter(request=req, bucket=name, error=error)
        )
        self._emit(
            "dead_letter", bucket=name, error=str(error),
            population_size=req.size, genome_len=req.genome_len,
        )
        ticket._complete(None, error=error)

    def flush(self, bucket: Optional[str] = None) -> int:
        """Launch pending buckets now (all of them, or just the named
        one). Returns the number of mega-runs launched."""
        with self._lock:
            if bucket is not None:
                sig = self._bucket_names.get(bucket)
                sigs = [] if sig is None else [sig]
            else:
                sigs = list(self._buckets)
            taken = [(s, self._take(s)) for s in sigs]
        count = 0
        for sig, launch in taken:
            if launch is not None:
                self._launch(sig, *launch)
                count += 1
        return count

    def drain(self) -> int:
        """Flush everything pending; returns launches performed. After
        drain() every previously returned ticket is completed (its
        result may still be device-lazy until read)."""
        return self.flush()

    # -------------------------------------------------------- timed flusher

    def _ensure_flusher(self) -> None:
        if (
            self._flusher is not None and self._flusher.is_alive()
        ) or self.serving.max_wait_ms <= 0 or self._closed:
            # max_wait_ms == 0 → flush on ticket.result()/drain() only
            # (pure size-triggered batching, fully deterministic: no
            # background thread races the test's own flushes).
            return
        # A dead flusher (crashed iteration — e.g. an injected
        # serving.flusher fault) is replaced here on the next submit:
        # thread death degrades the max_wait_ms latency bound until the
        # next admission, never the queue's correctness.
        self._flusher = threading.Thread(
            target=self._flush_loop, name="pga-serving-flusher", daemon=True
        )
        self._flusher.start()

    def _flush_loop(self) -> None:
        interval = min(max(self.serving.max_wait_ms / 4000.0, 0.001), 0.05)
        while not self._closed:
            self._wake.wait(interval)  # close() sets _wake to end the nap
            if self._closed:
                return
            # Fault-injection site (robustness/faults): a raise here
            # kills THIS thread — the failure mode of any unexpected
            # flusher crash — and _ensure_flusher resurrects it on the
            # next submit.
            if _faults.PLAN is not None:
                _faults.PLAN.fire("serving.flusher")
            deadline = time.monotonic() - self.serving.max_wait_ms / 1000.0
            with self._lock:
                expired = [
                    (sig, self._take(sig))
                    for sig, b in self._buckets.items()
                    if b.items and b.oldest <= deadline
                ]
            for sig, launch in expired:
                if launch is not None:
                    self._launch(sig, *launch)

    def close(self, timeout: float = 5.0) -> None:
        """Flush pending work and stop the background flusher.

        Deterministic teardown: the flusher thread is woken and JOINED
        (up to ``timeout`` seconds) BEFORE the final flush, so no
        ``_flush_loop`` iteration can race a post-close launch, and a
        ``submit`` after ``close()`` returns always raises. Blocked
        ``submit`` callers (overflow="block") are released with the
        closed error."""
        with self._lock:
            self._closed = True
            flusher, self._flusher = self._flusher, None
        self._wake.set()
        if flusher is not None and flusher.is_alive():
            flusher.join(timeout)
        self.flush()
        with self._pending_cv:
            self._pending_cv.notify_all()

    def __enter__(self) -> "RunQueue":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
