"""Async submission queue: accumulate → bucket → launch mega-runs.

The serving front door. ``submit()`` returns immediately with a
:class:`RunTicket`; requests accumulate per shape bucket and a bucket
launches when it reaches ``ServingConfig.max_batch`` or when its oldest
request has waited ``max_wait_ms`` (the continuous-batching admission
window — the same request-packing tradeoff as LLM serving schedulers;
see PAPERS.md). Mismatched shapes can never share a program: the bucket
key IS the executor's exact signature tuple.

Pipelining: a launch only DISPATCHES the mega-run — results come back
as unmaterialized device arrays (``serving/batch.RunResult``), and
host-side readback happens in ``ticket.result()``. With JAX's async
dispatch this overlaps the readback of batch k with the device
execution of batch k+1; nothing in the queue ever calls
``jax.block_until_ready`` on behalf of a caller that hasn't asked.

Failure isolation (ISSUE 5): a failing run inside a mega-batch fails
only its own ticket. A launch that raises is split by
``BatchedRuns.validate`` — statically invalid requests dead-letter
immediately with their diagnosis — and the surviving requests are
requeued ONCE as solo launches; a request that fails alone is itself
poisoned and joins :attr:`RunQueue.dead_letters` with its error, while
every innocent co-batched ticket completes normally. Bounded-queue
backpressure (``ServingConfig.max_pending`` + ``overflow``) makes an
unserviceable burst degrade predictably: ``submit`` blocks, or raises
:class:`QueueFull`, instead of accumulating without limit.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional

from libpga_tpu.config import ServingConfig, SLOConfig
from libpga_tpu.robustness import faults as _faults
from libpga_tpu.serving.batch import BatchedRuns, RunRequest, RunResult
from libpga_tpu.utils import metrics as _metrics
from libpga_tpu.utils import telemetry as _tl
from libpga_tpu.utils.tenancy import ANON, validate_tenant


class QueueFull(RuntimeError):
    """``submit`` under ``overflow="raise"`` with ``max_pending``
    admitted-but-incomplete tickets already in flight."""


@dataclasses.dataclass
class TicketTiming:
    """Monotonic lifecycle stamps for one ticket (ISSUE 6).

    Stamped by the queue at each transition: ``submitted`` (submit()
    entered, before any backpressure wait), ``admitted`` (appended to
    its shape bucket), ``launched`` (mega-run dispatch began; restamped
    if the ticket is relaunched solo after a failed batch), ``completed``
    (result or error assigned), ``readback`` (host readback finished in
    ``result()``). A dead-lettered ticket keeps every stamp up to the
    failure point — its post-mortem is exactly these timestamps.
    Derived spans are in milliseconds and ``None`` while the
    corresponding transition hasn't happened.

    ``tenant`` (ISSUE 14) is the submitting tenant's validated id —
    stamped at submit so every downstream consumer of this breakdown
    (``ticket_done`` events, worker result metas, flight dumps) can be
    sliced by tenant without a join.
    """

    submitted: Optional[float] = None
    admitted: Optional[float] = None
    launched: Optional[float] = None
    completed: Optional[float] = None
    readback: Optional[float] = None
    tenant: str = ANON

    @staticmethod
    def _ms(a: Optional[float], b: Optional[float]) -> Optional[float]:
        return None if a is None or b is None else max((b - a) * 1e3, 0.0)

    @property
    def queue_wait_ms(self) -> Optional[float]:
        return self._ms(self.submitted, self.launched)

    @property
    def execute_ms(self) -> Optional[float]:
        return self._ms(self.launched, self.completed)

    @property
    def readback_ms(self) -> Optional[float]:
        return self._ms(self.completed, self.readback)

    @property
    def e2e_ms(self) -> Optional[float]:
        end = self.readback if self.readback is not None else self.completed
        return self._ms(self.submitted, end)

    def as_dict(self) -> dict:
        # The pure latency breakdown — the ``ticket.latency()``
        # contract. The tenant rides the dataclass field and is added
        # explicitly where records need it (ticket_done, result metas).
        return {
            "queue_wait_ms": self.queue_wait_ms,
            "execute_ms": self.execute_ms,
            "readback_ms": self.readback_ms,
            "e2e_ms": self.e2e_ms,
        }

    def trace_spans(self, **attrs) -> List[dict]:
        """The stamped lifecycle as composable ``trace_span`` records
        (ISSUE 9): the worker-LOCAL sub-spans of a fleet ticket's
        execute span — ``local_queue_wait`` (submit -> mega-run
        launch), ``local_run`` (launch -> run complete),
        ``local_readback`` (complete -> host materialization) — with
        the monotonic stamps converted to this process's anchored wall
        clock (``telemetry.anchored_wall``), so they nest inside the
        cross-process span log a fleet worker publishes. Spans whose
        transitions haven't happened are omitted."""
        attrs.setdefault("tenant", self.tenant)
        out: List[dict] = []
        for name, a, b in (
            ("local_queue_wait", self.submitted, self.launched),
            ("local_run", self.launched, self.completed),
            ("local_readback", self.completed, self.readback),
        ):
            if a is not None and b is not None:
                out.append(_tl.trace_span_record(
                    name, _tl.anchored_wall(a), _tl.anchored_wall(b),
                    **attrs,
                ))
        return out


@dataclasses.dataclass(frozen=True)
class DeadLetter:
    """One poisoned request: what was submitted, where it was bucketed,
    and why it failed. Kept on :attr:`RunQueue.dead_letters` so an
    operator can inspect/replay instead of losing the diagnosis."""

    request: RunRequest
    bucket: str
    error: BaseException


def _bucket_id(sig: tuple) -> str:
    """Short stable-within-process label for a signature (event logs
    need a JSON-friendly name, not a tuple full of function objects)."""
    return f"b{abs(hash(sig)) & 0xFFFFFFFF:08x}"


class TenantBurnTracker:
    """Per-tenant error-budget burn tracking for one serving surface
    (ISSUE 14) — the glue between :class:`SLOConfig` (what the
    objective is, per tenant) and
    :class:`~libpga_tpu.utils.metrics.BurnRateMonitor` (how fast the
    budget is burning). One instance per surface: the RunQueue uses
    ``prefix="serving"``, the fleet coordinator ``prefix="fleet"`` —
    both export ``<prefix>.tenant.slo_burn{tenant=,window=}`` gauges
    and emit one transition-edge ``slo_burn`` event per excursion.
    """

    def __init__(self, slo: Optional[SLOConfig], registry, emit,
                 prefix: str):
        self.slo = slo
        self.registry = registry
        self._emit = emit
        self.prefix = prefix
        self.monitors: Dict[str, _metrics.BurnRateMonitor] = {}

    def _monitor(self, tenant: str):
        mon = self.monitors.get(tenant)
        if mon is not None:
            return mon
        if self.slo is None:
            return None
        burn = self.slo.for_tenant(tenant).burn
        if burn is None:
            return None
        mon = _metrics.BurnRateMonitor(
            budget=burn.budget, fast_window_s=burn.fast_window_s,
            slow_window_s=burn.slow_window_s, threshold=burn.threshold,
            min_samples=burn.min_samples,
        )
        self.monitors[tenant] = mon
        return mon

    def observe(self, tenant: str, e2e_ms: Optional[float]) -> None:
        """Record one completed request against the tenant's error
        budget, refresh that tenant's burn gauges, and emit alerts."""
        mon = self._monitor(tenant)
        if mon is None or e2e_ms is None:
            return
        objective = self.slo.for_tenant(tenant).burn.objective_ms
        mon.record(tenant, e2e_ms > objective)
        b = mon.burn(tenant)
        for window in ("fast", "slow"):
            self.registry.gauge(
                f"{self.prefix}.tenant.slo_burn",
                tenant=tenant, window=window,
            ).set(round(b[f"{window}_burn"], 4))
        for alert in mon.check():
            self.registry.counter(
                f"{self.prefix}.slo_burn_alerts", tenant=tenant
            ).bump()
            self._emit(
                "slo_burn", tenant=tenant,
                fast_burn=round(alert["fast_burn"], 4),
                slow_burn=round(alert["slow_burn"], 4),
                budget=alert["budget"], threshold=alert["threshold"],
                objective_ms=objective, where=self.prefix,
            )

    def status(self) -> List[dict]:
        """Current burn state of every tracked tenant (the
        ``check_slo``/console feed): burn rates plus whether the
        tenant is currently inside an alert excursion."""
        out = []
        for tenant, mon in sorted(self.monitors.items()):
            b = mon.burn(tenant)
            b["alerting"] = mon.alerting(tenant)
            out.append(b)
        return out


class RunTicket:
    """Handle for one submitted run.

    ``poll()`` is non-blocking; ``result()`` blocks until the run's
    bucket has launched and the mega-run finished, force-flushing the
    bucket first so a lone ticket never waits out ``max_wait_ms``. A
    ``result(timeout=...)`` that raises ``TimeoutError`` leaves the
    ticket intact — call ``result()`` again to keep waiting.
    """

    def __init__(self, queue: "RunQueue", bucket: str, tenant: str = ANON):
        self.bucket = bucket
        self.tenant = tenant
        self.timing = TicketTiming(tenant=tenant)
        self._queue = queue
        self._event = threading.Event()
        self._result: Optional[RunResult] = None
        self._error: Optional[BaseException] = None
        self._observed = False

    def _complete(self, result: Optional[RunResult], error=None) -> None:
        self.timing.completed = time.monotonic()
        self._result = result
        self._error = error
        self._event.set()
        self._queue._ticket_done(self)

    def latency(self) -> dict:
        """The latency breakdown recorded so far (ms; ``None`` for
        spans whose transitions haven't happened yet). Complete after
        ``result()``; a dead-lettered ticket reports every span up to
        its failure. ``drain()`` preserves tickets and their timing —
        draining completes the runs, it never discards the breakdown."""
        return self.timing.as_dict()

    def poll(self) -> bool:
        """True once the run's mega-run has been launched and assigned
        (the result may still be device-lazy — ``result()`` reads it
        back)."""
        return self._event.is_set()

    done = poll

    def result(self, timeout: Optional[float] = None) -> RunResult:
        if not self._event.is_set():
            self._queue.flush(bucket=self.bucket)
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"run in bucket {self.bucket} not completed "
                f"within {timeout}s"
            )
        if self._error is not None:
            raise self._error
        out = self._result.block()
        if not self._observed:
            self._observed = True
            self.timing.readback = time.monotonic()
            self._queue._observe_ticket(self)
        return out


class _Bucket:
    __slots__ = ("executor", "items", "oldest")

    def __init__(self, executor: BatchedRuns):
        self.executor = executor
        self.items: List[tuple] = []  # (RunRequest, RunTicket)
        self.oldest: float = time.monotonic()


class RunQueue:
    """Accumulating async front end over :class:`BatchedRuns` executors.

    One queue can serve many tenants: pass a default ``executor`` at
    construction and/or a per-call executor to :meth:`submit`. Requests
    land in the bucket named by ``executor.signature(request)``, so two
    tenants with identical configuration share buckets (and compiled
    programs) automatically, while any difference in shape, objective,
    operators, or config splits them.
    """

    def __init__(
        self,
        executor: Optional[BatchedRuns] = None,
        serving: Optional[ServingConfig] = None,
        events=None,
        slo: Optional[SLOConfig] = None,
        registry: Optional[_metrics.MetricsRegistry] = None,
    ):
        self.executor = executor
        self.serving = serving or (
            executor.serving if executor is not None else ServingConfig()
        )
        self.events = events if events is not None else (
            executor.events if executor is not None else None
        )
        self.slo = slo
        self.registry = registry if registry is not None else _metrics.REGISTRY
        self._buckets: Dict[tuple, _Bucket] = {}
        self._bucket_names: Dict[str, tuple] = {}
        self._lock = threading.RLock()
        self._closed = False
        self._flusher: Optional[threading.Thread] = None
        self._wake = threading.Event()  # close() interrupts the flusher nap
        # close() idempotence under CONCURRENT closers: the first caller
        # does the teardown; every other close() waits for it to finish
        # and returns — a deterministic no-op, never a double teardown.
        self._close_lock = threading.Lock()
        self._close_started = False
        self._close_done = threading.Event()
        # Backpressure accounting: tickets admitted but not completed.
        self._pending = 0
        self._pending_cv = threading.Condition()
        self.launches = 0
        self.submitted = 0
        self.requeues = 0
        self.dead_letters: List[DeadLetter] = []
        # Tenant attribution (ISSUE 14): ids seen (for one tenant_admit
        # event each), per-tenant pending counts behind the
        # serving.tenant.pending gauges, and the error-budget burn
        # tracker (active for tenants whose resolved SLO carries a
        # BurnRateConfig).
        self._tenants_seen: set = set()
        self._tenant_pending: Dict[str, int] = {}
        self.burn = TenantBurnTracker(
            self.slo, self.registry, self._emit, "serving"
        )

    # --------------------------------------------------------------- events

    def _emit(self, event: str, **fields) -> None:
        _tl.flight_note(event, fields)  # post-mortem ring, always on
        if self.events is not None:
            self.events.emit(event, **fields)

    # -------------------------------------------------------------- metrics

    def _observe_ticket(self, ticket: RunTicket) -> None:
        """Fold one successfully read-back ticket into the latency
        histograms (aggregate AND tenant-labeled), emit its
        ``ticket_done`` event, and apply the tenant-resolved per-ticket
        SLO + burn-rate checks. Called exactly once per ticket, from
        ``RunTicket.result()`` after readback."""
        t = ticket.timing
        tenant = ticket.tenant
        for name, value in (
            ("serving.ticket.queue_wait_ms", t.queue_wait_ms),
            ("serving.ticket.execute_ms", t.execute_ms),
            ("serving.ticket.readback_ms", t.readback_ms),
            ("serving.ticket.e2e_ms", t.e2e_ms),
        ):
            if value is not None:
                self.registry.histogram(name).observe(value)
        # Tenant-labeled twins of the latency histograms (ISSUE 14):
        # the aggregate series above stay label-free so every existing
        # consumer (check_slo, fleet_status, stragglers) is unchanged.
        for name, value in (
            ("serving.tenant.queue_wait_ms", t.queue_wait_ms),
            ("serving.tenant.e2e_ms", t.e2e_ms),
        ):
            if value is not None:
                self.registry.histogram(name, tenant=tenant).observe(value)
        self.registry.counter("serving.tickets_done").bump()
        self.registry.counter(
            "serving.tenant.completions", tenant=tenant
        ).bump()
        self._emit(
            "ticket_done", bucket=ticket.bucket, tenant=tenant,
            **t.as_dict(),
        )
        slo = self.slo
        tslo = None if slo is None else slo.for_tenant(tenant)
        if (
            tslo is not None
            and tslo.max_queue_wait_ms is not None
            and t.queue_wait_ms is not None
            and t.queue_wait_ms > tslo.max_queue_wait_ms
        ):
            self.registry.counter("serving.slo_violations").bump()
            self._emit(
                "slo_violation", what="queue_wait",
                value_ms=round(t.queue_wait_ms, 3),
                limit_ms=tslo.max_queue_wait_ms, bucket=ticket.bucket,
                tenant=tenant,
            )
        self.burn.observe(tenant, t.e2e_ms)

    def check_slo(
        self, slo: Optional[SLOConfig] = None,
        tenant: Optional[str] = None,
    ) -> List[dict]:
        """Aggregate SLO check: compare the end-to-end latency
        histogram's p99 against ``slo.p99_latency_ms`` (skipped until
        ``min_samples`` tickets completed). With ``tenant`` given
        (ISSUE 14), the TENANT-LABELED latency histogram is checked
        against that tenant's resolved override instead, and the
        tenant's current burn-rate alert state counts as a violation.
        Returns violation dicts (empty = within objective) and emits
        one ``slo_violation`` event per breach.
        ``tools/serving_throughput.py --slo`` exits nonzero on a
        non-empty return."""
        slo = slo or self.slo
        if slo is None:
            return []
        violations: List[dict] = []
        if tenant is not None:
            tenant = validate_tenant(tenant)
            slo = slo.for_tenant(tenant)
            snap = self.registry.histogram(
                "serving.tenant.e2e_ms", tenant=tenant
            ).snapshot()
            what = "tenant_p99_latency"
        else:
            snap = self.registry.histogram(
                "serving.ticket.e2e_ms"
            ).snapshot()
            what = "p99_latency"
        if slo.p99_latency_ms is not None and snap.count >= slo.min_samples:
            p99 = snap.percentile(99.0)
            if p99 > slo.p99_latency_ms:
                v = {
                    "what": what,
                    "value_ms": round(p99, 3),
                    "limit_ms": slo.p99_latency_ms,
                    "samples": snap.count,
                }
                if tenant is not None:
                    v["tenant"] = tenant
                violations.append(v)
        if tenant is not None:
            mon = self.burn.monitors.get(tenant)
            if mon is not None and mon.alerting(tenant):
                b = mon.burn(tenant)
                violations.append({
                    "what": "tenant_burn_rate", "tenant": tenant,
                    "value_ms": round(b["fast_burn"], 4),
                    "limit_ms": mon.threshold,
                })
        for v in violations:
            self.registry.counter("serving.slo_violations").bump()
            self._emit("slo_violation", **v)
        return violations

    # --------------------------------------------------------- backpressure

    def _ticket_done(self, ticket: Optional[RunTicket] = None) -> None:
        tenant = None if ticket is None else ticket.tenant
        with self._pending_cv:
            self._pending -= 1
            depth = self._pending
            t_depth = None
            if tenant is not None:
                t_depth = self._tenant_pending.get(tenant, 1) - 1
                self._tenant_pending[tenant] = max(t_depth, 0)
            self._pending_cv.notify_all()
        self.registry.gauge("serving.queue.depth").set(depth)
        if tenant is not None:
            self.registry.gauge(
                "serving.tenant.pending", tenant=tenant
            ).set(max(t_depth, 0))

    @property
    def pending(self) -> int:
        """Admitted-but-incomplete tickets (the backpressure quantity)."""
        with self._pending_cv:
            return self._pending

    def _admit_slot(self, tenant: str) -> None:
        """Reserve a pending slot, blocking or raising per the overflow
        policy at the ``max_pending`` bound. Called OUTSIDE the bucket
        lock (a blocked submit must not stall completions)."""
        limit = self.serving.max_pending
        with self._pending_cv:
            while limit is not None and self._pending >= limit:
                if self._closed:
                    raise RuntimeError("queue is closed")
                if self.serving.overflow == "raise":
                    raise QueueFull(
                        f"{self._pending} pending tickets >= "
                        f"max_pending={limit}"
                    )
                self._pending_cv.wait(timeout=0.05)
            self._pending += 1
            depth = self._pending
            t_depth = self._tenant_pending.get(tenant, 0) + 1
            self._tenant_pending[tenant] = t_depth
        self.registry.gauge("serving.queue.depth").set(depth)
        self.registry.gauge(
            "serving.tenant.pending", tenant=tenant
        ).set(t_depth)

    def _unadmit(self, tenant: str) -> None:
        """Roll back a slot reserved by :meth:`_admit_slot` when the
        admission itself fails (closed race, executor error)."""
        with self._pending_cv:
            self._pending -= 1
            self._tenant_pending[tenant] = max(
                self._tenant_pending.get(tenant, 1) - 1, 0
            )
            self._pending_cv.notify_all()

    # ---------------------------------------------------------------- admit

    def _admit_tenant(self, tenant: Optional[str], where: str) -> str:
        """Validate a tenant id at the submit boundary and emit one
        ``tenant_admit`` event the first time it is seen."""
        tenant = validate_tenant(tenant)
        if tenant not in self._tenants_seen:
            self._tenants_seen.add(tenant)
            self._emit("tenant_admit", tenant=tenant, where=where)
        return tenant

    def submit(
        self, request: RunRequest,
        executor: Optional[BatchedRuns] = None,
        tenant: Optional[str] = None,
    ) -> RunTicket:
        """Admit a run; returns its ticket. Launches the request's
        bucket inline when it reaches ``max_batch``. With
        ``max_pending`` set, applies the overflow policy first.
        ``tenant`` (ISSUE 14) attributes the ticket — it rides the
        ticket's timing, events, and every tenant-labeled metric
        series; ``None`` submits as the default ``anon`` tenant."""
        if self._closed:
            raise RuntimeError("queue is closed")
        ex = executor or self.executor
        if ex is None:
            raise ValueError("no executor: pass one here or at init")
        tenant = self._admit_tenant(tenant, "serving_queue")
        t_submit = time.monotonic()  # before any backpressure wait
        self._admit_slot(tenant)
        try:
            sig = ex.signature(request)
            name = _bucket_id(sig)
            launch = None
            with self._lock:
                if self._closed:
                    raise RuntimeError("queue is closed")
                bucket = self._buckets.get(sig)
                if bucket is None:
                    bucket = self._buckets[sig] = _Bucket(ex)
                    self._bucket_names[name] = sig
                if not bucket.items:
                    bucket.oldest = time.monotonic()
                ticket = RunTicket(self, name, tenant=tenant)
                ticket.timing.submitted = t_submit
                ticket.timing.admitted = time.monotonic()
                bucket.items.append((request, ticket))
                n_pending = len(bucket.items)
                self.submitted += 1
                self.registry.counter(
                    "serving.tenant.submissions", tenant=tenant
                ).bump()
                self._emit(
                    "batch_admit", bucket=name, pending=n_pending,
                    population_size=request.size,
                    genome_len=request.genome_len, tenant=tenant,
                )
                if n_pending >= self.serving.max_batch:
                    launch = self._take(sig)
                self._ensure_flusher()
            self.registry.gauge(
                "serving.bucket.pending", bucket=name
            ).set(0 if launch is not None else n_pending)
        except BaseException:
            self._unadmit(tenant)
            raise
        if launch is not None:
            self._launch(sig, *launch)
        return ticket

    # --------------------------------------------------------------- launch

    def _take(self, sig: tuple):
        """Detach a bucket's pending items (lock held by caller)."""
        bucket = self._buckets.get(sig)
        if bucket is None or not bucket.items:
            return None
        items, bucket.items = bucket.items, []
        self.registry.gauge(
            "serving.bucket.pending", bucket=_bucket_id(sig)
        ).set(0)
        return bucket.executor, items

    def _launch(self, sig: tuple, executor: BatchedRuns, items) -> None:
        name = _bucket_id(sig)
        # Batch occupancy: requests actually packed into this mega-run,
        # and how full the admission window ran vs max_batch — the
        # latency-vs-throughput knob's direct reading (ROADMAP item 5).
        fill = len(items) / self.serving.max_batch
        self.registry.histogram("serving.batch.occupancy").observe(
            len(items)
        )
        self.registry.histogram(
            "serving.batch.fill_ratio",
            bounds=tuple(i / 16 for i in range(1, 17)),
        ).observe(fill)
        self._emit(
            "batch_launch", bucket=name, batch_size=len(items),
            fill_ratio=round(fill, 4),
        )
        self.launches += 1
        t_launch = time.monotonic()
        for _, ticket in items:
            ticket.timing.launched = t_launch
        try:
            results = executor.run([req for req, _ in items])
        except BaseException as e:
            self._isolate(name, executor, items, e)
            return
        for (_, ticket), result in zip(items, results):
            ticket._complete(result)

    def _isolate(self, name: str, executor: BatchedRuns, items, error) -> None:
        """A failed mega-run fails only the tickets that are actually
        poisoned. Statically invalid requests (per
        ``executor.validate``) dead-letter immediately with their
        diagnosis; the survivors are requeued ONCE as solo launches — a
        request that then fails alone is itself the poison and
        dead-letters with its error, everything else completes. Bounded:
        one extra pass, no recursion."""
        survivors = []
        for req, ticket in items:
            diag = executor.validate(req)
            if diag is not None:
                self._dead_letter(name, req, ticket, diag)
            else:
                survivors.append((req, ticket))
        if not survivors:
            return
        if len(items) == 1:
            # The failed launch WAS a solo run of a statically valid
            # request: the failure is its own (objective raise,
            # poisoned params) — dead-letter rather than loop.
            req, ticket = survivors[0]
            self._dead_letter(name, req, ticket, error)
            return
        self.requeues += 1
        self._emit(
            "retry", attempt=1, bucket=name, batch_size=len(survivors),
            error=str(error), where="serving_launch",
        )
        for req, ticket in survivors:
            try:
                # Restamp: the solo relaunch is this ticket's real
                # dispatch — queue_wait then includes the failed batch
                # attempt (which IS time spent waiting to execute), and
                # the submit <= admit <= launch <= done ordering holds.
                ticket.timing.launched = time.monotonic()
                (result,) = executor.run([req])
            except BaseException as e:
                self._dead_letter(name, req, ticket, e)
            else:
                ticket._complete(result)

    def _dead_letter(self, name: str, req, ticket, error) -> None:
        self.dead_letters.append(
            DeadLetter(request=req, bucket=name, error=error)
        )
        self._emit(
            "dead_letter", bucket=name, error=str(error),
            population_size=req.size, genome_len=req.genome_len,
            tenant=ticket.tenant,
        )
        self.registry.counter("serving.dead_letters").bump()
        self.registry.counter(
            "serving.tenant.dead_letters", tenant=ticket.tenant
        ).bump()
        self.registry.gauge("serving.dead_letters.pending").set(
            len(self.dead_letters)
        )
        ticket._complete(None, error=error)
        # Post-mortem: the poisoned request's recent context (launches,
        # faults, retries, this dead_letter) + live metrics, on disk.
        _tl.flight_dump("dead_letter")

    def flush(self, bucket: Optional[str] = None) -> int:
        """Launch pending buckets now (all of them, or just the named
        one). Returns the number of mega-runs launched."""
        with self._lock:
            if bucket is not None:
                sig = self._bucket_names.get(bucket)
                sigs = [] if sig is None else [sig]
            else:
                sigs = list(self._buckets)
            taken = [(s, self._take(s)) for s in sigs]
        count = 0
        for sig, launch in taken:
            if launch is not None:
                self._launch(sig, *launch)
                count += 1
        return count

    def drain(self) -> int:
        """Flush everything pending; returns launches performed. After
        drain() every previously returned ticket is completed (its
        result may still be device-lazy until read). Draining preserves
        each ticket's latency breakdown: the tickets are launched and
        completed normally, so ``ticket.latency()`` afterwards reports
        the full submit -> admit -> launch -> complete history (readback
        is stamped when ``result()`` reads the ticket back)."""
        return self.flush()

    # -------------------------------------------------------- timed flusher

    def _ensure_flusher(self) -> None:
        if (
            self._flusher is not None and self._flusher.is_alive()
        ) or self.serving.max_wait_ms <= 0 or self._closed:
            # max_wait_ms == 0 → flush on ticket.result()/drain() only
            # (pure size-triggered batching, fully deterministic: no
            # background thread races the test's own flushes).
            return
        # A dead flusher (crashed iteration — e.g. an injected
        # serving.flusher fault) is replaced here on the next submit:
        # thread death degrades the max_wait_ms latency bound until the
        # next admission, never the queue's correctness.
        self._flusher = threading.Thread(
            target=self._flush_loop, name="pga-serving-flusher", daemon=True
        )
        self._flusher.start()

    def _flush_loop(self) -> None:
        interval = min(max(self.serving.max_wait_ms / 4000.0, 0.001), 0.05)
        while not self._closed:
            self._wake.wait(interval)  # close() sets _wake to end the nap
            if self._closed:
                return
            # Fault-injection site (robustness/faults): a raise here
            # kills THIS thread — the failure mode of any unexpected
            # flusher crash — and _ensure_flusher resurrects it on the
            # next submit.
            if _faults.PLAN is not None:
                _faults.PLAN.fire("serving.flusher")
            deadline = time.monotonic() - self.serving.max_wait_ms / 1000.0
            with self._lock:
                expired = [
                    (sig, self._take(sig))
                    for sig, b in self._buckets.items()
                    if b.items and b.oldest <= deadline
                ]
            for sig, launch in expired:
                if launch is not None:
                    self._launch(sig, *launch)

    def close(self, timeout: float = 5.0) -> None:
        """Flush pending work and stop the background flusher.

        Deterministic teardown: the flusher thread is woken and JOINED
        (up to ``timeout`` seconds) BEFORE the final flush, so no
        ``_flush_loop`` iteration can race a post-close launch, and a
        ``submit`` after ``close()`` returns always raises. Blocked
        ``submit`` callers (overflow="block") are released with the
        closed error.

        Idempotent under concurrency: exactly ONE caller performs the
        teardown; any close() racing it (or arriving later) waits for
        that teardown to finish — up to ``timeout`` — and returns
        without flushing or joining anything itself, so concurrent
        closers can never double-launch a bucket or observe a
        half-closed queue."""
        with self._close_lock:
            first, self._close_started = not self._close_started, True
        if not first:
            self._close_done.wait(timeout)
            return
        with self._lock:
            self._closed = True
            flusher, self._flusher = self._flusher, None
        self._wake.set()
        if flusher is not None and flusher.is_alive():
            flusher.join(timeout)
        try:
            self.flush()
            with self._pending_cv:
                self._pending_cv.notify_all()
        finally:
            self._close_done.set()

    def __enter__(self) -> "RunQueue":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
