"""Shared AOT compile cache for the serving subsystem.

The engine's ``_compiled`` dict is per-instance: N concurrent requests
served by N fresh ``PGA`` instances pay N full trace+compile pipelines
for the SAME program (the motivation of ISSUE 4 — on the CPU host a
fresh-engine 16k×100 request spends ~80% of its wall time compiling).
This module promotes compiled run programs to a MODULE-LEVEL cache
keyed on the exact bucket signature tuple, so every executor, queue,
and C-ABI solver in the process shares one compilation per shape
bucket.

Three properties the serving acceptance gates assert:

- **hit/miss/evict counters** — a :class:`~libpga_tpu.utils.metrics.Counters`
  instance (``COUNTERS``) bumps ``hits`` / ``misses`` / ``builds`` /
  ``evictions`` so a test (or an operator's dashboard) can prove "a
  second same-bucket submission triggers 0 new XLA compilations";
- **AOT warm-up** — builders may return ``jax.jit`` wrappers lowered and
  compiled ahead of time (``jit(...).lower(*shapes).compile()``), so the
  first request of a bucket pays compile at admission, not mid-launch;
- **bounded size** — LRU eviction at ``capacity`` programs (compiled
  mega-runs hold large executables; an unbounded cache is a slow leak
  in a long-lived server).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Callable, Optional

from libpga_tpu.robustness import faults as _faults
from libpga_tpu.utils import metrics as _metrics
from libpga_tpu.utils.metrics import Counters

#: Module-level counter set: hits / misses / builds / evictions.
COUNTERS = Counters()


def _entries_gauge(n: int) -> None:
    """Mirror the live entry count into the metrics registry (the
    operator-facing 'how many compiled mega-runs are resident' gauge)."""
    _metrics.REGISTRY.gauge("serving.cache.entries").set(n)


class ProgramCache:
    """LRU cache of compiled programs keyed by signature tuples.

    Thread-safe (the async queue's flusher thread and submitter threads
    race on it). The builder runs OUTSIDE the lock — compiles take
    seconds and must not serialize unrelated buckets — so two racing
    builders for the same key may both compile; the second result wins
    and the duplicate is dropped (counted as a single build miss each,
    which is the honest accounting: both paid the compile).
    """

    def __init__(
        self,
        capacity: Optional[int] = None,
        counters: Optional[Counters] = None,
    ):
        if capacity is not None and capacity < 1:
            raise ValueError("capacity must be >= 1 or None")
        self.capacity = capacity
        self.counters = counters if counters is not None else COUNTERS
        self._entries: "OrderedDict[tuple, object]" = OrderedDict()
        # Tuning-DB provenance per cached program (ISSUE 10): which
        # resolved knobs a tuned bucket compiled under, surfaced by
        # stats() so an operator (and the CI smoke) can prove "this
        # served signature runs its best-known config".
        self._tuned: "OrderedDict[tuple, dict]" = OrderedDict()
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def __contains__(self, key: tuple) -> bool:
        with self._lock:
            return key in self._entries

    def get(self, key: tuple):
        """The cached program, or None. Counts a hit/miss and refreshes
        LRU recency on hit."""
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                self.counters.bump("hits")
                _metrics.REGISTRY.counter("serving.cache.hits").bump()
                return self._entries[key]
        self.counters.bump("misses")
        _metrics.REGISTRY.counter("serving.cache.misses").bump()
        return None

    def put(self, key: tuple, program) -> None:
        evicted = []
        with self._lock:
            self._entries[key] = program
            self._entries.move_to_end(key)
            while (
                self.capacity is not None
                and len(self._entries) > self.capacity
            ):
                evicted.append(self._entries.popitem(last=False))
            for k, _ in evicted:
                self._tuned.pop(k, None)
            n = len(self._entries)
        _entries_gauge(n)
        for _ in evicted:
            self.counters.bump("evictions")

    def get_or_build(
        self,
        key: tuple,
        build: Callable[[], object],
        on_compile: Optional[Callable[[], None]] = None,
        tuned: Optional[dict] = None,
    ):
        """The cached program for ``key``, building (and counting a
        ``builds``) on miss. ``on_compile`` fires once per ACTUAL build
        — the hook the queue uses to emit a ``compile`` telemetry event
        per bucket, never per request. ``tuned`` (ISSUE 10) attaches
        the tuning-DB resolution provenance of this program — recorded
        hit or miss, surfaced by :meth:`stats`, dropped with the entry
        on eviction."""
        if tuned is not None:
            with self._lock:
                self._tuned[key] = dict(tuned)
        program = self.get(key)
        if program is not None:
            return program
        self.counters.bump("builds")
        if on_compile is not None:
            on_compile()
        # Fault-injection site (robustness/faults): a raise here is a
        # mega-run compile failure on the real build path — the queue's
        # launch isolation (serving/queue.py) decides who it poisons.
        if _faults.PLAN is not None:
            _faults.PLAN.fire("serving.compile")
        t0 = time.perf_counter()
        program = build()
        # Wall seconds per actual compile: the quantity an autotuner or
        # warm-up planner reads to decide what to pre-build (ROADMAP 4).
        _metrics.REGISTRY.histogram(
            "serving.cache.build_seconds"
        ).observe(time.perf_counter() - t0)
        self.put(key, program)
        return program

    def stats(self) -> dict:
        """Counter snapshot plus the live entry count — and, when any
        resident program was built under a tuning-DB resolution, the
        ``tuned`` provenance list (one dict per tuned program: resolved
        knobs, per-field provenance, source DB path)."""
        out = self.counters.snapshot()
        out["entries"] = len(self)
        with self._lock:
            if self._tuned:
                out["tuned"] = [dict(v) for v in self._tuned.values()]
        return out

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._tuned.clear()
        _entries_gauge(0)


#: The process-wide program cache every serving executor shares. Tests
#: that assert exact counter deltas should construct their own
#: ``ProgramCache`` (or snapshot-and-diff ``COUNTERS``).
PROGRAM_CACHE = ProgramCache(capacity=32)


def configure(capacity: Optional[int]) -> None:
    """Resize the shared cache (evicts LRU entries beyond the new cap)."""
    PROGRAM_CACHE.capacity = capacity
    if capacity is not None:
        with PROGRAM_CACHE._lock:
            while len(PROGRAM_CACHE._entries) > capacity:
                k, _ = PROGRAM_CACHE._entries.popitem(last=False)
                PROGRAM_CACHE._tuned.pop(k, None)
                PROGRAM_CACHE.counters.bump("evictions")
    _entries_gauge(len(PROGRAM_CACHE))
