"""libpga_tpu — a TPU-native genetic-algorithm framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of pbalcer/libpga
(reference: /root/reference — a CUDA C library running generational GAs with
one CUDA thread per individual, tournament selection, and pluggable
objective/crossover/mutate device functions; see `include/pga.h` for the
capability contract).

Design stance (TPU-first, not a port):

- The population is an HBM-resident ``(pop_size, genome_len)`` float array.
  The reference's double-buffered generations (``pga.h:124-129``) become
  functional updates with XLA buffer donation — no explicit swap.
- User callbacks (``obj_f``/``mutate_f``/``crossover_f``, ``pga.h:46-48``)
  are Python callables traced per-individual and ``vmap``-ed across the
  population, replacing CUDA device-function pointers.
- The whole generation step (evaluate → tournament-select → crossover →
  mutate) is ONE jitted XLA program (optionally a fused Pallas kernel),
  versus the reference's chunked kernel launches with a full device sync
  after every operator (``src/pga.cu:62-77,269``).
- Islands are sharded across TPU cores with ``shard_map``; migration — which
  the reference declared but never implemented (``pga.cu:368-374,393-395``)
  — is a ``lax.ppermute`` ring neighbor-exchange over ICI.
"""

from libpga_tpu.config import (
    AutoscaleConfig,
    FleetConfig,
    GPConfig,
    PBTConfig,
    PGAConfig,
    ServingConfig,
    SLOConfig,
    StreamingConfig,
    TenantPolicy,
)
from libpga_tpu.population import Population
from libpga_tpu.engine import PGA
from libpga_tpu.utils.telemetry import TelemetryConfig
from libpga_tpu import ops
from libpga_tpu import objectives
from libpga_tpu import parallel
from libpga_tpu import robustness
from libpga_tpu import gp
from libpga_tpu.api import (
    pga_init,
    pga_deinit,
    pga_create_population,
    pga_set_objective_function,
    pga_set_mutate_function,
    pga_set_crossover_function,
    pga_get_best,
    pga_get_best_top,
    pga_get_best_all,
    pga_get_best_top_all,
    pga_evaluate,
    pga_evaluate_all,
    pga_crossover,
    pga_crossover_all,
    pga_migrate,
    pga_migrate_between,
    pga_mutate,
    pga_mutate_all,
    pga_swap_generations,
    pga_fill_random_values,
    pga_run,
    pga_run_islands,
    RANDOM_POPULATION,
    TOURNAMENT,
)

__version__ = "0.1.0"

__all__ = [
    "PGA",
    "PGAConfig",
    "GPConfig",
    "ServingConfig",
    "SLOConfig",
    "FleetConfig",
    "TenantPolicy",
    "AutoscaleConfig",
    "StreamingConfig",
    "PBTConfig",
    "Population",
    "ops",
    "objectives",
    "parallel",
    "robustness",
    "gp",
    # C-shaped parity API
    "pga_init",
    "pga_deinit",
    "pga_create_population",
    "pga_set_objective_function",
    "pga_set_mutate_function",
    "pga_set_crossover_function",
    "pga_get_best",
    "pga_get_best_top",
    "pga_get_best_all",
    "pga_get_best_top_all",
    "pga_evaluate",
    "pga_evaluate_all",
    "pga_crossover",
    "pga_crossover_all",
    "pga_migrate",
    "pga_migrate_between",
    "pga_mutate",
    "pga_mutate_all",
    "pga_swap_generations",
    "pga_fill_random_values",
    "pga_run",
    "pga_run_islands",
    "RANDOM_POPULATION",
    "TOURNAMENT",
]
