"""Spool-resident session directory: suspended tenants any worker can
host (ISSUE 12).

A :class:`SessionStore` is a directory of suspended
:class:`~libpga_tpu.streaming.session.EvolutionSession` states under
the same atomic-rename discipline as the serving fleet's spool
(``serving/fleet.py``): every payload file (checkpoint npz, pending
tells npz) is written via temp-file + ``os.replace``, and the session
meta JSON is written LAST as the commit point — a crash mid-suspend
leaves either the previous good state or nothing, never a torn one.
``list()`` reads only committed metas.

Fleet integration: ``Fleet.session_store()`` returns the store rooted
at the fleet spool's ``sessions/`` directory, so a tenant suspended by
one worker process resumes bit-identically on ANY process that sees the
spool — the persistent-population half of "serving evolution".
"""

from __future__ import annotations

import glob
import json
import os
from typing import List, Optional

from libpga_tpu.streaming.session import EvolutionSession


class SessionStore:
    """Directory of suspended sessions, keyed by session id."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def path(self, sid: str) -> str:
        if not sid or "/" in sid or sid.startswith("."):
            raise ValueError(f"invalid session id {sid!r}")
        return os.path.join(self.root, f"{sid}.ckpt.npz")

    def suspend(self, session: EvolutionSession) -> str:
        """Suspend a session into the store under its own id."""
        return session.suspend(self.path(session.sid))

    def resume(self, sid: str, **kw) -> EvolutionSession:
        """Resume a stored session (``EvolutionSession.resume`` kwargs
        pass through — objective/config/operators)."""
        return EvolutionSession.resume(self.path(sid), **kw)

    def list(self) -> List[str]:
        """Committed session ids (meta file present), sorted."""
        out = []
        for meta in glob.glob(os.path.join(self.root, "*.session.json")):
            try:
                with open(meta) as fh:
                    out.append(json.load(fh)["session"])
            except (OSError, ValueError, KeyError):
                continue  # torn/foreign file — never committed
        return sorted(out)

    def meta(self, sid: str) -> Optional[dict]:
        meta = f"{self.path(sid)}.session.json"
        try:
            with open(meta) as fh:
                return json.load(fh)
        except FileNotFoundError:
            return None

    def discard(self, sid: str) -> None:
        """Drop a stored session (meta first, so a racing resume sees
        either the whole session or none of it)."""
        base = self.path(sid)
        for suffix in (".session.json", ".tells.npz", ".trace.jsonl", ""):
            p = f"{base}{suffix}"
            if os.path.exists(p):
                os.remove(p)
