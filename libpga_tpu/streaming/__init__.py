"""Streaming evolution service (ISSUE 12): ask/tell tenants, persistent
populations, warm engine pools, co-batched PBT.

The session layer that turns "serving runs" into "serving evolution":

- :class:`EvolutionSession` — a long-lived tenant: ``ask(k)`` /
  ``tell(genomes, fitnesses)`` / ``step(n)``, with external evaluations
  folded at generation boundaries inside the compiled engine loop
  (``engine.make_run_loop``'s injection slot) and ``step()``-only
  sessions bit-identical to plain ``PGA.run``;
- :class:`EnginePool` — warm pre-compiled engines keyed by the serving
  bucket signature, so a new tenant's first ask executes instead of
  compiling (``streaming.pool.POOL_COUNTERS`` + the
  ``streaming.pool.*`` metrics prove the 0-compile hit path);
- :class:`SessionGroup` — N same-signature sessions advanced as ONE
  mega-run, with optional population-based training across the
  co-batched runs (``StreamingConfig(pbt=PBTConfig(...))``);
- :class:`SessionStore` — suspended sessions in a spool directory any
  fleet worker can resume (``Fleet.session_store()``).
"""

from libpga_tpu.config import PBTConfig, StreamingConfig
from libpga_tpu.streaming.group import SessionGroup
from libpga_tpu.streaming.pool import POOL_COUNTERS, EnginePool
from libpga_tpu.streaming.session import EvolutionSession, make_ask_breed
from libpga_tpu.streaming.store import SessionStore

__all__ = [
    "EvolutionSession",
    "EnginePool",
    "SessionGroup",
    "SessionStore",
    "StreamingConfig",
    "PBTConfig",
    "POOL_COUNTERS",
    "make_ask_breed",
]
