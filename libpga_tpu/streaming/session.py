"""Long-lived ask/tell evolution sessions (ISSUE 12).

Every workload before this module was batch-shaped: submit a ticket, run
N generations, read one result. A :class:`EvolutionSession` is the
interactive class the ROADMAP's item 3 names — a TENANT that holds a
population open across requests and steers it:

- ``ask(k)``    — breed k candidate genomes from the current population
  for EXTERNAL evaluation (the autotuner's ask/measure/tell protocol,
  ``tuning/tuner.py``, generalized to arbitrary clients — cuPilot's
  strategy-coordination loop, PAPERS.md arxiv 2512.16465);
- ``tell(genomes, fitnesses)`` — hand externally evaluated candidates
  back; they are folded in at the NEXT GENERATION BOUNDARY (the
  ``inject_slots`` grown onto ``engine.make_run_loop``): the first
  breed after a fold selects over the told fitnesses, later
  generations re-score through the internal objective;
- ``step(n)``   — advance n generations on the internal objective.
  A session that is only ever ``step()``ped is **bit-identical** to a
  plain ``PGA.run`` of the same seed/config — the session owns a real
  :class:`~libpga_tpu.engine.PGA` and replays nothing: construction IS
  ``PGA(seed)`` + ``create_population``, so the PRNG chain, the
  telemetry history, and every composition (``pop_shards``, GP
  genomes, islands operators) hold with zero special cases;
- ``suspend(path)`` / ``resume(path)`` — persistent populations: the
  full session state (populations + PRNG key via the atomic
  ``utils/checkpoint`` machinery, pending tells + session meta via
  sidecar files, meta written LAST as the commit point — the
  ``serving/fleet.py`` atomic-rename discipline) round-trips across
  processes, so a tenant reconnecting can land on ANY fleet worker
  hosting the session directory (:class:`streaming.store.SessionStore`).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from libpga_tpu.config import PGAConfig, StreamingConfig
from libpga_tpu.engine import PGA, PopulationHandle
from libpga_tpu.ops.select import select_parent_pairs
from libpga_tpu.population import Population
from libpga_tpu.serving import cache as _cache
from libpga_tpu.utils import checkpoint as _ckpt
from libpga_tpu.utils import metrics as _metrics
from libpga_tpu.utils import telemetry as _tl
from libpga_tpu.utils.tenancy import validate_tenant

#: Session sidecar schema (the ``<path>.session.json`` commit file).
SESSION_META_VERSION = 1

_SID_LOCK = threading.Lock()
_SID_SEQ = 0


def _next_sid() -> str:
    global _SID_SEQ
    with _SID_LOCK:
        _SID_SEQ += 1
        return f"sess-{os.getpid()}-{_SID_SEQ}"


def _atomic_write_text(path: str, text: str) -> None:
    """Same temp-file + os.replace discipline as the checkpoint and the
    fleet spool: a crash mid-write never tears an existing good file."""
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as fh:
        fh.write(text)
    os.replace(tmp, path)


def make_ask_breed(
    crossover_fn: Callable,
    mutate_fn: Callable,
    k: int,
    *,
    tournament_size: int = 2,
    selection_kind: str = "tournament",
    selection_param: Optional[float] = None,
):
    """``ask(genomes, scores, key) -> (k, L) candidates``: one
    selection+variation pass producing exactly ``k`` children — the
    engine's breed semantics (``ops/step.make_breed``, same operator
    protocol: ``.batched`` / ``.rand_cols``) at candidate width instead
    of population width. No elitism: candidates are proposals for
    external evaluation, not survivors."""
    cross_batched = getattr(crossover_fn, "batched", None)
    cross_cols = getattr(crossover_fn, "rand_cols", None)
    mut_batched = getattr(mutate_fn, "batched", None)
    mut_cols = getattr(mutate_fn, "rand_cols", None)

    def ask(genomes, scores, key):
        L = genomes.shape[1]
        k_sel, k_cross, k_mut = jax.random.split(key, 3)
        i1, i2 = select_parent_pairs(
            k_sel, scores, k, k=tournament_size,
            kind=selection_kind, param=selection_param,
        )
        p1 = jnp.take(genomes, i1, axis=0)
        p2 = jnp.take(genomes, i2, axis=0)
        rand_c = jax.random.uniform(
            k_cross, (k, cross_cols or L), dtype=jnp.float32
        )
        if cross_batched is not None:
            children = cross_batched(p1, p2, rand_c)
        else:
            children = jax.vmap(crossover_fn)(p1, p2, rand_c)
        rand_m = jax.random.uniform(
            k_mut, (k, mut_cols or L), dtype=jnp.float32
        )
        if mut_batched is not None:
            out = mut_batched(children, rand_m)
        else:
            out = jax.vmap(mutate_fn)(children, rand_m)
        return out.astype(genomes.dtype)

    return ask


class EvolutionSession:
    """One streaming tenant: a persistent population + ask/tell/step.

    Construction is EXACTLY an engine construction — ``PGA(seed=seed,
    config=config)`` + ``create_population(size, genome_len)`` (or
    ``install_population(genomes)`` for non-noise representations like
    GP programs) — so a ``step()``-only session cannot diverge from a
    plain ``PGA.run`` by even a bit (final best AND telemetry history;
    pinned by ``tools/streaming_smoke.py``).
    """

    def __init__(
        self,
        objective=None,
        size: int = 0,
        genome_len: int = 0,
        seed: Optional[int] = None,
        config: Optional[PGAConfig] = None,
        streaming: Optional[StreamingConfig] = None,
        crossover: Optional[Callable] = None,
        mutate: Optional[Callable] = None,
        genomes=None,
        session_id: Optional[str] = None,
        tenant: Optional[str] = None,
        _engine: Optional[PGA] = None,
        _handle: Optional[PopulationHandle] = None,
    ):
        opened = _tl.anchored_wall()
        self.sid = session_id or _next_sid()
        self.tenant = validate_tenant(tenant)
        self.streaming = streaming or StreamingConfig()
        if _engine is not None:
            self.pga = _engine
            self.handle = _handle or PopulationHandle(0)
        else:
            self.pga = PGA(seed=seed, config=config)
            if genomes is not None:
                self.handle = self.pga.install_population(genomes)
            else:
                if size < 1 or genome_len < 1:
                    raise ValueError(
                        "EvolutionSession needs (size, genome_len) or an "
                        "explicit genomes matrix"
                    )
                self.handle = self.pga.create_population(size, genome_len)
        # Remembered for the suspend meta: a string objective resumes by
        # name alone; opaque callables must be re-provided at resume.
        self.objective_name = (
            objective if isinstance(objective, str)
            else getattr(objective, "name", None)
        )
        if objective is not None:
            self.pga.set_objective(objective)
        if crossover is not None:
            self.pga.set_crossover(crossover)
        if mutate is not None:
            self.pga.set_mutate(mutate)
        self.gens_done = 0
        # Pending external evaluations, folded at the next boundary.
        self._pending_g: List[np.ndarray] = []
        self._pending_s: List[np.ndarray] = []
        self._histories: List[_tl.History] = []
        # Session lifecycle trace (ISSUE 14): telescoping spans on the
        # anchored clock — each lifecycle operation's span runs from
        # the END of the previous one, so the spans TILE the session's
        # lifetime (the round-14 ticket-span discipline applied to a
        # long-lived tenant) and survive suspend/resume via the trace
        # sidecar.
        self._spans: List[dict] = []
        self._last_edge: float = opened
        self._closed = False
        pop = self.pga.population(self.handle)
        self._record_span("open")
        self._emit(
            "session_open", session=self.sid, tenant=self.tenant,
            population_size=pop.size, genome_len=pop.genome_len,
        )
        _metrics.REGISTRY.counter("streaming.sessions.opened").bump()
        _metrics.REGISTRY.counter(
            "streaming.tenant.sessions_opened", tenant=self.tenant
        ).bump()
        _metrics.REGISTRY.gauge(
            "streaming.tenant.sessions_active", tenant=self.tenant
        ).add(1)

    # ------------------------------------------------------------- plumbing

    def _emit(self, event: str, **fields) -> None:
        self.pga._emit(event, **fields)

    def _record_span(self, span: str, **attrs) -> dict:
        """Record one lifecycle span ending NOW and starting at the
        previous span's end (telescoping — any client idle time between
        operations is charged to the operation that ended it, exactly
        like a ticket's queue_wait). Records are schema-valid
        ``session_span`` events carrying the session and tenant ids."""
        now = _tl.anchored_wall()
        rec = _tl.trace_span_record(
            span, self._last_edge, now, session=self.sid,
            tenant=self.tenant, **attrs,
        )
        rec["event"] = "session_span"
        self._last_edge = now
        self._spans.append(rec)
        _tl.flight_note("session_span", {
            "session": self.sid, "span": span, "tenant": self.tenant,
            "t0": rec["t0"], "t1": rec["t1"],
        })
        return rec

    def trace(self) -> List[dict]:
        """The session's lifecycle span log (schema-valid
        ``session_span`` records): open → every ask/tell/step →
        suspend, persisted across suspend/resume — a tenant's trace
        survives re-hosting on another process."""
        return list(self._spans)

    def trace_coverage(self) -> float:
        """Fraction of the session's lifetime (first span start → last
        span end) covered by its spans — 1.0 by construction while the
        session lives in one process; the ≥0.95 CI gate guards the
        suspend/resume composition across processes."""
        if not self._spans:
            return 0.0
        total = self._spans[-1]["t1"] - self._spans[0]["t0"]
        if total <= 0:
            return 1.0
        covered = sum(_tl.span_ms(r) for r in self._spans) / 1e3
        return min(covered / total, 1.0)

    def close(self) -> None:
        """Mark the session closed for accounting (the active-sessions
        gauge). Idempotent; called by ``EnginePool.release``. The
        populations are untouched — suspend first to keep them."""
        if self._closed:
            return
        self._closed = True
        _metrics.REGISTRY.gauge(
            "streaming.tenant.sessions_active", tenant=self.tenant
        ).add(-1)

    @property
    def objective(self):
        return self.pga._objective

    @property
    def size(self) -> int:
        return self.pga.population(self.handle).size

    @property
    def genome_len(self) -> int:
        return self.pga.population(self.handle).genome_len

    def population(self) -> Population:
        return self.pga.population(self.handle)

    @property
    def history(self) -> Optional[_tl.History]:
        """Telemetry history of the most recent step (the engine
        contract — ``PGA.history``); ``histories`` keeps every step's."""
        return self.pga.history(self.handle)

    @property
    def histories(self) -> List[_tl.History]:
        return list(self._histories)

    @property
    def pending_tells(self) -> int:
        return sum(g.shape[0] for g in self._pending_g)

    def best(self) -> tuple:
        """(best genome host array, best score) of the current
        population under its last known scores."""
        pop = self.pga.population(self.handle)
        idx = int(jnp.argmax(pop.scores))
        return np.asarray(pop.genomes[idx]), float(pop.scores[idx])

    # -------------------------------------------------------------- ask/tell

    def tell(self, genomes, fitnesses) -> int:
        """Hand back externally evaluated candidates. Buffered host-side
        and folded at the next generation boundary (the next ``step`` —
        inside the compiled loop's injection slot — or the next ``ask``,
        host-side). Returns the pending count."""
        g = np.asarray(genomes, dtype=np.float32)
        if g.ndim == 1:
            g = g[None, :]
        s = np.asarray(fitnesses, dtype=np.float32).reshape(-1)
        L = self.genome_len
        if g.ndim != 2 or g.shape[1] != L:
            raise ValueError(
                f"tell genomes {g.shape} incompatible with genome_len {L}"
            )
        if g.shape[0] != s.shape[0]:
            raise ValueError(
                f"tell carries {g.shape[0]} genomes but {s.shape[0]} "
                "fitnesses"
            )
        if not np.isfinite(s).all():
            raise ValueError("tell fitnesses must be finite")
        self._pending_g.append(g)
        self._pending_s.append(s)
        _metrics.REGISTRY.counter("streaming.tells").bump(g.shape[0])
        _metrics.REGISTRY.counter(
            "streaming.tenant.tells", tenant=self.tenant
        ).bump(g.shape[0])
        self._record_span("tell", told=int(g.shape[0]))
        return self.pending_tells

    def take_pending(self, limit: Optional[int] = None) -> Optional[tuple]:
        """Drain (up to ``limit`` of) the pending tells as one
        ``(genomes, fitnesses)`` pair, newest last — the payload of the
        engine's injection slot. None when nothing is pending."""
        if not self._pending_g:
            return None
        g = np.concatenate(self._pending_g)
        s = np.concatenate(self._pending_s)
        cap = self.streaming.max_tell_slots
        cap = self.size if cap is None else min(cap, self.size)
        if limit is not None:
            cap = min(cap, limit)
        if g.shape[0] > cap:
            self._pending_g = [g[cap:]]
            self._pending_s = [s[cap:]]
            g, s = g[:cap], s[:cap]
        else:
            self._pending_g = []
            self._pending_s = []
        return g, s

    def _fold_pending_host(self) -> int:
        """Fold pending tells host-side (the ``ask`` boundary — no
        compiled loop runs, so the fold is a numpy scatter): told
        candidates replace the worst-scoring rows and their fitnesses
        are INSTALLED as those rows' scores, so the very next ask
        selects over them."""
        pending = self.take_pending()
        if pending is None:
            return 0
        g, s = pending
        pop = self.pga.population(self.handle)
        scores = np.array(pop.scores, dtype=np.float32)
        m = g.shape[0]
        worst = np.argsort(scores)[:m]
        genomes = np.asarray(pop.genomes).copy()
        genomes[worst] = g.astype(genomes.dtype)
        scores[worst] = s
        self.pga._populations[self.handle.index] = Population(
            genomes=jnp.asarray(
                genomes, dtype=self.pga.config.gene_dtype
            ),
            scores=jnp.asarray(scores),
        )
        self.pga._staged[self.handle.index] = None
        self._emit("session_fold", session=self.sid, folded=m, where="ask")
        _metrics.REGISTRY.counter("streaming.folds").bump(m)
        _metrics.REGISTRY.counter(
            "streaming.tenant.injected", tenant=self.tenant
        ).bump(m)
        return m

    def ask(self, k: int) -> np.ndarray:
        """Propose ``k`` candidate genomes for external evaluation, bred
        from the current population (tournament/ranked selection over
        the last known fitnesses — internal evaluations and told values
        alike). Pending tells fold first, so a tell→ask round trip
        selects over the told fitnesses. Before ANY fitness is known
        (fresh session, no tells, never stepped) the first ``k``
        population rows are returned unchanged — they are random, and
        breeding over uniform ``-inf`` scores would only pretend to
        select."""
        if k < 1:
            raise ValueError("ask k must be >= 1")
        if k > self.size:
            raise ValueError(f"ask k={k} exceeds population size {self.size}")
        t0 = time.perf_counter()
        try:
            self._fold_pending_host()
            pop = self.pga.population(self.handle)
            scores = np.asarray(pop.scores, dtype=np.float32)
            if not np.isfinite(scores).any():
                return np.asarray(pop.genomes[:k], dtype=np.float32)
            fn = self._ask_program(k)
            with _tl.span("ask"):
                out = fn(pop.genomes, pop.scores, self.pga.next_key())
            return np.asarray(out, dtype=np.float32)
        finally:
            _metrics.REGISTRY.counter(
                "streaming.tenant.asks", tenant=self.tenant
            ).bump()
            _metrics.REGISTRY.histogram(
                "streaming.tenant.ask_ms", tenant=self.tenant
            ).observe((time.perf_counter() - t0) * 1e3)
            self._record_span("ask", k=int(k))

    def _ask_program(self, k: int):
        """Compiled ask breed for candidate width ``k`` — shared
        process-wide through the serving program cache, so every session
        of one signature compiles it once (the warm-pool stats the CI
        smoke asserts count these builds too)."""
        cfg = self.pga.config
        key = (
            "streaming/ask", k, self.size, self.genome_len,
            self.pga._crossover, self.pga._mutate,
            cfg.tournament_size, cfg.selection, cfg.selection_param,
            np.dtype(cfg.gene_dtype).name,
        )

        def build():
            ask = make_ask_breed(
                self.pga._crossover, self.pga._mutate, k,
                tournament_size=cfg.tournament_size,
                selection_kind=cfg.selection,
                selection_param=cfg.selection_param,
            )
            return jax.jit(ask)

        def on_compile():
            self._emit(
                "compile", what="streaming_ask", k=k,
                population_size=self.size, genome_len=self.genome_len,
            )

        return _cache.PROGRAM_CACHE.get_or_build(
            key, build, on_compile=on_compile
        )

    # ------------------------------------------------------------------ step

    def step(self, n: int, target: Optional[float] = None) -> int:
        """Advance up to ``n`` generations on the internal objective.
        Pending tells fold at the boundary inside the compiled loop
        (``engine.make_run_loop``'s injection slot); with none pending
        this IS ``PGA.run`` — the bit-identity anchor."""
        t0 = time.perf_counter()
        inject = self.take_pending()
        if inject is not None:
            self._emit(
                "session_fold", session=self.sid,
                folded=int(inject[0].shape[0]), where="step",
            )
            _metrics.REGISTRY.counter("streaming.folds").bump(
                inject[0].shape[0]
            )
            _metrics.REGISTRY.counter(
                "streaming.tenant.injected", tenant=self.tenant
            ).bump(inject[0].shape[0])
        gens = self.pga.run(
            n, target=target, population=self.handle, inject=inject
        )
        self.gens_done += gens
        hist = self.pga.history(self.handle)
        if hist is not None:
            self._histories.append(hist)
        _metrics.REGISTRY.counter(
            "streaming.tenant.steps", tenant=self.tenant
        ).bump()
        _metrics.REGISTRY.histogram(
            "streaming.tenant.step_ms", tenant=self.tenant
        ).observe((time.perf_counter() - t0) * 1e3)
        self._record_span("step", gens=int(gens))
        return gens

    # ------------------------------------------------------- suspend/resume

    def suspend(self, path: str) -> str:
        """Write the session durably to ``path``: the engine checkpoint
        (atomic, CRC-manifested — ``utils/checkpoint``), a pending-tells
        sidecar, and the session meta JSON LAST as the commit point.
        The session object stays usable; a tenant reconnecting anywhere
        the files are visible resumes bit-identically."""
        self._record_span("suspend")
        _ckpt.save(self.pga, path)
        tells_path = f"{path}.tells.npz"
        if self._pending_g:
            _ckpt._atomic_savez(tells_path, {
                "genomes": np.concatenate(self._pending_g),
                "fitness": np.concatenate(self._pending_s),
            })
        elif os.path.exists(tells_path):
            os.remove(tells_path)
        # Lifecycle trace sidecar (ISSUE 14): the session's span log
        # rides the suspension, so a tenant's trace survives re-hosting
        # — written BEFORE the meta (the commit point), atomic like
        # every other payload file.
        _atomic_write_text(
            f"{path}.trace.jsonl",
            "".join(json.dumps(r, default=str) + "\n"
                    for r in self._spans),
        )
        cfg = self.pga.config
        obj = self.pga._objective
        meta = {
            "version": SESSION_META_VERSION,
            "session": self.sid,
            "tenant": self.tenant,
            "population_size": self.size,
            "genome_len": self.genome_len,
            "gens_done": self.gens_done,
            "pending_tells": self.pending_tells,
            "objective": self.objective_name or getattr(obj, "name", None),
            "config": {
                "tournament_size": cfg.tournament_size,
                "selection": cfg.selection,
                "selection_param": cfg.selection_param,
                "mutation_rate": cfg.mutation_rate,
                "elitism": cfg.elitism,
                "gene_dtype": np.dtype(cfg.gene_dtype).name,
                "pop_shards": cfg.pop_shards,
                "use_pallas": cfg.use_pallas,
                "history_gens": (
                    None if cfg.telemetry is None
                    else cfg.telemetry.history_gens
                ),
            },
        }
        _atomic_write_text(
            f"{path}.session.json",
            json.dumps(meta, sort_keys=True) + "\n",
        )
        self._emit(
            "session_suspend", session=self.sid, path=path,
            tenant=self.tenant,
        )
        _metrics.REGISTRY.counter("streaming.sessions.suspended").bump()
        _metrics.REGISTRY.counter(
            "streaming.tenant.suspends", tenant=self.tenant
        ).bump()
        return path

    @classmethod
    def resume(
        cls,
        path: str,
        objective=None,
        config: Optional[PGAConfig] = None,
        streaming: Optional[StreamingConfig] = None,
        crossover: Optional[Callable] = None,
        mutate: Optional[Callable] = None,
    ) -> "EvolutionSession":
        """Restore a suspended session bit-identically: populations and
        the PRNG key come back through ``checkpoint.restore`` (so the
        next ``step`` splits the exact key the uninterrupted session
        would have), pending tells from the sidecar. ``objective`` (and
        any custom operators) must be re-provided unless the suspended
        objective was a named builtin recorded in the meta. ``config``
        defaults to the serialized config fields (telemetry excluded —
        pass a config to re-enable history/events)."""
        meta_path = f"{path}.session.json"
        try:
            with open(meta_path) as fh:
                meta = json.load(fh)
        except FileNotFoundError:
            raise FileNotFoundError(
                f"no suspended session at {path} ({meta_path} missing — "
                "suspend() writes it last, so the session never committed)"
            )
        if int(meta.get("version", -1)) != SESSION_META_VERSION:
            raise _ckpt.CheckpointError(
                f"unsupported session meta version {meta.get('version')}",
                meta_path,
            )
        if config is None:
            c = meta["config"]
            import ml_dtypes

            dtype = (
                jnp.float32 if c["gene_dtype"] == "float32"
                else np.dtype(getattr(ml_dtypes, c["gene_dtype"]))
                if hasattr(ml_dtypes, c["gene_dtype"])
                else np.dtype(c["gene_dtype"])
            )
            from libpga_tpu.utils.telemetry import TelemetryConfig

            config = PGAConfig(
                tournament_size=c["tournament_size"],
                selection=c["selection"],
                selection_param=c["selection_param"],
                mutation_rate=c["mutation_rate"],
                elitism=c["elitism"],
                gene_dtype=dtype,
                pop_shards=c["pop_shards"],
                use_pallas=c["use_pallas"],
                telemetry=(
                    None if not c.get("history_gens")
                    else TelemetryConfig(history_gens=c["history_gens"])
                ),
            )
        if objective is None:
            objective = meta.get("objective")
            if objective is None:
                raise ValueError(
                    "suspended session has no named objective — pass "
                    "objective= to resume()"
                )
        pga = PGA(seed=0, config=config)
        _ckpt.restore(pga, path)
        session = cls(
            objective=objective,
            streaming=streaming,
            crossover=crossover,
            mutate=mutate,
            session_id=meta["session"],
            tenant=meta.get("tenant"),
            _engine=pga,
            _handle=PopulationHandle(0),
        )
        session.gens_done = int(meta.get("gens_done", 0))
        tells_path = f"{path}.tells.npz"
        if os.path.exists(tells_path):
            with np.load(tells_path) as data:
                session._pending_g = [np.asarray(data["genomes"])]
                session._pending_s = [np.asarray(data["fitness"])]
        # Rejoin the suspended lifecycle trace (ISSUE 14): the restored
        # span log replaces this construction's "open" span, and the
        # resume span telescopes from the suspend edge — anchored walls
        # agree across the processes of one host, so the trace keeps
        # tiling the session's WHOLE lifetime across the re-hosting.
        trace_path = f"{path}.trace.jsonl"
        try:
            with open(trace_path, encoding="utf-8") as fh:
                prior = [
                    json.loads(line) for line in fh
                    if line.strip()
                ]
        except (OSError, ValueError):
            prior = []
        if prior:
            session._spans = prior
            session._last_edge = float(prior[-1]["t1"])
        session._record_span("resume")
        session._emit(
            "session_resume", session=session.sid, path=path,
            tenant=session.tenant,
        )
        _metrics.REGISTRY.counter("streaming.sessions.resumed").bump()
        return session
