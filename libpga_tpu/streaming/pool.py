"""Warm engine pools: a tenant's first ask costs milliseconds, not a
compile (ISSUE 12).

A cold :class:`~libpga_tpu.streaming.session.EvolutionSession` pays the
full trace+compile pipeline on its first ``step``/``ask`` — on the CPU
host that is ~hundreds of milliseconds; on a TPU with Mosaic kernels it
is tens of seconds. The pool removes that cost from the tenant path the
same way the serving cache (``serving/cache.py``) removes it from the
batch path, and reuses its SIGNATURE discipline: engines are keyed by
the exact tuple of everything baked into their compiled programs —
shape, objective, operator instances, and
``PGAConfig.serving_signature_fields()`` — so two tenants share warm
state iff they could share a compiled program.

Three mechanisms, cheapest first:

- **engine reuse** — a released session's engine returns to the pool
  with its ``_compiled`` programs intact; ``acquire`` resets ONLY its
  PRNG/population state to the new tenant's seed (the reset replays the
  ``PGA(seed)`` construction exactly, so a pooled session stays
  bit-identical to a fresh one — pinned in tests);
- **compiled-program sharing** — engines of one signature share their
  compiled-program dict entries (the cache keys are equal because the
  pool hands every engine the same objective/operator instances), so
  even a pool that must GROW under concurrent tenants compiles each
  program once;
- **prewarm** — ``prewarm()`` (and ``acquire`` on a cold signature,
  when ``StreamingConfig.prewarm``) compiles the run program eagerly
  with one zero-generation dispatch — the engine-path analog of the
  serving cache's AOT ``lower().compile()`` warm-up.

``hits``/``misses``/``prewarms`` land in the round-11 metrics registry
(``streaming.pool.*``) and in :data:`POOL_COUNTERS` for exact-delta
asserts (the CI smoke proves a pooled signature compiles 0 programs).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

import jax

from libpga_tpu.config import PGAConfig, StreamingConfig
from libpga_tpu.engine import PGA, _kind_key
from libpga_tpu.streaming.session import EvolutionSession
from libpga_tpu.utils import metrics as _metrics
from libpga_tpu.utils.metrics import Counters

#: Module-level counter set: hits / misses / prewarms / releases.
POOL_COUNTERS = Counters()


class EnginePool:
    """Pool of pre-compiled, pre-warmed engines keyed by bucket
    signature. Thread-safe (tenant handlers race on acquire/release)."""

    def __init__(
        self,
        config: Optional[PGAConfig] = None,
        streaming: Optional[StreamingConfig] = None,
        counters: Optional[Counters] = None,
    ):
        self.config = config or PGAConfig()
        self.streaming = streaming or StreamingConfig()
        self.counters = counters if counters is not None else POOL_COUNTERS
        self._lock = threading.Lock()
        # signature -> {"idle": [PGA...], "objective", "crossover",
        #               "mutate", "compiled": shared template dict}
        self._entries: Dict[tuple, dict] = {}

    # ------------------------------------------------------------ signature

    def signature(
        self, objective, size: int, genome_len: int,
        crossover=None, mutate=None,
    ) -> tuple:
        """The warm-pool bucket signature: the serving signature
        discipline (everything baked into a compiled program) applied
        to engine-path sessions."""
        return (
            "streaming/engine", size, genome_len, objective,
            _kind_key(crossover), _kind_key(mutate),
            self.config.serving_signature_fields(),
        )

    def _gauge(self) -> None:
        with self._lock:
            n = sum(len(e["idle"]) for e in self._entries.values())
        _metrics.REGISTRY.gauge("streaming.pool.idle").set(n)

    # --------------------------------------------------------------- warmup

    def _warm_engine(self, eng: PGA, size: int, genome_len: int) -> None:
        """Compile the run program eagerly: one zero-generation dispatch
        at the real shape fills the jit wrapper's executable cache, so
        the tenant's first step only executes. Consumes no engine PRNG
        state (the dummy key is synthesized here)."""
        import jax.numpy as jnp

        fn, _ = eng._compiled_run_meta(size, genome_len)
        dummy = jnp.zeros((size, genome_len), dtype=eng.config.gene_dtype)
        fn(
            dummy, jax.random.key(0), jnp.int32(0), jnp.float32(jnp.inf),
            eng._mutate_params(),
        )

    def prewarm(
        self, objective, size: int, genome_len: int,
        crossover=None, mutate=None,
    ) -> None:
        """Admit a signature and compile its programs ahead of the first
        tenant. Idempotent; parks one warm idle engine."""
        if isinstance(objective, str):
            from libpga_tpu import objectives

            objective = objectives.get(objective)
        sig = self.signature(objective, size, genome_len, crossover, mutate)
        with self._lock:
            entry = self._entries.get(sig)
            if entry is not None and entry["idle"]:
                return
        eng = self._fresh_engine(sig, objective, crossover, mutate, seed=0)
        self._warm_engine(eng, size, genome_len)
        self.counters.bump("prewarms")
        _metrics.REGISTRY.counter("streaming.pool.prewarms").bump()
        with self._lock:
            entry = self._entries[sig]
            entry["compiled"].update(eng._compiled)
            self._reset_engine(eng, 0)
            entry["idle"].append(eng)
        self._gauge()

    # -------------------------------------------------------------- engines

    def _entry(self, sig: tuple, objective, crossover, mutate) -> dict:
        with self._lock:
            entry = self._entries.get(sig)
            if entry is None:
                entry = {
                    "idle": [], "objective": objective,
                    "crossover": crossover, "mutate": mutate,
                    "compiled": {},
                }
                self._entries[sig] = entry
            return entry

    def _fresh_engine(
        self, sig: tuple, objective, crossover, mutate, seed,
    ) -> PGA:
        entry = self._entry(sig, objective, crossover, mutate)
        eng = PGA(seed=seed, config=self.config)
        # The pool's canonical operator instances make the compiled-
        # program cache keys EQUAL across this signature's engines, so
        # the shared template dict below actually shares programs.
        eng.set_objective(entry["objective"])
        if entry["crossover"] is not None:
            eng.set_crossover(entry["crossover"])
        if entry["mutate"] is not None:
            eng.set_mutate(entry["mutate"])
        eng._compiled.update(entry["compiled"])
        return eng

    @staticmethod
    def _reset_engine(eng: PGA, seed: Optional[int]) -> None:
        """Replay the ``PGA(seed)`` construction on a pooled engine:
        fresh key chain, no populations — everything EXCEPT the compiled
        programs, which are the point of the pool."""
        if seed is None:
            import os

            seed = int.from_bytes(os.urandom(4), "little")
        eng._key = jax.random.key(seed)
        eng._populations = []
        eng._staged = []
        eng._history = []

    # ------------------------------------------------------ acquire/release

    def acquire(
        self,
        objective,
        size: int,
        genome_len: int,
        seed: Optional[int] = None,
        crossover=None,
        mutate=None,
        genomes=None,
        session_id: Optional[str] = None,
        tenant: Optional[str] = None,
    ) -> EvolutionSession:
        """A warm :class:`EvolutionSession` for one tenant: a pooled
        engine when the signature is warm (hit — 0 compiles), a fresh
        one otherwise (miss — prewarmed per ``StreamingConfig.prewarm``
        before the session sees it). Bit-identity with a cold session
        holds either way. ``tenant`` (ISSUE 14) attributes the session
        and this acquire's warm-pool hit/miss."""
        from libpga_tpu.utils.tenancy import validate_tenant

        tenant_id = validate_tenant(tenant)
        objective_name = objective if isinstance(objective, str) else None
        if isinstance(objective, str):
            from libpga_tpu import objectives

            objective = objectives.get(objective)
        sig = self.signature(objective, size, genome_len, crossover, mutate)
        eng = None
        with self._lock:
            entry = self._entries.get(sig)
            if entry is not None and entry["idle"]:
                eng = entry["idle"].pop()
        if eng is not None:
            self.counters.bump("hits")
            _metrics.REGISTRY.counter("streaming.pool.hits").bump()
            _metrics.REGISTRY.counter(
                "streaming.tenant.pool_hits", tenant=tenant_id
            ).bump()
            self._reset_engine(eng, seed)
        else:
            self.counters.bump("misses")
            _metrics.REGISTRY.counter("streaming.pool.misses").bump()
            _metrics.REGISTRY.counter(
                "streaming.tenant.pool_misses", tenant=tenant_id
            ).bump()
            eng = self._fresh_engine(
                sig, objective, crossover, mutate, seed
            )
            if self.streaming.prewarm and genomes is None:
                t0 = time.perf_counter()
                self._warm_engine(eng, size, genome_len)
                _metrics.REGISTRY.histogram(
                    "streaming.pool.prewarm_seconds"
                ).observe(time.perf_counter() - t0)
                # The dummy dispatch consumed nothing from the tenant's
                # chain, but set_* cleared per-op caches — re-share.
                with self._lock:
                    self._entries[sig]["compiled"].update(eng._compiled)
        self._gauge()
        # Create the tenant's population through the engine exactly like
        # a cold construction would — this consumes the first key split
        # of the fresh chain, which is what keeps pooled sessions
        # bit-identical to cold ones.
        if genomes is not None:
            handle = eng.install_population(genomes)
        else:
            handle = eng.create_population(size, genome_len)
        session = EvolutionSession(
            streaming=self.streaming,
            session_id=session_id,
            tenant=tenant,
            _engine=eng,
            _handle=handle,
        )
        session.objective_name = objective_name
        session._pool = (self, sig)
        return session

    def release(self, session: EvolutionSession) -> None:
        """Return a session's engine to the pool (idle, populations
        dropped, compiled programs kept). Suspend first if the tenant
        may come back — release alone discards the population."""
        pool_mark = getattr(session, "_pool", None)
        if pool_mark is None or pool_mark[0] is not self:
            raise ValueError("session was not acquired from this pool")
        _, sig = pool_mark
        eng = session.pga
        session._pool = None
        session.close()  # active-sessions accounting (idempotent)
        self.counters.bump("releases")
        with self._lock:
            entry = self._entries.get(sig)
            if entry is None:
                return
            entry["compiled"].update(eng._compiled)
            cap = self.streaming.pool_capacity
            if cap is None or len(entry["idle"]) < cap:
                self._reset_engine(eng, 0)
                entry["idle"].append(eng)
        self._gauge()

    def stats(self) -> dict:
        out = self.counters.snapshot()
        with self._lock:
            out["signatures"] = len(self._entries)
            out["idle"] = sum(
                len(e["idle"]) for e in self._entries.values()
            )
        return out
