"""Co-batched session stepping + population-based training (ISSUE 12).

N same-signature sessions stepped one at a time cost N dispatches of N
programs' worth of launch overhead; a :class:`SessionGroup` advances
them as ONE compiled mega-run over a leading run axis — the round-9
serving layout (``serving/batch.py``) driven by live sessions instead
of one-shot requests. Each session contributes its current population,
its next engine key split, and its runtime mutation parameters; results
install back into each session's engine, so group stepping is
**bit-identical** to stepping every session individually (the breed is
``ops/step.make_param_breed``, whose equal-parameter trace is the
engine breed's — the serving bit-exactness contract).

The group's program always carries the ``inject_slots`` boundary fold
(``engine.make_run_loop``) at a fixed width ``tell_slots``: sessions
with pending tells fold them INSIDE the loop (told fitnesses seed the
next selection); sessions without pending pass ``inj_n = 0``, and the
zero-mask fold writes back exactly the values it read — so a no-tell
session's group step stays bit-identical to its solo step
(tests/test_streaming.py pins both).

**PBT** (``StreamingConfig(pbt=PBTConfig(...))``): at every
``epoch_gens`` boundary the group argsorts the sessions by best fitness
— one cross-run argsort over N scalars — and each bottom-quantile
session copies its mutation rate/sigma from a top-quantile partner,
then perturbs (exploit/explore). Rate/sigma are RUNTIME inputs of the
shared program, so adaptation never recompiles. Off by default;
``pbt=None`` never touches a session's parameters.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from libpga_tpu.config import StreamingConfig
from libpga_tpu.engine import make_run_loop
from libpga_tpu.ops.step import make_param_breed
from libpga_tpu.population import Population
from libpga_tpu.serving import cache as _cache
from libpga_tpu.streaming.session import EvolutionSession
from libpga_tpu.utils import metrics as _metrics
from libpga_tpu.utils import telemetry as _tl


class SessionGroup:
    """Advance N same-signature sessions as one compiled mega-run.

    Sessions must share shape, objective, config signature, and
    operator KINDS with a runtime-parameter form (the builtin
    point/gaussian/swap mutations and any ``param_batched`` callable —
    ``ops/step.make_param_breed``'s contract).
    """

    def __init__(
        self,
        sessions: Sequence[EvolutionSession],
        streaming: Optional[StreamingConfig] = None,
        tell_slots: int = 8,
        layout: Optional[str] = None,
    ):
        if not sessions:
            raise ValueError("SessionGroup needs at least one session")
        self.sessions: List[EvolutionSession] = list(sessions)
        self.streaming = streaming or sessions[0].streaming
        lead = sessions[0]
        self.size = lead.size
        self.genome_len = lead.genome_len
        self.tell_slots = min(int(tell_slots), self.size)
        if self.tell_slots < 1:
            raise ValueError("tell_slots must be >= 1")
        self._epoch = 0
        eng = lead.pga
        self._objective = eng._require_objective()
        self._mutate_kind = eng._mutate_kind()
        if self._mutate_kind is None:
            raise ValueError(
                "group stepping needs a runtime-parameter mutation kind "
                "(builtin point/gaussian/swap or a param_batched operator)"
            )
        self._crossover = eng._crossover
        self._config = eng.config
        mark = self._signature(lead)
        for s in sessions[1:]:
            if self._signature(s) != mark:
                raise ValueError(
                    "group sessions must share one bucket signature "
                    "(shape, objective, operators, config)"
                )
        # Per-session runtime mutation parameters — the PBT-adapted
        # state. Seeded from each engine's own operator resolution so a
        # group step of an unadapted session equals its solo step.
        self._mparams = [
            np.asarray(
                [[s.pga._mutation_rate(),
                  s.pga._operator_param("sigma", 0.0)]], np.float32
            )
            for s in self.sessions
        ]
        if layout is None:
            try:
                backend = jax.default_backend()
            except RuntimeError:
                backend = "cpu"
            layout = "run_major" if backend == "cpu" else "lockstep"
        self.layout = layout

    def _signature(self, s: EvolutionSession) -> tuple:
        from libpga_tpu.engine import _kind_key

        eng = s.pga
        return (
            s.size, s.genome_len, eng._objective,
            _kind_key(eng._crossover_kind()),
            _kind_key(eng._mutate_kind()),
            eng.config.serving_signature_fields(),
        )

    # ------------------------------------------------------------- program

    def mutation_params(self, i: int) -> tuple:
        """(rate, sigma) currently applied to session ``i`` — the
        PBT-adapted values, runtime inputs of the shared program."""
        return float(self._mparams[i][0, 0]), float(self._mparams[i][0, 1])

    def _hist_gens(self) -> Optional[int]:
        t = self._config.telemetry
        return (
            t.history_gens if t is not None and t.history_gens > 0 else None
        )

    def _program(self, N: int):
        cfg = self._config
        hist = self._hist_gens()
        K = self.tell_slots
        key = (
            "streaming/group", N, self.size, self.genome_len,
            self._objective, self._crossover,
            ("kind", getattr(self._mutate_kind, "kernel_cache_key",
                             self._mutate_kind)),
            cfg.serving_signature_fields(), K, self.layout,
        )

        def build():
            breed = make_param_breed(
                self._crossover,
                self._mutate_kind,
                tournament_size=cfg.tournament_size,
                selection_kind=cfg.selection,
                selection_param=cfg.selection_param,
                elitism=cfg.elitism,
            )
            run_loop = make_run_loop(
                self._objective, breed, hist, inject_slots=K
            )
            if self.layout == "lockstep":

                def mega(genomes, key_data, n, target, mparams,
                         inj_g, inj_s, inj_n):
                    keys = jax.random.wrap_key_data(key_data)
                    return jax.vmap(run_loop)(
                        genomes, keys, n, target, mparams,
                        inj_g, inj_s, inj_n,
                    )

            else:

                def mega(genomes, key_data, n, target, mparams,
                         inj_g, inj_s, inj_n):
                    keys = jax.random.wrap_key_data(key_data)

                    def one(carry, xs):
                        return carry, run_loop(*xs)

                    _, out = jax.lax.scan(
                        one, 0,
                        (genomes, keys, n, target, mparams,
                         inj_g, inj_s, inj_n),
                    )
                    return out

            donate = (0,) if cfg.donate_buffers else ()
            return jax.jit(mega, donate_argnums=donate)

        def on_compile():
            self.sessions[0]._emit(
                "compile", what="streaming_group", batch_width=N,
                population_size=self.size, genome_len=self.genome_len,
                tell_slots=K,
            )

        return _cache.PROGRAM_CACHE.get_or_build(
            key, build, on_compile=on_compile
        )

    # ---------------------------------------------------------------- step

    def _step_once(self, n: int, target: Optional[float]) -> None:
        """One co-batched advance of every session by up to ``n``
        generations (one device program)."""
        N = len(self.sessions)
        K = self.tell_slots
        L = self.genome_len
        genomes, key_data, mparams = [], [], []
        inj_g = np.zeros((N, K, L), np.float32)
        inj_s = np.full((N, K), -np.inf, np.float32)
        inj_n = np.zeros((N,), np.int32)
        for i, s in enumerate(self.sessions):
            pending = s.take_pending(limit=K)
            if pending is not None:
                g, f = pending
                m = g.shape[0]
                inj_g[i, :m] = g
                inj_s[i, :m] = f
                inj_n[i] = m
                s._emit(
                    "session_fold", session=s.sid, folded=int(m),
                    where="group_step",
                )
                _metrics.REGISTRY.counter("streaming.folds").bump(m)
                _metrics.REGISTRY.counter(
                    "streaming.tenant.injected", tenant=s.tenant
                ).bump(m)
            pop = s.pga.population(s.handle)
            genomes.append(pop.genomes)
            key_data.append(jax.random.key_data(s.pga.next_key()))
            mparams.append(self._mparams[i])
        fn = self._program(N)
        tgt = np.float32(np.inf if target is None else target)
        with _tl.span("group_step"):
            out = fn(
                jnp.stack(genomes),
                jnp.stack(key_data).astype(jnp.uint32),
                jnp.full((N,), n, jnp.int32),
                jnp.full((N,), tgt, jnp.float32),
                jnp.stack([jnp.asarray(m) for m in mparams]),
                jnp.asarray(inj_g), jnp.asarray(inj_s),
                jnp.asarray(inj_n),
            )
        g, s_, gens = out[:3]
        buf = out[3] if len(out) > 3 else None
        hist_gens = self._hist_gens()
        for i, sess in enumerate(self.sessions):
            sess.pga._populations[sess.handle.index] = Population(
                genomes=g[i], scores=s_[i]
            )
            sess.pga._staged[sess.handle.index] = None
            done = int(gens[i])
            sess.gens_done += done
            hist = None
            if buf is not None and hist_gens:
                hist = _tl.History(buf[i], done)
                sess._histories.append(hist)
            sess.pga._history[sess.handle.index] = hist
            # Each co-batched session's lifecycle trace keeps tiling
            # (ISSUE 14): a group step is that session's step.
            sess._record_span("group_step", gens=done)
            _metrics.REGISTRY.counter(
                "streaming.tenant.steps", tenant=sess.tenant
            ).bump()

    def step(self, n: int, target: Optional[float] = None) -> int:
        """Advance every session ``n`` generations. With PBT enabled the
        advance is chunked at ``PBTConfig.epoch_gens`` boundaries and
        the exploit/explore pass runs between chunks. Returns the
        generations advanced (``n``)."""
        pbt = self.streaming.pbt
        if pbt is None:
            self._step_once(n, target)
            return n
        left = n
        while left > 0:
            chunk = min(left, pbt.epoch_gens)
            self._step_once(chunk, target)
            left -= chunk
            if left > 0 or chunk == pbt.epoch_gens:
                self._pbt_epoch()
        return n

    # ----------------------------------------------------------------- pbt

    def _pbt_epoch(self) -> None:
        """One exploit/explore pass: ONE cross-run argsort over the
        sessions' best fitnesses, then a parameter copy + perturbation
        for the bottom quantile. Deterministic (epoch-indexed PRNG)."""
        pbt = self.streaming.pbt
        N = len(self.sessions)
        q = max(1, int(N * pbt.exploit_frac))
        if N < 2:
            return
        self._epoch += 1
        best = np.asarray([
            float(jnp.max(s.pga.population(s.handle).scores))
            for s in self.sessions
        ])
        order = np.argsort(best)  # ascending: worst first
        bottom, top = order[:q], order[-q:]
        rng = np.random.default_rng(pbt.seed * 1_000_003 + self._epoch)
        moved = 0
        for idx in bottom:
            partner = int(rng.choice(top))
            rate, sigma = self._mparams[partner][0]
            factor = (
                pbt.explore_factor
                if rng.random() < 0.5 else 1.0 / pbt.explore_factor
            )
            rate = float(np.clip(rate * factor, *pbt.rate_bounds))
            sigma = float(np.clip(sigma, *pbt.sigma_bounds))
            self._mparams[idx] = np.asarray(
                [[rate, sigma]], np.float32
            )
            moved += 1
        _metrics.REGISTRY.counter("streaming.pbt.exploits").bump(moved)
        self.sessions[0]._emit(
            "pbt_epoch", epoch=self._epoch, exploited=moved,
            best=float(best.max()),
        )
