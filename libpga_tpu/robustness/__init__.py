"""Fault-tolerant execution layer (ISSUE 5).

The reference's entire correctness net is ``CUDA_CALL`` exit-on-error
(``src/pga.cu:24-31``): any fault kills the process and loses the run.
This package is the opposite stance — every long-running entry point
survives the failure modes we can name, and we can *prove* it with
injected faults:

- :mod:`libpga_tpu.robustness.faults` — a process-global,
  seed-deterministic fault-injection registry. Injection sites are
  threaded through the REAL code paths (kernel build, serving compile,
  objective evaluation, checkpoint I/O, the serving flusher thread);
  with no plan installed every site is a single ``PLAN is None``
  attribute read, so production lowering and hot paths are untouched.
- :mod:`libpga_tpu.robustness.supervisor` — ``supervised_run``: retry
  with exponential backoff + deterministic jitter, periodic
  auto-checkpoint through the atomic ``utils/checkpoint.save``, crash
  resume that replays the engine key chain (a supervised run that died
  and resumed is bit-identical to an uninterrupted same-seed run), and
  a stall watchdog fed by the telemetry stall counter.

Graceful kernel degradation (``PGAConfig(fallback=...)``) and serving
failure isolation (dead-letter + bounded requeue + backpressure) live
in the engine and ``serving/`` respectively; ``tools/chaos_smoke.py``
drives the whole matrix.
"""

from libpga_tpu.robustness.faults import (
    FaultPlan,
    FaultRegistry,
    InjectedFault,
    SITES,
    active,
    clear,
    install,
)

__all__ = [
    "FaultPlan",
    "FaultRegistry",
    "InjectedFault",
    "SITES",
    "active",
    "clear",
    "install",
    # lazily resolved (see __getattr__): supervisor surface
    "supervised_run",
    "RetryPolicy",
    "SupervisedReport",
    "NaNStorm",
]

# The supervisor imports utils/checkpoint (which itself reaches back to
# the fault registry for its injection sites); importing it lazily keeps
# ``robustness.faults`` importable from anywhere in the package without
# a cycle.
_SUPERVISOR_NAMES = (
    "supervised_run", "RetryPolicy", "SupervisedReport", "NaNStorm",
    "supervisor",
)


def __getattr__(name):
    if name in _SUPERVISOR_NAMES:
        # importlib, not ``from ... import supervisor``: the from-form
        # probes the package attribute first (PEP 562), which re-enters
        # this __getattr__ before the submodule import ever starts —
        # infinite recursion on the first lazy access.
        import importlib

        supervisor = importlib.import_module(
            "libpga_tpu.robustness.supervisor"
        )
        if name == "supervisor":
            return supervisor
        return getattr(supervisor, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
