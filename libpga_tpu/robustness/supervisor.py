"""Supervised execution: retry, backoff, auto-checkpoint, stall watchdog.

``PGA.run`` is fail-fast: any exception propagates and the run's progress
since the last *manual* checkpoint is gone — the Python analog of the
reference's ``CUDA_CALL`` exit-on-error (``src/pga.cu:24-31``).
:func:`supervised_run` is the layer you leave running:

- the run executes in CHUNKS of ``checkpoint_every`` generations, each
  followed by an atomic :func:`libpga_tpu.utils.checkpoint.save` plus a
  tiny JSON progress sidecar (``<path>.meta.json``);
- a failing chunk is retried with exponential backoff + deterministic
  jitter after ROLLING BACK to the pre-chunk snapshot (PRNG key +
  populations), so the retry replays the exact key chain — a supervised
  run that failed and retried is bit-identical to one that never failed;
- a process death between chunks is recovered by calling
  :func:`supervised_run` again with ``resume=True``: the engine restores
  the last durable checkpoint (populations + PRNG key) and continues
  from the recorded generation count — again bit-identical to an
  uninterrupted same-seed supervised run with the same cadence (the
  contract ``tools/chaos_smoke.py`` proves with injected faults);
- NaN-storm detection: a chunk that completes with NaN scores is treated
  as a failure (rolled back + retried) — deterministic NaN sources
  exhaust the retries and raise :class:`NaNStorm` instead of silently
  burning the remaining budget on a poisoned population;
- a STALL WATCHDOG fed by the telemetry stall counter
  (``TelemetryConfig(history_gens=...)``) aborts-and-reports once the
  best score has not improved for ``stall_abort_gens`` generations,
  instead of burning the rest of the budget (the engine's existing
  ``stall_alert`` event fires on the same counter).
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import time
from typing import Callable, List, Optional, TYPE_CHECKING, Tuple

from libpga_tpu.utils import metrics as _metrics
from libpga_tpu.utils import telemetry as _tl

if TYPE_CHECKING:
    from libpga_tpu.engine import PGA


class NaNStorm(RuntimeError):
    """Raised (after retries are exhausted) when a chunk completes with
    NaN scores — the numeric-blowup failure mode."""


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Retry/backoff settings for :func:`supervised_run`.

    ``max_retries`` bounds attempts PER CHUNK. Backoff for attempt k is
    ``min(base * factor**(k-1), max)``, scaled by a deterministic jitter
    factor in ``[1 - jitter, 1]`` drawn from a PRNG seeded with
    ``jitter_seed`` — two supervised runs with the same policy and
    failure sequence sleep the same amounts (reproducible chaos runs).
    """

    max_retries: int = 3
    backoff_base_s: float = 0.05
    backoff_factor: float = 2.0
    backoff_max_s: float = 5.0
    jitter: float = 0.5
    jitter_seed: int = 0

    def __post_init__(self):
        if self.max_retries < 0:
            raise ValueError("max_retries must be >= 0")
        if self.backoff_base_s < 0 or self.backoff_max_s < 0:
            raise ValueError("backoff times must be >= 0")
        if self.backoff_factor < 1.0:
            raise ValueError("backoff_factor must be >= 1")
        if not (0.0 <= self.jitter <= 1.0):
            raise ValueError("jitter must be in [0, 1]")

    def delay(self, attempt: int, rng: random.Random) -> float:
        """Backoff before retry ``attempt`` (1-based)."""
        base = min(
            self.backoff_base_s * self.backoff_factor ** (attempt - 1),
            self.backoff_max_s,
        )
        return base * (1.0 - self.jitter * rng.random())


@dataclasses.dataclass
class SupervisedReport:
    """What :func:`supervised_run` did — returned, never printed."""

    generations: int = 0  # total toward n, including resumed progress
    retries: int = 0
    checkpoints: int = 0
    restored: bool = False  # this call resumed from a checkpoint
    aborted_on_stall: bool = False
    stopped: bool = False  # the stop callback ended the run early
    target_reached: bool = False
    best_score: float = float("-inf")
    errors: List[str] = dataclasses.field(default_factory=list)


def _meta_path(path: str) -> str:
    return f"{path}.meta.json"


def _ckpt_file(path: str) -> str:
    """The filename ``checkpoint.save`` actually writes for a
    single-process save (np.savez appends .npz when missing)."""
    return path if path.endswith(".npz") else f"{path}.npz"


def _ckpt_sig(path: str) -> Optional[List[int]]:
    """Identity of the current checkpoint FILE VERSION (mtime_ns +
    size). Recorded in the sidecar after each save and checked at
    resume: on a shared spool two processes can race on the same
    checkpoint (a lease-expired-but-alive fleet worker finishing its
    last chunk while a survivor resumes — serving/fleet.py), and a
    resume that read sidecar@g but checkpoint@g+K would overrun the
    generation budget. None when the file is not statable (e.g. the
    multi-process per-shard format) — then the check is skipped, as
    before."""
    try:
        st = os.stat(_ckpt_file(path))
    except OSError:
        return None
    return [st.st_mtime_ns, st.st_size]


def _write_meta(path: str, meta: dict) -> None:
    """Atomic sidecar write — same durability stance as the checkpoint
    itself (a torn sidecar must not shadow a good one)."""
    tmp = f"{_meta_path(path)}.{os.getpid()}.tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(meta, fh)
    os.replace(tmp, _meta_path(path))


def read_meta(path: str) -> Optional[dict]:
    """The progress sidecar of a supervised checkpoint, or None."""
    try:
        with open(_meta_path(path), "r", encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None


def _snapshot(pga: "PGA"):
    """Pre-chunk rollback state: the PRNG key plus HOST copies of every
    population's buffers.

    Copies, not references: with buffer donation on, a retried chunk
    donates the installed genome buffer — the snapshot must survive a
    second rollback. Host (numpy) copies specifically: ``np.array``
    blocks until the buffer is ready and materializes off-device, so
    the snapshot can never alias — or hold an in-flight async
    device-to-device copy of — a buffer the very next dispatch donates.
    (Unrelated but found by the chaos matrix: the PERSISTENT
    compilation cache on jaxlib 0.4.37/CPU corrupts the heap under
    donation-heavy checkpoint/restore loops — see tools/ci.sh; the
    cache, not this snapshot, was the culprit.)"""
    import numpy as np

    return (
        pga._key,
        [
            (np.array(p.genomes), np.array(p.scores))
            for p in pga._populations
        ],
    )


def _rollback(pga: "PGA", snap) -> None:
    """Reinstate a snapshot. Uploads fresh device buffers from the host
    copies, so the snapshot stays pristine for further rollbacks (see
    :func:`_snapshot`)."""
    import jax.numpy as jnp

    from libpga_tpu.population import Population

    key, pops = snap
    pga._key = key
    pga._populations = [
        Population(genomes=jnp.asarray(g), scores=jnp.asarray(s))
        for g, s in pops
    ]
    pga._staged = [None] * len(pops)
    pga._history = [None] * len(pops)


def _has_nan_scores(pga: "PGA") -> bool:
    import jax.numpy as jnp

    return any(
        bool(jnp.isnan(p.scores).any()) for p in pga._populations
    )


def _best(pga: "PGA") -> float:
    best = float("-inf")
    for p in pga._populations:
        import jax.numpy as jnp

        v = float(jnp.max(p.scores))
        if v > best:
            best = v
    return best


def _stalled_gens(pga: "PGA") -> int:
    """Final stall-counter value across the populations' most recent
    histories (0 when telemetry is off)."""
    worst = 0
    for hist in pga._history:
        if hist is not None and len(hist) > 0:
            worst = max(worst, int(hist.stall[-1]))
    return worst


def supervised_run(
    pga: "PGA",
    n: int,
    *,
    target: Optional[float] = None,
    islands: Optional[Tuple[int, float]] = None,
    checkpoint_path: Optional[str] = None,
    checkpoint_every: int = 0,
    retry: Optional[RetryPolicy] = None,
    stall_abort_gens: int = 0,
    detect_nan: bool = True,
    resume: bool = False,
    stop: Optional[Callable[[], bool]] = None,
    sleep: Callable[[float], None] = time.sleep,
) -> SupervisedReport:
    """Run ``pga`` for up to ``n`` generations under supervision.

    Args:
      pga: the solver (objective + populations already set up).
      n: total generation budget (including any resumed progress).
      target: early-stop objective value (as in ``PGA.run``).
      islands: ``(m, pct)`` to supervise ``run_islands`` (migration
        every ``m`` generations of the top ``pct``) instead of ``run``.
      checkpoint_path: where auto-checkpoints go; None disables
        durability (retry/rollback still works in memory).
      checkpoint_every: auto-checkpoint cadence in generations (the
        chunk size). 0 = one chunk of ``n`` generations — the
        supervisor then adds only the snapshot + bookkeeping (the
        bench ``supervised`` arm's K=0 overhead case) and, when
        ``checkpoint_path`` is set, a single final save.
      retry: :class:`RetryPolicy`; default ``RetryPolicy()``.
      stall_abort_gens: abort once the telemetry stall counter reaches
        this (0 = no watchdog; requires
        ``PGAConfig(telemetry=TelemetryConfig(history_gens>0))``).
      detect_nan: treat NaN scores after a chunk as a failure.
      resume: restore ``checkpoint_path`` (+ its progress sidecar)
        before running — the crash-recovery entry point.
      stop: polled AFTER each completed (and checkpointed) chunk; a
        True return ends the run at that chunk boundary with
        ``report.stopped`` set. This is the preemption-safe drain hook
        (``serving/worker.py``): because the check sits on a chunk
        boundary, the durable checkpoint + sidecar written for that
        chunk is exactly the state a later ``resume=True`` continues
        from, and the resumed run replays the SAME cadence — so a
        stopped-and-resumed run stays bit-identical to an uninterrupted
        one.
      sleep: backoff sleeper (injectable for tests).

    Returns a :class:`SupervisedReport`. Raises the last chunk error
    once ``retry.max_retries`` is exhausted.
    """
    from libpga_tpu.utils import checkpoint as _ckpt

    if n < 0:
        raise ValueError("n must be >= 0")
    if checkpoint_every < 0:
        raise ValueError("checkpoint_every must be >= 0")
    if islands is not None and len(islands) != 2:
        raise ValueError("islands must be (m, pct)")
    retry = retry or RetryPolicy()
    rng = random.Random(retry.jitter_seed)
    report = SupervisedReport()

    done = 0
    if resume:
        if not checkpoint_path:
            raise ValueError("resume=True needs a checkpoint_path")
        # Consistent (sidecar, checkpoint) pair: when the sidecar
        # carries a checkpoint signature, re-read until the checkpoint
        # file matches it AFTER the restore — otherwise a concurrent
        # writer's save landing mid-resume could pair sidecar@g with
        # checkpoint@g+K and the resumed run would overrun ``n``.
        for _ in range(40):
            meta = read_meta(checkpoint_path)
            _ckpt.restore(pga, checkpoint_path)
            want = None if meta is None else meta.get("ckpt_sig")
            if want is None or _ckpt_sig(checkpoint_path) == list(want):
                break
            sleep(0.05)
        report.restored = True
        if meta is not None:
            done = int(meta.get("generations", 0))
            report.target_reached = bool(meta.get("target_reached", False))

    chunk = checkpoint_every if checkpoint_every > 0 else max(n - done, 0)

    def save_progress(generations: int) -> None:
        if not checkpoint_path:
            return
        t0 = time.perf_counter()
        _ckpt.save(pga, checkpoint_path)
        _write_meta(
            checkpoint_path,
            {
                "schema": 1,
                "generations": generations,
                "n": n,
                "target_reached": report.target_reached,
                "ckpt_sig": _ckpt_sig(checkpoint_path),
            },
        )
        # Durability cost per auto-checkpoint (atomic save + sidecar):
        # the number an operator tunes checkpoint_every against.
        _metrics.REGISTRY.histogram(
            "supervisor.checkpoint_write_seconds"
        ).observe(time.perf_counter() - t0)
        report.checkpoints += 1

    while done < n and not report.target_reached:
        step = min(chunk, n - done)
        snap = _snapshot(pga)
        attempt = 0
        while True:
            try:
                if islands is None:
                    gens = pga.run(step, target=target)
                else:
                    m, pct = islands
                    gens = pga.run_islands(step, m, pct, target=target)
                if detect_nan and _has_nan_scores(pga):
                    raise NaNStorm(
                        "NaN scores after chunk — numeric storm"
                    )
                # Checkpoint INSIDE the attempt scope: a save that dies
                # (preemption mid-write, injected checkpoint.save fault)
                # rolls back and replays the chunk deterministically —
                # the atomic writer guarantees the previous checkpoint
                # survived the failed save.
                if checkpoint_every > 0 and checkpoint_path:
                    save_progress(done + gens)
                break
            except KeyboardInterrupt:
                raise
            except BaseException as e:
                attempt += 1
                report.errors.append(f"{type(e).__name__}: {e}")
                if attempt > retry.max_retries:
                    # Retries exhausted: the supervised run is about to
                    # abort — capture the recent fault/retry context +
                    # live metrics before the raise unwinds it.
                    _tl.flight_dump("supervisor_abort")
                    raise
                _rollback(pga, snap)
                _metrics.REGISTRY.counter("supervisor.rollbacks").bump()
                delay = retry.delay(attempt, rng)
                report.retries += 1
                _metrics.REGISTRY.counter("supervisor.retries").bump()
                pga._emit(
                    "retry", attempt=attempt, error=str(e),
                    backoff_s=round(delay, 4), where="supervised_run",
                )
                sleep(delay)
        done += gens
        if target is not None and gens < step:
            report.target_reached = True
        if (
            stall_abort_gens > 0
            and _stalled_gens(pga) >= stall_abort_gens
        ):
            report.aborted_on_stall = True
            _metrics.REGISTRY.counter("supervisor.stall_aborts").bump()
            _tl.flight_dump("stall_abort")
            break
        if stop is not None and done < n and not report.target_reached:
            if stop():
                report.stopped = True
                break

    report.generations = done
    report.best_score = _best(pga)
    # Final durable state (covers checkpoint_every == 0, early stop,
    # and stall aborts) so a later resume=True sees completion.
    if checkpoint_path:
        save_progress(done)
    return report
