"""Process-global, seed-deterministic fault injection.

Chaos testing needs failures that are (a) injected through the REAL code
paths — a fault raised by the registry travels the exact except/retry/
fallback machinery a hardware or runtime fault would — and (b)
reproducible, so a failing chaos run can be replayed. Both properties
live here:

- a :class:`FaultPlan` names a SITE (one of :data:`SITES`, each a real
  call point in the library), a KIND (``"raise"`` — an
  :class:`InjectedFault` propagates from the site — or ``"nan"`` — the
  site's caller poisons the produced scores with NaN, the numeric-storm
  mode), and a trigger: ``at_call_n`` (fire on exactly the Nth call to
  the site) or ``probability`` (an independent per-call draw from the
  registry's seeded PRNG — deterministic for a given seed and call
  sequence);
- :func:`install` activates a :class:`FaultRegistry` in the module
  global :data:`PLAN`. Every injection site is guarded by
  ``if faults.PLAN is not None`` — with no plan installed the site is a
  single attribute read and the surrounding code is exactly the
  pre-robustness path (the disabled-path purity the acceptance gate
  asserts);
- every fired fault is recorded in ``registry.injected`` and emitted as
  a ``fault_injected`` telemetry event when the registry carries an
  event log.

This is OFF by default, forever: nothing in the library installs a plan;
only tests, ``tools/chaos_smoke.py``, and the C ABI's
``pga_set_fault_plan`` do.
"""

from __future__ import annotations

import contextlib
import dataclasses
import random
import threading
from typing import Dict, List, Optional, Tuple


#: Injection sites threaded through the real code paths. The registry
#: accepts unknown site names (forward compatibility for drivers probing
#: a newer library), but these are the ones the library actually fires.
SITES = (
    # fused-kernel build/compile: ops/pallas_step.make_pallas_run (and
    # its per-shape factory), make_pallas_breed, make_pallas_multigen
    "kernel.build",
    # serving program build: serving/cache.ProgramCache.get_or_build
    "serving.compile",
    # objective evaluation around the fused run dispatch
    # (engine.PGA.run / run_islands) — supports kind="nan" (NaN storm)
    "objective.eval",
    # one mega-run launch: serving/batch.BatchedRuns.run
    "serving.launch",
    # checkpoint I/O: utils/checkpoint save (fires between the temp
    # write and the atomic rename — the kill-mid-checkpoint point) and
    # restore
    "checkpoint.save",
    "checkpoint.restore",
    # the serving queue's background flusher thread loop
    "serving.flusher",
    # fleet worker (serving/worker.py): fires at the start of each
    # claimed-batch execution — a "raise" plan propagates out of the
    # worker main loop and kills the WORKER PROCESS mid-batch (the
    # injected analog of a crash; the coordinator's liveness watch must
    # requeue the batch)
    "worker.execute",
    # fleet worker heartbeat thread: fires per heartbeat tick — a
    # "raise" plan kills only the heartbeat thread, so the worker keeps
    # computing while its lease goes stale (the injected lease-expiry
    # scenario; the coordinator must requeue and the worker must notice
    # the lost lease before publishing)
    "worker.heartbeat",
    # bench measurement path (bench._best_gps, inside the timed
    # window, scaled per generation): a kind="slow" plan injects a
    # per-generation delay of ``param`` seconds — the synthetic
    # regression tools/perf_gate.py proves its trip wire on (ISSUE
    # 17). Per-generation, not per-call: the two-length-subtraction
    # estimator cancels any constant per-call overhead by design, so
    # only work-proportional slowdowns are measurable — exactly like a
    # real kernel regression.
    "bench.measure",
    # shared-memory ticket ring (serving/shm_ring.py, ISSUE 18): fires
    # on every framed ring WRITE (frame advertise, depth store, worker
    # slot heartbeat/claim/publish note) — a "raise" plan makes ring
    # writes fail, forcing the writer onto the pure-spool degradation
    # path (the chaos proof that the ring is never load-bearing)
    "ring.publish",
    # ring wait helpers (worker pending-wait, coordinator
    # activity-wait): a "raise" plan breaks the event-driven wake so
    # waiters must fall back to their bounded plain poll; a "slow"
    # plan delays wakeups without breaking them
    "ring.wake",
    # HA coordinator (serving/ha.py + serving/fleet.py, ISSUE 20):
    # "coordinator.monitor" fires once per leader monitor tick (before
    # any scan work) — a "raise" plan kills the monitor thread, the
    # injected analog of a wedged leader whose lease goes stale;
    # "coordinator.elect" fires on every leader-lease acquisition
    # attempt (first-boot election, standby retry, stale-lease
    # takeover) — a "raise" plan makes this candidate lose the round
    # and retry, so elections are failure-injectable;
    # "coordinator.journal" fires on every durable intake-journal
    # operation (ticket-file write, admission-log append, replay scan)
    # — a "raise" plan propagates through the submit/replay machinery
    # exactly like a full disk or torn spool would
    "coordinator.monitor",
    "coordinator.elect",
    "coordinator.journal",
)

_KINDS = ("raise", "nan", "slow")


class InjectedFault(RuntimeError):
    """The exception a ``kind="raise"`` plan throws from its site."""

    def __init__(self, site: str, call: int = 0, message: str = ""):
        self.site = site
        self.call = call
        super().__init__(
            message or f"injected fault at {site!r} (call {call})"
        )


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """One fault to inject.

    Attributes:
      site: injection-site name (see :data:`SITES`).
      kind: ``"raise"`` (an :class:`InjectedFault` propagates from the
        site), ``"nan"`` (the site's caller NaN-poisons the scores it
        produces — the numeric-storm mode; only honored at sites that
        produce scores), or ``"slow"`` (the site's caller stalls by
        :attr:`param` — the injected-regression mode; only honored at
        sites that time work, currently ``bench.measure``).
      at_call_n: fire on exactly the Nth call to the site (1-based).
      probability: when ``at_call_n`` is None, fire each call with this
        probability (drawn from the registry's seeded PRNG — the SAME
        seed and call sequence always fires the same calls).
      times: maximum number of fires for this plan; None = unlimited.
        The default of 1 models a transient fault (fails once, then the
        retried operation succeeds).
      param: magnitude for value-transform kinds (``"slow"``: seconds
        of injected delay per unit of work at the site). Ignored by
        ``"raise"``/``"nan"``.
    """

    site: str
    kind: str = "raise"
    at_call_n: Optional[int] = None
    probability: float = 0.0
    times: Optional[int] = 1
    param: float = 0.0

    def __post_init__(self):
        if not self.site:
            raise ValueError("FaultPlan needs a site name")
        if self.kind not in _KINDS:
            raise ValueError(f"kind must be one of {_KINDS}, got {self.kind!r}")
        if self.at_call_n is not None and self.at_call_n < 1:
            raise ValueError("at_call_n is 1-based (must be >= 1)")
        if not (0.0 <= self.probability <= 1.0):
            raise ValueError("probability must be in [0, 1]")
        if self.at_call_n is None and self.probability == 0.0:
            raise ValueError(
                "FaultPlan needs a trigger: at_call_n or probability > 0"
            )
        if self.times is not None and self.times < 1:
            raise ValueError("times must be >= 1 or None (unlimited)")


class FaultRegistry:
    """An installed set of :class:`FaultPlan` s with per-site call
    accounting. Thread-safe: sites fire from the serving flusher and
    submitter threads concurrently."""

    def __init__(
        self,
        plans: Tuple[FaultPlan, ...],
        seed: int = 0,
        events=None,
    ):
        self.plans = tuple(plans)
        self.seed = seed
        self.events = events
        self.calls: Dict[str, int] = {}
        self.injected: List[dict] = []
        self._fired: Dict[int, int] = {}
        self._rng = random.Random(seed)
        self._lock = threading.Lock()

    def fire(self, site: str) -> bool:
        """Count a call at ``site``; raise :class:`InjectedFault` when a
        matching ``"raise"`` plan triggers, return True when a matching
        value-transform plan (``"nan"``) triggers, else False."""
        with self._lock:
            n = self.calls.get(site, 0) + 1
            self.calls[site] = n
            for i, plan in enumerate(self.plans):
                if plan.site != site:
                    continue
                if (
                    plan.times is not None
                    and self._fired.get(i, 0) >= plan.times
                ):
                    continue
                if plan.at_call_n is not None:
                    hit = plan.at_call_n == n
                else:
                    hit = self._rng.random() < plan.probability
                if not hit:
                    continue
                self._fired[i] = self._fired.get(i, 0) + 1
                self.injected.append(
                    {"site": site, "kind": plan.kind, "call": n}
                )
                # Flight-recorder tee (lazy import: faults must stay
                # importable before utils wiring in stripped builds).
                try:
                    from libpga_tpu.utils import telemetry as _tl

                    _tl.flight_note(
                        "fault_injected",
                        {"site": site, "kind": plan.kind, "call": n},
                    )
                except Exception:
                    pass
                if self.events is not None:
                    try:
                        self.events.emit(
                            "fault_injected", site=site, kind=plan.kind,
                            call=n,
                        )
                    except Exception:
                        pass  # an injected fault must not also break logging
                if plan.kind == "raise":
                    raise InjectedFault(site, n)
                return True
        return False

    def param_of(self, site: str) -> float:
        """Largest ``param`` among this registry's plans at ``site`` —
        the magnitude a value-transform site applies after
        :meth:`fire` returns True (e.g. the ``bench.measure`` injected
        slowdown)."""
        return max(
            (p.param for p in self.plans if p.site == site), default=0.0
        )


#: The active registry, or None (the default, and the production state).
#: Injection sites read this ONCE per call: ``if faults.PLAN is not
#: None: faults.PLAN.fire("<site>")``.
PLAN: Optional[FaultRegistry] = None


def install(*plans: FaultPlan, seed: int = 0, events=None) -> FaultRegistry:
    """Activate a fault plan process-wide; returns the registry (whose
    ``calls``/``injected`` the chaos driver asserts on)."""
    global PLAN
    PLAN = FaultRegistry(tuple(plans), seed=seed, events=events)
    return PLAN


def clear() -> None:
    """Deactivate fault injection (the default state)."""
    global PLAN
    PLAN = None


def install_spec(spec: str, events=None) -> Optional[FaultRegistry]:
    """Install (or clear) the process-global plan from a JSON spec — the
    transport format shared by the C ABI (``pga_set_fault_plan``) and
    the fleet worker's ``PGA_FAULT_SPEC`` environment hook
    (``serving/worker.py``), so a chaos driver can inject faults into a
    process it cannot call into.

    Spec forms:
      - ``""`` / ``"[]"`` / ``"{}"`` / ``"null"`` / ``"off"``: clear;
      - a JSON object: one plan — ``{"site": ..., "kind":
        "raise"|"nan", "at_call_n": N | "probability": p,
        "times": M|null}``;
      - a JSON array of such objects;
      - ``{"seed": S, "plans": [...]}`` to also seed the registry's
        PRNG for probability-triggered plans.

    Returns the installed registry, or None when the spec cleared it.
    """
    import json

    if not spec or spec.strip() in ("[]", "{}", "null", "off"):
        clear()
        return None
    data = json.loads(spec)
    seed = 0
    if isinstance(data, dict) and "plans" in data:
        seed = int(data.get("seed", 0))
        data = data["plans"]
    if isinstance(data, dict):
        data = [data]
    plans = [FaultPlan(**d) for d in data]
    return install(*plans, seed=seed, events=events)


@contextlib.contextmanager
def active(*plans: FaultPlan, seed: int = 0, events=None):
    """Scoped installation::

        with faults.active(FaultPlan("objective.eval", at_call_n=2)) as reg:
            ...
        # cleared on exit, even on error
    """
    global PLAN
    prev = PLAN
    registry = FaultRegistry(tuple(plans), seed=seed, events=events)
    PLAN = registry
    try:
        yield registry
    finally:
        PLAN = prev
