"""Runtime configuration.

The reference's tuning surface is compile-time only: a ``SHARED_MEM`` define,
``MAX_THREADS``, ``MAX_POPULATIONS=10``, ``TOURNAMENT_POPULATION=2``, a
hardcoded ``blocks=8`` grid, and a mutation rate of 0.01 buried inside the
default mutate callback (reference ``src/pga.cu:58,66,278,200,128``,
``include/pga.h:44``). Here all of those are promoted to one runtime config
object.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp

from libpga_tpu.utils.telemetry import TelemetryConfig

# The GP encoding config is part of the library's runtime-config
# surface (a solver's GP search space is configuration, exactly like
# its serving or fleet settings) but lives with the encoding it
# describes — re-exported here so ``from libpga_tpu.config import
# GPConfig`` works like every other *Config.
from libpga_tpu.gp.encoding import GPConfig  # noqa: F401


@dataclasses.dataclass(frozen=True)
class PGAConfig:
    """Configuration for a PGA solver instance.

    Attributes:
      tournament_size: number of candidates per tournament (reference
        hardcodes 2, ``pga.cu:278``).
      selection: parent-selection strategy — "tournament" (the only one
        the reference implements; its ``crossover_selection_type`` enum
        is a self-described placeholder, ``pga.h:37-42``), "truncation"
        (uniform over the top ``selection_param`` fraction, default τ
        0.5), or "linear_rank" (linear ranking with pressure
        ``selection_param`` in (1, 2], default 2.0 — same intensity as
        tournament-2 at s=2). Every strategy runs in-kernel at identical
        cost: the fused kernel samples winners in rank space, so a
        strategy is just an inverse CDF (``ops/pallas_step.py``).
      selection_param: strategy parameter (τ or s above); None uses the
        strategy's default.
      mutation_rate: probability an individual receives a point mutation
        (reference default-callback rate 0.01, ``pga.cu:128``).
      elitism: number of top individuals copied unchanged into the next
        generation. The reference has none (generational replacement only);
        0 preserves that behavior.
      gene_dtype: dtype of the genome matrix. float32 matches the reference's
        ``typedef float gene`` (``pga.h:29``).
      max_populations: cap on populations per solver; the reference fixes 10
        (``pga.h:44``). ``None`` = unlimited.
      migration_topology: "ring" (deterministic neighbor ring over ICI) or
        "random" (random island permutation each migration event, matching
        the "randomly migrate" wording of ``pga.h:108-111``).
      use_pallas: route the default-operator generation step through the
        fused Pallas deme kernel instead of the XLA-fused path. ``None``
        (default) = auto: on when running on TPU, off elsewhere. The
        kernel's selection is tournament-2 within per-generation shuffled
        demes (see ``ops/pallas_step.py``); set False for exact panmictic
        tournament semantics.
      pallas_deme_size: rows per VMEM deme in the Pallas kernel. None
        (default) auto-selects the measured sweet spot for the gene
        dtype (256 for float32, 512 for bfloat16 — the bf16 selection
        matmul is cheap enough that larger demes win). An explicit size
        is honored when it is a power of two in [128, 1024] that
        divides the population; other exact divisors are tried next,
        and remaining populations of >= 128 rows are padded internally
        to a deme multiple (pad rows are masked out of selection) using
        the size that minimizes padding. The engine falls back to the
        XLA path only for sub-tile populations (< 128) or when every
        padded fit would leave a degenerate tail deme.
      pallas_generations_per_launch: generations bred per fused-kernel
        launch. ``None`` (default) = auto: BOTH ``PGA.run`` and
        ``run_islands`` use the one-generation kernel for both dtypes —
        interleaved A/Bs showed the multi-generation amortization
        within drift on single populations (BASELINE.md round 4) and
        LOSING on islands once score stores were batched (round 5:
        one-generation 149.2 vs multigen 127.0 gens/sec, 5/5 rounds).
        An explicit value rules both paths: > 1 holds each deme group
        VMEM-resident across that many generations — the inter-deme
        riffle reshuffle then happens every T generations instead of
        every generation (convergence impact unmeasurable at T <= 8,
        see BASELINE.md), target checks gain launch granularity, and
        islands run one multigen launch per migration interval; 1
        forces the one-generation kernel everywhere.
      pallas_layout: output layout of the fused kernel. ``None``
        (default) = auto: the alias-compatible PING-PONG layout — each
        grid step writes its children IN PLACE over the rows it read
        (``input_output_aliases``), with generations alternating
        between two row groupings so deme cohorts still reshuffle —
        ships on the fused paths whenever its mixing gate admits
        (``ops/pallas_step.pingpong_admissible``), and the staged
        riffle-shuffle layout serves everything else. ``"riffle"`` /
        ``"pingpong"`` force a layout (forcing ping-pong raises where
        its gate fails rather than degrading silently).
      pallas_subblock: sub-blocks per grid step of the one-generation
        ping-pong kernel. > 1 streams that many deme groups through a
        manually double-buffered VMEM scratch pair per grid step —
        the grid (and its per-step dispatch floor) shrinks by the same
        factor at unchanged scoped-VMEM budget. ``None``/1 = off (the
        default until the hardware A/B in tools/ablate_floor.py rules);
        ignored by the multi-generation kernel, which keeps its deme
        group VMEM-resident instead.
      pop_shards: split the POPULATION AXIS of each ``run`` across this
        many mesh devices via ``shard_map`` (``parallel/shard_pop.py``
        — ROADMAP item 2, "giant populations"). Each shard breeds its
        local rows with the existing machinery; cross-shard comb
        mixing (one ``ppermute``) plus global rank thresholds (one
        ``all_gather`` of S·k scalars) keep the run panmictic-
        equivalent at exactly one cross-shard collective pair per
        generation. 1 (default) = the unsharded path, byte-identical
        StableHLO to the pre-sharding code. Requires ``S² | pop`` and
        S <= devices (``shard_pop.validate_shards`` names the valid
        counts); sharded elitism is global (rank-threshold based) and
        selection cohorts are per-shard — measured panmictic-
        equivalent, see README "Giant populations". Applies to
        ``run`` only: ``run_islands`` already shards the ISLAND axis
        via its ``mesh`` argument (composing both axes is ROADMAP
        work).
      donate_buffers: donate the genome buffer to jit so XLA updates it in
        place (the TPU-native replacement for the reference's
        current/next-generation pointer swap, ``pga.h:124-129``).
      validate: runtime validation mode — the debug stand-in for a
        device sanitizer (``utils/validate.py``). After every
        state-installing operation the engine checks gene domain,
        score/NaN sanity, and score consistency against the independent
        XLA evaluation oracle, raising ``ValidationError`` with the
        operation and population named. Adds a host copy + one XLA
        evaluation per checked op; off by default.
      fallback: what a kernel-BUILD or first-dispatch failure on the
        fused Pallas path does. "xla" (default): the run degrades
        per-config to the XLA ``step`` path — bit-equal semantics to a
        run whose shape the kernel had declined — with a one-time
        warning and a ``degraded`` telemetry event, so an unvalidated
        Mosaic lowering can never take down a serving process.
        "raise": propagate the build/dispatch error (the fail-fast
        stance for development and for the StableHLO purity gates).
        Host-side policy only — it never changes a traced program.
      telemetry: in-run telemetry settings
        (``utils/telemetry.TelemetryConfig``): per-generation on-device
        history carried through the fused run loops (best/mean/std
        fitness, diversity proxy, stall counter — read back with
        ``PGA.history``), optional JSONL event log, stall alerts.
        ``None`` (default) disables telemetry entirely — the run loops
        then trace to the exact pre-telemetry jaxpr (zero cost off).
      seed: base PRNG seed. The reference seeds cuRAND with ``time(NULL)``
        (``pga.cu:154``); here an explicit seed gives reproducibility, and
        ``None`` picks an OS-entropy seed.
    """

    tournament_size: int = 2
    selection: str = "tournament"
    selection_param: Optional[float] = None
    mutation_rate: float = 0.01
    elitism: int = 0
    gene_dtype: jnp.dtype = jnp.float32
    max_populations: Optional[int] = None
    migration_topology: str = "ring"
    use_pallas: Optional[bool] = None
    pallas_deme_size: Optional[int] = None
    pallas_generations_per_launch: Optional[int] = None
    pallas_layout: Optional[str] = None
    pallas_subblock: Optional[int] = None
    pop_shards: int = 1
    donate_buffers: bool = True
    validate: bool = False
    fallback: str = "xla"
    telemetry: Optional[TelemetryConfig] = None
    seed: Optional[int] = None

    def serving_signature_fields(self) -> tuple:
        """The config fields that shape a compiled run program — the
        config part of a serving bucket signature (``serving/batch.py``).
        Everything here is baked into the traced program; everything
        else (seed, n, target, mutation rate/sigma) is a runtime input
        and therefore free to vary across the runs of one bucket."""
        import numpy as _np

        return (
            _np.dtype(self.gene_dtype).name,
            self.tournament_size, self.selection, self.selection_param,
            self.elitism, self.pallas_generations_per_launch,
            self.pallas_layout, self.pallas_subblock,
            self.pop_shards,
            None if self.telemetry is None else self.telemetry.history_gens,
        )

    def pallas_enabled(self) -> bool:
        """Resolve the use_pallas auto setting against the live backend."""
        if self.use_pallas is not None:
            return self.use_pallas
        import jax

        try:
            return jax.default_backend() == "tpu"
        except RuntimeError:
            return False

    def __post_init__(self):
        if self.tournament_size < 1:
            raise ValueError("tournament_size must be >= 1")
        if not (0.0 <= self.mutation_rate <= 1.0):
            raise ValueError("mutation_rate must be in [0, 1]")
        if self.elitism < 0:
            raise ValueError("elitism must be >= 0")
        if self.migration_topology not in ("ring", "random"):
            raise ValueError("migration_topology must be 'ring' or 'random'")
        if (
            self.pallas_generations_per_launch is not None
            and self.pallas_generations_per_launch < 1
        ):
            raise ValueError("pallas_generations_per_launch must be >= 1")
        if self.pallas_layout not in (None, "riffle", "pingpong"):
            raise ValueError(
                "pallas_layout must be None, 'riffle' or 'pingpong'"
            )
        if self.pallas_subblock is not None and self.pallas_subblock < 1:
            raise ValueError("pallas_subblock must be >= 1")
        if self.pop_shards < 1:
            raise ValueError("pop_shards must be >= 1")
        if self.fallback not in ("xla", "raise"):
            raise ValueError("fallback must be 'xla' or 'raise'")


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    """Settings for the multi-tenant batched run engine (``serving/``).

    Attributes:
      max_batch: a bucket launches as soon as this many same-signature
        requests are pending (the mega-run's leading run axis width).
      max_wait_ms: a non-empty bucket launches at most this many
        milliseconds after its OLDEST pending request was admitted, even
        if under ``max_batch`` — the latency bound of the accumulation
        window (the Orca/vLLM-style admission tradeoff; see PAPERS.md).
      cache_capacity: LRU capacity of the module-level compiled-program
        cache (``serving/cache.py``), counted in compiled mega-run
        programs. ``None`` = unbounded.
      layout: how the mega-run lays out the run axis — "run_major"
        (``lax.scan`` over runs: each run's working set stays
        cache-resident and finished runs cost nothing; the measured
        winner on CPU hosts), "lockstep" (``vmap`` over runs: one wide
        program advancing every run per step, with the branchless
        per-run freeze; the accelerator layout), or "auto" (default:
        run_major on CPU backends, lockstep elsewhere).
      donate_buffers: donate the stacked population buffer to the
        mega-run so XLA updates it in place (same stance as
        ``PGAConfig.donate_buffers``).
      aot_warmup: compile the mega-run ahead of time at bucket-build
        time via ``jit(...).lower(...).compile()`` — the first launch
        then only executes. Disable to defer compilation to first use.
      max_pending: bounded-queue backpressure — the maximum number of
        admitted-but-incomplete tickets. ``None`` (default) = unbounded
        (the pre-robustness behavior). With a bound, an unserviceable
        burst degrades predictably instead of accumulating memory
        without limit; what ``submit`` does at the bound is the
        ``overflow`` policy.
      overflow: "block" (default) — ``submit`` waits until a pending
        ticket completes (requires a flusher or a concurrent
        ``result()`` reader to make progress); "raise" — ``submit``
        raises :class:`libpga_tpu.serving.QueueFull` immediately, the
        load-shedding policy.
    """

    max_batch: int = 32
    max_wait_ms: float = 20.0
    cache_capacity: Optional[int] = 32
    layout: str = "auto"
    donate_buffers: bool = True
    aot_warmup: bool = True
    max_pending: Optional[int] = None
    overflow: str = "block"

    def __post_init__(self):
        if self.max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if self.max_wait_ms < 0:
            raise ValueError("max_wait_ms must be >= 0")
        if self.cache_capacity is not None and self.cache_capacity < 1:
            raise ValueError("cache_capacity must be >= 1 or None")
        if self.layout not in ("auto", "run_major", "lockstep"):
            raise ValueError(
                "layout must be 'auto', 'run_major' or 'lockstep'"
            )
        if self.max_pending is not None and self.max_pending < 1:
            raise ValueError("max_pending must be >= 1 or None")
        if self.overflow not in ("block", "raise"):
            raise ValueError("overflow must be 'block' or 'raise'")

    def resolve_layout(self) -> str:
        if self.layout != "auto":
            return self.layout
        import jax

        try:
            backend = jax.default_backend()
        except RuntimeError:
            backend = "cpu"
        return "run_major" if backend == "cpu" else "lockstep"


@dataclasses.dataclass(frozen=True)
class TenantPolicy:
    """Per-tenant scheduling policy for the elastic fleet (ISSUE 15).

    One entry of ``FleetConfig.tenants`` — how the coordinator's
    weighted-fair scheduler (``serving/scheduler.py``) treats one
    tenant's tickets. Tenants without an entry run under the default
    policy (weight 1, no quota, priority 0), so enabling scheduling
    never changes behavior for unconfigured tenants.

    Attributes:
      weight: deficit-round-robin service share. A tenant with weight 2
        accrues scheduling credit twice as fast as a weight-1 tenant,
        so under contention it is served ~2x as often. Must be > 0.
      max_pending: per-tenant submission quota — the admission-control
        bound on this tenant's submitted-but-incomplete tickets.
        Breaching it raises
        :class:`~libpga_tpu.serving.scheduler.QuotaExceeded`
        DETERMINISTICALLY (never blocks, unlike the fleet-wide
        ``max_pending``) and emits one ``quota_reject`` event.
        ``None`` = unlimited.
      priority: scheduling lane, 0-9 (higher = more urgent). Lanes are
        served strictly priority-first: batch files sort so workers
        claim higher lanes before lower ones, and a high-priority
        arrival may preempt a worker busy on a lower-priority
        SUPERVISED batch (chunk-boundary drain, bit-identical resume —
        the round-13 machinery). Fairness (the DRR weights) applies
        WITHIN a lane; across lanes priority wins, which is the point.
        A ticket's own ``priority`` field overrides this default.
    """

    weight: float = 1.0
    max_pending: Optional[int] = None
    priority: int = 0

    def __post_init__(self):
        if not (self.weight > 0.0 and self.weight == self.weight):
            raise ValueError("weight must be a positive number")
        if self.max_pending is not None and self.max_pending < 1:
            raise ValueError("max_pending must be >= 1 or None")
        if not (0 <= int(self.priority) <= 9):
            raise ValueError("priority must be in [0, 9]")


@dataclasses.dataclass(frozen=True)
class AutoscaleConfig:
    """Closed-loop worker autoscaling for the fleet coordinator
    (ISSUE 15): a policy thread spawns/retires workers from the signals
    the fleet already exports — claimable backlog, spool-wait p99,
    per-tenant SLO burn alerts, straggler health — with hysteresis and
    cooldowns so worker count follows offered load up AND down without
    flapping. Scale-down always DRAINS (SIGTERM, chunk-boundary
    checkpoint, lease return) and never kills, so results stay
    bit-identical to a fixed-size fleet on the same seeds.

    Attributes:
      min_workers: the floor the fleet drains back to when idle.
      max_workers: hard ceiling on concurrently live workers.
      target_backlog: scale-up threshold — claimable batches (pending
        spool files + queued coordinator batches) per live worker the
        fleet tolerates before adding capacity. The DOWN condition is
        deliberately far away (complete idleness for ``idle_grace_s``),
        which is the hysteresis band.
      spool_wait_p99_ms: optional latency up-trigger: scale up when the
        coordinator's cumulative ``fleet.ticket.spool_wait_ms`` p99
        exceeds this. ``None`` disables the trigger.
      up_cooldown_s / down_cooldown_s: minimum spacing between
        consecutive scale-ups / scale-downs.
      idle_grace_s: the fleet must be COMPLETELY idle (no queued
        tickets, no pending or claimed batches) this long before one
        worker is retired.
      step: workers added/removed per decision.
      check_s: policy-thread evaluation cadence.
    """

    min_workers: int = 1
    max_workers: int = 4
    target_backlog: float = 2.0
    spool_wait_p99_ms: Optional[float] = None
    up_cooldown_s: float = 2.0
    down_cooldown_s: float = 5.0
    idle_grace_s: float = 2.0
    step: int = 1
    check_s: float = 0.25

    def __post_init__(self):
        if self.min_workers < 0:
            raise ValueError("min_workers must be >= 0")
        if self.max_workers < max(self.min_workers, 1):
            raise ValueError(
                "max_workers must be >= max(min_workers, 1)"
            )
        if self.target_backlog <= 0:
            raise ValueError("target_backlog must be > 0")
        if (
            self.spool_wait_p99_ms is not None
            and self.spool_wait_p99_ms <= 0
        ):
            raise ValueError("spool_wait_p99_ms must be > 0 or None")
        if self.up_cooldown_s < 0 or self.down_cooldown_s < 0:
            raise ValueError("cooldowns must be >= 0")
        if self.idle_grace_s < 0:
            raise ValueError("idle_grace_s must be >= 0")
        if self.step < 1:
            raise ValueError("step must be >= 1")
        if self.check_s <= 0:
            raise ValueError("check_s must be > 0")


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Settings for the cross-process serving fleet (``serving/fleet.py``).

    The fleet lifts :class:`ServingConfig`'s single-process semantics to
    a coordinator + N worker processes: ``max_batch``/``max_wait_ms``
    keep their admission-window meaning (the coordinator forms
    shape-bucket batch files instead of in-process mega-runs), and
    ``max_pending``/``overflow`` keep their backpressure meaning but
    count tickets outstanding across the WHOLE fleet.

    Attributes:
      n_workers: worker processes ``Fleet.start`` spawns.
      max_batch: a shape bucket becomes a claimable batch file as soon
        as this many same-signature tickets are pending.
      max_wait_ms: a non-empty bucket is batched at most this many
        milliseconds after its oldest ticket was admitted.
      lease_timeout_s: a claimed batch whose lease heartbeat is older
        than this is requeued onto the pending spool — the recovery
        path for a worker that is wedged or paused (SIGSTOP) rather
        than dead. Workers that EXIT while holding a lease are requeued
        immediately (the coordinator watches the processes it spawned).
      heartbeat_s: how often a worker touches its lease file. Must be
        well under ``lease_timeout_s`` (validated: at most half).
      max_worker_deaths: a batch that has cost this many DISTINCT
        workers their lease (death or expiry) is quarantined into the
        spool's ``dead/`` directory with a flight-recorder dump instead
        of being retried forever — the fleet-level dead-letter policy.
      max_pending: fleet-wide bound on submitted-but-incomplete
        tickets; ``None`` = unbounded. At the bound ``submit`` follows
        ``overflow`` exactly like ``ServingConfig``: ``"block"`` waits
        for a completion, ``"raise"`` raises
        :class:`~libpga_tpu.serving.queue.QueueFull`.
      overflow: see ``max_pending``.
      poll_s: coordinator monitor cadence (batch formation, lease
        scan, worker liveness) — also the worker's pending-spool poll
        cadence.
      drain_timeout_s: how long ``Fleet.drain``/``close`` waits for a
        SIGTERM'd worker to checkpoint and exit before escalating to
        SIGKILL (the worker's in-flight batch is then recovered by the
        normal lease-expiry path on the next ``start``).
      trace: cross-process trace propagation (ISSUE 9). On (default),
        every ticket carries a ``trace_id`` and a span log through the
        spool — coordinator intake, spool wait, worker claim / lease
        held / execute, publish, coordinator readback — and
        ``FleetHandle.latency()`` returns the true cross-process
        end-to-end breakdown. Off disables span recording fleet-wide
        (the batch files carry the flag to the workers); the overhead
        A/B lives in ``bench.py --fleet``.
      metrics_flush_s: cadence at which each worker (and the
        coordinator's monitor) flushes its ``MetricsRegistry`` snapshot
        to the spool's ``metrics/`` directory via atomic rename — the
        feed of the merged fleet exposition, ``Fleet.status()``, and
        ``tools/fleet_top.py``.
      straggler_factor: a worker whose execute-latency p95 exceeds the
        fleet median of worker p95s by this factor (with at least
        ``straggler_min_samples`` observations) is flagged: one
        ``straggler_alert`` event, a ``fleet.straggler_alerts`` bump,
        and its ``fleet.worker.health`` gauge drops to 0 until it
        recovers.
      straggler_min_samples: minimum execute-latency observations a
        worker needs before the straggler check considers it (a p95
        over three tickets is noise, not a verdict).
      tuning_db: path to a kernel tuning database
        (``libpga_tpu/tuning/db.py``, ISSUE 10). When set, every
        spawned worker inherits it through the ``PGA_TUNING_DB``
        environment variable (the same transport pattern as
        ``PGA_FAULT_SPEC``) and installs it at startup, so fleet-served
        buckets AOT-compile their best-known kernel configs. ``None``
        (default) = untuned — workers run the stock resolution unless
        their environment already carries ``PGA_TUNING_DB``.
      tenants: per-tenant :class:`TenantPolicy` map (ISSUE 15) —
        weights for the deficit-round-robin batch former, per-tenant
        submission quotas, and priority-lane defaults. Unlisted
        tenants get ``TenantPolicy()``; ``Fleet.set_tenant_policy``
        adjusts policies on a live fleet.
      autoscale: :class:`AutoscaleConfig` enabling the coordinator's
        load-following worker autoscaler; ``None`` (default) keeps the
        fixed ``n_workers`` pool.
      sched_quantum: deficit credit a weight-1 tenant accrues per
        scheduler rotation, in tickets. The fairness bound: a steady
        tenant's next batch is delayed by a burst tenant's deep queue
        by at most the release window plus ``1/quantum`` rotations.
      sched_lookahead: claimable-batch release window per live worker —
        the coordinator keeps at most ``sched_lookahead x
        max(live_workers, 1)`` unclaimed batch files on the spool and
        holds the rest back in its fair queues, so late-arriving
        tenants compete against a bounded runway instead of a fully
        spooled burst. ``Fleet.flush()`` overrides the window.
      poll_idle_max_s: ceiling of the coordinator monitor's adaptive
        idle backoff (ISSUE 15 satellite): with no queued work, no
        outstanding tickets, and no claimed batches, the monitor's
        poll interval doubles from ``poll_s`` up to this cap, and any
        submission wakes it immediately.
      ring: same-host shared-memory ticket ring (ISSUE 18,
        ``serving/shm_ring.py``). On (default), the coordinator creates
        an mmap'd notification ring under the spool root: workers wake
        on ring frames instead of polling ``pending/``, lease
        heartbeats become one framed slot store instead of a file
        touch, and the monitor wakes on worker notify counters. The
        spool stays the durable source of truth — any torn, stale, or
        absent ring record falls back to the pre-ring spool scan
        bit-for-bit, so the chaos matrix is unchanged. Off disables
        the ring entirely (pure-spool coordination, the A/B arm of
        ``bench.py --fleet``).
      ring_fallback_s: bounded fallback-scan cadence in ring mode:
        even with a healthy ring, every worker re-lists the pending
        spool and the coordinator reconciles its advertised depth at
        least this often, so a wedged or SIGKILL'd peer can never
        stall the fleet behind a quiet ring.
      coordinators: how many coordinator processes share this spool
        (ISSUE 20). 1 (default) is the round-23 single-coordinator
        fleet, byte-for-byte: no leader lease, no epoch stamps, no
        intake journal on the spool. >1 turns on coordinator HA —
        candidates elect a leader through a spool-resident lease
        (same ``lease_timeout_s``/``heartbeat_s`` discipline as worker
        batch leases), every leader-authored durable artifact carries
        the election epoch, and standbys journal submissions durably
        so a takeover rebuilds the fair backlog from the spool alone.
    """

    n_workers: int = 2
    max_batch: int = 8
    max_wait_ms: float = 20.0
    lease_timeout_s: float = 3.0
    heartbeat_s: float = 0.5
    max_worker_deaths: int = 3
    max_pending: Optional[int] = None
    overflow: str = "block"
    poll_s: float = 0.05
    drain_timeout_s: float = 60.0
    trace: bool = True
    metrics_flush_s: float = 1.0
    straggler_factor: float = 3.0
    straggler_min_samples: int = 8
    tuning_db: Optional[str] = None
    tenants: Optional[dict] = None
    autoscale: Optional[AutoscaleConfig] = None
    sched_quantum: float = 1.0
    sched_lookahead: int = 2
    poll_idle_max_s: float = 1.0
    ring: bool = True
    ring_fallback_s: float = 1.0
    coordinators: int = 1

    def __post_init__(self):
        if self.n_workers < 1:
            raise ValueError("n_workers must be >= 1")
        if self.max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if self.max_wait_ms < 0:
            raise ValueError("max_wait_ms must be >= 0")
        if self.lease_timeout_s <= 0:
            raise ValueError("lease_timeout_s must be > 0")
        if not (0 < self.heartbeat_s <= self.lease_timeout_s / 2):
            raise ValueError(
                "heartbeat_s must be in (0, lease_timeout_s / 2]"
            )
        if self.max_worker_deaths < 1:
            raise ValueError("max_worker_deaths must be >= 1")
        if self.max_pending is not None and self.max_pending < 1:
            raise ValueError("max_pending must be >= 1 or None")
        if self.overflow not in ("block", "raise"):
            raise ValueError("overflow must be 'block' or 'raise'")
        if self.poll_s <= 0:
            raise ValueError("poll_s must be > 0")
        if self.drain_timeout_s <= 0:
            raise ValueError("drain_timeout_s must be > 0")
        if self.metrics_flush_s <= 0:
            raise ValueError("metrics_flush_s must be > 0")
        if self.straggler_factor <= 1.0:
            raise ValueError(
                "straggler_factor must be > 1 (a worker at the fleet "
                "median is not a straggler)"
            )
        if self.straggler_min_samples < 1:
            raise ValueError("straggler_min_samples must be >= 1")
        if self.tenants is not None:
            for tid, pol in self.tenants.items():
                if not isinstance(pol, TenantPolicy):
                    raise ValueError(
                        f"tenants[{tid!r}] must be a TenantPolicy, "
                        f"got {type(pol).__name__}"
                    )
        if self.autoscale is not None and not isinstance(
            self.autoscale, AutoscaleConfig
        ):
            raise ValueError("autoscale must be an AutoscaleConfig or None")
        if self.sched_quantum <= 0:
            raise ValueError("sched_quantum must be > 0")
        if self.sched_lookahead < 1:
            raise ValueError("sched_lookahead must be >= 1")
        if self.poll_idle_max_s < self.poll_s:
            raise ValueError("poll_idle_max_s must be >= poll_s")
        if self.ring_fallback_s <= 0:
            raise ValueError("ring_fallback_s must be > 0")
        if self.coordinators < 1:
            raise ValueError("coordinators must be >= 1")


@dataclasses.dataclass(frozen=True)
class PBTConfig:
    """Population-based-training hyperparameter adaptation across the
    co-batched sessions of a :class:`~libpga_tpu.streaming.SessionGroup`
    (ISSUE 12). At every ``epoch_gens``-generation boundary the group
    argsorts the sessions by best fitness (ONE cross-run argsort over N
    scalars); each of the bottom ``exploit_frac`` sessions copies its
    mutation rate/sigma from a uniformly drawn top-``exploit_frac``
    partner (exploit), then multiplies the rate by ``explore_factor``
    or its inverse, coin-flipped (explore), clipped to ``rate_bounds``/
    ``sigma_bounds``. Rate and sigma are RUNTIME inputs of the shared
    mega-run (``ops/step.make_param_breed``), so adaptation never
    recompiles. Deterministic for a fixed ``seed`` (epoch-indexed host
    PRNG).

    Off by default: ``StreamingConfig.pbt = None`` never touches a
    session's parameters — byte-identity asserted in
    ``tests/test_streaming.py``.
    """

    epoch_gens: int = 10
    exploit_frac: float = 0.25
    explore_factor: float = 1.2
    rate_bounds: tuple = (1e-4, 0.5)
    sigma_bounds: tuple = (0.0, 1.0)
    seed: int = 0

    def __post_init__(self):
        if self.epoch_gens < 1:
            raise ValueError("epoch_gens must be >= 1")
        if not (0.0 < self.exploit_frac <= 0.5):
            raise ValueError("exploit_frac must be in (0, 0.5]")
        if self.explore_factor <= 1.0:
            raise ValueError("explore_factor must be > 1")
        if not (0 < self.rate_bounds[0] <= self.rate_bounds[1] <= 1.0):
            raise ValueError("rate_bounds must satisfy 0 < lo <= hi <= 1")
        if not (0 <= self.sigma_bounds[0] <= self.sigma_bounds[1]):
            raise ValueError("sigma_bounds must satisfy 0 <= lo <= hi")


@dataclasses.dataclass(frozen=True)
class StreamingConfig:
    """Settings for the streaming evolution service (``streaming/``,
    ISSUE 12) — long-lived ask/tell tenants over the serving stack.

    Attributes:
      pool_capacity: idle warm engines retained per signature by an
        :class:`~libpga_tpu.streaming.EnginePool` (each holds compiled
        programs; beyond the cap a released engine is dropped).
        ``None`` = unbounded.
      prewarm: compile a fresh signature's run program at pool admission
        (one zero-generation dummy dispatch — the engine-path analog of
        the serving cache's AOT ``lower().compile()`` warm-up), so a
        tenant's first ``ask``/``step`` executes, never compiles.
      max_tell_slots: cap on pending external evaluations folded per
        generation boundary; ``None`` = the population size (everything
        pending folds).
      pbt: live hyperparameter adaptation across co-batched sessions
        (:class:`PBTConfig`). ``None`` (default) = off — session
        parameters are never touched and group stepping is
        byte-identical to the pre-PBT path.
    """

    pool_capacity: Optional[int] = 8
    prewarm: bool = True
    max_tell_slots: Optional[int] = None
    pbt: Optional[PBTConfig] = None

    def __post_init__(self):
        if self.pool_capacity is not None and self.pool_capacity < 1:
            raise ValueError("pool_capacity must be >= 1 or None")
        if self.max_tell_slots is not None and self.max_tell_slots < 1:
            raise ValueError("max_tell_slots must be >= 1 or None")


@dataclasses.dataclass(frozen=True)
class BurnRateConfig:
    """Multi-window error-budget burn-rate alerting (ISSUE 14) —
    the SRE alerting shape applied to per-tenant latency objectives.

    Each completed request either met ``objective_ms`` (end-to-end
    latency) or violated it; ``budget`` is the fraction of requests
    allowed to violate. The burn rate over a window is the observed
    violation rate divided by the budget, and an ``slo_burn`` alert
    fires when BOTH the fast window (catches sharp regressions
    quickly) and the slow window (confirms they are sustained) burn at
    >= ``threshold`` — fast to fire, slow to flap. Evaluated by
    :class:`~libpga_tpu.utils.metrics.BurnRateMonitor` on the serving
    queue and fleet coordinator readback paths; burn rates export as
    ``*.tenant.slo_burn{tenant=,window=}`` gauges either way, alerts
    additionally emit one schema-valid ``slo_burn`` event per
    excursion (transition-edge, re-armed on recovery).

    Attributes:
      objective_ms: per-request end-to-end latency objective whose
        violations consume the error budget.
      budget: allowed violation fraction (0 < budget <= 1).
      fast_window_s / slow_window_s: the two alerting windows.
      threshold: burn-rate multiple (in both windows) that alerts.
      min_samples: slow-window observations required before alerting —
        a burn rate over three requests is noise, not an incident.
    """

    objective_ms: float = 1000.0
    budget: float = 0.01
    fast_window_s: float = 300.0
    slow_window_s: float = 3600.0
    threshold: float = 10.0
    min_samples: int = 20

    def __post_init__(self):
        if self.objective_ms <= 0:
            raise ValueError("objective_ms must be > 0")
        if not (0.0 < self.budget <= 1.0):
            raise ValueError("budget must be in (0, 1]")
        if not (0.0 < self.fast_window_s <= self.slow_window_s):
            raise ValueError("need 0 < fast_window_s <= slow_window_s")
        if self.threshold <= 0:
            raise ValueError("threshold must be > 0")
        if self.min_samples < 1:
            raise ValueError("min_samples must be >= 1")


@dataclasses.dataclass(frozen=True)
class SLOConfig:
    """Latency service-level objectives for the serving queue (ISSUE 6).

    Pass to :class:`~libpga_tpu.serving.queue.RunQueue` (``slo=...``).
    Two kinds of check, both host-side and advisory — a breach emits an
    ``slo_violation`` telemetry event and bumps the
    ``serving.slo_violations`` counter, it never fails a request:

    - **per-ticket**: a completed ticket whose queue wait exceeded
      ``max_queue_wait_ms`` violates immediately (checked as each
      result is read back);
    - **aggregate**: ``RunQueue.check_slo()`` compares the p99 of the
      end-to-end ticket latency histogram against ``p99_latency_ms``
      (meaningful once ``min_samples`` tickets completed — a p99 over
      three tickets is noise, not an objective).

    Per-tenant attribution (ISSUE 14) adds two layers:

    - **tenants**: a mapping of tenant id -> :class:`SLOConfig`
      overriding this config for that tenant's tickets
      (:meth:`for_tenant` resolves; overrides must not nest).
      ``RunQueue.check_slo(tenant=...)`` / ``Fleet.check_slo(tenant=
      ...)`` check the TENANT-LABELED latency histogram against the
      resolved objective.
    - **burn**: a :class:`BurnRateConfig` enabling the multi-window
      error-budget burn-rate monitor over per-tenant request
      outcomes (``slo_burn`` events + ``*.tenant.slo_burn`` gauges).

    ``tools/serving_throughput.py --slo`` turns violations into a
    nonzero exit — the CI/SLO gate; ``None`` fields are unchecked.
    """

    p99_latency_ms: Optional[float] = None
    max_queue_wait_ms: Optional[float] = None
    min_samples: int = 20
    tenants: Optional[dict] = None
    burn: Optional[BurnRateConfig] = None

    def __post_init__(self):
        if self.p99_latency_ms is not None and self.p99_latency_ms <= 0:
            raise ValueError("p99_latency_ms must be > 0 or None")
        if (
            self.max_queue_wait_ms is not None
            and self.max_queue_wait_ms < 0
        ):
            raise ValueError("max_queue_wait_ms must be >= 0 or None")
        if self.min_samples < 1:
            raise ValueError("min_samples must be >= 1")
        if self.tenants is not None:
            for tenant, cfg in self.tenants.items():
                if not isinstance(cfg, SLOConfig):
                    raise ValueError(
                        f"tenants[{tenant!r}] must be an SLOConfig"
                    )
                if cfg.tenants is not None:
                    raise ValueError(
                        f"tenants[{tenant!r}]: per-tenant overrides "
                        "must not nest further overrides"
                    )
        if self.burn is not None and not isinstance(
            self.burn, BurnRateConfig
        ):
            raise ValueError("burn must be a BurnRateConfig or None")

    def for_tenant(self, tenant: Optional[str]) -> "SLOConfig":
        """The SLO governing one tenant: its override when present
        (inheriting this config's ``burn`` unless the override carries
        its own), else this config unchanged."""
        if not self.tenants or tenant not in self.tenants:
            return self
        override = self.tenants[tenant]
        if override.burn is None and self.burn is not None:
            override = dataclasses.replace(override, burn=self.burn)
        return override
