"""Pure-numpy reference interpreter — the GP semantics oracle.

The slow, obviously-correct implementation of the postfix stack
machine (``gp/encoding.py`` token format, skip-rule semantics). The
fused evaluators — the XLA batched interpreter
(``gp/interpreter.py``) and the Pallas VMEM-stack kernel
(``ops/gp_eval.py``) — are verified against THIS on randomized
well-formed programs and on arbitrary gene matrices
(tests/test_gp.py, tools/gp_smoke.py); it never runs on a hot path.

Semantics (one copy of the rules, stated once):

- tokens execute left to right; a ``pad`` token, or a token whose
  arity exceeds the current stack depth, is a NO-OP (the skip rule —
  evaluation is total over arbitrary gene values);
- binary operands pop right-then-left (postfix ``a b op`` computes
  ``op(a, b)``);
- protected forms: ``div(a, b) = 1.0 where |b| < DIV_EPS``,
  ``sqrt(x) = sqrt(|x|)``, ``log(x) = log(|x| + LOG_EPS)``;
- the program's value is the top of the stack; an empty stack reads
  0.0.
"""

from __future__ import annotations

import numpy as np

from libpga_tpu.gp.encoding import (
    DIV_EPS,
    GPConfig,
    LOG_EPS,
    PAD_OP,
)


def _apply(name: str, a, b):
    """One function-table entry over numpy operands (vectorized across
    the sample axis)."""
    if name == "neg":
        return -a
    if name == "sin":
        return np.sin(a)
    if name == "cos":
        return np.cos(a)
    if name == "sqrt":
        return np.sqrt(np.abs(a))
    if name == "abs":
        return np.abs(a)
    if name == "exp":
        return np.exp(a)
    if name == "log":
        return np.log(np.abs(a) + np.float32(LOG_EPS))
    if name == "add":
        return a + b
    if name == "sub":
        return a - b
    if name == "mul":
        return a * b
    if name == "div":
        return np.where(np.abs(b) < DIV_EPS, np.float32(1.0), a / np.where(
            np.abs(b) < DIV_EPS, np.float32(1.0), b
        ))
    if name == "min":
        return np.minimum(a, b)
    if name == "max":
        return np.maximum(a, b)
    raise ValueError(f"unknown op {name!r}")


def reference_predict(
    genomes: np.ndarray, X: np.ndarray, gp: GPConfig
) -> np.ndarray:
    """Evaluate every genome's program on every sample row.

    Args:
      genomes: ``(P, 2 * max_nodes)`` gene matrix (any float values —
        the skip rule totalizes).
      X: ``(B, n_vars)`` input samples.

    Returns:
      ``(P, B)`` float32 predictions.
    """
    g = np.asarray(genomes, np.float32)
    X = np.asarray(X, np.float32)
    P = g.shape[0]
    B = X.shape[0]
    names = gp.op_names()
    arity = gp.op_arities()
    consts = np.asarray(gp.consts, np.float32)
    ops = np.clip(
        np.floor(g[:, 0::2] * gp.n_ops).astype(np.int64), 0, gp.n_ops - 1
    )
    args = g[:, 1::2]
    out = np.zeros((P, B), np.float32)
    with np.errstate(all="ignore"):
        for p in range(P):
            stack: list = []
            for t in range(gp.max_nodes):
                op = int(ops[p, t])
                name = names[op]
                a = arity[op]
                if op == PAD_OP or len(stack) < a:
                    continue
                if name == "var":
                    v = min(int(args[p, t] * gp.n_vars), gp.n_vars - 1)
                    stack.append(X[:, max(v, 0)].astype(np.float32))
                elif name == "const":
                    c = min(int(args[p, t] * len(consts)), len(consts) - 1)
                    stack.append(np.full(B, consts[max(c, 0)], np.float32))
                elif a == 1:
                    stack.append(
                        _apply(name, stack.pop(), None).astype(np.float32)
                    )
                else:
                    rhs = stack.pop()
                    lhs = stack.pop()
                    stack.append(_apply(name, lhs, rhs).astype(np.float32))
            if stack:
                out[p] = stack[-1]
    return out


def reference_scores(
    genomes: np.ndarray,
    X: np.ndarray,
    y: np.ndarray,
    gp: GPConfig,
    parsimony: float = 0.0,
) -> np.ndarray:
    """``-RMSE`` fitness (higher is better, like every objective in the
    library), minus an optional per-live-token parsimony penalty;
    non-finite scores sanitize to ``-inf`` so one overflowing program
    can never poison the run loop's ``max(scores)`` target check."""
    from libpga_tpu.gp.encoding import program_length

    preds = reference_predict(genomes, X, gp)
    y = np.asarray(y, np.float32)
    with np.errstate(all="ignore"):
        rmse = np.sqrt(np.mean((preds - y[None, :]) ** 2, axis=1))
        scores = -rmse
        if parsimony:
            lengths = np.asarray(
                [program_length(row, gp) for row in np.asarray(genomes)],
                np.float32,
            )
            scores = scores - np.float32(parsimony) * lengths
    return np.where(np.isfinite(scores), scores, -np.inf).astype(np.float32)


__all__ = ["reference_predict", "reference_scores"]
