"""Tree-based genetic programming subsystem (ROADMAP item 1).

Programs are linear postfix-encoded trees packed into the library's
ordinary fixed-width gene vectors (``gp/encoding.py``), evaluated by a
fused stack machine — an XLA interpreter everywhere
(``gp/interpreter.py``), a Pallas VMEM-stack kernel on TPU
(``ops/gp_eval.py``), a pure-numpy oracle behind both
(``gp/reference.py``) — and bred by size-fair subtree crossover and
subtree/point mutation on the existing operator protocol
(``gp/operators.py``). The symbolic-regression objective family
(``gp/sr.py``) closes the loop: dataset-resident ``-RMSE`` fitness
with tuning-DB-resolved evaluator knobs. ``gp/optimize.py`` is the
eval-time fast path: fold + DCE + compact genomes into a transient
:class:`~libpga_tpu.gp.optimize.EvalProgram` so evaluation pays for
live tokens only — stored genomes are never touched.

Submodules load lazily (PEP 562): importing :mod:`libpga_tpu` must not
pay for GP, and a vector-genome engine's traced programs are
byte-identical with this package imported or not (structural test,
tests/test_gp.py). NOTE the round-11 lesson: the lazy getattr must
never recurse through itself — attribute names are resolved through an
explicit table only.
"""

from __future__ import annotations

import importlib

_SUBMODULES = (
    "encoding", "interpreter", "operators", "optimize", "reference", "sr",
)

_LAZY_NAMES = {
    # encoding
    "GPConfig": "encoding",
    "encode_program": "encoding",
    "decode_expression": "encoding",
    "is_well_formed": "encoding",
    "random_population": "encoding",
    "program_structure": "encoding",
    "canonicalize": "encoding",
    "DISPATCH_KINDS": "encoding",
    # optimize
    "EvalProgram": "optimize",
    "optimize_for_eval": "optimize",
    "live_lengths": "optimize",
    "compaction_stats": "optimize",
    # operators
    "make_subtree_crossover": "operators",
    "make_subtree_mutate": "operators",
    "make_gp_point_mutate": "operators",
    "make_gp_mutate": "operators",
    "CROSSOVER_KINDS": "operators",
    "MUTATE_KINDS": "operators",
    # sr
    "symbolic_regression": "sr",
    "make_dataset": "sr",
    # reference
    "reference_predict": "reference",
    "reference_scores": "reference",
}

__all__ = sorted(set(_LAZY_NAMES) | set(_SUBMODULES))


def __getattr__(name: str):
    if name in _SUBMODULES:
        return importlib.import_module(f"{__name__}.{name}")
    target = _LAZY_NAMES.get(name)
    if target is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    module = importlib.import_module(f"{__name__}.{target}")
    return getattr(module, name)


def __dir__():
    return __all__
