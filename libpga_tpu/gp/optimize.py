"""Eval-time program optimizer: canonicalize → fold → DCE → compact.

The host-side pre-eval pass of the GP fast path (ROADMAP item 1;
"Enabling Population-Level Parallelism in Tree-Based GP", arxiv
2501.17168, attacks the same cost shape with compact program
representations). One vectorized forward scan — the same stack walk
``encoding.program_structure`` runs, carrying folded VALUES alongside
subtree heads — classifies every token of every genome, and one stable
argsort compacts the survivors:

- **canonicalize**: dead tokens (the skip rule's no-ops) never reach
  the eval buffer — live tokens compact to the front, pads stamp the
  tail (the ``encoding.canonicalize`` normalization, subsumed by the
  compact step);
- **constant-fold**: a maximal constant-headed subtree collapses to one
  synthetic ``LIT`` token whose OPERAND is the folded float32 value
  itself. Folding runs the evaluator's OWN jnp function table
  (``interpreter._UNARY_FNS`` / ``_BINARY_FNS``) on-device, so the
  folded value carries device rounding semantics and optimized
  evaluation is BIT-EQUAL to unoptimized evaluation — not merely close
  (property-gated in tests/test_gp_optimize.py);
- **dead-code-eliminate**: a live subtree whose value is never consumed
  and is not the final top (possible only in non-strictly-well-formed
  genomes — buried stack slots) is deleted whole. Removing a complete
  never-consumed subtree preserves every other token's execution and
  the final top value: any token that executed without popping into the
  buried value still finds its operands at the stack top.

Stored genomes are NEVER touched: crossover geometry, checkpoints,
``pop_shards``, and serving buckets all see the original ``(P, L)``
gene matrix. The optimizer emits a transient :class:`EvalProgram` —
decoded int32 opcodes over the EXTENDED table (``lit_op(gp) ==
gp.n_ops``), float32 operands, and per-individual live lengths — that
only the evaluators consume (``gp/interpreter.stack_predict_program``,
``ops/gp_eval.make_gp_eval``), bounding their token loops at the
population-block max live length.

Everything here is traceable jnp (the engine's jitted run loop calls it
every generation through the ``prepare_eval`` hook on
``ops/evaluate.evaluate``); gathers are fine — this pass never runs
inside a Mosaic kernel.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from libpga_tpu.gp.encoding import (
    GPConfig,
    PAD_OP,
    decode_args,
    decode_ops,
)


class EvalProgram(NamedTuple):
    """The transient compacted eval buffer (a pytree — flows through
    jit/vmap/scan like any array triple).

    Attributes:
      ops: ``(P, max_nodes)`` int32 opcodes over the EXTENDED table —
        the config's ``op_names()`` plus the synthetic ``LIT`` opcode
        at index ``gp.n_ops`` (arity 0; its operand IS the value).
      args: ``(P, max_nodes)`` float32 operands; for ``LIT`` tokens the
        folded constant value, for kept tokens the original gene.
      length: ``(P,)`` int32 live token count after fold + DCE. Tokens
        at positions >= length are pads.
    """

    ops: jax.Array
    args: jax.Array
    length: jax.Array


def lit_op(gp: GPConfig) -> int:
    """The synthetic literal opcode id — one past the config's table
    (safe: ``decode_ops`` clips genome decodes to ``n_ops - 1``, so a
    stored genome can never alias it)."""
    return gp.n_ops


def optimize_for_eval(genomes: jax.Array, gp: GPConfig) -> EvalProgram:
    """Fold + DCE + compact one gene matrix into an :class:`EvalProgram`.

    Total over arbitrary gene values (the skip rule classifies dead
    tokens before anything else). Traceable; ~``T``-step scan over
    ``(P,)``/``(P, T)`` carries — negligible next to one evaluation's
    ``T·P·B`` lattice.
    """
    from libpga_tpu.gp.interpreter import _BINARY_FNS, _UNARY_FNS

    P, L = genomes.shape
    T = gp.max_nodes
    if L != 2 * T:
        raise ValueError(
            f"genome_len {L} != 2 * max_nodes ({2 * T}) for this GPConfig"
        )
    ops = decode_ops(genomes, gp)
    args = decode_args(genomes, gp)
    arity = jnp.asarray(gp.op_arities(), jnp.int32)
    names = gp.op_names()
    const_op = names.index("const") if gp.consts else -1
    consts = jnp.asarray(gp.consts or (0.0,), jnp.float32)
    n_consts = max(len(gp.consts), 1)
    unary_ids = [(names.index(n), _UNARY_FNS[n]) for n in gp.unary]
    binary_ids = [(names.index(n), _BINARY_FNS[n]) for n in gp.binary]
    iota_t = jnp.arange(T, dtype=jnp.int32)

    def body(carry, xs):
        sp, vstk, cstk, hstk, pconst = carry
        t, op, arg = xs
        a = arity[op]
        ex = (op != PAD_OP) & (sp >= a)
        i1 = jnp.clip(sp - 1, 0, T - 1)[:, None]
        i2 = jnp.clip(sp - 2, 0, T - 1)[:, None]
        topv = jnp.take_along_axis(vstk, i1, axis=1)[:, 0]
        topc = jnp.take_along_axis(cstk, i1, axis=1)[:, 0] & (sp >= 1)
        toph = jnp.take_along_axis(hstk, i1, axis=1)[:, 0]
        secv = jnp.take_along_axis(vstk, i2, axis=1)[:, 0]
        secc = jnp.take_along_axis(cstk, i2, axis=1)[:, 0] & (sp >= 2)
        sech = jnp.take_along_axis(hstk, i2, axis=1)[:, 0]
        # Folded value + const-headed flag. The decode mirrors the
        # interpreter's exactly; the function applications ARE the
        # interpreter's (same jnp table, same operand order), evaluated
        # at (P,) — XLA elementwise lowering is shape-invariant, so the
        # fold rounds exactly as the unfolded subtree would.
        val = jnp.zeros_like(arg)
        if const_op >= 0:
            cidx = jnp.clip(
                jnp.floor(arg * n_consts).astype(jnp.int32), 0, n_consts - 1
            )
            cval = jnp.zeros_like(arg)
            for c in range(n_consts):
                cval = jnp.where(cidx == c, consts[c], cval)
            val = jnp.where(op == const_op, cval, val)
            rc = op == const_op
        else:
            rc = jnp.zeros_like(ex)
        for k, fn in unary_ids:
            val = jnp.where(op == k, fn(topv), val)
            rc = jnp.where(op == k, topc, rc)
        for k, fn in binary_ids:
            val = jnp.where(op == k, fn(secv, topv), val)
            rc = jnp.where(op == k, secc & topc, rc)
        # Mark popped operands with the PARENT's const flag: a const
        # token consumed by a const parent is fold interior (dropped);
        # a const head with a non-const (or no) parent is a fold ROOT.
        m1 = ex & (a >= 1)
        m2 = ex & (a == 2)
        oh1 = (iota_t[None, :] == toph[:, None]) & m1[:, None]
        oh2 = (iota_t[None, :] == sech[:, None]) & m2[:, None]
        pconst = jnp.where(oh1, rc[:, None], pconst)
        pconst = jnp.where(oh2, rc[:, None], pconst)
        nsp = jnp.where(ex, sp - a + 1, sp)
        wid = jnp.clip(nsp - 1, 0, T - 1)
        ohw = (iota_t[None, :] == wid[:, None]) & ex[:, None]
        vstk = jnp.where(ohw, val[:, None], vstk)
        cstk = jnp.where(ohw, (rc & ex)[:, None], cstk)
        hstk = jnp.where(ohw, t, hstk)
        out = (
            ex,
            rc & ex,
            val,
            jnp.where(m1, toph, jnp.int32(-1)),
            jnp.where(m2, sech, jnp.int32(-1)),
        )
        return (nsp, vstk, cstk, hstk, pconst), out

    zeros_i = jnp.zeros((P,), jnp.int32)
    carry0 = (
        zeros_i,
        jnp.zeros((P, T), jnp.float32),
        jnp.zeros((P, T), bool),
        jnp.zeros((P, T), jnp.int32),
        jnp.zeros((P, T), bool),
    )
    (sp_f, _, _, hstk, pconst), (live_t, rc_t, val_t, ch1_t, ch2_t) = (
        jax.lax.scan(
            body, carry0,
            (iota_t, ops.T, args.astype(jnp.float32).T),
        )
    )
    live, rcm, val = live_t.T, rc_t.T, val_t.T
    ch1, ch2 = ch1_t.T, ch2_t.T

    # DCE: need-propagation from the final top, parents to children
    # (postfix order puts every parent after its children, so one
    # reverse scan settles the whole forest).
    i_f = jnp.clip(sp_f - 1, 0, T - 1)[:, None]
    top_head = jnp.take_along_axis(hstk, i_f, axis=1)[:, 0]
    needed0 = (iota_t[None, :] == top_head[:, None]) & (sp_f > 0)[:, None]

    def back(needed, xs):
        t, c1, c2 = xs
        nt = jnp.any(needed & (iota_t[None, :] == t), axis=1)
        o1 = (iota_t[None, :] == c1[:, None]) & nt[:, None]
        o2 = (iota_t[None, :] == c2[:, None]) & nt[:, None]
        return needed | o1 | o2, None

    needed, _ = jax.lax.scan(
        back, needed0, (iota_t, ch1.T, ch2.T), reverse=True
    )

    keep_lit = live & needed & rcm & ~pconst
    keep = (live & needed & ~rcm) | keep_lit
    out_ops = jnp.where(keep_lit, jnp.int32(lit_op(gp)), ops)
    out_args = jnp.where(keep_lit, val, args.astype(jnp.float32))
    # Stable live-first compaction (jax sorts are stable — the same
    # move as encoding.canonicalize).
    order = jnp.argsort((~keep).astype(jnp.int32), axis=1)
    ops_c = jnp.take_along_axis(out_ops, order, axis=1)
    args_c = jnp.take_along_axis(out_args, order, axis=1)
    length = jnp.sum(keep.astype(jnp.int32), axis=1)
    tail = iota_t[None, :] >= length[:, None]
    ops_c = jnp.where(tail, jnp.int32(PAD_OP), ops_c)
    args_c = jnp.where(tail, jnp.float32(0.5), args_c)
    return EvalProgram(ops=ops_c, args=args_c, length=length)


def live_lengths(genomes: jax.Array, gp: GPConfig) -> jax.Array:
    """``(P,)`` int32 post-optimization live lengths (traceable)."""
    return optimize_for_eval(genomes, gp).length


def mean_live_length(genomes, gp: GPConfig) -> float:
    """Host-side mean post-optimization live length — the measured
    token count ``perf/cost.gp_plan_cost`` prices instead of the static
    ``max_nodes`` cap (``pga.program_report`` passes it through)."""
    import numpy as np

    return float(np.mean(np.asarray(live_lengths(genomes, gp))))


def compaction_stats(genomes, gp: GPConfig) -> dict:
    """Host-side optimizer effectiveness summary (the gp_smoke /
    bench compaction-stats line): live token counts before (skip-rule
    live, ``program_structure``) and after (fold + DCE), and the
    fraction of live tokens the optimizer removed."""
    import numpy as np

    from libpga_tpu.gp.encoding import program_structure

    before = np.asarray(program_structure(genomes, gp).length)
    after = np.asarray(live_lengths(genomes, gp))
    total_before = float(before.sum())
    return {
        "pop": int(before.shape[0]),
        "max_nodes": int(gp.max_nodes),
        "mean_live_before": float(before.mean()),
        "mean_live_after": float(after.mean()),
        "max_live_after": int(after.max()) if after.size else 0,
        "removed_frac": (
            float((before - after).sum() / total_before)
            if total_before else 0.0
        ),
    }


__all__ = [
    "EvalProgram",
    "lit_op",
    "optimize_for_eval",
    "live_lengths",
    "mean_live_length",
    "compaction_stats",
]
