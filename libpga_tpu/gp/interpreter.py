"""Fused stack-machine interpreter for postfix GP genomes (XLA path).

One jitted program evaluates the WHOLE population on the WHOLE sample
batch: a bounded ``lax.fori_loop`` over token positions carrying a
``(stack_depth, P, B)`` value-stack tensor and a ``(P,)`` per-individual
stack pointer. Every stack access is an iota-compare mask (no gathers,
no scatters — the same scatter-free formulation as the batched
order-preserving crossover, ``ops/crossover.py``), so the IDENTICAL
token-step code lowers both here under XLA and inside the Pallas VMEM
kernel (``ops/gp_eval.py``) — one copy of the semantics, which is what
keeps the fused path and the fallback path from drifting
(``tools/gp_smoke.py`` gates their agreement; the pure-numpy oracle in
``gp/reference.py`` anchors both).

Knobs (the ``gp_stack_depth`` / ``gp_opcode_block`` tuning axes,
``tuning/space.py``):

- ``stack_depth`` — rows of the value stack. Auto = ``max_nodes`` (the
  provable worst case); anything smaller is rejected by the plan
  (``ops/gp_eval.gp_eval_plan``) rather than silently mis-evaluating.
  Larger values trade scratch for nothing on paper — which is exactly
  why they are a MEASURED axis, not a hardcoded choice.
- ``opcode_block`` — tokens interpreted per loop iteration (the body
  unrolls this many steps). Must divide ``max_nodes``.

Both knobs change the traced program, so distinct settings are
distinct compiled plans even on CPU — the first non-null autotuner
search space off-chip (ISSUE 11 tentpole).
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from libpga_tpu.gp.encoding import (
    DIV_EPS,
    GPConfig,
    LOG_EPS,
    PAD_OP,
    decode_args,
    decode_ops,
)

_UNARY_FNS = {
    "neg": lambda a: -a,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "sqrt": lambda a: jnp.sqrt(jnp.abs(a)),
    "abs": jnp.abs,
    "exp": jnp.exp,
    "log": lambda a: jnp.log(jnp.abs(a) + jnp.float32(LOG_EPS)),
}

_BINARY_FNS = {
    "add": lambda a, b: a + b,
    "sub": lambda a, b: a - b,
    "mul": lambda a, b: a * b,
    "div": lambda a, b: jnp.where(
        jnp.abs(b) < DIV_EPS,
        jnp.float32(1.0),
        a / jnp.where(jnp.abs(b) < DIV_EPS, jnp.float32(1.0), b),
    ),
    "min": jnp.minimum,
    "max": jnp.maximum,
}


def make_token_step(
    gp: GPConfig,
    *,
    dispatch: Optional[str] = None,
    lit: bool = False,
) -> Callable:
    """The one token-step implementation both evaluators share.

    Returns ``step(stack, sp, op, arg, xt, consts) -> (stack, sp)``
    with ``stack (S, P, B)`` f32, ``sp (P,)`` i32, ``op (P,)`` i32,
    ``arg (P,)`` f32, ``xt (n_vars, B)`` f32 (the sample matrix,
    variable-major), ``consts (n_consts,)`` f32. Mask-only: terminal
    lookups are masked accumulations over the (small) variable/constant
    tables, stack reads/writes are iota-compare selects — Mosaic-legal
    inside a kernel, ordinary VPU code under XLA.

    ``dispatch`` selects the candidate-plane strategy (the
    ``gp_dispatch`` tuning axis): ``None``/``"dense"`` is the original
    every-op-every-token lattice (byte-identical trace to the
    pre-optimizer step — the ``GPConfig(optimize=False)`` escape hatch
    depends on it); ``"blocked"`` groups candidates by arity class —
    one composite plane per class, selected once by arity, with the
    add/sub pair fused into a single signed add. Every strategy
    computes the same IEEE operations on the same operands, so all
    dispatches score bit-identically; which is FASTER is a measured
    question per backend (``tools/autotune.py``).

    ``lit=True`` additionally understands the optimizer's synthetic
    ``LIT`` opcode (``gp/optimize.lit_op``: arity 0, value = operand) —
    only the compacted-program paths enable it, so the legacy trace is
    untouched.
    """
    names = gp.op_names()
    arity_tab = gp.op_arities()
    var_op = names.index("var")
    const_op = names.index("const") if gp.consts else -1
    unary_ids = [(names.index(n), _UNARY_FNS[n]) for n in gp.unary]
    binary_ids = [(names.index(n), _BINARY_FNS[n]) for n in gp.binary]
    n_vars = gp.n_vars
    n_consts = len(gp.consts)
    mode = dispatch or gp.dispatch or "dense"
    if mode not in ("dense", "blocked"):
        raise ValueError(
            f"gp_dispatch must be 'dense' or 'blocked'; got {mode!r}"
        )
    lit_id = gp.n_ops if lit else None

    def step(stack, sp, op, arg, xt, consts):
        S = stack.shape[0]
        # Per-row arity: masked accumulation over the static table.
        a_of = jnp.zeros_like(op)
        for k, a in enumerate(arity_tab):
            if a:
                a_of = jnp.where(op == k, jnp.int32(a), a_of)
        sidx = jax.lax.broadcasted_iota(jnp.int32, stack.shape, 0)
        spb = sp[None, :, None]
        top = jnp.sum(jnp.where(sidx == spb - 1, stack, 0.0), axis=0)
        sec = jnp.sum(jnp.where(sidx == spb - 2, stack, 0.0), axis=0)

        # Terminals: masked accumulation over the variable / constant
        # tables (both small by construction — no gather).
        opb = op[:, None]
        argb = arg[:, None]
        vidx = jnp.clip(
            jnp.floor(argb * n_vars).astype(jnp.int32), 0, n_vars - 1
        )
        leaf = jnp.zeros_like(top)
        for v in range(n_vars):
            leaf = jnp.where(vidx == v, xt[v][None, :], leaf)
        if const_op >= 0:
            cidx = jnp.clip(
                jnp.floor(argb * n_consts).astype(jnp.int32), 0, n_consts - 1
            )
            cval = jnp.zeros_like(top)
            for c in range(n_consts):
                cval = jnp.where(cidx == c, consts[c], cval)
            leaf = jnp.where(opb == const_op, cval, leaf)
        if lit_id is not None:
            # The folded literal: its operand IS the value (broadcast
            # over the sample axis).
            leaf = jnp.where(opb == lit_id, argb, leaf)

        if mode == "dense":
            res = leaf
            for k, fn in unary_ids:
                res = jnp.where(opb == k, fn(top), res)
            for k, fn in binary_ids:
                res = jnp.where(opb == k, fn(sec, top), res)
        else:  # blocked: one composite candidate per arity class
            abm = a_of[:, None]
            res = leaf
            if unary_ids:
                (k0, f0), rest = unary_ids[0], unary_ids[1:]
                un = f0(top)
                for k, fn in rest:
                    un = jnp.where(opb == k, fn(top), un)
                res = jnp.where(abm == 1, un, res)
            if binary_ids:
                fuse = "add" in gp.binary and "sub" in gp.binary
                if fuse:
                    sub_id = names.index("sub")
                    # a - b == a + (-b) bit-exactly in IEEE: one signed
                    # add serves both ops.
                    bi = sec + jnp.where(opb == sub_id, -top, top)
                    rest = [
                        (names.index(n), _BINARY_FNS[n])
                        for n in gp.binary
                        if n not in ("add", "sub")
                    ]
                else:
                    (k0, f0), rest = binary_ids[0], binary_ids[1:]
                    bi = f0(sec, top)
                for k, fn in rest:
                    bi = jnp.where(opb == k, fn(sec, top), bi)
                res = jnp.where(abm == 2, bi, res)

        ex = (op != PAD_OP) & (sp >= a_of) & (sp - a_of < S)
        nsp = jnp.where(ex, sp - a_of + 1, sp)
        write = (sidx == nsp[None, :, None] - 1) & ex[None, :, None]
        stack = jnp.where(write, res[None, :, :], stack)
        return stack, nsp

    return step


def stack_predict(
    genomes: jax.Array,
    xt: jax.Array,
    gp: GPConfig,
    *,
    stack_depth: Optional[int] = None,
    opcode_block: Optional[int] = None,
    dispatch: Optional[str] = None,
) -> jax.Array:
    """Run the stack machine over a gene matrix: ``(P, 2T)`` genomes ×
    ``(n_vars, B)`` variable-major samples → ``(P, B)`` predictions.
    Total over arbitrary gene values (skip rule). Traceable — the
    engine's ``evaluate`` jits straight through it. This is the
    UNOPTIMIZED path (static ``max_nodes`` trip count); with the
    default knobs it lowers byte-identically to the pre-optimizer
    interpreter — the ``GPConfig(optimize=False)`` escape hatch.
    """
    S = int(stack_depth or gp.required_stack())
    block = int(opcode_block or 1)
    T = gp.max_nodes
    if S < gp.required_stack():
        raise ValueError(
            f"stack_depth {S} < required bound {gp.required_stack()} "
            f"(a well-formed {T}-token program can hold {T} values)"
        )
    if T % block:
        raise ValueError(f"opcode_block {block} does not divide {T}")
    P = genomes.shape[0]
    B = xt.shape[1]
    ops = decode_ops(genomes, gp)
    args = decode_args(genomes, gp)
    consts = jnp.asarray(gp.consts or (0.0,), jnp.float32)
    step = make_token_step(gp, dispatch=dispatch)

    def body(i, carry):
        stack, sp = carry
        for j in range(block):
            t = i * block + j
            op = jax.lax.dynamic_index_in_dim(ops, t, 1, keepdims=False)
            arg = jax.lax.dynamic_index_in_dim(args, t, 1, keepdims=False)
            stack, sp = step(stack, sp, op, arg, xt, consts)
        return stack, sp

    stack0 = jnp.zeros((S, P, B), jnp.float32)
    sp0 = jnp.zeros((P,), jnp.int32)
    stack, sp = jax.lax.fori_loop(0, T // block, body, (stack0, sp0))
    sidx = jax.lax.broadcasted_iota(jnp.int32, stack.shape, 0)
    top = jnp.sum(
        jnp.where(sidx == sp[None, :, None] - 1, stack, 0.0), axis=0
    )
    return jnp.where(sp[:, None] > 0, top, 0.0)


#: Rows per length-sorted population block of the optimized path. Each
#: block's token loop bounds at ITS OWN max live length, so the total
#: trip count tracks the length distribution's quantiles instead of the
#: population max — the multiplicative win of compaction.
SEG_ROWS = 128


def stack_predict_program(
    prog,
    xt: jax.Array,
    gp: GPConfig,
    *,
    stack_depth: Optional[int] = None,
    opcode_block: Optional[int] = None,
    dispatch: Optional[str] = None,
    seg_rows: Optional[int] = None,
) -> jax.Array:
    """Run the stack machine over a compacted :class:`~libpga_tpu.gp.
    optimize.EvalProgram` with live-length trip reduction.

    The population is sorted by live length (a transient permutation —
    predictions scatter back; stored genomes are untouched) and split
    into ``seg_rows`` blocks; each block's ``fori_loop`` bounds at the
    block's max live length — a RUNTIME scalar, so the trip count
    follows each generation's programs with zero recompiles (the bound
    lowers to a ``while``; the traced program is shape-static).
    Tokens past an individual's own live length are pads and mask out
    exactly as in the unoptimized path.
    """
    preds, inv = _predict_program_sorted(
        prog, xt, gp,
        stack_depth=stack_depth, opcode_block=opcode_block,
        dispatch=dispatch, seg_rows=seg_rows,
    )
    return preds[inv]


def _predict_program_sorted(
    prog,
    xt: jax.Array,
    gp: GPConfig,
    *,
    stack_depth: Optional[int] = None,
    opcode_block: Optional[int] = None,
    dispatch: Optional[str] = None,
    seg_rows: Optional[int] = None,
):
    """:func:`stack_predict_program` minus the final un-permute:
    returns ``(preds_sorted, inv)`` with predictions in live-length
    order. Reductions over the sample axis (the RMSE in
    ``make_eval_rows``) must run on the SORTED array and gather the
    per-row results through ``inv`` afterwards: fusing the row gather
    into a sample-axis reduce lets XLA pick a different summation
    order than the unoptimized path's, and the 1-ulp wobble breaks
    bit-equality with ``optimize=False`` inside the engine's jit.
    """
    S = int(stack_depth or gp.required_stack())
    block = int(opcode_block or 1)
    T = gp.max_nodes
    if S < gp.required_stack():
        raise ValueError(
            f"stack_depth {S} < required bound {gp.required_stack()} "
            f"(a well-formed {T}-token program can hold {T} values)"
        )
    if T % block:
        raise ValueError(f"opcode_block {block} does not divide {T}")
    P = prog.ops.shape[0]
    B = xt.shape[1]
    consts = jnp.asarray(gp.consts or (0.0,), jnp.float32)
    step = make_token_step(gp, dispatch=dispatch, lit=True)
    R = int(seg_rows or min(P, SEG_ROWS))
    G = -(-P // R)
    pad_n = G * R - P

    order = jnp.argsort(prog.length)
    inv = jnp.argsort(order)
    ops_s = prog.ops[order]
    args_s = prog.args[order]
    len_s = prog.length[order]
    if pad_n:
        ops_s = jnp.pad(
            ops_s, ((0, pad_n), (0, 0)), constant_values=PAD_OP
        )
        args_s = jnp.pad(args_s, ((0, pad_n), (0, 0)), constant_values=0.5)
        len_s = jnp.pad(len_s, (0, pad_n))

    def seg(_, xs):
        o, a, ln = xs
        maxlen = jnp.max(ln)
        nblk = (maxlen + block - 1) // block

        def body(i, carry):
            stack, sp = carry
            for j in range(block):
                t = i * block + j
                op = jax.lax.dynamic_index_in_dim(o, t, 1, keepdims=False)
                arg = jax.lax.dynamic_index_in_dim(a, t, 1, keepdims=False)
                stack, sp = step(stack, sp, op, arg, xt, consts)
            return stack, sp

        stack0 = jnp.zeros((S, R, B), jnp.float32)
        sp0 = jnp.zeros((R,), jnp.int32)
        stack, sp = jax.lax.fori_loop(0, nblk, body, (stack0, sp0))
        sidx = jax.lax.broadcasted_iota(jnp.int32, stack.shape, 0)
        top = jnp.sum(
            jnp.where(sidx == sp[None, :, None] - 1, stack, 0.0), axis=0
        )
        return None, jnp.where(sp[:, None] > 0, top, 0.0)

    _, preds = jax.lax.scan(
        seg,
        None,
        (
            ops_s.reshape(G, R, T),
            args_s.reshape(G, R, T),
            len_s.reshape(G, R),
        ),
    )
    return preds.reshape(G * R, B)[:P], inv


def make_eval_rows(
    gp: GPConfig,
    X,
    y,
    *,
    stack_depth: Optional[int] = None,
    opcode_block: Optional[int] = None,
    parsimony: float = 0.0,
    optimize: Optional[bool] = None,
    dispatch: Optional[str] = None,
) -> Callable:
    """Whole-population symbolic-regression scorer: ``rows(m) -> (P,)``
    float32 ``-RMSE`` scores (higher is better), with non-finite scores
    sanitized to ``-inf`` (one overflowing ``exp``/``mul`` chain must
    not poison the run loop's ``max(scores)`` target check), and an
    optional parsimony penalty per non-pad token.

    ``optimize`` (None = ``gp.optimize``) routes evaluation through the
    eval-time program optimizer (``gp/optimize.py``): fold + DCE +
    compact, then the live-length-bounded
    :func:`stack_predict_program`. Scores are bit-equal either way
    within a given compile context (the fold uses the evaluator's own
    jnp table, and the RMSE reduce runs before the row un-permute);
    across DIFFERENT enclosing programs XLA may re-emit the sample
    reduce with 1-ulp wobble — exactly as the unoptimized path already
    wobbles eager-vs-jit. ``rows`` also accepts an
    already-optimized ``EvalProgram`` directly — how the engine's
    ``prepare_eval`` hook hands over pre-compacted buffers — except
    under parsimony, which must count the STORED genome's tokens.
    """
    import numpy as np

    # NUMPY closures deliberately: this factory may run INSIDE an
    # active jit trace (the engine's first evaluate builds the rows fn
    # lazily), where any jnp op would stage a tracer into the cached
    # closure and leak it into later traces. Numpy constants convert
    # fresh per trace.
    Xa = np.asarray(X, np.float32)
    if Xa.ndim == 1:
        Xa = Xa[:, None]
    if Xa.shape[1] != gp.n_vars:
        raise ValueError(
            f"X has {Xa.shape[1]} columns; GPConfig.n_vars is {gp.n_vars}"
        )
    ya = np.asarray(y, np.float32).reshape(-1)
    if ya.shape[0] != Xa.shape[0]:
        raise ValueError(
            f"X has {Xa.shape[0]} samples but y has {ya.shape[0]}"
        )
    xt = np.ascontiguousarray(Xa.T)  # (n_vars, B), variable-major
    pfloat = float(parsimony)
    opt_on = bool(gp.optimize if optimize is None else optimize)

    def rows(m):
        from libpga_tpu.gp.optimize import EvalProgram, optimize_for_eval

        live_src = m
        inv = None
        if isinstance(m, EvalProgram):
            if pfloat:
                raise ValueError(
                    "parsimony scoring counts the stored genome's "
                    "tokens; pass the gene matrix, not an EvalProgram"
                )
            preds, inv = _predict_program_sorted(
                m, xt, gp,
                stack_depth=stack_depth, opcode_block=opcode_block,
                dispatch=dispatch,
            )
        elif opt_on:
            prog = optimize_for_eval(m, gp)
            preds, inv = _predict_program_sorted(
                prog, xt, gp,
                stack_depth=stack_depth, opcode_block=opcode_block,
                dispatch=dispatch,
            )
        else:
            preds = stack_predict(
                m, xt, gp,
                stack_depth=stack_depth, opcode_block=opcode_block,
                dispatch=dispatch,
            )
        err = preds - ya[None, :]
        score = -jnp.sqrt(jnp.mean(err * err, axis=1))
        if inv is not None:
            # Un-permute AFTER the sample-axis reduce: gathering rows
            # first lets the reduce fuse with the gather and pick a
            # different summation order than the unoptimized path
            # (1-ulp drift that breaks bit-equality under jit).
            score = score[inv]
        if pfloat:
            live = jnp.sum(
                (decode_ops(live_src, gp) != PAD_OP).astype(jnp.float32),
                axis=1,
            )
            score = score - jnp.float32(pfloat) * live
        return jnp.where(jnp.isfinite(score), score, -jnp.inf).astype(
            jnp.float32
        )

    return rows


__all__ = [
    "make_token_step",
    "stack_predict",
    "stack_predict_program",
    "SEG_ROWS",
    "make_eval_rows",
]
