"""Linear postfix tree encoding packed into fixed-width gene vectors.

Tree-based genetic programming (ROADMAP item 1: population-level
parallel tree GP, arxiv 2501.17168; TensorGP, arxiv 2103.07512) on the
library's EXISTING genome contract: a program is a bounded sequence of
``max_nodes`` postfix tokens, each token TWO genes of the ordinary
``(P, L)`` float population matrix (``L = 2 * max_nodes``, genes in
[0, 1) — the same domain every other workload uses, so checkpointing,
``pop_shards``, islands, serving buckets, and the validation oracle all
compose with zero special cases):

- gene ``2t``   — the OPCODE: ``floor(g * n_ops)`` indexes the config's
  opcode table (explicit arity per entry, below);
- gene ``2t+1`` — the OPERAND: terminals decode it (``var`` →
  ``floor(g * n_vars)`` input column, ``const`` → ``floor(g *
  n_consts)`` row of the registered constant table); internal nodes
  ignore it (a neutral mutation surface, like the reference TSP
  drivers' unused gene tails).

Opcode table layout (``op_table``): index 0 is always ``pad`` —
tokens after the program's end — then ``var``, then ``const`` (present
only when the constant table is non-empty), then the configured unary
and binary function sets, in declaration order. Encoded opcode genes
are CENTERED on their bucket (``(k + 0.5) / n_ops``) so float32
round-trips exactly; the decode floors, so ANY gene value still maps
to a token (the decode is total).

**Well-formedness.** A genome is *strictly well-formed* when its
non-pad tokens form one contiguous prefix, every one of them executes
(stack depth ≥ arity at its position), and the final stack depth is
exactly 1 — i.e. the token sequence IS the postfix traversal of one
expression tree. Every genome the subsystem's own machinery produces
is strictly well-formed *by construction*: random initialization grows
programs under a feasibility invariant (:func:`random_program_genes`),
and the GP operators (``gp/operators.py``) splice complete subtrees
only. For ARBITRARY gene matrices (e.g. a plain ``create_population``
random init arriving through the serving path) the evaluator and the
operators first apply the SKIP RULE — a token whose arity exceeds the
current stack depth is a no-op — which makes every decode a
well-formed program (the executable subsequence) and every operator
total; :func:`canonicalize` materializes that normalization (live
tokens compacted front, pads stamped behind), and the pure-numpy
reference interpreter (``gp/reference.py``) is the semantics oracle
the fused evaluators are tested against.

Subtree geometry is recovered in one forward scan
(:func:`program_structure`): the same stack walk the interpreter runs,
carrying the SUBTREE-START position of every stack slot — so the
subtree ending at token ``i`` is exactly the gene slice ``[start[i],
i]``, which is what size-fair crossover swaps.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

#: Unary/binary function vocabulary. Protected forms keep every
#: program total: div guards |b| < DIV_EPS -> 1.0, sqrt takes |x|,
#: log takes log(|x| + LOG_EPS). One table — the numpy reference, the
#: XLA interpreter, and the Pallas kernel all derive from it.
UNARY_NAMES: Tuple[str, ...] = ("neg", "sin", "cos", "sqrt", "abs", "exp", "log")
BINARY_NAMES: Tuple[str, ...] = ("add", "sub", "mul", "div", "min", "max")

DIV_EPS = 1e-6
LOG_EPS = 1e-9

PAD_OP = 0  #: opcode index 0 is always the pad token

#: Token-step dispatch strategies (the ``gp_dispatch`` tuning axis).
#: ``None`` = auto (dense — the original every-op-every-token lattice).
DISPATCH_KINDS: Tuple = (None, "dense", "blocked")


@dataclasses.dataclass(frozen=True)
class GPConfig:
    """Encoding of one GP search space (re-exported by
    ``libpga_tpu.config``).

    Attributes:
      max_nodes: token capacity per program; the genome length is
        ``2 * max_nodes`` genes. Programs shorter than the cap carry
        pad tokens behind their prefix.
      n_vars: input-variable count (``x0 .. x{n_vars-1}`` — the
        feature columns of a symbolic-regression dataset).
      consts: indexed constant table terminals may reference. Empty
        drops the ``const`` opcode entirely.
      unary: enabled unary function names (subset of
        :data:`UNARY_NAMES`). May be empty — random growth then
        rounds target lengths to odd (binary trees over terminals
        have odd token counts).
      binary: enabled binary function names (subset of
        :data:`BINARY_NAMES`).
      min_nodes: ramped-init lower bound on program length.
      stack_depth: explicit evaluator stack depth, or None = auto
        (``max_nodes``, the provable worst case — a program of
        ``max_nodes`` terminals). Explicit values below the bound are
        rejected by the evaluator plan (``ops/gp_eval.gp_eval_plan``);
        values above it are admissible and form the
        ``gp_stack_depth`` tuning axis.
      opcode_block: tokens interpreted per fused-loop iteration
        (unroll factor), or None = auto (1). Must divide
        ``max_nodes``; the ``gp_opcode_block`` tuning axis.
      optimize: run the eval-time program optimizer (``gp/optimize.py``
        — canonicalize → constant-fold → DCE → compact) before every
        evaluation. On by default; ``optimize=False`` is the escape
        hatch that lowers the PRE-OPTIMIZER traced program
        byte-identically (``tools/gp_smoke.py`` gates it via
        ``analysis.fingerprint``). Stored genomes are never touched
        either way — the optimizer rewrites only the transient eval
        buffer.
      dispatch: token-step dispatch strategy — ``None`` = auto
        (``"dense"``, the original every-op-every-token mask lattice)
        or ``"blocked"`` (arity-class-grouped candidate planes with
        shared-operand fusions); the ``gp_dispatch`` tuning axis.

    The gene dtype for GP populations is float32: bfloat16's ~0.004
    resolution near 1.0 corrupts ``floor(g * n)`` opcode decodes, the
    same reason order crossover is f32-only (``ops/pallas_step``).
    """

    max_nodes: int = 16
    n_vars: int = 1
    consts: Tuple[float, ...] = (0.5, 1.0, 2.0, 3.0, 5.0)
    unary: Tuple[str, ...] = ("neg", "sin", "cos")
    binary: Tuple[str, ...] = ("add", "sub", "mul", "div")
    min_nodes: int = 1
    stack_depth: Optional[int] = None
    opcode_block: Optional[int] = None
    optimize: bool = True
    dispatch: Optional[str] = None

    def __post_init__(self):
        if self.max_nodes < 2:
            # genome_len = 2*max_nodes must satisfy the library's
            # reference-parity floor of 4 genes.
            raise ValueError("max_nodes must be >= 2")
        if self.n_vars < 1:
            raise ValueError("n_vars must be >= 1")
        bad = sorted(set(self.unary) - set(UNARY_NAMES))
        if bad:
            raise ValueError(
                f"unknown unary ops {bad}; available: {list(UNARY_NAMES)}"
            )
        bad = sorted(set(self.binary) - set(BINARY_NAMES))
        if bad:
            raise ValueError(
                f"unknown binary ops {bad}; available: {list(BINARY_NAMES)}"
            )
        if not (1 <= self.min_nodes <= self.max_nodes):
            raise ValueError("min_nodes must be in [1, max_nodes]")
        if self.stack_depth is not None and self.stack_depth < 1:
            raise ValueError("stack_depth must be >= 1 or None")
        if self.opcode_block is not None and (
            self.opcode_block < 1 or self.max_nodes % self.opcode_block
        ):
            raise ValueError(
                f"opcode_block must divide max_nodes ({self.max_nodes})"
            )
        if self.dispatch not in DISPATCH_KINDS:
            raise ValueError(
                f"dispatch must be one of {DISPATCH_KINDS}; "
                f"got {self.dispatch!r}"
            )

    @property
    def genome_len(self) -> int:
        return 2 * self.max_nodes

    def op_names(self) -> Tuple[str, ...]:
        """The opcode table: pad, terminals, then functions."""
        terms = ("pad", "var") + (("const",) if self.consts else ())
        return terms + tuple(self.unary) + tuple(self.binary)

    def op_arities(self) -> Tuple[int, ...]:
        arity = {"pad": 0, "var": 0, "const": 0}
        arity.update({n: 1 for n in self.unary})
        arity.update({n: 2 for n in self.binary})
        return tuple(arity[n] for n in self.op_names())

    @property
    def n_ops(self) -> int:
        return len(self.op_names())

    def op_index(self, name: str) -> int:
        return self.op_names().index(name)

    def opcode_gene(self, op: int) -> float:
        """Bucket-centered gene value encoding opcode ``op``."""
        return (op + 0.5) / self.n_ops

    def operand_gene(self, idx: int, domain: int) -> float:
        return (idx + 0.5) / max(domain, 1)

    @property
    def pad_gene(self) -> float:
        return self.opcode_gene(PAD_OP)

    def required_stack(self) -> int:
        """The provable stack bound: a well-formed program of
        ``max_nodes`` tokens can hold at most ``max_nodes`` pending
        values (all-terminal sequences under the skip rule)."""
        return self.max_nodes

    def cache_key(self) -> tuple:
        """Hashable identity of the encoding (operator/objective cache
        keys and the serving bucket signature derive from it). The
        evaluator-shaping fields (``optimize``/``dispatch``) are part of
        the identity: distinct settings are distinct compiled programs,
        so tuning entries and serving buckets must not alias them."""
        return (
            "gp", self.max_nodes, self.n_vars, tuple(self.consts),
            tuple(self.unary), tuple(self.binary), self.min_nodes,
            self.optimize, self.dispatch,
        )


# ------------------------------------------------------------- decoding


def decode_ops(genomes: jax.Array, gp: GPConfig) -> jax.Array:
    """(P, max_nodes) int32 opcode matrix from the even gene columns.
    Total: any float gene decodes (floored, clipped into the table)."""
    opg = genomes[:, 0 :: 2].astype(jnp.float32)
    return jnp.clip(
        jnp.floor(opg * gp.n_ops).astype(jnp.int32), 0, gp.n_ops - 1
    )


def decode_args(genomes: jax.Array, gp: GPConfig) -> jax.Array:
    """(P, max_nodes) float32 operand matrix (the odd gene columns)."""
    return genomes[:, 1 :: 2].astype(jnp.float32)


@dataclasses.dataclass(frozen=True)
class Structure:
    """Per-token program geometry under the skip rule (all ``(P, T)``
    unless noted): ``live`` — the token executes; ``start`` — first
    token of the subtree it completes (= its own index for dead
    tokens); ``span`` — ``t - start + 1``; ``length`` ``(P,)`` — live
    token count; ``final_depth`` ``(P,)`` — stack depth after the last
    token (1 for strictly well-formed programs)."""

    live: jax.Array
    start: jax.Array
    span: jax.Array
    length: jax.Array
    final_depth: jax.Array


def program_structure(genomes: jax.Array, gp: GPConfig) -> Structure:
    """One forward stack walk recovering subtree geometry.

    The same scan the interpreter runs, but carrying subtree START
    positions instead of values: executing a leaf pushes its own
    index; executing an arity-``a`` function pushes the start of its
    DEEPEST popped operand (the leftmost token of the completed
    subtree). Pure XLA (the GP operators are XLA-path operators —
    gathers are fine here, unlike in the Mosaic kernel).
    """
    P, L = genomes.shape
    T = gp.max_nodes
    if L != 2 * T:
        raise ValueError(
            f"genome_len {L} != 2 * max_nodes ({2 * T}) for this GPConfig"
        )
    ops = decode_ops(genomes, gp)
    arity = jnp.asarray(gp.op_arities(), jnp.int32)

    def body(carry, xs):
        sp, sstack = carry  # (P,), (P, T)
        t, op = xs
        a = arity[op]
        ex = (op != PAD_OP) & (sp >= a)
        idx = jnp.clip(sp - a, 0, T - 1)
        st_inner = jnp.take_along_axis(sstack, idx[:, None], axis=1)[:, 0]
        st = jnp.where(a == 0, t, st_inner)
        nsp = jnp.where(ex, sp - a + 1, sp)
        wid = jnp.clip(nsp - 1, 0, T - 1)
        onehot = (
            jnp.arange(T, dtype=jnp.int32)[None, :] == wid[:, None]
        ) & ex[:, None]
        sstack = jnp.where(onehot, st[:, None], sstack)
        return (nsp, sstack), (ex, jnp.where(ex, st, t))

    zeros = jnp.zeros((P,), jnp.int32)
    (sp_f, _), (live_t, start_t) = jax.lax.scan(
        body,
        (zeros, jnp.zeros((P, T), jnp.int32)),
        (jnp.arange(T, dtype=jnp.int32), ops.T),
    )
    live = live_t.T
    start = start_t.T
    span = jnp.arange(T, dtype=jnp.int32)[None, :] - start + 1
    return Structure(
        live=live,
        start=start,
        span=span,
        length=jnp.sum(live.astype(jnp.int32), axis=1),
        final_depth=sp_f,
    )


def canonicalize(genomes: jax.Array, gp: GPConfig) -> jax.Array:
    """Normalize arbitrary genomes to strict layout: live tokens
    compacted to the front (order preserved — their stack profile, and
    therefore the program's value, is unchanged: dead tokens never
    altered the depth), pad tokens STAMPED behind (a dead token left in
    the tail could come alive at the shallower depth of a future
    splice site). Idempotent; strictly well-formed genomes (modulo the
    pad tail's operand genes) pass through with the same live prefix.
    """
    st = program_structure(genomes, gp)
    T = gp.max_nodes
    # Stable live-first token order (jax sorts are stable).
    order = jnp.argsort((~st.live).astype(jnp.int32), axis=1)
    gidx = jnp.stack([2 * order, 2 * order + 1], axis=2).reshape(
        genomes.shape[0], 2 * T
    )
    out = jnp.take_along_axis(genomes, gidx, axis=1)
    tail = jnp.arange(T, dtype=jnp.int32)[None, :] >= st.length[:, None]
    pad_pair = jnp.stack(
        [jnp.full((), gp.pad_gene, out.dtype), jnp.full((), 0.5, out.dtype)]
    )
    tail_genes = jnp.repeat(tail, 2, axis=1)
    pad_row = jnp.tile(pad_pair, T)[None, :]
    return jnp.where(tail_genes, pad_row, out)


# ----------------------------------------------------- random programs

#: Column layout of the random-growth rand block: one length gene,
#: then max_nodes opcode-choice genes, then max_nodes operand genes.
def grow_rand_cols(gp: GPConfig) -> int:
    return 1 + 2 * gp.max_nodes


def random_program_genes(rand: jax.Array, gp: GPConfig) -> jax.Array:
    """Grow one strictly well-formed program per row from a uniform
    rand block (``(P, grow_rand_cols)``).

    Ramped lengths in ``[min_nodes, max_nodes]`` (rounded to odd when
    the unary set is empty — pure binary trees have odd token counts),
    then a left-to-right draw under the feasibility invariant
    ``depth' <= remaining'``: at every step the allowed arities are
    ``a <= depth`` with ``depth - a <= remaining - 1``, which is never
    empty and forces the final depth to exactly 1 — well-formed BY
    CONSTRUCTION, no repair pass. Deterministic in the rand block, so
    the same draw is reusable as a mutation donor (``gp/operators``)
    and as a seeded population init (:func:`random_population`).
    """
    P = rand.shape[0]
    T = gp.max_nodes
    arity = jnp.asarray(gp.op_arities(), jnp.int32)
    n_ops = gp.n_ops
    lo, hi = gp.min_nodes, gp.max_nodes
    tlen = lo + jnp.floor(rand[:, 0] * (hi - lo + 1)).astype(jnp.int32)
    tlen = jnp.clip(tlen, lo, hi)
    if not gp.unary:
        # No arity-1 filler: only odd lengths close to depth 1.
        tlen = jnp.maximum(tlen - (1 - tlen % 2), 1)
    op_ids = jnp.arange(n_ops, dtype=jnp.int32)

    def body(carry, xs):
        d = carry
        t, r_op, r_arg = xs
        active = t < tlen
        remaining = tlen - t
        allowed = (
            (arity[None, :] <= d[:, None])
            & ((d[:, None] - arity[None, :]) <= remaining[:, None] - 1)
            & (op_ids != PAD_OP)[None, :]
            & active[:, None]
        )
        cnt = jnp.sum(allowed.astype(jnp.int32), axis=1)
        choice = jnp.floor(r_op * cnt).astype(jnp.int32)
        cum = jnp.cumsum(allowed.astype(jnp.int32), axis=1)
        sel = allowed & (cum == choice[:, None] + 1)
        op = jnp.argmax(sel, axis=1).astype(jnp.int32)
        d = jnp.where(active, d - arity[op] + 1, d)
        op_gene = jnp.where(
            active, (op.astype(jnp.float32) + 0.5) / n_ops, gp.pad_gene
        )
        arg_gene = jnp.where(active, r_arg, 0.5)
        return d, (op_gene, arg_gene)

    _, (op_g, arg_g) = jax.lax.scan(
        body,
        jnp.zeros((P,), jnp.int32),
        (
            jnp.arange(T, dtype=jnp.int32),
            rand[:, 1 : T + 1].T.astype(jnp.float32),
            rand[:, T + 1 : 2 * T + 1].T.astype(jnp.float32),
        ),
    )
    genes = jnp.stack([op_g.T, arg_g.T], axis=2).reshape(P, 2 * T)
    return genes.astype(jnp.float32)


def random_population(key: jax.Array, size: int, gp: GPConfig) -> jax.Array:
    """``(size, 2 * max_nodes)`` float32 matrix of strictly well-formed
    random programs — the GP init (install with
    ``PGA.install_population``)."""
    rand = jax.random.uniform(key, (size, grow_rand_cols(gp)))
    return random_program_genes(rand, gp)


# --------------------------------------------------------- host helpers


def encode_program(tokens: Sequence, gp: GPConfig) -> np.ndarray:
    """Encode an explicit token list into one genome (host-side — test
    fixtures and known-target construction). Tokens: ``("var", i)``,
    ``("const", i)``, or a function name string."""
    T = gp.max_nodes
    if len(tokens) > T:
        raise ValueError(f"{len(tokens)} tokens exceed max_nodes {T}")
    names = gp.op_names()
    g = np.empty(2 * T, np.float32)
    g[0::2] = gp.pad_gene
    g[1::2] = 0.5
    for t, tok in enumerate(tokens):
        if isinstance(tok, tuple):
            kind, idx = tok
            if kind == "var":
                if not (0 <= idx < gp.n_vars):
                    raise ValueError(f"var index {idx} out of range")
                g[2 * t] = gp.opcode_gene(names.index("var"))
                g[2 * t + 1] = gp.operand_gene(idx, gp.n_vars)
            elif kind == "const":
                if not (0 <= idx < len(gp.consts)):
                    raise ValueError(f"const index {idx} out of range")
                g[2 * t] = gp.opcode_gene(names.index("const"))
                g[2 * t + 1] = gp.operand_gene(idx, len(gp.consts))
            else:
                raise ValueError(f"unknown terminal kind {kind!r}")
        else:
            if tok not in names or tok == "pad":
                raise ValueError(f"unknown op {tok!r}; table: {names}")
            g[2 * t] = gp.opcode_gene(names.index(tok))
    return g


def is_well_formed(genome: np.ndarray, gp: GPConfig) -> bool:
    """STRICT host-side well-formedness check (the property-test
    oracle): non-pad tokens form one prefix, every one executes, and
    the final stack depth is exactly 1."""
    g = np.asarray(genome, np.float32)
    T = gp.max_nodes
    if g.shape != (2 * T,):
        return False
    ops = np.clip(
        np.floor(g[0::2] * gp.n_ops).astype(np.int64), 0, gp.n_ops - 1
    )
    arity = np.asarray(gp.op_arities())
    nonpad = ops != PAD_OP
    length = int(nonpad.sum())
    if length == 0:
        return False
    if not np.all(nonpad[:length]) or np.any(nonpad[length:]):
        return False  # pads interleaved with live tokens
    depth = 0
    for t in range(length):
        a = int(arity[ops[t]])
        if depth < a:
            return False  # token would underflow (skip rule would fire)
        depth += 1 - a
    return depth == 1


def decode_expression(genome: np.ndarray, gp: GPConfig) -> str:
    """Human-readable infix rendering of one genome's program (under
    the skip rule, so it is total). Empty programs render ``"0"``."""
    g = np.asarray(genome, np.float32)
    ops = np.clip(
        np.floor(g[0::2] * gp.n_ops).astype(np.int64), 0, gp.n_ops - 1
    )
    args = g[1::2]
    names = gp.op_names()
    arity = np.asarray(gp.op_arities())
    infix = {"add": "+", "sub": "-", "mul": "*", "div": "/"}
    stack: list = []
    for t in range(gp.max_nodes):
        name = names[ops[t]]
        a = int(arity[ops[t]])
        if name == "pad" or len(stack) < a:
            continue
        if name == "var":
            v = min(int(args[t] * gp.n_vars), gp.n_vars - 1)
            stack.append(f"x{v}")
        elif name == "const":
            c = min(int(args[t] * len(gp.consts)), len(gp.consts) - 1)
            stack.append(repr(float(gp.consts[c])))
        elif a == 1:
            x = stack.pop()
            stack.append(f"(-{x})" if name == "neg" else f"{name}({x})")
        else:
            rhs, lhs = stack.pop(), stack.pop()
            if name in infix:
                stack.append(f"({lhs} {infix[name]} {rhs})")
            else:
                stack.append(f"{name}({lhs}, {rhs})")
    return stack[-1] if stack else "0"


def program_length(genome: np.ndarray, gp: GPConfig) -> int:
    """Host-side live-token count (skip-rule semantics)."""
    g = np.asarray(genome, np.float32)
    ops = np.clip(
        np.floor(g[0::2] * gp.n_ops).astype(np.int64), 0, gp.n_ops - 1
    )
    arity = np.asarray(gp.op_arities())
    depth = 0
    n = 0
    for t in range(gp.max_nodes):
        a = int(arity[ops[t]])
        if ops[t] == PAD_OP or depth < a:
            continue
        depth += 1 - a
        n += 1
    return n


__all__ = [
    "GPConfig",
    "UNARY_NAMES",
    "BINARY_NAMES",
    "PAD_OP",
    "DISPATCH_KINDS",
    "DIV_EPS",
    "LOG_EPS",
    "decode_ops",
    "decode_args",
    "Structure",
    "program_structure",
    "canonicalize",
    "grow_rand_cols",
    "random_program_genes",
    "random_population",
    "encode_program",
    "is_well_formed",
    "decode_expression",
    "program_length",
]
