"""Symbolic-regression objective family: dataset-resident GP fitness.

``symbolic_regression(X, y, gp=...)`` compiles a dataset into the
library's standard objective protocol — a per-genome callable whose
whole-population ``.rows`` form the engine's ``evaluate`` dispatches
through (``ops/evaluate.py``) — scoring ``-RMSE`` of each genome's
decoded program over the ``(B, n_vars)``/``(B,)`` sample batch (higher
is better, like every objective in the library; non-finite scores
sanitize to ``-inf``).

Evaluator selection mirrors the engine's kernel stance: the fused
Pallas stack machine (``ops/gp_eval.py``) on a real TPU backend (or
when forced with ``fused=True`` — how the interpret-mode agreement
gates run off-chip), the XLA interpreter (``gp/interpreter.py``)
everywhere else; a fused build/dispatch failure degrades to the
interpreter with one warning (the ``PGAConfig.fallback="xla"``
stance), never a crash.

Tuning integration (the round-15 autotuner finally gets a >1-plan
space on CPU):

- **reverse-registry name**: every objective carries a stable
  ``registry_name`` (``gp_sr/<dataset+encoding digest>``), so
  ``tuning.db.objective_class`` derives the SAME tuning-DB key from
  the engine's resolved callable and from the tuner's handle —
  collision-tested against the builtin registry names
  (tests/test_gp.py).
- **knob resolution** (``gp_stack_depth`` / ``gp_opcode_block``):
  explicit factory argument > tuning-DB entry for this
  ``(pop, genome_len, dtype, backend, device, objective, "gp+gp")``
  signature > built-in auto — resolved at trace time per population
  shape and recorded on ``obj.resolved`` for provenance.
- ``with_knobs(...)`` rebuilds the objective at explicit knob values —
  the hook the measurement oracle uses to time candidate configs
  (``tuning/tuner.py``).
"""

from __future__ import annotations

import hashlib
import warnings
from typing import Callable, Optional

import numpy as np

from libpga_tpu.gp.encoding import GPConfig
from libpga_tpu.gp.interpreter import make_eval_rows


def _digest(X: np.ndarray, y: np.ndarray, gp: GPConfig,
            parsimony: float) -> str:
    h = hashlib.sha1()
    h.update(np.ascontiguousarray(X, np.float32).tobytes())
    h.update(np.ascontiguousarray(y, np.float32).tobytes())
    h.update(repr(gp.cache_key()).encode())
    h.update(repr(float(parsimony)).encode())
    return h.hexdigest()[:12]


def symbolic_regression(
    X,
    y,
    *,
    gp: Optional[GPConfig] = None,
    stack_depth: Optional[int] = None,
    opcode_block: Optional[int] = None,
    dispatch: Optional[str] = None,
    parsimony: float = 0.0,
    fused: Optional[bool] = None,
) -> Callable:
    """Build a symbolic-regression objective over an ``(B, n_vars)``
    dataset. ``stack_depth``/``opcode_block``/``dispatch`` pin the
    evaluator knobs explicitly (user precedence over any installed
    tuning DB); ``parsimony`` subtracts that many score units per
    program token; ``fused`` forces the Pallas evaluator on (True),
    off (False), or auto — TPU backends only (None).

    When ``gp.optimize`` (the default) and ``parsimony == 0``, the
    objective exposes the ``prepare_eval`` hook (``ops/evaluate.py``):
    the engine compacts the population once per generation
    (``gp/optimize.optimize_for_eval``) and ``rows`` consumes the
    resulting :class:`~libpga_tpu.gp.optimize.EvalProgram` directly —
    stored genomes are never touched. Parsimony pins the legacy path:
    its token-count penalty is defined over the ORIGINAL program's
    live tokens, which compaction erases."""
    gp = gp or GPConfig()
    Xa = np.asarray(X, np.float32)
    if Xa.ndim == 1:
        Xa = Xa[:, None]
    if Xa.ndim != 2 or Xa.shape[1] != gp.n_vars:
        raise ValueError(
            f"X must be (samples, {gp.n_vars}); got {Xa.shape}"
        )
    ya = np.asarray(y, np.float32).reshape(-1)
    if ya.shape[0] != Xa.shape[0]:
        raise ValueError(
            f"X has {Xa.shape[0]} samples but y has {ya.shape[0]}"
        )
    if (
        stack_depth is not None
        or opcode_block is not None
        or dispatch is not None
    ):
        # Validate explicit knobs eagerly (registration-time errors,
        # the expression-objective stance).
        from libpga_tpu.ops.gp_eval import gp_eval_plan

        gp_eval_plan(
            8, gp, Xa.shape[0],
            stack_depth=stack_depth, opcode_block=opcode_block,
            dispatch=dispatch,
        )

    name = f"gp_sr/{_digest(Xa, ya, gp, parsimony)}"
    opt_on = bool(gp.optimize) and float(parsimony) == 0.0
    #: (pop, active-db path) ->
    #:     (stack_depth, opcode_block, dispatch, provenance)
    resolved: dict = {}
    #: (stack_depth, opcode_block, dispatch) -> rows fn
    rows_cache: dict = {}
    #: (pop, stack_depth, opcode_block, dispatch) -> fused fn or None
    fused_cache: dict = {}
    degraded: set = set()

    def _resolve(pop: int):
        from libpga_tpu.tuning import db as _tdb

        tdb = _tdb.active_db()
        mark = (pop, _tdb.active_path())
        hit = resolved.get(mark)
        if hit is not None:
            return hit
        S, B, D, prov = stack_depth, opcode_block, dispatch, None
        if tdb is not None and (S is None or B is None or D is None):
            entry = tdb.lookup(_tdb.current_key(
                pop, gp.genome_len, np.float32, per_genome, "gp", "gp",
            ))
            if entry is not None:
                prov = {}
                if S is None:
                    S = entry.knobs.get("gp_stack_depth")
                    prov["gp_stack_depth"] = (
                        "db" if S is not None else "default"
                    )
                else:
                    prov["gp_stack_depth"] = "user"
                if B is None:
                    B = entry.knobs.get("gp_opcode_block")
                    prov["gp_opcode_block"] = (
                        "db" if B is not None else "default"
                    )
                else:
                    prov["gp_opcode_block"] = "user"
                if D is None:
                    D = entry.knobs.get("gp_dispatch")
                    prov["gp_dispatch"] = (
                        "db" if D is not None else "default"
                    )
                else:
                    prov["gp_dispatch"] = "user"
        out = (S, B, D, prov)
        resolved[mark] = out
        return out

    def _fused_wanted() -> bool:
        if fused is not None:
            return fused
        import jax

        try:
            return jax.default_backend() == "tpu"
        except RuntimeError:
            return False

    def _fused_eval(pop: int, S, B, D):
        mark = (pop, S, B, D)
        if mark in fused_cache:
            return fused_cache[mark]
        fn = None
        try:
            from libpga_tpu.ops.gp_eval import make_gp_eval

            fn = make_gp_eval(
                gp, Xa, ya, pop=pop, stack_depth=S, opcode_block=B,
                dispatch=D, optimize=opt_on,
            )
        except Exception as exc:  # declines or fails: interpreter serves
            if "fused" not in degraded:
                degraded.add("fused")
                warnings.warn(
                    f"fused GP evaluator unavailable for pop={pop} "
                    f"({type(exc).__name__}: {exc}) — scoring through "
                    "the XLA interpreter",
                    stacklevel=3,
                )
        fused_cache[mark] = fn
        return fn

    def rows(m):
        from libpga_tpu.gp.optimize import EvalProgram

        is_prog = isinstance(m, EvalProgram)
        pop = int(m.ops.shape[0] if is_prog else m.shape[0])
        S, B, D, prov = _resolve(pop)
        if _fused_wanted() and parsimony == 0.0:
            fn = _fused_eval(pop, S, B, D)
            if fn is not None:
                return fn(m)
        key = (S, B, D)
        fn = rows_cache.get(key)
        if fn is None:
            fn = make_eval_rows(
                gp, Xa, ya,
                stack_depth=S, opcode_block=B, dispatch=D,
                parsimony=parsimony,
            )
            rows_cache[key] = fn
        del prov  # provenance is inspectable via obj.resolved
        return fn(m)

    def per_genome(genome):
        return rows(genome[None, :])[0]

    def with_knobs(
        stack_depth: Optional[int] = None,
        opcode_block: Optional[int] = None,
        dispatch: Optional[str] = None,
    ):
        """Rebuild at explicit evaluator knobs (the autotuner's
        measurement hook — user-precedence semantics)."""
        return symbolic_regression(
            Xa, ya, gp=gp,
            stack_depth=stack_depth, opcode_block=opcode_block,
            dispatch=dispatch, parsimony=parsimony, fused=fused,
        )

    per_genome.rows = rows
    per_genome.registry_name = name
    per_genome.gp_config = gp
    per_genome.sr_samples = int(Xa.shape[0])
    per_genome.with_knobs = with_knobs
    per_genome.resolved = resolved
    per_genome.knob_args = (stack_depth, opcode_block, dispatch)
    per_genome.parsimony = float(parsimony)
    if opt_on:
        def prepare_eval(genomes):
            """Compact the population for evaluation (``ops/evaluate``
            hook) — genomes in, transient EvalProgram out."""
            from libpga_tpu.gp.optimize import optimize_for_eval

            return optimize_for_eval(genomes, gp)

        per_genome.prepare_eval = prepare_eval
    per_genome.__doc__ = (
        f"Symbolic-regression objective ({Xa.shape[0]} samples, "
        f"{gp.n_vars} vars, {gp.max_nodes}-token programs): -RMSE."
    )
    return per_genome


def make_dataset(
    fn: Callable,
    n_samples: int = 64,
    n_vars: int = 1,
    lo: float = -1.0,
    hi: float = 1.0,
    seed: int = 0,
):
    """Sample ``(X, y)`` from a ground-truth function on a uniform grid
    of random points — test/bench/example fixture."""
    rng = np.random.default_rng(seed)
    X = rng.uniform(lo, hi, size=(n_samples, n_vars)).astype(np.float32)
    y = np.asarray(
        fn(*[X[:, v] for v in range(n_vars)]), np.float32
    ).reshape(-1)
    return X, y


__all__ = ["symbolic_regression", "make_dataset"]
