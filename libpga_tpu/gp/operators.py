"""GP variation operators: size-fair subtree crossover, subtree and
point mutation — named operator kinds on the library's EXISTING
operator protocol.

Each factory returns a standard per-individual callable ``(p1, p2,
rand) -> child`` / ``(genome, rand) -> genome`` carrying the optional
attributes the engine's breed step already dispatches on
(``ops/step.make_breed``): ``.batched`` (whole-population
implementation), ``.rand_cols`` (uniform columns consumed per
individual), plus the identity attributes the rest of the stack keys
on — ``kernel_cache_key`` (compiled-program caches and the serving
bucket signature derive operator identity from it, ``engine._kind_key``)
and ``param_batched`` (mutation rate as a RUNTIME input — how the
serving mega-run packs distinct rates into one compilation,
``ops/step.make_param_breed``). ``xla_only = True`` marks them as
legitimately kernel-less: they run on the XLA operator path everywhere
(the fused path for GP is the EVALUATOR, ``ops/gp_eval.py``), and the
engine's "no in-kernel form" warning stays quiet.

**Closure.** Both structural operators provably preserve strict postfix
well-formedness (``gp/encoding.is_well_formed``) for all admissible
genome pairs — the property test in tests/test_gp.py:

- a complete postfix subtree is a contiguous token slice with net
  stack effect +1 whose every proper prefix keeps at least one pending
  value, so replacing the slice ``[start[i], i]`` with ANOTHER complete
  subtree leaves every suffix token's stack depth unchanged — no
  underflow can appear;
- size-fair donor choice bounds growth two ways: the Langdon-style
  fairness cap (donor span ≤ ``2 * span(A) + 1``) and the hard
  capacity cap (donor span ≤ ``span(A) + max_nodes - len(parent)``,
  so the child NEVER exceeds ``max_nodes`` tokens). A leaf (span 1)
  always qualifies, so the choice set is never empty;
- subtree mutation is crossover against a freshly GROWN donor
  (``encoding.random_program_genes`` — well-formed by construction);
  point mutation replaces one token's opcode ARITY-PRESERVINGLY (and
  refreshes its operand gene), which leaves the depth profile
  untouched.

Arbitrary (non-canonical) inputs are first normalized by
``encoding.canonicalize`` — the operators are total, so a plain
random-float population arriving through the serving path breeds
instead of crashing.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from libpga_tpu.gp.encoding import (
    GPConfig,
    PAD_OP,
    canonicalize,
    decode_ops,
    grow_rand_cols,
    program_structure,
    random_program_genes,
)


def _pick_nth(mask: jax.Array, n: jax.Array) -> jax.Array:
    """Index of the (n+1)-th True per row of ``mask`` (cumsum trick —
    callers guarantee at least one True where the result is used)."""
    cum = jnp.cumsum(mask.astype(jnp.int32), axis=1)
    sel = mask & (cum == n[:, None] + 1)
    return jnp.argmax(sel, axis=1).astype(jnp.int32)


def _gene_gather(p: jax.Array, src: jax.Array, T: int) -> jax.Array:
    """Gather whole tokens (gene pairs) by token index ``src (P, T)``."""
    src = jnp.clip(src, 0, T - 1)
    gidx = jnp.stack([2 * src, 2 * src + 1], axis=2).reshape(
        p.shape[0], 2 * T
    )
    return jnp.take_along_axis(p, gidx, axis=1)


def _splice(p1c, p2c, r0, r1, gp: GPConfig) -> jax.Array:
    """Size-fair subtree replacement on CANONICAL parents: swap a
    uniformly chosen subtree of ``p1c`` for a size-capped subtree of
    ``p2c``. The closure argument lives in the module docstring."""
    T = gp.max_nodes
    st1 = program_structure(p1c, gp)
    st2 = program_structure(p2c, gp)
    len1, len2 = st1.length, st2.length
    # Subtree A: uniform over p1's live prefix.
    i1 = jnp.clip(
        jnp.floor(r0 * len1).astype(jnp.int32), 0, jnp.maximum(len1 - 1, 0)
    )
    spanA = jnp.take_along_axis(st1.span, i1[:, None], axis=1)[:, 0]
    startA = i1 - spanA + 1
    # Size-fair cap ∧ hard capacity cap (child <= max_nodes tokens).
    limit = jnp.minimum(spanA + (T - len1), 2 * spanA + 1)
    iota = jnp.arange(T, dtype=jnp.int32)[None, :]
    valid = (iota < len2[:, None]) & (st2.span <= limit[:, None])
    cnt = jnp.sum(valid.astype(jnp.int32), axis=1)
    k2 = jnp.clip(
        jnp.floor(r1 * cnt).astype(jnp.int32), 0, jnp.maximum(cnt - 1, 0)
    )
    j2 = _pick_nth(valid, k2)
    spanB = jnp.take_along_axis(st2.span, j2[:, None], axis=1)[:, 0]
    startB = j2 - spanB + 1

    in_mid = (iota >= startA[:, None]) & (
        iota < (startA + spanB)[:, None]
    )
    after = iota >= (startA + spanB)[:, None]
    src1 = jnp.where(after, iota - spanB[:, None] + spanA[:, None], iota)
    src2 = startB[:, None] + (iota - startA[:, None])
    g1 = _gene_gather(p1c, src1, T)
    g2 = _gene_gather(p2c, src2, T)
    child = jnp.where(jnp.repeat(in_mid, 2, axis=1), g2, g1)
    newlen = len1 - spanA + spanB
    tail = jnp.repeat(iota >= newlen[:, None], 2, axis=1)
    pad_row = jnp.tile(
        jnp.asarray([gp.pad_gene, 0.5], child.dtype), T
    )[None, :]
    child = jnp.where(tail, pad_row, child)
    # Degenerate guards: an empty parent contributes nothing to splice.
    child = jnp.where((len1 == 0)[:, None], p2c, child)
    return jnp.where((len2 == 0)[:, None], p1c, child)


def make_subtree_crossover(gp: GPConfig) -> Callable:
    """Size-fair subtree crossover (named kind ``gp_subtree``)."""

    def batched(p1, p2, rand):
        p1c = canonicalize(p1, gp)
        p2c = canonicalize(p2, gp)
        return _splice(p1c, p2c, rand[:, 0], rand[:, 1], gp)

    def op(p1, p2, rand):
        return batched(p1[None, :], p2[None, :], rand[None, :])[0]

    op.batched = batched
    op.rand_cols = 2
    op.kernel_cache_key = f"gp_subtree_crossover/{gp.cache_key()}"
    op.xla_only = True
    op.gp_config = gp
    return op


def make_subtree_mutate(gp: GPConfig, rate: float = 0.3) -> Callable:
    """Subtree mutation (named kind ``gp_subtree``): with probability
    ``rate`` per individual, size-fair-splice a freshly grown random
    subtree over a uniformly chosen one. ``param_batched`` takes the
    rate as a runtime input (the serving mega-run contract)."""
    gc = grow_rand_cols(gp)

    def _mutate(genomes, rand, rate_val):
        donors = random_program_genes(rand[:, 3:], gp)  # canonical
        base = canonicalize(genomes, gp)
        mutated = _splice(base, donors, rand[:, 1], rand[:, 2], gp)
        fire = (rand[:, 0] < rate_val)[:, None]
        return jnp.where(fire, mutated, genomes)

    def batched(genomes, rand):
        return _mutate(genomes, rand, rate)

    def param_batched(genomes, rand, rate_val, sigma):
        del sigma  # GP mutation has no sigma axis
        return _mutate(genomes, rand, rate_val)

    def op(genome, rand):
        return batched(genome[None, :], rand[None, :])[0]

    op.batched = batched
    op.param_batched = param_batched
    op.rand_cols = 3 + gc
    op.rate = rate
    op.kernel_cache_key = f"gp_subtree_mutate/{gp.cache_key()}"
    op.xla_only = True
    op.gp_config = gp
    return op


def make_gp_point_mutate(gp: GPConfig, rate: float = 0.2) -> Callable:
    """Point mutation (named kind ``gp_point``): with probability
    ``rate`` per individual, replace one uniformly chosen live token's
    opcode with a random SAME-ARITY opcode and refresh its operand
    gene — the depth profile is untouched, so well-formedness is
    preserved by construction."""
    arity = jnp.asarray(gp.op_arities(), jnp.int32)
    op_ids = jnp.arange(gp.n_ops, dtype=jnp.int32)
    n_ops = gp.n_ops

    def _mutate(genomes, rand, rate_val):
        P, L = genomes.shape
        T = gp.max_nodes
        st = program_structure(genomes, gp)
        length = st.length
        k = jnp.clip(
            jnp.floor(rand[:, 1] * length).astype(jnp.int32),
            0,
            jnp.maximum(length - 1, 0),
        )
        pos = _pick_nth(st.live, k)
        ops = decode_ops(genomes, gp)
        op_i = jnp.take_along_axis(ops, pos[:, None], axis=1)[:, 0]
        a_i = arity[op_i]
        allowed = (arity[None, :] == a_i[:, None]) & (
            op_ids != PAD_OP
        )[None, :]
        cnt = jnp.sum(allowed.astype(jnp.int32), axis=1)
        choice = jnp.clip(
            jnp.floor(rand[:, 2] * cnt).astype(jnp.int32),
            0,
            jnp.maximum(cnt - 1, 0),
        )
        new_op = _pick_nth(allowed, choice)
        new_opg = (new_op.astype(jnp.float32) + 0.5) / n_ops
        fire = (rand[:, 0] < rate_val) & (length > 0)
        cols = jnp.arange(L, dtype=jnp.int32)[None, :]
        hit_op = (cols == (2 * pos)[:, None]) & fire[:, None]
        hit_arg = (cols == (2 * pos + 1)[:, None]) & fire[:, None]
        out = jnp.where(hit_op, new_opg[:, None].astype(genomes.dtype),
                        genomes)
        return jnp.where(
            hit_arg, rand[:, 3:4].astype(genomes.dtype), out
        )

    def batched(genomes, rand):
        return _mutate(genomes, rand, rate)

    def param_batched(genomes, rand, rate_val, sigma):
        del sigma
        return _mutate(genomes, rand, rate_val)

    def op(genome, rand):
        return batched(genome[None, :], rand[None, :])[0]

    op.batched = batched
    op.param_batched = param_batched
    op.rand_cols = 4
    op.rate = rate
    op.kernel_cache_key = f"gp_point_mutate/{gp.cache_key()}"
    op.xla_only = True
    op.gp_config = gp
    return op


def make_gp_mutate(
    gp: GPConfig, subtree_rate: float = 0.4, point_rate: float = 0.6
) -> Callable:
    """The STANDARD GP mutation (named kind ``gp_mutate``): subtree
    mutation chained with point mutation — structural innovation plus
    the local repair pressure that keeps populations from collapsing
    onto one shape (measured on the recovery smoke: subtree-only
    stalls a third of seeds at a local optimum; the chain recovers
    them). Runtime-parameter mapping for the serving mega-run:
    ``mparams`` rate drives the SUBTREE rate and sigma drives the
    POINT rate, so both axes stay sweepable per request."""
    sub = make_subtree_mutate(gp, rate=subtree_rate)
    pt = make_gp_point_mutate(gp, rate=point_rate)
    c1 = sub.rand_cols

    def batched(genomes, rand):
        return pt.batched(sub.batched(genomes, rand[:, :c1]), rand[:, c1:])

    def param_batched(genomes, rand, rate_val, sigma):
        mid = sub.param_batched(genomes, rand[:, :c1], rate_val, 0.0)
        return pt.param_batched(mid, rand[:, c1:], sigma, 0.0)

    def op(genome, rand):
        return batched(genome[None, :], rand[None, :])[0]

    op.batched = batched
    op.param_batched = param_batched
    op.rand_cols = c1 + pt.rand_cols
    op.rate = subtree_rate
    op.sigma = point_rate  # the serving mparams mapping above
    op.kernel_cache_key = (
        f"gp_mutate/{subtree_rate}/{point_rate}/{gp.cache_key()}"
    )
    op.xla_only = True
    op.gp_config = gp
    return op


#: Named operator registry — the GP analog of the builtin
#: crossover/mutation name maps the C ABI dispatches on
#: (``capi_bridge.set_crossover_name`` / ``set_mutate_name``).
CROSSOVER_KINDS = {"gp_subtree": make_subtree_crossover}
MUTATE_KINDS = {
    "gp_subtree": make_subtree_mutate,
    "gp_point": make_gp_point_mutate,
    "gp_mutate": make_gp_mutate,
}


__all__ = [
    "make_subtree_crossover",
    "make_subtree_mutate",
    "make_gp_point_mutate",
    "make_gp_mutate",
    "CROSSOVER_KINDS",
    "MUTATE_KINDS",
]
