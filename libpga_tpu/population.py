"""Population container and initialization strategies.

The reference models a population as four device buffers — two genome
buffers (current/next generation), a score vector, and a pre-generated
uniform random pool (``src/pga.cu:37-46``). TPU-natively a population is a
single functional pytree: one ``(size, genome_len)`` genome matrix plus a
``(size,)`` score vector. Double buffering is XLA's job (buffer donation),
and randomness is threaded `jax.random` keys rather than a mutable pool.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class Population:
    """A single population (island). A JAX pytree — safe to jit/vmap/shard.

    genomes: ``(size, genome_len)`` gene matrix, values in [0, 1) by
      convention (drivers decode ints/permutations from normalized floats,
      as the reference drivers do, e.g. ``test3/test.cu:31-32``).
    scores: ``(size,)`` fitness per individual; higher is better (the
      reference argmaxes in ``pga_get_best``, ``pga.cu:224``).
    """

    genomes: jax.Array
    scores: jax.Array

    @property
    def size(self) -> int:
        return self.genomes.shape[0]

    @property
    def genome_len(self) -> int:
        return self.genomes.shape[1]


def random_population(
    key: jax.Array, size: int, genome_len: int, dtype=jnp.float32
) -> Population:
    """RANDOM_POPULATION init: uniform [0,1) genomes (``pga.cu:81-97``)."""
    genomes = jax.random.uniform(key, (size, genome_len), dtype=dtype)
    scores = jnp.full((size,), -jnp.inf, dtype=jnp.float32)
    return Population(genomes=genomes, scores=scores)


def zeros_population(
    key: jax.Array, size: int, genome_len: int, dtype=jnp.float32
) -> Population:
    """All-zero genomes (useful for tests and warm starts)."""
    del key
    genomes = jnp.zeros((size, genome_len), dtype=dtype)
    scores = jnp.full((size,), -jnp.inf, dtype=jnp.float32)
    return Population(genomes=genomes, scores=scores)


# Init-strategy registry — the TPU analog of the reference's
# ``population_generators[]`` dispatch table (``pga.cu:95-97``).
POPULATION_GENERATORS: Dict[str, Callable[..., Population]] = {
    "random": random_population,
    "zeros": zeros_population,
}


def create_population(
    key: jax.Array,
    size: int,
    genome_len: int,
    init: str = "random",
    dtype=jnp.float32,
) -> Population:
    if genome_len < 4:
        # The reference enforces genome_len >= 4 because its default mutate
        # callback consumes rand[0..2] (``pga.cu:184,127-133``). We keep the
        # guard for behavioral parity.
        raise ValueError("genome_len must be >= 4")
    if size < 1:
        raise ValueError("population size must be >= 1")
    try:
        gen = POPULATION_GENERATORS[init]
    except KeyError:
        raise ValueError(
            f"unknown population init {init!r}; have {sorted(POPULATION_GENERATORS)}"
        ) from None
    return gen(key, size, genome_len, dtype=dtype)
